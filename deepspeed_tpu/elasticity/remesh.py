"""Elastic live remesh: re-shard a LIVE engine's params + optimizer state
onto a different topology from an in-memory host snapshot — no disk read.

The cold elastic story (``run_resilient`` + :class:`ElasticAgent`) recovers
from worker loss by full restart-from-checkpoint: minutes of tensorstore
reads for a state the process mostly still HAS. This module closes that
gap: :func:`capture_snapshot` folds the engine's checkpoint-state tree
through the SAME per-parameter universal layout math the offline converter
uses (``checkpoint/ds_to_universal.universal_state_from_tree`` — the code
path whose pp2×tp2 → pp1×tp4 bit-exactness is already test-pinned), and
:func:`restore_snapshot` overlays it onto an engine built for ANY new mesh
via ``checkpoint/universal_checkpoint.apply_universal_state``. A topology
change then costs one host-RAM round trip instead of a checkpoint restore.

Fallback ladder (what ``run_resilient(warm_remesh=True)`` implements):

    1. **snapshot** — a published :class:`HostSnapshot` at least as new as
       the newest valid disk tag: warm re-shard, zero disk reads;
    2. **disk** — newest manifest-valid checkpoint tag (the PR 4 path);
    3. **cold** — fresh initialization.

Snapshots are published by the engine's save path when
``checkpoint.remesh_snapshot`` is on (piggybacking the host copy the async
saver already takes), or explicitly via :func:`publish_snapshot`. The store
is process-global and holds exactly ONE snapshot (the newest wins): a
snapshot is a full fp32 model + two moments in host RAM — depth 1 is the
same bound the async saver keeps for its in-flight payload.
"""

import threading
import time

from ..monitor.metrics import get_metrics
from ..utils.logging import logger


class HostSnapshot:
    """One captured universal-layout state: ``sd`` is the per-parameter
    ``{path: {fp32, exp_avg?, exp_avg_sq?}}`` dict, ``meta`` the sidecar
    (step counters, has_optimizer, …). ``scope`` is the job identity the
    publisher stamps (the checkpoint save_dir) so a consumer can refuse a
    snapshot that belongs to a DIFFERENT job in the same process."""

    __slots__ = ("sd", "meta", "step", "captured_unix", "scope")

    def __init__(self, sd, meta, captured_unix=None, scope=None):
        self.sd = sd
        self.meta = meta
        self.step = int(meta.get("global_steps") or meta.get("step") or 0)
        self.captured_unix = time.time() if captured_unix is None else captured_unix
        self.scope = _norm_scope(scope)

    def nbytes(self):
        total = 0
        for entry in self.sd.values():
            for arr in entry.values():
                total += getattr(arr, "nbytes", 0)
        return total

    def __repr__(self):
        return (f"HostSnapshot(step={self.step}, params={len(self.sd)}, "
                f"bytes={self.nbytes()})")


def capture_snapshot(engine, state=None):
    """Snapshot ``engine``'s full training state (weights + Adam moments +
    counters) into the universal layout, host-resident. ``state`` lets the
    save path hand in the checkpoint tree it already built (on the async
    single-host path that tree is ALREADY host numpy — the snapshot then
    costs fp32 casts, not a second device_get)."""
    import jax
    import numpy as np

    from ..checkpoint.ds_to_universal import universal_state_from_tree

    tree = engine._ckpt_state() if state is None else state
    # host-materialize array leaves; universal_state_from_tree handles the
    # rest (numpy passes through device_get untouched)
    tree = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x, tree)
    sd, meta = universal_state_from_tree(tree)
    snap = HostSnapshot(sd, meta)
    get_metrics().counter("checkpoint/remesh_snapshots_total").inc()
    return snap


def restore_snapshot(engine, snap, load_optimizer_states=True):
    """Overlay ``snap`` onto ``engine`` under its CURRENT mesh (any
    topology whose param tree matches): the warm half of an elastic
    restart. Returns the snapshot's meta."""
    from ..checkpoint.universal_checkpoint import apply_universal_state

    t0 = time.perf_counter()
    meta = apply_universal_state(engine, snap.sd, snap.meta,
                                 load_optimizer_states=load_optimizer_states)
    get_metrics().histogram("checkpoint/remesh_restore_ms").observe(
        (time.perf_counter() - t0) * 1e3)
    logger.info(f"warm remesh: restored {len(snap.sd)} params from host snapshot "
                f"(step={snap.step}) without touching disk")
    return meta


# ---------------------------------------------------------------------------
# process-global snapshot store (depth 1: newest wins within a scope)
# ---------------------------------------------------------------------------
_lock = threading.Lock()
_latest = None


def _norm_scope(scope):
    import os

    return os.path.abspath(str(scope)) if scope is not None else None


def publish_snapshot(snap, scope=None):
    """Make ``snap`` the warm-resume candidate. ``scope`` (a checkpoint
    save_dir) stamps the snapshot's job identity when the snapshot itself
    carries none. A snapshot from a DIFFERENT scope replaces the held one
    unconditionally — a new job in the same process must not lose its warm
    path to a stale predecessor; within one scope the newer step wins."""
    global _latest
    if scope is not None and snap.scope is None:
        snap.scope = _norm_scope(scope)
    with _lock:
        if (_latest is not None and _latest.scope == snap.scope
                and _latest.step > snap.step):
            logger.warning(f"remesh: published snapshot step {snap.step} is older than "
                           f"held step {_latest.step}; keeping the newer one")
            return _latest
        _latest = snap
    return snap


def latest_snapshot(scope=None):
    """The held snapshot, or None. With ``scope`` given, only a snapshot
    stamped for that scope (or an explicitly scope-less one, published by
    hand) is returned — the cross-job safety check ``run_resilient`` relies
    on: a previous job's snapshot must never warm-resume an unrelated one."""
    with _lock:
        snap = _latest
    if snap is None:
        return None
    if scope is not None and snap.scope is not None and snap.scope != _norm_scope(scope):
        return None
    return snap


def clear_snapshots():
    """Drop the held snapshot (tests / explicit cold-restart policy)."""
    global _latest
    with _lock:
        _latest = None
