from .elasticity import (compute_elastic_config, get_compatible_gpus_v01, get_compatible_gpus_v02,
                         elasticity_enabled, ensure_immutable_elastic_config, ElasticityError,
                         ElasticityConfigError, ElasticityIncompatibleWorldSize)
from .elastic_agent import ElasticAgent
from . import remesh
from .remesh import (HostSnapshot, capture_snapshot, restore_snapshot,
                     publish_snapshot, latest_snapshot, clear_snapshots)
