"""Bloom model family configs.

Analog of the reference ``module_inject/containers/bloom.py`` +
``model_implementations/bloom/``: LayerNorm (plus a word-embedding
LayerNorm), ALiBi positions, GELU MLP, biases everywhere, tied embeddings,
fused per-head query_key_value in HF checkpoints (split by the converter).
"""

from .transformer import TransformerConfig, TransformerLM


def bloom_config(size: str = "560m", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=512),
        "560m": dict(vocab_size=250880, hidden_size=1024, num_layers=24, num_heads=16, max_seq_len=2048),
        "7b1": dict(vocab_size=250880, hidden_size=4096, num_layers=30, num_heads=32, max_seq_len=2048),
        "176b": dict(vocab_size=250880, hidden_size=14336, num_layers=70, num_heads=112, max_seq_len=2048),
    }
    base = dict(presets[size], norm="layernorm", positions="alibi", mlp="gelu", use_bias=True,
                intermediate_size=4 * presets[size]["hidden_size"], tie_embeddings=True,
                embed_layernorm=True, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def bloom(size: str = "560m", **overrides) -> TransformerLM:
    return TransformerLM(bloom_config(size, **overrides))
