"""Qwen2 model family configs.

The reference's AutoTP supports Qwen via its name-based policy inference
(``module_inject/auto_tp.py`` + ``supported_models``). Architecture:
Llama-shaped (RMSNorm + rotary + SwiGLU + GQA) with BIASED qkv projections
only (o/mlp bias-free — the ``qkv_bias`` knob) and a large rope theta.
"""

from .transformer import TransformerConfig, TransformerLM


def qwen2_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=32000, hidden_size=256, num_layers=4, num_heads=8, num_kv_heads=2,
                     intermediate_size=704, max_seq_len=2048),
        "0.5b": dict(vocab_size=151936, hidden_size=896, num_layers=24, num_heads=14, num_kv_heads=2,
                     intermediate_size=4864, max_seq_len=32768, tie_embeddings=True),
        "7b": dict(vocab_size=152064, hidden_size=3584, num_layers=28, num_heads=28, num_kv_heads=4,
                   intermediate_size=18944, max_seq_len=32768),
    }
    base = dict(presets[size], norm="rmsnorm", positions="rotary", mlp="swiglu",
                use_bias=False, qkv_bias=True, rope_theta=1e6, norm_eps=1e-6)
    base.update(overrides)
    return TransformerConfig(**base)


def qwen2(size: str = "7b", **overrides) -> TransformerLM:
    return TransformerLM(qwen2_config(size, **overrides))
