"""GPT-2/NeoX-style configs (LayerNorm + learned positions + gelu, biases,
tied embeddings) — the reference's gpt2/gptneox containers
(``module_inject/containers/gpt2.py``, ``gptneox.py``)."""

from .transformer import TransformerConfig, TransformerLM


def gpt2_config(size: str = "small", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=50257, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=512),
        "small": dict(vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12, max_seq_len=1024),
        "medium": dict(vocab_size=50257, hidden_size=1024, num_layers=24, num_heads=16, max_seq_len=1024),
        "large": dict(vocab_size=50257, hidden_size=1280, num_layers=36, num_heads=20, max_seq_len=1024),
        "xl": dict(vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25, max_seq_len=1024),
    }
    base = dict(presets[size], norm="layernorm", positions="learned", mlp="gelu", use_bias=True,
                tie_embeddings=True)
    base.update(overrides)
    return TransformerConfig(**base)


def gpt2(size: str = "small", **overrides) -> TransformerLM:
    return TransformerLM(gpt2_config(size, **overrides))
