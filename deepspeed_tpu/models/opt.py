"""OPT model family configs.

Analog of the reference ``inference/v2/model_implementations/opt/`` and
``module_inject/containers/opt.py``: LayerNorm + learned positions + ReLU
MLP, biases everywhere, tied embeddings.
"""

from .transformer import TransformerConfig, TransformerLM


def opt_config(size: str = "125m", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=50272, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=512),
        "125m": dict(vocab_size=50272, hidden_size=768, num_layers=12, num_heads=12, max_seq_len=2048),
        "1.3b": dict(vocab_size=50272, hidden_size=2048, num_layers=24, num_heads=32, max_seq_len=2048),
        "6.7b": dict(vocab_size=50272, hidden_size=4096, num_layers=32, num_heads=32, max_seq_len=2048),
        "13b": dict(vocab_size=50272, hidden_size=5120, num_layers=40, num_heads=40, max_seq_len=2048),
        "30b": dict(vocab_size=50272, hidden_size=7168, num_layers=48, num_heads=56, max_seq_len=2048),
        "66b": dict(vocab_size=50272, hidden_size=9216, num_layers=64, num_heads=72, max_seq_len=2048),
    }
    base = dict(presets[size], norm="layernorm", positions="learned", mlp="relu", use_bias=True,
                intermediate_size=4 * presets[size]["hidden_size"], tie_embeddings=True)
    base.update(overrides)
    return TransformerConfig(**base)


def opt(size: str = "125m", **overrides) -> TransformerLM:
    return TransformerLM(opt_config(size, **overrides))
