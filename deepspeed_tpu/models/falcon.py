"""Falcon model family configs.

Analog of the reference ``module_inject/containers/`` falcon-style
parallel-attention container: parallel residual with a single pre-norm
(falcon-7b ``parallel_attn`` + no ``new_decoder_architecture``), full
rotary, GELU, no biases, MQA/GQA (falcon-7b: 1 kv head), tied embeddings.
"""

from .transformer import TransformerConfig, TransformerLM


def falcon_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, num_kv_heads=1,
                     max_seq_len=512),
        "7b": dict(vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71, num_kv_heads=1,
                   max_seq_len=2048),
        "40b": dict(vocab_size=65024, hidden_size=8192, num_layers=60, num_heads=128, num_kv_heads=8,
                    max_seq_len=2048),
    }
    base = dict(presets[size], norm="layernorm", positions="rotary", mlp="gelu", use_bias=False,
                intermediate_size=4 * presets[size]["hidden_size"], tie_embeddings=True,
                parallel_residual=True, shared_ln=True, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def falcon(size: str = "7b", **overrides) -> TransformerLM:
    return TransformerLM(falcon_config(size, **overrides))
