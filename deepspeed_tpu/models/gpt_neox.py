"""GPT-NeoX / Pythia model family configs.

Analog of the reference ``module_inject/containers/gptneox.py``: parallel
residual with TWO pre-norms, partial rotary (rotary_pct, NeoX-half style),
GELU, biases, untied embeddings, fused per-head query_key_value in HF
checkpoints (split by the converter).
"""

from .transformer import TransformerConfig, TransformerLM


def gpt_neox_config(size: str = "20b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=512,
                     rotary_dim=8),
        "pythia-1b": dict(vocab_size=50304, hidden_size=2048, num_layers=16, num_heads=8,
                          max_seq_len=2048, rotary_dim=64),
        "20b": dict(vocab_size=50432, hidden_size=6144, num_layers=44, num_heads=64, max_seq_len=2048,
                    rotary_dim=24),
    }
    base = dict(presets[size], norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
                intermediate_size=4 * presets[size]["hidden_size"], tie_embeddings=False,
                parallel_residual=True, shared_ln=False, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def gpt_neox(size: str = "20b", **overrides) -> TransformerLM:
    return TransformerLM(gpt_neox_config(size, **overrides))
