"""Llama-2 model family configs.

Analog of the reference's llama containers (``module_inject/containers/llama.py``,
``inference/v2/model_implementations/llama_v2/``): RMSNorm + rotary + SwiGLU +
GQA(70B), untied head. Sizes follow the published Llama-2 architecture table.
"""

from .transformer import TransformerConfig, TransformerLM


def llama2_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=32000, hidden_size=256, num_layers=4, num_heads=8, num_kv_heads=8,
                     intermediate_size=688, max_seq_len=2048),
        "7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=32,
                   intermediate_size=11008, max_seq_len=4096),
        "13b": dict(vocab_size=32000, hidden_size=5120, num_layers=40, num_heads=40, num_kv_heads=40,
                    intermediate_size=13824, max_seq_len=4096),
        "70b": dict(vocab_size=32000, hidden_size=8192, num_layers=80, num_heads=64, num_kv_heads=8,
                    intermediate_size=28672, max_seq_len=4096),
    }
    base = dict(presets[size], norm="rmsnorm", positions="rotary", mlp="swiglu", use_bias=False,
                tie_embeddings=False, rope_theta=10000.0, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def llama2(size: str = "7b", **overrides) -> TransformerLM:
    return TransformerLM(llama2_config(size, **overrides))
