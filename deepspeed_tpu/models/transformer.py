"""TPU-first decoder-only transformer.

This is the framework's flagship training model family, covering the model
space of the reference's containers (``deepspeed/module_inject/containers/``
gpt2…llama2, ``model_implementations/``): configurable norm (LayerNorm /
RMSNorm), positions (learned / rotary), MLP (gelu / SwiGLU), GQA, tied or
untied LM head. Design choices are TPU-native, not a port:

  * **Scan-stacked layers**: all L blocks live in single stacked arrays
    ([L, ...]) consumed by ``lax.scan`` — one block compiled once, and when
    ZeRO-3 shards the stacked arrays over the data axis, XLA's scan lowering
    all-gathers exactly one layer's params per iteration: the same per-submodule
    allgather/release lifecycle the reference drives with module hooks
    (``partitioned_param_coordinator.py:256 fetch_sub_module``), but from the
    compiler.
  * **Mixed precision by policy**: params fp32 (master weights, reference
    ``bf16_optimizer.py``), compute in bf16 on the MXU.
  * **Remat**: ``jax.checkpoint`` with a named policy replaces the reference's
    activation-checkpointing machinery (``activation_checkpointing/checkpointing.py``).
  * **Parallelism by sharding**: TP via PartitionRules over the ``model`` axis,
    sequence parallel via Ulysses sharding constraints, batch over ``data``.
"""

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS
from ..runtime.zero.partition import PartitionRules


@dataclass
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    intermediate_size: Optional[int] = None  # default 4x (gelu) or 8/3x (swiglu)
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # GQA; None = MHA
    max_seq_len: int = 2048
    norm: str = "rmsnorm"  # 'rmsnorm' | 'layernorm'
    positions: str = "rotary"  # 'rotary' | 'learned' | 'alibi'
    mlp: str = "swiglu"  # 'swiglu' | 'gelu' | 'relu'
    use_bias: bool = False
    # per-site override for the qkv projections only (Qwen2: biased qkv,
    # bias-free o/mlp). None = follow use_bias.
    qkv_bias: Optional[bool] = None
    tie_embeddings: bool = False
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    # GPT-J / GPT-NeoX / Falcon style: attention and MLP read the SAME
    # residual input and their outputs add jointly (x + attn + mlp)
    parallel_residual: bool = False
    # parallel_residual models with a single pre-norm (GPT-J, Falcon-7B);
    # False = separate ln2 for the MLP branch (GPT-NeoX)
    shared_ln: bool = False
    # partial rotary (GPT-J rotary_dim, NeoX rotary_pct): rope applies to the
    # first rotary_dim dims of each head; None = full head_dim
    rotary_dim: Optional[int] = None
    # Bloom: LayerNorm right after the token embedding
    embed_layernorm: bool = False
    dtype: Any = jnp.bfloat16  # compute dtype; params are fp32 masters
    # sequence-chunked cross entropy: compute/remat the vocabulary logits
    # one [B, loss_chunk, V] slice at a time instead of materializing the
    # full [B, S, V] — at seq 32k x vocab 32k the full fp32 logits alone are
    # 4GiB/sample, the long-context HBM binding term. None = full logits.
    loss_chunk: Optional[int] = None
    remat: bool = False
    remat_policy: str = "nothing_saveable"
    attention_impl: str = "auto"  # 'auto' | 'reference' | 'flash'
    # sliding-window attention (Mistral): query i sees keys in (i-window, i];
    # None = full causal context. Applies to training (flash/reference),
    # the v1 KV-cache path, and the v2 paged path.
    sliding_window: Optional[int] = None
    sequence_parallel: bool = False  # Ulysses/ring sharding over the seq axis
    sequence_parallel_impl: str = "ulysses"  # 'ulysses' (a2a) | 'ring' (ppermute)
    dropout: float = 0.0
    # block-sparse attention: the ds_config 'sparse_attention' dict (mode +
    # per-mode keys, reference config.py:289). None = dense attention.
    sparse_attention: Optional[dict] = None
    # MoE (reference deepspeed/moe): 0 = dense; experts shard over the data
    # axes (expert parallelism); XLA inserts the dispatch/combine all-to-alls
    # at the sharding-constraint boundaries.
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    moe_noisy_gate_policy: Optional[str] = None
    # "einsum": the [S, E, C] one-hot dispatch/combine (EP-shardable, the
    # GSPMD default); "grouped": the megablocks-style Pallas ragged matmul
    # (ops/pallas/grouped_matmul.py) — work scales with routed tokens, the
    # single-shard win at large E (reference cutlass_ops moe_gemm analog)
    moe_impl: str = "einsum"
    # ZeRO++ qwZ (reference partition_parameters.py:1139 quantized all-gather
    # handles): when set (by the engine, from zero_quantized_weights), the
    # per-layer stage-3 weight gathers inside the scan body travel as int8
    # payload + per-block scales instead of fp32 — 4x less ICI traffic —
    # with a straight-through gradient to the fp32 masters.
    quantized_weights: bool = False
    # Explicit ZeRO-3 gather/compute overlap (set by the engine from
    # zero_optimization.overlap_comm at stage 3): the scan double-buffers the
    # NEXT layer's gathered params in the carry — layer l+1's all-gather is
    # issued at the top of iteration l, so the collective overlaps layer l's
    # compute explicitly instead of relying on XLA's latency-hiding
    # scheduler. Bit-identical loss vs the implicit path (test-enforced).
    overlap_gather: bool = False

    def __post_init__(self):
        if self.moe_impl not in ("einsum", "grouped"):
            raise ValueError(f"moe_impl must be 'einsum' or 'grouped', got {self.moe_impl!r}")
        if self.intermediate_size is None:
            if self.mlp == "swiglu":
                self.intermediate_size = int(8 * self.hidden_size / 3 / 128 + 1) * 128
            else:
                self.intermediate_size = 4 * self.hidden_size
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.sparse_attention is not None:
            if self.sliding_window is not None or self.positions == "alibi":
                raise NotImplementedError("sparse_attention does not compose with sliding_window "
                                          "or alibi (express the window via the layout instead)")
            if self.num_kv_heads != self.num_heads:
                raise NotImplementedError(
                    "sparse_attention requires num_kv_heads == num_heads (MHA) — reject at "
                    "config time rather than deep inside the first jitted forward")
        assert self.hidden_size % self.num_heads == 0
        assert self.num_heads % self.num_kv_heads == 0

    @property
    def qkv_bias_enabled(self) -> bool:
        return self.use_bias if self.qkv_bias is None else self.qkv_bias

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


# ---------------------------------------------------------------------------
# Param init
# ---------------------------------------------------------------------------

def init_params(cfg: TransformerConfig, rng: jax.Array) -> Dict[str, Any]:
    """fp32 master params; stacked [L, ...] block arrays for lax.scan."""
    L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    k = jax.random.split(rng, 12)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in))

    blocks = {
        "ln1_scale": jnp.ones((L, H), jnp.float32),
        "wq": dense_init(k[0], (L, H, nq * d), H),
        "wk": dense_init(k[1], (L, H, nkv * d), H),
        "wv": dense_init(k[2], (L, H, nkv * d), H),
        "wo": dense_init(k[3], (L, nq * d, H), nq * d) / math.sqrt(2 * L),
        "ln2_scale": jnp.ones((L, H), jnp.float32),
    }
    if cfg.moe_num_experts > 0:
        E = cfg.moe_num_experts
        blocks["gate_wg"] = dense_init(k[4], (L, H, E), H)
        blocks["moe_wi"] = dense_init(k[5], (L, E, H, F), H)
        blocks["moe_wo"] = dense_init(k[6], (L, E, F, H), F) / math.sqrt(2 * L)
        if cfg.mlp == "swiglu":
            blocks["moe_wg"] = dense_init(k[10], (L, E, H, F), H)
    else:
        blocks["w_up"] = dense_init(k[4], (L, H, F), H)
        blocks["w_down"] = dense_init(k[5], (L, F, H), F) / math.sqrt(2 * L)
        if cfg.mlp == "swiglu":
            blocks["w_gate"] = dense_init(k[6], (L, H, F), H)
    if cfg.parallel_residual and cfg.shared_ln:
        del blocks["ln2_scale"]  # single pre-norm feeds both branches
    if cfg.norm == "layernorm":
        blocks["ln1_bias"] = jnp.zeros((L, H), jnp.float32)
        if not (cfg.parallel_residual and cfg.shared_ln):
            blocks["ln2_bias"] = jnp.zeros((L, H), jnp.float32)
    if cfg.qkv_bias_enabled:
        blocks["bq"] = jnp.zeros((L, nq * d), jnp.float32)
        blocks["bk"] = jnp.zeros((L, nkv * d), jnp.float32)
        blocks["bv"] = jnp.zeros((L, nkv * d), jnp.float32)
    if cfg.use_bias:
        blocks["bo"] = jnp.zeros((L, H), jnp.float32)
        blocks["b_up"] = jnp.zeros((L, F), jnp.float32)
        blocks["b_down"] = jnp.zeros((L, H), jnp.float32)

    params = {
        "embed": {"embedding": jax.random.normal(k[7], (cfg.vocab_size, H), jnp.float32) * 0.02},
        "blocks": blocks,
        "final_norm": {"scale": jnp.ones((H, ), jnp.float32)},
    }
    if cfg.norm == "layernorm":
        params["final_norm"]["bias"] = jnp.zeros((H, ), jnp.float32)
    if cfg.embed_layernorm:  # Bloom word_embeddings_layernorm
        params["embed_norm"] = {"scale": jnp.ones((H, ), jnp.float32)}
        if cfg.norm == "layernorm":
            params["embed_norm"]["bias"] = jnp.zeros((H, ), jnp.float32)
    if cfg.positions == "learned":
        params["pos_embed"] = {"embedding": jax.random.normal(k[8], (cfg.max_seq_len, H), jnp.float32) * 0.02}
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": dense_init(k[9], (H, cfg.vocab_size), H)}
    return params


# ---------------------------------------------------------------------------
# TP partition rules (composed with ZeRO by ZeroShardingPolicy)
# ---------------------------------------------------------------------------

def partition_rules(cfg: Optional[TransformerConfig] = None) -> PartitionRules:
    """Megatron-style TP sharding over the ``model`` mesh axis: qkv/up
    column-parallel, out/down row-parallel, vocab-sharded embeddings — the
    layout the reference's AutoTP infers (``module_inject/auto_tp.py:187``)."""
    return PartitionRules([
        (r"embed/embedding", P(MODEL_AXIS, None)),
        (r"pos_embed/embedding", P(None, None)),
        # blocks dim 0 is the stacked layer dim: sharding it over 'pipe' IS
        # pipeline stage assignment (uniform partitioning, reference
        # PipelineModule._partition_layers); dropped automatically at pipe=1
        (r"blocks/w[qkv]$", P(PIPE_AXIS, None, MODEL_AXIS)),
        (r"blocks/b[qkv]$", P(PIPE_AXIS, MODEL_AXIS)),
        (r"blocks/wo$", P(PIPE_AXIS, MODEL_AXIS, None)),
        (r"blocks/(w_up|w_gate)$", P(PIPE_AXIS, None, MODEL_AXIS)),
        (r"blocks/b_up$", P(PIPE_AXIS, MODEL_AXIS)),
        (r"blocks/w_down$", P(PIPE_AXIS, MODEL_AXIS, None)),
        (r"blocks/(ln1_scale|ln2_scale|ln1_bias|ln2_bias|b_down|bo)$", P(PIPE_AXIS, None)),
        # MoE: experts shard over the data axes (= expert parallelism; this IS
        # their ZeRO sharding), FFN dims over model (TP inside each expert)
        (r"blocks/gate_wg$", P(PIPE_AXIS, None, None)),
        (r"blocks/(moe_wi|moe_wg)$", P(PIPE_AXIS, DATA_AXIS, None, MODEL_AXIS)),
        (r"blocks/moe_wo$", P(PIPE_AXIS, DATA_AXIS, MODEL_AXIS, None)),
        (r"lm_head/kernel", P(None, MODEL_AXIS)),
    ])


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _norm(x, scale, bias, kind, eps):
    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
        out = x32 * scale
    else:
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean((x32 - mu)**2, axis=-1, keepdims=True)
        out = (x32 - mu) * jax.lax.rsqrt(var + eps) * scale + (bias if bias is not None else 0.0)
    return out.astype(x.dtype)


def rope_table(cfg: TransformerConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    d = cfg.rotary_dim or cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta**(jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = jnp.einsum("s,f->sf", positions.astype(jnp.float32), inv_freq)
    return jnp.sin(freqs), jnp.cos(freqs)


def apply_rope(x, sin, cos):
    """x: [B, S, n, d]; sin/cos: [S, r/2] with r <= d (partial rotary, GPT-J
    ``rotary_dim`` / NeoX ``rotary_pct``): the first r dims rotate in half
    style, the rest pass through."""
    r = 2 * sin.shape[-1]
    d = x.shape[-1]
    xr = x[..., :r] if r < d else x
    x1, x2 = jnp.split(xr.astype(jnp.float32), 2, axis=-1)
    sinb = sin[None, :, None, :]
    cosb = cos[None, :, None, :]
    rot = jnp.concatenate([x1 * cosb - x2 * sinb, x2 * cosb + x1 * sinb], axis=-1).astype(x.dtype)
    if r < d:
        return jnp.concatenate([rot, x[..., r:]], axis=-1)
    return rot


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (train-short-test-long paper / Bloom
    ``build_alibi_tensor``): pure powers of two for power-of-2 head counts,
    the standard interleave otherwise."""

    def pow2_slopes(n):
        start = 2.0**(-(2.0**-(math.log2(n) - 3)))
        return [start * (start**i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return np.asarray(pow2_slopes(n_heads), np.float32)
    closest = 2**int(math.floor(math.log2(n_heads)))
    out = pow2_slopes(closest)
    extra = pow2_slopes(2 * closest)[0::2][:n_heads - closest]
    return np.asarray(out + extra, np.float32)


def reference_attention(q, k, v, causal=True, segment_ids=None, window=None, alibi=None):
    """jnp einsum attention — the numerics baseline every Pallas kernel is
    tested against (mirrors reference tests/unit/ops strategy). ``window``:
    sliding-window attention (Mistral) — query at position i sees keys in
    (i - window, i]. ``alibi``: per-head slopes [nq]; adds
    ``slope * (k_pos - q_pos)`` to the scores (Bloom)."""
    B, S, nq, d = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    qf = q.astype(jnp.float32) / math.sqrt(d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    qf = qf.reshape(B, S, nkv, group, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", qf, kf)
    if alibi is not None:
        rel = (jnp.arange(S, dtype=jnp.float32)[None, :] - jnp.arange(S, dtype=jnp.float32)[:, None])
        scores = scores + jnp.asarray(alibi, jnp.float32).reshape(nkv, group)[:, :, None, None] * rel[None, None]
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        if window is not None:
            mask = jnp.logical_and(mask, ~jnp.tril(jnp.ones((S, S), bool), k=-int(window)))
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = jnp.where(seg_mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bkgst,btkd->bskgd", probs, vf)
    return ctx.reshape(B, S, nq, d).astype(q.dtype)


def mlp_activation(cfg: TransformerConfig, up, gate=None):
    """Shared MLP nonlinearity (swiglu/gelu/relu — relu for OPT-era models)."""
    if cfg.mlp == "swiglu":
        return jax.nn.silu(gate) * up
    if cfg.mlp == "relu":
        return jax.nn.relu(up)
    return jax.nn.gelu(up)


_SPARSE_LAYOUT_CACHE = {}


def _sparse_attention(cfg: TransformerConfig, q, k, v):
    """Block-sparse training attention, configured by the ds_config
    ``sparse_attention`` block (reference ``SparseSelfAttention`` training
    path). The layout/LUT is a host-side trace-time constant cached per
    (config, heads, S); causality follows the layout's ``attention`` type
    (unidirectional layouts get the token-level causal mask in-kernel)."""
    B, S, nq, d = q.shape
    assert k.shape[2] == nq, "MHA enforced at config time (TransformerConfig.__post_init__)"
    key = (repr(sorted(cfg.sparse_attention.items())), nq, S)
    if key not in _SPARSE_LAYOUT_CACHE:
        from ..ops.sparse_attention import build_sparsity_config, make_layout_lut

        sc = build_sparsity_config(cfg.sparse_attention, nq)
        layout = sc.make_layout(S)
        causal = getattr(sc, "attention", "bidirectional") == "unidirectional"
        if not causal:
            from ..utils.logging import warning_once

            warning_once("sparse_attention layout is BIDIRECTIONAL: next-token training would "
                         "see future tokens. Set attention='unidirectional' in the sparsity "
                         "config unless this is an encoder-style objective.")
        _SPARSE_LAYOUT_CACHE[key] = (sc.block, causal, layout) + make_layout_lut(layout)
    block, causal, layout, lut, nvalid = _SPARSE_LAYOUT_CACHE[key]
    from ..ops.pallas.block_sparse_attention import block_sparse_attention

    ctx = block_sparse_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                                 v.transpose(0, 2, 1, 3), layout, block, causal=causal,
                                 lut=lut, nvalid=nvalid)
    return ctx.transpose(0, 2, 1, 3)


def _attention(cfg: TransformerConfig, q, k, v):
    if cfg.sparse_attention is not None:
        return _sparse_attention(cfg, q, k, v)
    impl = cfg.attention_impl
    if impl == "auto":
        try:
            import jax

            impl = "flash" if jax.default_backend() == "tpu" else "reference"
        except Exception:
            impl = "reference"
    alibi = cfg.positions == "alibi"
    if impl == "flash":
        from ..ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True, window=cfg.sliding_window, alibi=alibi)
    return reference_attention(q, k, v, causal=True, window=cfg.sliding_window,
                               alibi=alibi_slopes(cfg.num_heads) if alibi else None)


def _qwz_target_specs(cfg: TransformerConfig, layer):
    """ZeRO++ qwZ: the per-layer compute layout each big weight is gathered
    into (``layer`` holds per-layer slices — the stacked dim is already
    gone). 1-D vectors and expert-parallel weights are skipped; the spec
    derivation itself is shared with overlap_comm (``_layer_gather_spec``)."""
    rules = partition_rules(cfg)
    out = {}
    for k, v in layer.items():
        if np.ndim(v) < 2:
            continue
        spec = _layer_gather_spec(rules, k, np.ndim(v))
        if spec is not None:
            out[k] = spec
    return out


def _layer_gather_spec(rules: PartitionRules, key: str, per_layer_ndim: int):
    """Gathered compute layout for ONE stacked-blocks leaf: its TP spec with
    the stacked-L/pipe dim dropped — replicated over the ZeRO data axes,
    still sharded over 'model'. Returns None when the spec's data axes are
    expert parallelism (MoE expert weights), not a ZeRO shard to gather.
    Shared by the qwZ and overlap_comm planes so their layouts cannot
    drift from ``partition_rules`` or from each other."""
    full = rules.spec_for(f"blocks/{key}", per_layer_ndim + 1)
    entries = list(full)[1:]  # drop the stacked-L/pipe dim
    flat = [a for e in entries if e is not None
            for a in (e if isinstance(e, (tuple, list)) else (e, ))]
    return None if DATA_AXIS in flat else P(*entries)


def _zero3_gather_specs(cfg: TransformerConfig, blocks):
    """Per-leaf gathered layouts for the explicit overlap_comm schedule
    (stacked [L, ...] input; None entries are left unconstrained)."""
    rules = partition_rules(cfg)
    return {k: _layer_gather_spec(rules, k, np.ndim(v) - 1) for k, v in blocks.items()}


def _qwz_layer_view(cfg: TransformerConfig, layer):
    """Route the stage-3 per-layer weight gathers through int8
    (ops/pallas/quant.quantized_gather_ste)."""
    from ..parallel import groups
    from ..ops.pallas.quant import quantized_gather_ste
    from ..utils.logging import logger

    if not groups.is_initialized():
        return layer
    mesh = groups.get_mesh()
    out = dict(layer)
    for k, spec in _qwz_target_specs(cfg, layer).items():
        try:
            out[k] = quantized_gather_ste(out[k], spec, mesh)
        except (ValueError, jax.errors.JaxRuntimeError, RuntimeError) as e:
            # e.g. manual mesh axes inside shard_map: keep the plain view,
            # but say so — a silent fp32 fallback would defeat the flag
            logger.warning(f"qwZ: falling back to unquantized gather for blocks/{k}: {e}")
    return out


def _attn_branch(cfg: TransformerConfig, layer, h, sin, cos):
    """Attention sub-block on pre-normed input ``h`` [B, S, H]."""
    dt = cfg.dtype
    B, S, H = h.shape
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    q = jnp.einsum("bsh,hd->bsd", h, layer["wq"].astype(dt))
    k = jnp.einsum("bsh,hd->bsd", h, layer["wk"].astype(dt))
    v = jnp.einsum("bsh,hd->bsd", h, layer["wv"].astype(dt))
    if cfg.qkv_bias_enabled:
        q = q + layer["bq"].astype(dt)
        k = k + layer["bk"].astype(dt)
        v = v + layer["bv"].astype(dt)
    q = q.reshape(B, S, nq, d)
    k = k.reshape(B, S, nkv, d)
    v = v.reshape(B, S, nkv, d)
    if cfg.positions == "rotary":
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)

    if cfg.sequence_parallel:
        if cfg.sequence_parallel_impl == "ring":
            if cfg.sliding_window is not None:
                raise NotImplementedError(
                    "sliding_window + ring attention is not supported yet; use "
                    "sequence_parallel_impl='ulysses' (its local attention honors the window)")
            if cfg.positions == "alibi":
                raise NotImplementedError("alibi + ring attention is not supported yet; use ulysses")
            if cfg.sparse_attention is not None:
                raise NotImplementedError("sparse_attention + ring attention is not supported; "
                                          "use sequence_parallel_impl='ulysses' (its local "
                                          "attention routes through the sparse kernel)")
            from ..parallel import groups
            from ..parallel.mesh import mesh_axis_size
            from ..sequence.ring import ring_attention_gspmd

            # degrade to plain attention when no mesh registry is live (same
            # graceful behavior as ulysses' sharding constraints outside a mesh)
            if groups.is_initialized() and mesh_axis_size(groups.get_mesh(), SEQ_AXIS) > 1:
                ctx = ring_attention_gspmd(q, k, v, groups.get_mesh(), causal=True)
            else:
                ctx = _attention(cfg, q, k, v)
        else:
            if cfg.positions == "alibi":
                # ulysses shards the SEQUENCE dim around local attention: the
                # local attention sees global positions only via rope tables;
                # alibi's relative bias would use local indices — wrong
                raise NotImplementedError("alibi + ulysses sequence parallel is not supported yet")
            from ..sequence.layer import ulysses_attention_gspmd

            ctx = ulysses_attention_gspmd(partial(_attention, cfg), q, k, v)
    else:
        ctx = _attention(cfg, q, k, v)
    ctx = ctx.reshape(B, S, nq * d)
    # named for remat_policy="save_only_these_names(attn_out)": saving the
    # attention context keeps the flash kernel out of the backward recompute
    # while everything else (cheap elementwise + refusable matmuls) remats
    from jax.ad_checkpoint import checkpoint_name

    ctx = checkpoint_name(ctx, "attn_out")
    attn_out = jnp.einsum("bsd,dh->bsh", ctx, layer["wo"].astype(dt))
    if cfg.use_bias:
        attn_out = attn_out + layer["bo"].astype(dt)
    return attn_out


def _mlp_branch(cfg: TransformerConfig, layer, h, rng=None, constrain=True):
    """MLP (dense or MoE) sub-block on pre-normed input ``h``. Returns
    (out, moe_aux_loss)."""
    dt = cfg.dtype
    if cfg.moe_num_experts > 0:
        return _moe_mlp(cfg, layer, h, rng, constrain=constrain)
    up = jnp.einsum("bsh,hf->bsf", h, layer["w_up"].astype(dt))
    if cfg.use_bias:
        up = up + layer["b_up"].astype(dt)
    if cfg.mlp == "swiglu":
        gate = jnp.einsum("bsh,hf->bsf", h, layer["w_gate"].astype(dt))
        act = mlp_activation(cfg, up, gate)
    else:
        act = mlp_activation(cfg, up)
    down = jnp.einsum("bsf,fh->bsh", act, layer["w_down"].astype(dt))
    if cfg.use_bias:
        down = down + layer["b_down"].astype(dt)
    return down, jnp.zeros([], jnp.float32)


def _block(cfg: TransformerConfig, x, layer, sin, cos, rng=None, constrain=True):
    """One transformer block; ``layer`` holds this layer's slice of the
    stacked arrays. Returns (x, moe_aux_loss). ``constrain=False`` disables
    GSPMD sharding constraints (for use inside shard_map pipeline stages).
    ``parallel_residual`` (GPT-J/NeoX/Falcon): attention and MLP both read
    the block input and add jointly; ``shared_ln`` reuses ln1 for the MLP."""
    if cfg.quantized_weights and constrain:
        layer = _qwz_layer_view(cfg, layer)
    h1 = _norm(x, layer["ln1_scale"], layer.get("ln1_bias"), cfg.norm, cfg.norm_eps)
    attn_out = _attn_branch(cfg, layer, h1, sin, cos)
    if cfg.parallel_residual:
        h2 = h1 if cfg.shared_ln else _norm(x, layer["ln2_scale"], layer.get("ln2_bias"),
                                            cfg.norm, cfg.norm_eps)
        mlp_out, l_aux = _mlp_branch(cfg, layer, h2, rng, constrain=constrain)
        x = x + attn_out + mlp_out
        return _activation_constraint(cfg, x, enabled=constrain), l_aux
    x = x + attn_out
    h2 = _norm(x, layer["ln2_scale"], layer.get("ln2_bias"), cfg.norm, cfg.norm_eps)
    mlp_out, l_aux = _mlp_branch(cfg, layer, h2, rng, constrain=constrain)
    x = x + mlp_out
    return _activation_constraint(cfg, x, enabled=constrain), l_aux


def _moe_mlp(cfg: TransformerConfig, layer, h, rng=None, constrain=True):
    """MoE FFN in GSPMD form: per-row top-k gating (moe/sharded_moe.py math),
    dispatch to [B, E, C, M] slots, flip the sharding from batch-over-data to
    experts-over-data (XLA lowers the constraint boundary to the dispatch
    all-to-all of the reference's ``_AllToAll``), expert FFN, flip back,
    combine."""
    from ..moe.sharded_moe import top1gating, top2gating, multiplicative_jitter

    dt = cfg.dtype
    B, S, H = h.shape
    E = cfg.moe_num_experts
    gate_in = h.astype(jnp.float32)
    if cfg.moe_noisy_gate_policy == "Jitter" and rng is not None:
        rng, jit_key = jax.random.split(rng)
        gate_in = multiplicative_jitter(gate_in, jit_key)
    logits = jnp.einsum("bsh,he->bse", gate_in, layer["gate_wg"].astype(jnp.float32))

    def gate_row(lg, key):
        if cfg.moe_top_k == 1:
            return top1gating(lg, cfg.moe_capacity_factor, cfg.moe_min_capacity,
                              noisy_gate_policy=cfg.moe_noisy_gate_policy, rng=key,
                              use_rts=key is not None)[:3]
        return top2gating(lg, cfg.moe_capacity_factor, cfg.moe_min_capacity, rng=key)[:3]

    if rng is not None:
        keys = jax.random.split(rng, B)
        l_aux, combine, dispatch = jax.vmap(gate_row)(logits, keys)
    else:
        l_aux, combine, dispatch = jax.vmap(lambda lg: gate_row(lg, None))(logits)

    if cfg.moe_impl == "grouped":
        # grouped ragged-matmul path: FFN work scales with routed tokens
        # (B*S*k + alignment), not B*S*E*C. Kept set and gate weights come
        # from the SAME capacity gating above, so numerics match the einsum
        # path. Global sort/scatter makes this the single-shard choice; the
        # einsum path remains the EP/GSPMD default.
        from ..moe.grouped import grouped_moe_ffn

        w_se = combine.sum(axis=3).reshape(B * S, E).astype(dt)  # [B*S, E]
        y = grouped_moe_ffn(
            h.reshape(B * S, H), w_se, layer["moe_wi"], layer["moe_wo"],
            top_k=cfg.moe_top_k, wg=layer.get("moe_wg") if cfg.mlp == "swiglu" else None,
            activation=lambda up, gate: mlp_activation(cfg, up, gate))
        return y.reshape(B, S, H), jnp.mean(l_aux)

    dispatched = jnp.einsum("bsec,bsm->becm", dispatch.astype(dt), h)
    if constrain:
        try:
            dispatched = lax.with_sharding_constraint(dispatched, P(None, DATA_AXIS, None, None))
        except (ValueError, jax.errors.JaxRuntimeError, RuntimeError, NameError):
            pass
    up = jnp.einsum("becm,emf->becf", dispatched, layer["moe_wi"].astype(dt))
    gate = jnp.einsum("becm,emf->becf", dispatched, layer["moe_wg"].astype(dt)) if cfg.mlp == "swiglu" else None
    hmid = mlp_activation(cfg, up, gate)
    expert_out = jnp.einsum("becf,efm->becm", hmid, layer["moe_wo"].astype(dt))
    if constrain:
        try:
            expert_out = lax.with_sharding_constraint(expert_out, P(BATCH_AXES, None, None, None))
        except (ValueError, jax.errors.JaxRuntimeError, RuntimeError, NameError):
            pass
    out = jnp.einsum("bsec,becm->bsm", combine.astype(dt), expert_out)
    return out, jnp.mean(l_aux)


def _activation_constraint(cfg: TransformerConfig, x, enabled=True):
    """Pin activation layout [B, S, H]: batch over data, sequence over seq."""
    if not enabled:
        return x
    try:
        return lax.with_sharding_constraint(x, P(BATCH_AXES, SEQ_AXIS if cfg.sequence_parallel else None, None))
    except (ValueError, jax.errors.JaxRuntimeError, RuntimeError, NameError):
        return x


def _remat_policy(name: str):
    """Resolve a remat policy name. Supports every ``jax.checkpoint_policies``
    attribute plus ``"save_only_these_names(a,b,...)"`` for checkpoint_name-
    tagged values (e.g. ``attn_out``)."""
    if name.startswith("save_only_these_names(") and name.endswith(")"):
        names = [n.strip() for n in name[len("save_only_these_names("):-1].split(",") if n.strip()]
        return jax.checkpoint_policies.save_only_these_names(*names)
    policy = getattr(jax.checkpoint_policies, name, None)
    if policy is None:
        raise ValueError(f"unknown remat_policy {name!r}: expected an attribute of "
                         f"jax.checkpoint_policies or 'save_only_these_names(a,b,...)'")
    return policy


def forward_hidden(cfg: TransformerConfig, params: Dict[str, Any], input_ids: jax.Array, rng=None,
                   pld_theta=None):
    """Token ids [B, S] → (final-norm hidden [B, S, H], moe_aux_loss).
    Split from :func:`forward_with_aux` so the chunked-CE long-context path
    can unembed sequence chunks without materializing [B, S, V] logits.

    ``pld_theta``: progressive layer dropping (reference
    ``runtime/progressive_layer_drop.py``) — traced keep-rate scalar;
    requires ``rng``. Each layer is wrapped in ``lax.cond`` so dropped
    layers are genuinely skipped at runtime (the training-time saving)."""
    dt = cfg.dtype
    B, S = input_ids.shape
    x = params["embed"]["embedding"].astype(dt)[input_ids]
    if cfg.positions == "learned":
        x = x + params["pos_embed"]["embedding"].astype(dt)[:S][None]
    if cfg.embed_layernorm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg.norm, cfg.norm_eps)
    x = _activation_constraint(cfg, x)

    positions = jnp.arange(S)
    sin, cos = rope_table(cfg, positions) if cfg.positions == "rotary" else (None, None)

    block_fn = partial(_block, cfg)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn, policy=_remat_policy(cfg.remat_policy),
                                  static_argnums=())

    pld_keep = None
    if pld_theta is not None:
        assert rng is not None, "progressive layer drop needs an rng"
        from ..runtime.progressive_layer_drop import layer_keep_probs

        rng, pld_rng = jax.random.split(rng)
        pld_keep = jax.random.bernoulli(pld_rng, layer_keep_probs(cfg.num_layers, pld_theta))

    use_layer_keys = cfg.moe_num_experts > 0 and rng is not None
    layer_keys = jax.random.split(rng, cfg.num_layers) if use_layer_keys else None

    # Explicit overlap_comm schedule (ZeRO-3): double-buffer the gathered
    # next-layer params in the scan carry. Layer l+1's all-gather (a
    # resharding constraint, routed through comm.zero3_params_allgather so
    # the trace bus / in-flight table see it) is issued BEFORE layer l's
    # compute in program order — the explicit analog of the reference's
    # overlap_comm side stream. Values are untouched (same slices, same
    # math), so the loss is bit-identical to the implicit path. PLD drops
    # layers at runtime (prefetching a dropped layer's params would waste
    # the gather) and qwZ owns its own quantized gather — both keep the
    # plain scan.
    if cfg.overlap_gather and pld_keep is None and not cfg.quantized_weights:
        from ..parallel import groups as _groups

        mesh = _groups.get_mesh() if _groups.is_initialized() else None
        specs = _zero3_gather_specs(cfg, params["blocks"]) if mesh is not None else None
        from ..comm.comm import zero3_params_allgather

        blocks = params["blocks"]
        L = cfg.num_layers

        def fetch(i):
            layer = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), blocks)
            return zero3_params_allgather(layer, specs=specs, mesh=mesh)

        def overlap_body(carry, xs):
            x, cur = carry
            if use_layer_keys:
                i, key = xs
            else:
                i, key = xs, None
            # last iteration: no next layer — reuse cur instead of issuing a
            # redundant gather whose result the scan would discard
            nxt = lax.cond(i + 1 < L, lambda: fetch(jnp.minimum(i + 1, L - 1)), lambda: cur)
            y, aux = block_fn(x, cur, sin, cos, key)
            return (y, nxt), jnp.asarray(aux, jnp.float32)

        idx = jnp.arange(L, dtype=jnp.int32)
        xs = (idx, layer_keys) if use_layer_keys else idx
        (x, _), l_auxs = lax.scan(overlap_body, (x, fetch(jnp.int32(0))), xs)
        x = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"),
                  cfg.norm, cfg.norm_eps)
        return x, jnp.sum(l_auxs)

    xs_list = [params["blocks"]]
    if use_layer_keys:
        xs_list.append(layer_keys)
    if pld_keep is not None:
        xs_list.append(pld_keep)

    def scan_body(carry, xs):
        items = list(xs) if isinstance(xs, tuple) else [xs]
        layer = items.pop(0)
        key = items.pop(0) if use_layer_keys else None
        if pld_keep is None:
            return block_fn(carry, layer, sin, cos, key)
        keep = items.pop(0)

        def run(x):
            y, aux = block_fn(x, layer, sin, cos, key)
            return y, jnp.asarray(aux, jnp.float32)

        def skip(x):
            return x, jnp.zeros((), jnp.float32)

        return lax.cond(keep, run, skip, carry)

    x, l_auxs = lax.scan(scan_body, x, tuple(xs_list) if len(xs_list) > 1 else xs_list[0])
    x = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    return x, jnp.sum(l_auxs)


def _unembed(cfg: TransformerConfig, params, x):
    """Final hidden [..., H] → vocabulary logits [..., V] in fp32."""
    dt = cfg.dtype
    if cfg.tie_embeddings:
        logits = jnp.einsum("...h,vh->...v", x, params["embed"]["embedding"].astype(dt))
    else:
        logits = jnp.einsum("...h,hv->...v", x, params["lm_head"]["kernel"].astype(dt))
        if "bias" in params["lm_head"]:  # GPT-J style biased unembedding
            logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
    return logits.astype(jnp.float32)


def forward_with_aux(cfg: TransformerConfig, params: Dict[str, Any], input_ids: jax.Array, rng=None,
                     pld_theta=None):
    """Token ids [B, S] → (logits [B, S, V], moe_aux_loss)."""
    x, moe_aux = forward_hidden(cfg, params, input_ids, rng, pld_theta=pld_theta)
    return _unembed(cfg, params, x), moe_aux


def forward(cfg: TransformerConfig, params: Dict[str, Any], input_ids: jax.Array) -> jax.Array:
    """Token ids [B, S] → logits [B, S, V]."""
    return forward_with_aux(cfg, params, input_ids)[0]


# ---------------------------------------------------------------------------
# KV-cache inference path (v1 inference engine; reference
# ``ops/transformer/inference`` fused qkv+rotary+kv-append+softmax_context)
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: TransformerConfig, batch_size: int, max_len: int, dtype=None):
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch_size, max_len, cfg.num_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype), "length": jnp.zeros([], jnp.int32)}


def _cached_attention(cfg, q, ck, cv, q_pos0, cache_len_total):
    """q: [B, T, nq, d] at absolute positions q_pos0..q_pos0+T-1; ck/cv:
    [B, Smax, nkv, d] (positions < cache_len_total are valid).

    On TPU with kernel-friendly shapes the dense cache is viewed as a paged
    pool with an identity block table and handed to the fused paged-attention
    decode kernel (the v1 analog of the reference's fused softmax_context,
    ``csrc/transformer/inference/csrc/softmax.cu``) — one kernel per step
    instead of the materialized [B, nq, T, Smax] score tensor."""
    B, T, nq, d = q.shape
    Smax = ck.shape[1]
    nkv = ck.shape[2]
    group = nq // nkv
    if _use_fused_decode(cfg, nq, d, Smax):
        from ..ops.pallas.paged_attention import paged_attention

        bs = 128
        nb = Smax // bs
        kp = ck.reshape(B * Smax, nkv, d)
        vp = cv.reshape(B * Smax, nkv, d)
        tables = (jnp.arange(B, dtype=jnp.int32)[:, None] * nb
                  + jnp.arange(nb, dtype=jnp.int32)[None, :])
        seq_idx = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)
        pos = jnp.tile(q_pos0 + jnp.arange(T, dtype=jnp.int32), B)
        slopes = alibi_slopes(nq) if cfg.positions == "alibi" else None
        ctx = paged_attention(q.reshape(B * T, nq, d), kp, vp, tables, seq_idx, pos, bs,
                              window=cfg.sliding_window, alibi=slopes)
        return ctx.reshape(B, T, nq * d).astype(q.dtype)
    qf = q.astype(jnp.float32).reshape(B, T, nkv, group, d) / math.sqrt(d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qf, ck.astype(jnp.float32))
    k_pos = jnp.arange(Smax)[None, None, None, None, :]
    q_pos = (q_pos0 + jnp.arange(T))[None, None, None, :, None]
    if cfg.positions == "alibi":
        slopes = jnp.asarray(alibi_slopes(nq), jnp.float32).reshape(nkv, group)
        scores = scores + slopes[None, :, :, None, None] * (k_pos - q_pos).astype(jnp.float32)
    mask = (k_pos <= q_pos) & (k_pos < cache_len_total)
    if cfg.sliding_window is not None:
        mask = mask & (q_pos - k_pos < cfg.sliding_window)
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bkgts,bskd->btkgd", probs, cv.astype(jnp.float32))
    return ctx.reshape(B, T, nq * d).astype(q.dtype)


def _use_fused_decode(cfg, nq, d, Smax) -> bool:
    """Engage the paged decode kernel for the dense v1 cache: TPU backend,
    MXU-friendly shapes, and no tensor parallelism (a pallas call on
    model-sharded pools would make XLA replicate them)."""
    if cfg.attention_impl == "reference":
        return False
    try:
        import jax as _jax

        if _jax.default_backend() != "tpu":
            return False
        from ..parallel import groups
        from ..parallel.mesh import MODEL_AXIS, mesh_axis_size

        if groups.is_initialized() and mesh_axis_size(groups.get_mesh(), MODEL_AXIS) > 1:
            return False
    except Exception:
        return False
    return nq >= 8 and d % 128 == 0 and Smax % 128 == 0


def forward_with_cache(cfg: TransformerConfig, params, input_ids, cache):
    """Prefill/decode step: consumes tokens at positions [len, len+T), appends
    their k/v into the cache and returns (logits [B, T, V], new_cache)."""
    if cfg.sparse_attention is not None:
        # serving a sparse-trained model with dense cached attention would
        # silently use a distribution the model never saw — reject loudly
        # (same policy as the other unsupported combinations)
        raise NotImplementedError("sparse_attention serving is not implemented: the KV-cache "
                                  "decode applies dense attention; unset sparse_attention "
                                  "for inference")
    dt = cfg.dtype
    B, T = input_ids.shape
    start = cache["length"]
    x = params["embed"]["embedding"].astype(dt)[input_ids]
    if cfg.positions == "learned":
        pos_table = params["pos_embed"]["embedding"].astype(dt)
        x = x + jax.lax.dynamic_slice_in_dim(pos_table, start, T, axis=0)[None]
    if cfg.embed_layernorm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg.norm, cfg.norm_eps)
    positions = start + jnp.arange(T)
    sin, cos = rope_table(cfg, positions) if cfg.positions == "rotary" else (None, None)
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def scan_body(carry, layer_and_cache):
        x = carry
        layer, ck, cv = layer_and_cache
        h1 = _norm(x, layer["ln1_scale"], layer.get("ln1_bias"), cfg.norm, cfg.norm_eps)
        q = jnp.einsum("bsh,hd->bsd", h1, layer["wq"].astype(dt))
        k = jnp.einsum("bsh,hd->bsd", h1, layer["wk"].astype(dt))
        v = jnp.einsum("bsh,hd->bsd", h1, layer["wv"].astype(dt))
        if cfg.qkv_bias_enabled:
            q, k, v = q + layer["bq"].astype(dt), k + layer["bk"].astype(dt), v + layer["bv"].astype(dt)
        q = q.reshape(B, T, nq, d)
        k = k.reshape(B, T, nkv, d)
        v = v.reshape(B, T, nkv, d)
        if cfg.positions == "rotary":
            q = apply_rope(q, sin, cos)
            k = apply_rope(k, sin, cos)
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k.astype(ck.dtype), start, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v.astype(cv.dtype), start, axis=1)
        ctx = _cached_attention(cfg, q, ck, cv, start, start + T)
        attn_out = jnp.einsum("bsd,dh->bsh", ctx, layer["wo"].astype(dt)) + \
            (layer["bo"].astype(dt) if cfg.use_bias else 0.0)

        def mlp(h):
            # deterministic gating at inference (rng=None)
            return _mlp_branch(cfg, layer, h, rng=None)[0]

        if cfg.parallel_residual:
            h2 = h1 if cfg.shared_ln else _norm(x, layer["ln2_scale"], layer.get("ln2_bias"),
                                                cfg.norm, cfg.norm_eps)
            return x + attn_out + mlp(h2), (ck, cv)
        x = x + attn_out
        h2 = _norm(x, layer["ln2_scale"], layer.get("ln2_bias"), cfg.norm, cfg.norm_eps)
        return x + mlp(h2), (ck, cv)

    x, (new_k, new_v) = lax.scan(scan_body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsh,vh->bsv", x, params["embed"]["embedding"].astype(dt))
    else:
        logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"]["kernel"].astype(dt))
        if "bias" in params["lm_head"]:
            logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
    new_cache = {"k": new_k, "v": new_v, "length": start + T}
    return logits.astype(jnp.float32), new_cache


def _ce_aux(batch, input_ids):
    """Normalize a batch into the CE aux dict consumed by ``_ce_loss``."""
    aux = {}
    if isinstance(batch, dict) and "labels" in batch:
        aux["labels"] = batch["labels"]
    else:
        aux["shift_ids"] = input_ids
    if isinstance(batch, dict) and "loss_mask" in batch:
        aux["loss_mask"] = batch["loss_mask"]
    return aux


def _ce_loss(logits, aux, use_onehot=False):
    """Next-token cross entropy. ``aux``: {'labels'} or {'shift_ids'} plus
    optional 'loss_mask'. ``use_onehot`` contracts against a one-hot instead
    of gathering: the gather op makes XLA's SPMD partitioner CHECK-fail when
    the vocab dim is sharded over an auto axis inside a manual-subset
    shard_map (the 1F1B pipeline); the einsum partitions cleanly (the vocab
    sum lowers to a psum over 'model')."""
    if "labels" in aux:
        shift_logits, labels = logits, aux["labels"]
    else:
        shift_logits, labels = logits[..., :-1, :], aux["shift_ids"][..., 1:]
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    if use_onehot:
        onehot = (labels[..., None] == jnp.arange(logp.shape[-1])).astype(logp.dtype)
        token_ll = jnp.einsum("...v,...v->...", logp, onehot)
    else:
        token_ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if "loss_mask" in aux:
        mask = aux["loss_mask"][..., :token_ll.shape[-1]].astype(jnp.float32)
        return -(token_ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return -token_ll.mean()


def _stage_scan_fn(cfg: TransformerConfig, with_aux: bool = False):
    """One pipeline stage: scan this stage's contiguous layer slice (shared
    by the GPipe and 1F1B executors so the schedules cannot diverge).
    ``with_aux`` (MoE models): also return the stage's summed load-balancing
    loss so the executors can thread it into the total loss."""
    if cfg.moe_num_experts > 0 and cfg.moe_noisy_gate_policy:
        # the stage fn runs gating with rng=None, which would silently turn
        # Jitter/RSample off — pipeline and serial runs would optimize
        # different objectives (same stance as PLD+pipeline, engine.py)
        raise NotImplementedError(
            f"moe_noisy_gate_policy={cfg.moe_noisy_gate_policy!r} does not compose with "
            "pipeline parallelism yet (stage executors run gating without an rng); "
            "disable the noisy gate or run without the pipe axis")

    def stage_fn(blocks_local, xb, sin, cos):
        def body(carry, layer):
            y, aux = _block(cfg, carry, layer, sin, cos, None, constrain=False)
            return y, jnp.asarray(aux, jnp.float32)

        y, auxs = lax.scan(body, xb, blocks_local)
        if with_aux:
            return y, jnp.sum(auxs)
        return y

    return stage_fn


def _chunked_ce_loss(cfg: TransformerConfig, params, h, aux, chunk: int):
    """Sequence-chunked next-token CE over final hidden ``h`` [B, S, H].

    Each chunk's logits are computed inside ``jax.checkpoint``, so neither
    forward nor backward ever holds more than one [B, chunk, V] logits
    slice — the memory that caps long-context training. Numerically
    identical to ``_ce_loss`` (same masked-mean semantics)."""
    if "labels" in aux:
        h_eff, labels = h, aux["labels"]
    else:
        h_eff, labels = h[:, :-1], aux["shift_ids"][..., 1:]
    B, Sp, H = h_eff.shape
    mask = aux.get("loss_mask")
    mask = jnp.ones((B, Sp), jnp.float32) if mask is None else \
        mask[..., :Sp].astype(jnp.float32)
    pad = (-Sp) % chunk
    if pad:
        h_eff = jnp.pad(h_eff, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (Sp + pad) // chunk
    hc = h_eff.reshape(B, n, chunk, H).transpose(1, 0, 2, 3)
    lc = labels.reshape(B, n, chunk).transpose(1, 0, 2)
    mc = mask.reshape(B, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_fn(h_c, l_c, m_c):
        logp = jax.nn.log_softmax(_unembed(cfg, params, h_c), axis=-1)
        ll = jnp.take_along_axis(logp, l_c[..., None], axis=-1)[..., 0]
        return (ll * m_c).sum()

    def scan_body(tot, xs):
        return tot + chunk_fn(*xs), None

    total_ll, _ = lax.scan(scan_body, jnp.float32(0.0), (hc, lc, mc))
    return -total_ll / jnp.maximum(mask.sum(), 1.0)


def loss_fn(cfg: TransformerConfig, params, batch, rng=None):
    """Next-token cross entropy (+ MoE aux loss). ``batch``: dict with
    'input_ids' [B, S] and optional 'labels' (defaults to shifted input) and
    'loss_mask'. ``cfg.loss_chunk`` routes through the sequence-chunked CE
    (logits never fully materialized)."""
    input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
    aux_d = _ce_aux(batch, input_ids)
    pld_theta = batch.get("pld_theta") if isinstance(batch, dict) else None
    if cfg.loss_chunk and input_ids.shape[1] > cfg.loss_chunk:
        h, moe_aux = forward_hidden(cfg, params, input_ids, rng, pld_theta=pld_theta)
        ce = _chunked_ce_loss(cfg, params, h, aux_d, int(cfg.loss_chunk))
    else:
        logits, moe_aux = forward_with_aux(cfg, params, input_ids, rng, pld_theta=pld_theta)
        ce = _ce_loss(logits, aux_d)
    aux = cfg.moe_aux_loss_coef * moe_aux if cfg.moe_num_experts > 0 else 0.0
    return ce + aux


def pipeline_loss_fn(cfg: TransformerConfig, params, batches, rng=None, *, mesh, num_stages: int):
    """Pipelined loss over microbatches [M, b, S] (runtime/pipe/spmd.py).

    Embedding and head run replicated over the pipe axis; the L blocks are
    split into ``num_stages`` contiguous slices (blocks dim 0 is sharded over
    'pipe' — see partition_rules) and executed in a compiled fill/drain loop
    with ppermute handoffs. jax.grad through this function generates the
    backward pipeline automatically.
    """
    from ..runtime.pipe.spmd import pipeline_apply

    ids = batches["input_ids"] if isinstance(batches, dict) else batches
    M, B, S = ids.shape
    dt = cfg.dtype
    assert cfg.num_layers % num_stages == 0, (
        f"num_layers {cfg.num_layers} must divide evenly into {num_stages} pipeline stages")
    moe = cfg.moe_num_experts > 0

    x = params["embed"]["embedding"].astype(dt)[ids]  # [M, B, S, H]
    if cfg.positions == "learned":
        x = x + params["pos_embed"]["embedding"].astype(dt)[:S][None, None]
    sin, cos = rope_table(cfg, jnp.arange(S)) if cfg.positions == "rotary" else (
        jnp.zeros((S, 1)), jnp.zeros((S, 1)))

    outs = pipeline_apply(_stage_scan_fn(cfg, with_aux=moe), params["blocks"], x, sin, cos,
                          mesh=mesh, num_stages=num_stages,
                          remat=True, with_aux=moe)  # [M, B, S, H]
    moe_aux = jnp.zeros([], jnp.float32)
    if moe:
        outs, aux_total = outs
        moe_aux = cfg.moe_aux_loss_coef * aux_total / M  # mean over microbatches
    h = _norm(outs, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("mbsh,vh->mbsv", h, params["embed"]["embedding"].astype(dt))
    else:
        logits = jnp.einsum("mbsh,hv->mbsv", h, params["lm_head"]["kernel"].astype(dt))
    logits = logits.astype(jnp.float32)
    if isinstance(batches, dict) and "labels" in batches:
        shift_logits, labels = logits, batches["labels"]
    else:
        shift_logits, labels = logits[:, :, :-1], ids[:, :, 1:]
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    token_ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if isinstance(batches, dict) and "loss_mask" in batches:
        # per-microbatch masked mean, then mean over microbatches — identical
        # weighting to the non-pipeline path (loss_fn averaged over gas), so
        # enabling pipe does not change the training objective
        mask = batches["loss_mask"][:, :, :token_ll.shape[2]].astype(jnp.float32)
        per_mb = -(token_ll * mask).sum(axis=(1, 2)) / jnp.maximum(mask.sum(axis=(1, 2)), 1.0)
        return per_mb.mean() + moe_aux
    return -token_ll.mean() + moe_aux


def pipeline_loss_fn_1f1b(cfg: TransformerConfig, params, batches, rng=None, *, mesh, num_stages: int):
    """1F1B pipelined loss over microbatches [M, b, S] (runtime/pipe/spmd.py
    ``pipeline_1f1b`` — the reference ``TrainSchedule`` schedule.py:189
    compiled into one program).

    Because 1F1B interleaves backward work into the forward loop, gradients
    are produced by the pipeline itself; a ``custom_vjp`` hands them to
    ``jax.grad`` so the engine's ``jax.grad(scaled_loss)`` contract is
    unchanged. The embedding runs outside the pipeline (GSPMD) and its VJP is
    chained through the pipeline's d(injected activations); the loss head
    (final norm + LM head + CE) runs inside at the last stage, per tick.
    """
    from ..runtime.pipe.spmd import pipeline_1f1b

    ids = batches["input_ids"] if isinstance(batches, dict) else batches
    M, B, S = ids.shape
    dt = cfg.dtype
    assert cfg.num_layers % num_stages == 0, (
        f"num_layers {cfg.num_layers} must divide evenly into {num_stages} pipeline stages")
    moe = cfg.moe_num_experts > 0

    sin, cos = rope_table(cfg, jnp.arange(S)) if cfg.positions == "rotary" else (
        jnp.zeros((S, 1)), jnp.zeros((S, 1)))

    head_keys = ["final_norm"] + (["embed"] if cfg.tie_embeddings else ["lm_head"])
    aux = _ce_aux(batches, ids)

    def head_fn(hp, y, aux_mb):
        h = _norm(y, hp["final_norm"]["scale"], hp["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsh,vh->bsv", h, hp["embed"]["embedding"].astype(dt))
        else:
            logits = jnp.einsum("bsh,hv->bsv", h, hp["lm_head"]["kernel"].astype(dt))
        return _ce_loss(logits.astype(jnp.float32), aux_mb, use_onehot=True)

    def embed_fn(p):
        x = p["embed"]["embedding"].astype(dt)[ids]
        if cfg.positions == "learned":
            x = x + p["pos_embed"]["embedding"].astype(dt)[:S][None, None]
        return x

    def _loss_and_grads(params):
        xs, embed_vjp = jax.vjp(embed_fn, params)
        head_params = {k: params[k] for k in head_keys}
        loss, g_blocks, g_head, d_xs = pipeline_1f1b(
            _stage_scan_fn(cfg, with_aux=moe), head_fn, params["blocks"], head_params, xs, aux,
            sin, cos, mesh=mesh, num_stages=num_stages,
            with_aux=moe, aux_weight=cfg.moe_aux_loss_coef)
        (grads, ) = embed_vjp(d_xs)  # full-tree cotangent (embedding only)
        grads = dict(grads)
        grads["blocks"] = g_blocks
        for k in head_keys:  # tied embeddings: head grads add to embed grads
            grads[k] = jax.tree_util.tree_map(jnp.add, grads[k], g_head[k])
        return loss, grads

    @jax.custom_vjp
    def run(params):
        return _loss_and_grads(params)[0]

    def run_fwd(params):
        loss, grads = _loss_and_grads(params)
        return loss, grads

    def run_bwd(grads, g):
        return (jax.tree_util.tree_map(lambda x: x * g, grads), )

    run.defvjp(run_fwd, run_bwd)
    return run(params)


class TransformerLM:
    """Model object consumed by ``deepspeed_tpu.initialize``: bundles config,
    init, loss and TP partition rules (the engine's model protocol)."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def init(self, rng, example_batch=None):
        return init_params(self.config, rng)

    def apply(self, params, input_ids):
        return forward(self.config, params, input_ids)

    def loss(self, params, batch, rng=None):
        return loss_fn(self.config, params, batch, rng)

    def pipeline_loss(self, params, batches, rng=None, *, mesh, num_stages, schedule="1f1b"):
        if schedule == "1f1b":
            return pipeline_loss_fn_1f1b(self.config, params, batches, rng, mesh=mesh, num_stages=num_stages)
        return pipeline_loss_fn(self.config, params, batches, rng, mesh=mesh, num_stages=num_stages)

    def partition_rules(self):
        return partition_rules(self.config)

    def num_params(self, params=None):
        if params is None:
            params = jax.eval_shape(lambda r: init_params(self.config, r), jax.random.PRNGKey(0))
        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
