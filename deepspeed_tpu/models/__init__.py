from .transformer import TransformerConfig, TransformerLM, reference_attention
from .llama import llama2, llama2_config
from .gpt import gpt2, gpt2_config
from .mistral import mistral, mistral_config
from .phi import phi, phi_config
from .qwen import qwen2, qwen2_config
from .opt import opt, opt_config
from .bloom import bloom, bloom_config
from .gptj import gptj, gptj_config
from .gpt_neox import gpt_neox, gpt_neox_config
from .falcon import falcon, falcon_config
