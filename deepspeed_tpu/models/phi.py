"""Phi model family configs (phi-1.5 / phi-2).

Reference inventory: the v2 model catalog grew phi containers alongside
falcon/mistral (``inference/v2/model_implementations/``). Architecture:
GPT-J-shaped — parallel residual with a SINGLE shared pre-norm, partial
rotary (``rotary_dim``), LayerNorm, gelu MLP, biases everywhere.
"""

from .transformer import TransformerConfig, TransformerLM


def phi_config(size: str = "2", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=32000, hidden_size=256, num_layers=4, num_heads=8,
                     intermediate_size=1024, max_seq_len=2048, rotary_dim=16),
        "1.5": dict(vocab_size=51200, hidden_size=2048, num_layers=24, num_heads=32,
                    intermediate_size=8192, max_seq_len=2048, rotary_dim=32),
        "2": dict(vocab_size=51200, hidden_size=2560, num_layers=32, num_heads=32,
                  intermediate_size=10240, max_seq_len=2048, rotary_dim=32),
    }
    base = dict(presets[size], norm="layernorm", positions="rotary", mlp="gelu",
                use_bias=True, parallel_residual=True, shared_ln=True,
                tie_embeddings=False, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def phi(size: str = "2", **overrides) -> TransformerLM:
    return TransformerLM(phi_config(size, **overrides))
