"""Mistral model family configs.

Analog of the reference ``inference/v2/model_implementations/mistral/``
(policy+containers): Llama-shaped (RMSNorm + rotary + SwiGLU) with GQA(8 kv
heads), a 32k context, and sliding-window attention (window=4096 for 7B) —
wired through the training flash kernel, the v1 KV-cache path, and the v2
paged kernel via ``TransformerConfig.sliding_window``.
"""

from .transformer import TransformerConfig, TransformerLM


def mistral_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=32000, hidden_size=256, num_layers=4, num_heads=8, num_kv_heads=2,
                     intermediate_size=896, max_seq_len=2048, sliding_window=256),
        "7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8,
                   intermediate_size=14336, max_seq_len=32768, sliding_window=4096),
    }
    base = dict(presets[size], norm="rmsnorm", positions="rotary", mlp="swiglu", use_bias=False,
                tie_embeddings=False, rope_theta=10000.0, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def mistral(size: str = "7b", **overrides) -> TransformerLM:
    return TransformerLM(mistral_config(size, **overrides))
