"""GPT-J model family configs.

Analog of the reference ``module_inject/containers/gptj.py``: parallel
attention+MLP residual with a SINGLE pre-norm (shared_ln), partial rotary
(rotary_dim=64), GELU, no attention biases (the converter zero-fills them),
untied lm_head with bias. HF's interleaved rotary is handled by permuting
the q/k projection columns at conversion time (half-style equivalence).
"""

from .transformer import TransformerConfig, TransformerLM


def gptj_config(size: str = "6b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=512,
                     rotary_dim=16),
        "6b": dict(vocab_size=50400, hidden_size=4096, num_layers=28, num_heads=16, max_seq_len=2048,
                   rotary_dim=64),
    }
    base = dict(presets[size], norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
                intermediate_size=4 * presets[size]["hidden_size"], tie_embeddings=False,
                parallel_residual=True, shared_ln=True, norm_eps=1e-5)
    base.update(overrides)
    return TransformerConfig(**base)


def gptj(size: str = "6b", **overrides) -> TransformerLM:
    return TransformerLM(gptj_config(size, **overrides))
