"""Ring attention — blockwise context parallelism over the ``seq`` mesh axis.

The reference fork's long-context answer is Ulysses all-to-all
(``deepspeed/sequence/layer.py``; SURVEY.md §2.2 notes ring/blockwise variants
are absent there and that a ring implementation is the TPU-idiomatic
addition). Ring attention removes Ulysses' head-count ceiling: sequence
parallel degree can exceed the number of KV heads because the sequence stays
sharded end-to-end and only K/V blocks rotate around the ring.

Design (TPU-first):
  - Each device in the ``seq`` axis holds a contiguous shard of the sequence
    [B, S/P, n, d].  K and V shards rotate ring-wise with ``lax.ppermute``
    (neighbor hops = pure ICI traffic, bandwidth-optimal like the
    reference's NCCL p2p pipeline but compiler-scheduled).
  - Attention is accumulated with a streaming (online) softmax across ring
    steps — the cross-device generalization of the flash-attention update,
    so per-device memory is O(S/P · d), never O(S²).
  - The whole loop is a ``lax.scan`` body inside ``shard_map``: one compiled
    program, XLA overlaps the ppermute for step i+1 with the matmuls of step
    i (double-buffered by construction: the permute result is only consumed
    next iteration).
  - Differentiable by construction (scan + ppermute transpose natively);
    ``jax.checkpoint`` on the step body keeps backward memory at one ring
    step's activations.

Usage: inside ``shard_map`` over a mesh with a ``seq`` axis, or via
``ring_attention_gspmd`` which wraps the shard_map for you on sharded global
arrays.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax: experimental API; rep-checking there rejects
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map

    shard_map = _functools.partial(_shard_map, check_rep=False)

from ..parallel.mesh import SEQ_AXIS, BATCH_AXES, MODEL_AXIS


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, causal: bool = True, axis_size: Optional[int] = None,
                   remat: bool = True):
    """Ring attention on per-device shards (call inside ``shard_map``).

    q/k/v: [B, S_local, n_heads, head_dim] — the local sequence shard.
    GQA allowed (k/v may have fewer heads; n_q % n_kv == 0).
    Returns the attention output in the same [B, S_local, n_q, d] layout.
    """
    B, S_loc, nq, d = q.shape
    nkv = k.shape[2]
    assert nq % nkv == 0, f"GQA head mismatch: {nq} % {nkv}"
    g = nq // nkv
    if axis_size is None:
        axis_size = lax.psum(1, axis_name)  # static under shard_map
    P_sz = axis_size
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(d)

    # GQA stays folded as a grouped einsum — only the raw nkv-head K/V rotate
    # around the ring, so ICI traffic and carry memory are not inflated by the
    # group factor. qt: [B, nkv, g, S_loc, d]; kt/vt: [B, nkv, S_loc, d].
    qt = (q.transpose(0, 2, 1, 3) * scale).astype(jnp.float32).reshape(B, nkv, g, S_loc, d)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    perm = [(j, (j + 1) % P_sz) for j in range(P_sz)]

    def accumulate(k_cur, v_cur, acc, m, l, i):
        # after i hops, we hold the chunk originally at rank (my_idx - i) % P
        src = (my_idx - i) % P_sz
        s = jnp.einsum("bngqd,bnkd->bngqk", qt, k_cur.astype(jnp.float32))
        if causal:
            q_pos = my_idx * S_loc + lax.broadcasted_iota(jnp.int32, (S_loc, S_loc), 0)
            k_pos = src * S_loc + lax.broadcasted_iota(jnp.int32, (S_loc, S_loc), 1)
            s = jnp.where((q_pos >= k_pos)[None, None, None], s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bngqk,bnkd->bngqd", p, v_cur.astype(jnp.float32))
        return acc, m_new, l

    if remat:
        accumulate = jax.checkpoint(accumulate)

    def step(carry, i):
        k_cur, v_cur, acc, m, l = carry
        acc, m, l = accumulate(k_cur, v_cur, acc, m, l, i)
        # rotate KV to the next rank; consumed only next iteration so XLA can
        # overlap the ICI transfer with this step's matmuls
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, m, l), None

    # derive from qt so the carries inherit qt's varying manual axes under
    # shard_map (a plain jnp.zeros would be device-invariant and trip scan's
    # carry type check)
    acc0 = jnp.zeros_like(qt)
    m0 = jnp.zeros_like(qt[..., :1]) - 1e30
    l0 = jnp.zeros_like(qt[..., :1])
    # P-1 rotate-and-accumulate steps in a scan, then the last chunk's
    # accumulate outside it — the final ppermute would be dead traffic.
    (kt, vt, acc, m, l), _ = lax.scan(step, (kt, vt, acc0, m0, l0), jnp.arange(P_sz - 1))
    acc, _, l = accumulate(kt, vt, acc, m, l, P_sz - 1)
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, nq, S_loc, d).astype(q.dtype).transpose(0, 2, 1, 3)


class RingAttention:
    """Drop-in alternative to ``DistributedAttention`` (Ulysses) with no
    head-count ceiling on the sequence-parallel degree.

    Unlike Ulysses this ignores the wrapped local attention's internals — the
    blockwise computation *is* the attention — so it takes no
    ``local_attention`` argument; signature otherwise mirrors
    ``sequence.layer.DistributedAttention``.
    """

    def __init__(self, sequence_process_group: str = SEQ_AXIS, causal: bool = True):
        self.spg = sequence_process_group
        self.causal = causal

    def __call__(self, query, key, value, axis_size: Optional[int] = None):
        return ring_attention(query, key, value, axis_name=self.spg, causal=self.causal, axis_size=axis_size)


def ring_attention_gspmd(q, k, v, mesh, causal: bool = True, seq_axis: str = SEQ_AXIS,
                         batch_axes=BATCH_AXES, model_axis: str = MODEL_AXIS):
    """Ring attention on *global* arrays sharded over ``mesh``.

    q/k/v: [B, S, n, d] with B sharded over ``batch_axes``, S over
    ``seq_axis``, heads over ``model_axis`` (TP). Wraps the per-shard kernel
    in ``shard_map``; everything composes with an outer ``jit``.
    """
    spec = P(batch_axes, seq_axis, model_axis, None)
    P_sz = mesh.shape.get(seq_axis, 1)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal, axis_size=P_sz),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
