from .layer import DistributedAttention, single_all_to_all, ulysses_attention_gspmd
from .ring import RingAttention, ring_attention, ring_attention_gspmd
