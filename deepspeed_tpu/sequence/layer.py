"""DeepSpeed-Ulysses sequence parallelism, TPU-native.

Analog of the reference ``deepspeed/sequence/layer.py`` (113 LoC):
``single_all_to_all:15`` / ``_SeqAllToAll:44`` / ``DistributedAttention:60``.
The decomposition is identical — all-to-all(scatter heads, gather sequence)
before local attention, all-to-all(scatter sequence, gather heads) after — but
on TPU it exists in two equivalent forms:

1. **GSPMD form** (``ulysses_attention_gspmd``): inside plain ``jit`` we only
   annotate shardings — activations arrive sharded over the ``seq`` axis
   [B, S/sp, H]; constraining q/k/v to head-sharded [B, S, n/sp, d] makes XLA
   insert exactly the all-to-all the reference issues by hand. This is the
   production path: the collective rides ICI and overlaps with the qkv matmul.

2. **shard_map form** (``DistributedAttention``): explicit
   ``lax.all_to_all`` over the ``seq`` mesh axis, for use inside
   ``shard_map``-style code and for tests that check the collective layout.

Parity bar (SURVEY.md §5 long-context): same a2a decomposition, per-link
communication volume O(S·H/P) independent of sequence parallel degree.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import SEQ_AXIS, BATCH_AXES


def single_all_to_all(x, scatter_idx: int, gather_idx: int, axis_name: str = SEQ_AXIS):
    """Reference ``sequence/layer.py:15`` — tiled all-to-all moving shards from
    dim ``gather_idx`` (gathered) to dim ``scatter_idx`` (scattered)."""
    return lax.all_to_all(x, axis_name, split_axis=scatter_idx, concat_axis=gather_idx, tiled=True)


class _SeqAllToAll:
    """Functional stand-in for the reference autograd.Function (:44); JAX
    differentiates ``lax.all_to_all`` natively so no custom VJP is needed."""

    @staticmethod
    def apply(group, x, scatter_idx, gather_idx):
        return single_all_to_all(x, scatter_idx, gather_idx, axis_name=group)


class DistributedAttention:
    """Reference ``sequence/layer.py:60`` — wraps any local attention.

    Expects q/k/v of shape [B, S/sp, n_heads, head_dim] (sequence sharded);
    runs the wrapped attention on [B, S, n_heads/sp, head_dim] (heads
    sharded); returns [B, S/sp, n_heads, head_dim].

    Use inside ``shard_map`` over a mesh containing ``sequence_process_group``
    as an axis name.
    """

    def __init__(self,
                 local_attention: Callable,
                 sequence_process_group: str = SEQ_AXIS,
                 scatter_idx: int = 2,
                 gather_idx: int = 1):
        self.local_attn = local_attention
        self.spg = sequence_process_group
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query, key, value, *args, **kwargs):
        q = _SeqAllToAll.apply(self.spg, query, self.scatter_idx, self.gather_idx)
        k = _SeqAllToAll.apply(self.spg, key, self.scatter_idx, self.gather_idx)
        v = _SeqAllToAll.apply(self.spg, value, self.scatter_idx, self.gather_idx)
        ctx = self.local_attn(q, k, v, *args, **kwargs)
        # scatter back along sequence, gather heads
        return _SeqAllToAll.apply(self.spg, ctx, self.gather_idx, self.scatter_idx)


def ulysses_qkv_constraint(x, mesh=None, batch_axes=BATCH_AXES, seq_axis=SEQ_AXIS):
    """GSPMD head-sharding constraint for q/k/v [B, S, n, d]: puts the seq
    mesh axis on the head dim, triggering XLA's all-to-all."""
    spec = P(tuple(batch_axes), None, seq_axis, None)
    return lax.with_sharding_constraint(x, spec if mesh is None else jax.NamedSharding(mesh, spec))


def ulysses_output_constraint(x, mesh=None, batch_axes=BATCH_AXES, seq_axis=SEQ_AXIS):
    """GSPMD constraint restoring sequence sharding on attention output."""
    spec = P(tuple(batch_axes), seq_axis, None, None)
    return lax.with_sharding_constraint(x, spec if mesh is None else jax.NamedSharding(mesh, spec))


def ulysses_attention_gspmd(attn_fn: Callable,
                            query,
                            key,
                            value,
                            *args,
                            batch_axes=BATCH_AXES,
                            seq_axis: str = SEQ_AXIS,
                            **kwargs):
    """GSPMD-form Ulysses: sharding constraints around ``attn_fn``.

    q/k/v: [B, S, n_heads, head_dim] global shapes, activations sharded
    (B over data axes, S over seq axis). XLA lowers the two constraint
    boundaries to the pair of all-to-alls of the reference implementation.
    """
    q = ulysses_qkv_constraint(query, batch_axes=batch_axes, seq_axis=seq_axis)
    k = ulysses_qkv_constraint(key, batch_axes=batch_axes, seq_axis=seq_axis)
    v = ulysses_qkv_constraint(value, batch_axes=batch_axes, seq_axis=seq_axis)
    ctx = attn_fn(q, k, v, *args, **kwargs)
    return ulysses_output_constraint(ctx, batch_axes=batch_axes, seq_axis=seq_axis)
