"""deepspeed_tpu.comm — the torch.distributed-compatible API surface.

Analog of the reference ``deepspeed/comm/comm.py`` (contract stated at lines
13-19: mirror torch.distributed signatures). Two planes:

  * Host plane (this module): ``init_distributed`` (reference :604),
    ``get_rank``/``get_world_size`` (:530-564), ``barrier`` (:405) —
    process-level bootstrap and control, backed by ``XlaBackend``.
  * Traced plane (``comm.functional`` re-exported here): ``all_reduce``,
    ``all_gather``, ``reduce_scatter``, ``all_to_all_single`` etc. that compile
    into step programs over mesh axes.

The global backend handle is ``cdb`` — same name as reference ``comm.py:41``.
"""

import inspect
import os
import threading
import time
import functools

from .backend import XlaBackend
from . import functional as _functional
from .functional import ReduceOp, axis_index, axis_size  # noqa: F401 — pure helpers, no comm payload
from ..monitor.trace import get_tracer
from ..runtime.resilience import chaos
from ..utils.logging import logger, log_dist
from ..utils.comms_logging import CommsLogger, calc_bw_log

cdb = None
comms_logger = CommsLogger()
timers = None


class CommException(Exception):
    pass


# ---------------------------------------------------------------------------
# instrumentation: real message sizes, wall times, trace spans
# ---------------------------------------------------------------------------
def _leaf_nbytes(x):
    """Bytes carried by one pytree leaf: concrete arrays via ``nbytes``,
    tracers via their aval shape/dtype, non-tensor leaves count zero."""
    import numpy as np

    nb = getattr(x, "nbytes", None)
    if isinstance(nb, (int, np.integer)):
        return int(nb)
    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape") and hasattr(aval, "dtype"):
        try:
            return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
        except Exception:
            return 0
    return 0


def _msg_bytes(args, kwargs):
    """Pytree-aware payload size: the nbytes sum over every tensor leaf in
    the call (the reference sizes ``tensor.element_size() * tensor.nelement()``;
    here a collective may carry a whole tree)."""
    import jax

    return sum(_leaf_nbytes(l) for l in jax.tree_util.tree_leaves((args, kwargs)))


def _has_tracer(args, kwargs):
    import jax

    return any(isinstance(l, jax.core.Tracer) for l in jax.tree_util.tree_leaves((args, kwargs)))


def _group_degree(group):
    """Participant count of a collective over ``group`` — the ``n`` in the
    algbw/busbw formulas. Mesh-axis groups use the axis extent (devices);
    rank-list groups their length; fallback is the process world size."""
    try:
        from ..parallel import groups as pgroups

        if pgroups.is_initialized():
            mesh = pgroups.get_mesh()
            if group is None:
                return max(1, mesh.size)
            names = group if isinstance(group, (list, tuple)) else (group, )
            if all(isinstance(a, str) and a in mesh.shape for a in names):
                d = 1
                for a in names:
                    d *= mesh.shape[a]
                return max(1, d)
    except Exception:
        pass
    if isinstance(group, (list, tuple)) and group and all(isinstance(r, int) for r in group):
        return len(group)
    if cdb is not None:
        return max(1, cdb.get_world_size())
    return 1


def _block_on(result):
    """Drain async dispatch so the wall time covers the transfer, giving the
    same 'device work up to here is done' point CUDA events give the
    reference's timed_op."""
    try:
        import jax

        jax.block_until_ready(result)
    except Exception:
        pass
    return result


class _InflightCollectives:
    """Registry of collectives currently executing on this host — the table
    the health plane (``monitor/health.py``) dumps when a run wedges: a hung
    all-reduce is invisible from outside the process, but THIS table names
    the op, its payload size, how long it has been in flight, and which
    thread sits in it. Fed by ``@timed_op`` (device collectives) and the
    host-plane gather/broadcast helpers. Disabled by default: one attribute
    check per call, no locking, no allocations — the health config block
    flips ``enabled`` and installs the ``on_enter``/``on_exit`` heartbeat
    hooks (the ``collective`` stall-watchdog source)."""

    __slots__ = ("enabled", "on_enter", "on_exit", "_lock", "_entries", "_next")

    def __init__(self):
        self.enabled = False
        self.on_enter = None  # health hook: begin("collective")
        self.on_exit = None  # health hook: end("collective")
        self._lock = threading.Lock()
        self._entries = {}
        self._next = 0

    def enter(self, op, msg_size=0):
        """Register an in-flight collective; returns the token for exit()."""
        with self._lock:
            token = self._next
            self._next += 1
            self._entries[token] = {"op": op, "msg_size": int(msg_size),
                                    "t0": time.perf_counter(),
                                    "thread": threading.current_thread().name}
        cb = self.on_enter
        if cb is not None:
            cb()
        return token

    def exit(self, token):
        with self._lock:
            self._entries.pop(token, None)
        cb = self.on_exit
        if cb is not None:
            cb()

    def snapshot(self):
        """Ordered view of the table: ``[{op, msg_size, age_s, thread}]``,
        oldest first."""
        now = time.perf_counter()
        with self._lock:
            entries = sorted(self._entries.items())
        return [{"op": e["op"], "msg_size": e["msg_size"],
                 "age_s": round(now - e["t0"], 4), "thread": e["thread"]}
                for _, e in entries]

    def __len__(self):
        return len(self._entries)


inflight_collectives = _InflightCollectives()


def timed_op(func):
    """Reference ``comm.py:101`` @timed_op — wall-times collectives with REAL
    payload bytes (pytree nbytes sum, not the old hardcoded 0).

    Three regimes:
      * profiling off (default): straight call — zero overhead;
      * under jit (tracer args): the collective compiles into the step
        program, so host wall time is meaningless — record an instant trace
        event carrying the traced payload size;
      * eager concrete call: wall-time around a ``block_until_ready`` and
        feed latency + bytes through ``calc_bw_log`` (comms logger + a
        ``comm/<op>`` trace span with algo/bus bandwidth).
    """
    name = func.__name__
    try:
        sig = inspect.signature(func)
        group_default = sig.parameters["group"].default if "group" in sig.parameters else None
    except (TypeError, ValueError):
        sig, group_default = None, None

    def _call_group(args, kwargs):
        """The group actually in effect — positional, keyword or default."""
        if sig is not None:
            try:
                return sig.bind(*args, **kwargs).arguments.get("group", group_default)
            except TypeError:
                pass
        return kwargs.get("group", group_default)

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        tracer = get_tracer()
        watch = inflight_collectives
        prof = comms_logger.enabled and (comms_logger.prof_all or name in comms_logger.prof_ops)
        timing = prof or tracer.enabled
        chaotic = chaos.armed("comm/collective")
        if not (timing or watch.enabled or chaotic):
            return func(*args, **kwargs)
        if _has_tracer(args, kwargs):
            # under jit the call only records into the step program: nothing
            # can block here, so it is neither timed nor held in flight (and
            # a chaos delay/kill here would poison the compile, not the
            # transfer — the chaos bracket covers CONCRETE calls only)
            if tracer.enabled:
                tracer.instant(f"comm/{name}", tid="comm",
                               msg_size=_msg_bytes(args, kwargs), traced=True)
            return func(*args, **kwargs)
        msg_size = _msg_bytes(args, kwargs)
        chaos.fire("comm/collective", {"op": name})
        token = watch.enter(name, msg_size) if watch.enabled else None
        try:
            if not timing:
                # watch-only mode (health plane armed, profiling off): the
                # in-flight entry brackets the call with NO forced device
                # sync — eager dispatch keeps its async perf profile
                return func(*args, **kwargs)
            n = _group_degree(_call_group(args, kwargs))
            _eager_state["compiled"] = False
            t0 = time.perf_counter()
            result = _block_on(func(*args, **kwargs))
            duration = time.perf_counter() - t0
            compiled = _eager_state["compiled"]
            if prof and not compiled:
                # a call that just compiled its eager executable is not a
                # steady-state sample — keep it out of the bandwidth stats
                comms_logger.append(name, name, duration, msg_size, n=n)
            if tracer.enabled:
                algbw, busbw, _ = calc_bw_log(name, msg_size, duration, n=n)
                span_args = {"msg_size": msg_size, "algbw_gbps": round(algbw, 4),
                             "busbw_gbps": round(busbw, 4), "n": n}
                if compiled:
                    span_args["compiled"] = True  # disclosed, excluded from stats
                tracer.complete(f"comm/{name}", t0, duration, tid="comm", args=span_args)
            return result
        finally:
            if token is not None:
                watch.exit(token)

    return wrapper


# eager-executable subset: replicated-operand semantics are well defined for
# these (the result every participant agrees on); all_to_all and the ring/p2p
# ops have inherently per-participant results and stay jit-only
_EAGER_OK = frozenset({
    "all_reduce", "inference_all_reduce", "all_gather", "reduce_scatter", "broadcast"
})

# signal from _eagerize to timed_op: the call it just serviced compiled a new
# executable, so its wall time is NOT a steady-state comm sample
_eager_state = {"compiled": False}
_EAGER_CACHE_MAX = 64  # per-op bound; entries pin their mesh + executable


def _eager_out_spec(name, axes, bound_args):
    from jax.sharding import PartitionSpec as P

    if name == "reduce_scatter":
        dim = bound_args.get("scatter_dimension", 0)
        return P(*([None] * dim + [tuple(axes) if len(axes) > 1 else axes[0]]))
    return P()


def _eagerize(func):
    """Let a traced-plane collective run with CONCRETE arrays outside jit:
    the call is wrapped in a one-off ``shard_map`` over the current mesh
    (operands replicated), jitted, executed and cached by shape — the
    torch.distributed ergonomics, and what lets ``timed_op`` wall-time a real
    device collective (``bench.py --trace``'s comm spans). Inside jit, or
    with no mesh initialized, the call passes through untouched.

    Caveat: the FIRST eager call per (op, shape, dtype, group) includes the
    jit compile in its wall time — discard or warm past that sample when
    deriving steady-state bandwidth (bench.py does)."""
    name = func.__name__
    sig = inspect.signature(func)
    cache = {}

    tensor_param = next(iter(sig.parameters))  # the payload is always first

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        if name in _EAGER_OK and (args or kwargs) and not _has_tracer(args, kwargs):
            try:
                from ..parallel import groups as pgroups

                eligible = pgroups.is_initialized()
                rest = tensor_val = None
                if eligible:
                    try:
                        bound = sig.bind(*args, **kwargs)
                    except TypeError:
                        eligible = False  # malformed call: let func raise its own error
                if eligible:
                    bound.apply_defaults()
                    rest = dict(bound.arguments)
                    tensor_val = rest.pop(tensor_param, None)
                    group = rest.get("group")
                    mesh = pgroups.get_mesh()
                    axes = group if isinstance(group, (list, tuple)) else (group, )
                    eligible = tensor_val is not None and \
                        all(isinstance(a, str) and a in mesh.shape for a in axes)
                if eligible:
                    import jax
                    import jax.numpy as jnp
                    from jax.sharding import PartitionSpec as P

                    tensor = jnp.asarray(tensor_val)
                    key = (name, tensor.shape, str(tensor.dtype), tuple(axes),
                           repr(sorted((k, repr(v)) for k, v in rest.items())), id(mesh))
                    fn = cache.get(key)
                    if fn is None:
                        from ..parallel.mesh import shard_map_compat

                        out_spec = _eager_out_spec(name, tuple(axes), bound.arguments)
                        inner = lambda x, _rest=rest: func(x, **_rest)
                        fn = jax.jit(shard_map_compat(inner, mesh, P(), out_spec))
                        while len(cache) >= _EAGER_CACHE_MAX:  # FIFO bound:
                            cache.pop(next(iter(cache)))  # entries pin meshes
                        cache[key] = fn
                        _eager_state["compiled"] = True
                    with mesh:
                        return fn(tensor)
            except Exception as e:
                raise CommException(
                    f"eager {name} over mesh failed ({type(e).__name__}: {e}); call it inside "
                    "jit/shard_map over the target axis for full control") from e
        # in-jit, no mesh, or non-eagerable op: the traced plane as before
        return func(*args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# public traced-plane surface: EVERY collective rides @timed_op (the static
# check tools/check_timed_ops.py keeps this from rotting)
# ---------------------------------------------------------------------------
all_reduce = timed_op(_eagerize(_functional.all_reduce))
inference_all_reduce = timed_op(_eagerize(_functional.inference_all_reduce))
all_gather = timed_op(_eagerize(_functional.all_gather))
all_gather_into_tensor = all_gather  # alias parity with the functional plane
reduce_scatter = timed_op(_eagerize(_functional.reduce_scatter))
reduce_scatter_tensor = reduce_scatter
all_to_all_single = timed_op(_eagerize(_functional.all_to_all_single))
broadcast = timed_op(_eagerize(_functional.broadcast))
ppermute = timed_op(_functional.ppermute)
send_recv_next = timed_op(_functional.send_recv_next)
send_recv_prev = timed_op(_functional.send_recv_prev)
send = timed_op(_functional.send)
recv = timed_op(_functional.recv)


@timed_op
def zero3_params_allgather(params, specs=None, mesh=None, group=None):
    """Explicit ZeRO-3 per-layer parameter all-gather (the
    ``zero_optimization.overlap_comm`` schedule — ``models/transformer.py``
    issues layer *l+1*'s gather during layer *l*'s compute).

    In GSPMD form the gather IS a sharding constraint: each leaf is pinned to
    its gathered compute layout (TP spec with the ZeRO data axes dropped) and
    XLA lowers the boundary to the all-gather. Riding ``@timed_op`` puts the
    prefetch on the same observability surface as every other collective:
    under jit a ``comm/zero3_params_allgather`` instant (with real payload
    bytes) lands on the trace bus per compile, and eager executions bracket
    the PR 5 ``_InflightCollectives`` table / heartbeat hooks.

    ``specs``: dict leaf-name -> PartitionSpec (None entries skipped, e.g.
    expert-parallel weights whose data-axis sharding is EP, not ZeRO).
    No mesh/specs (CPU tests, no registry) -> identity.
    """
    if mesh is None or specs is None:
        return params
    import jax
    from jax.sharding import NamedSharding

    out = {}
    for k, v in params.items():
        s = specs.get(k)
        out[k] = v if s is None else jax.lax.with_sharding_constraint(v, NamedSharding(mesh, s))
    return out


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1):
    """Initialize the distributed runtime (reference ``comm.py:604``).

    On TPU this (a) optionally runs MPI/env rank discovery (reference
    :650-658 ``mpi_discovery``), (b) initializes ``jax.distributed`` when a
    coordinator is configured, and (c) installs the global ``cdb`` backend.
    Collectives themselves need no process groups — they compile into step
    programs over the mesh.
    """
    global cdb
    if cdb is not None and cdb.is_initialized():
        return cdb

    if auto_mpi_discovery and not _env_ranks_present() and _in_mpi_environment():
        mpi_discovery(distributed_port=distributed_port, verbose=verbose)

    cdb = XlaBackend(init_method=init_method, rank=rank, world_size=world_size)
    if verbose:
        log_dist(f"initialized comm backend '{dist_backend}' rank={cdb.get_rank()} "
                 f"world_size={cdb.get_world_size()}", ranks=[0])
    if config is not None:
        configure(config)
    return cdb


def _env_ranks_present():
    return all(v in os.environ for v in ("RANK", "WORLD_SIZE"))


def _in_mpi_environment():
    return any(v in os.environ for v in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"))


def mpi_discovery(distributed_port=29500, verbose=True):
    """Rank discovery from MPI/SLURM env (reference ``comm.py:673-771``)."""
    if "OMPI_COMM_WORLD_RANK" in os.environ:
        rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
        world_size = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        local_rank = int(os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", 0))
    elif "SLURM_PROCID" in os.environ:
        rank = int(os.environ["SLURM_PROCID"])
        world_size = int(os.environ.get("SLURM_NTASKS", 1))
        local_rank = int(os.environ.get("SLURM_LOCALID", 0))
    else:
        rank = int(os.environ.get("PMI_RANK", 0))
        world_size = int(os.environ.get("PMI_SIZE", 1))
        local_rank = 0
    os.environ.setdefault("RANK", str(rank))
    os.environ.setdefault("WORLD_SIZE", str(world_size))
    os.environ.setdefault("LOCAL_RANK", str(local_rank))
    from ..launcher.constants import ENV_COORDINATOR_ADDRESS

    if "MASTER_ADDR" in os.environ and ENV_COORDINATOR_ADDRESS not in os.environ:
        os.environ[ENV_COORDINATOR_ADDRESS] = f"{os.environ['MASTER_ADDR']}:{distributed_port}"
    if verbose:
        logger.info(f"mpi_discovery: rank={rank} world_size={world_size} local_rank={local_rank}")


def is_initialized():
    return cdb is not None and cdb.is_initialized()


def _ensure():
    global cdb
    if cdb is None:
        init_distributed()
    return cdb


def get_rank(group=None):
    return _ensure().get_rank()


def get_world_size(group=None):
    return _ensure().get_world_size()


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", 0))


@timed_op
def barrier(group=None):
    _ensure().barrier()


# goodput's exposed-comm feed: fn(op, seconds) set by monitor/goodput.py
# while the ledger is armed (None = one global read + branch per host op).
# The host-plane collectives already BLOCK the caller, so timing them here
# adds no sync the call wasn't paying.
goodput_comm_hook = None


def _watched_host_op(op, fn):
    """Host-plane collectives (key-value-store gather/broadcast) BLOCK the
    calling thread until every process arrives — they are the ops a dead
    peer wedges first (the step-boundary resilience vote rides
    ``all_gather_host``). Register them in the in-flight table while the
    health plane watches."""
    # chaos bracket: collective-delay/kill storms land on the host plane
    # here — these are the blocking ops a dead peer wedges first
    hook = goodput_comm_hook
    t0 = time.perf_counter() if hook is not None else 0.0
    try:
        chaos.fire("comm/host_collective", {"op": op})
        watch = inflight_collectives
        if not watch.enabled:
            return fn()
        token = watch.enter(op)
        try:
            return fn()
        finally:
            watch.exit(token)
    finally:
        if hook is not None:
            hook(op, time.perf_counter() - t0)


def broadcast_object_list(object_list, src=0, group=None):
    out = _watched_host_op("broadcast_object_list",
                           lambda: _ensure().broadcast_host(object_list, src=src))
    object_list[:] = list(out) if not isinstance(out, list) else out
    return object_list


def broadcast_host(value, src=0):
    return _watched_host_op("broadcast_host",
                            lambda: _ensure().broadcast_host(value, src=src))


def all_gather_host(value):
    return _watched_host_op("all_gather_host",
                            lambda: _ensure().all_gather_host(value))


def new_group(ranks=None):
    """Groups are mesh axes on TPU; host-plane subgroup creation is a no-op
    returning the rank list for API compatibility (reference ``comm.py:181``)."""
    return tuple(ranks) if ranks is not None else None


def destroy_process_group(group=None):
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None


def configure(config=None, deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    cfg = config or deepspeed_config
    if cfg is not None and getattr(cfg, "comms_config", None) is not None:
        comms_logger.configure(cfg.comms_config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def log_summary(show_straggler=False):
    """Print the comms profile (reference ``comm.py:422``)."""
    return comms_logger.log_all(print_log=(get_rank() == 0), show_straggler=show_straggler)


# ---------------------------------------------------------------------------
# reference comm.py surface parity — host-level introspection & environment
# ---------------------------------------------------------------------------
def is_available() -> bool:
    """Reference ``is_available``: the XLA backend ships with jax."""
    return True


def get_world_group():
    """Reference ``get_world_group``: None IS the world group in this API
    (every op treats group=None as all processes)."""
    return None


def get_global_rank(group=None, group_rank: int = 0) -> int:
    """Reference ``get_global_rank``: groups here are mesh-axis names whose
    members enumerate in world order, so a group-local rank maps through the
    group's rank list."""
    ranks = get_all_ranks_from_group(group)
    return ranks[group_rank]


def get_all_ranks_from_group(group=None):
    """Reference helper of the same name. For a mesh-axis-name group the
    ranks are DEVICE ids (one process owns many devices here): the group is
    the set of devices varying along that axis with this process's first
    addressable device's other coordinates held fixed — the device-level
    analog of "the subgroup containing my rank"."""
    if group is None:
        return list(range(get_world_size()))
    if isinstance(group, (list, tuple)) and all(isinstance(r, int) for r in group):
        return list(group)
    if isinstance(group, str):
        from ..parallel import groups as pgroups

        if pgroups.is_initialized():
            import jax
            import numpy as np

            mesh = pgroups.get_mesh()
            if group in mesh.axis_names:
                ids = np.vectorize(lambda d: d.id)(mesh.devices)
                ax = mesh.axis_names.index(group)
                my = jax.local_devices()[0].id
                pos = np.argwhere(ids == my)
                if pos.size:
                    idx = list(pos[0])
                    idx[ax] = slice(None)
                    return sorted(int(x) for x in np.ravel(ids[tuple(idx)]))
    return list(range(get_world_size()))


def monitored_barrier(group=None, timeout=None, wait_all_ranks: bool = False):
    """Reference ``monitored_barrier``: barrier + a log line (the jax
    coordination service already detects/reports stragglers by timeout)."""
    from ..utils.logging import logger

    t0 = time.time()
    barrier(group)
    dt = time.time() - t0
    if timeout is not None and dt > float(timeout):
        logger.warning(f"monitored_barrier took {dt:.1f}s (> {timeout})")
    return None


def set_backend(backend_name: str = "xla"):
    """Reference ``set_backend``: only the XLA backend exists here."""
    if backend_name not in ("xla", "hccl", "nccl", "ccl"):
        raise ValueError(f"unknown backend {backend_name!r}")
    return None


def init_deepspeed_backend(ds_backend=None, timeout=None, init_method=None, rank=-1, world_size=-1):
    """Reference ``init_deepspeed_backend``: folded into init_distributed."""
    return None


def in_aml() -> bool:
    """Azure ML env detection (reference comm.py)."""
    return "AZUREML_EXPERIMENT_ID" in os.environ


def in_aws_sm() -> bool:
    return "SM_TRAINING_ENV" in os.environ


def in_dlts() -> bool:
    return "DLTS_JOB_ID" in os.environ
