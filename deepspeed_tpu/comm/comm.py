"""deepspeed_tpu.comm — the torch.distributed-compatible API surface.

Analog of the reference ``deepspeed/comm/comm.py`` (contract stated at lines
13-19: mirror torch.distributed signatures). Two planes:

  * Host plane (this module): ``init_distributed`` (reference :604),
    ``get_rank``/``get_world_size`` (:530-564), ``barrier`` (:405) —
    process-level bootstrap and control, backed by ``XlaBackend``.
  * Traced plane (``comm.functional`` re-exported here): ``all_reduce``,
    ``all_gather``, ``reduce_scatter``, ``all_to_all_single`` etc. that compile
    into step programs over mesh axes.

The global backend handle is ``cdb`` — same name as reference ``comm.py:41``.
"""

import os
import time
import functools

from .backend import XlaBackend
from .functional import (  # noqa: F401 — traced-plane re-exports
    ReduceOp, all_reduce, inference_all_reduce, all_gather, all_gather_into_tensor, reduce_scatter,
    reduce_scatter_tensor, all_to_all_single, broadcast, ppermute, send_recv_next, send_recv_prev, axis_index,
    axis_size)
from ..utils.logging import logger, log_dist
from ..utils.comms_logging import CommsLogger

cdb = None
comms_logger = CommsLogger()
timers = None


class CommException(Exception):
    pass


def timed_op(func):
    """Reference ``comm.py:101`` @timed_op — wall-times host-plane ops."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        if comms_logger.enabled and (comms_logger.prof_all or func.__name__ in comms_logger.prof_ops):
            t0 = time.time()
            result = func(*args, **kwargs)
            comms_logger.append(func.__name__, func.__name__, time.time() - t0, 0)
            return result
        return func(*args, **kwargs)

    return wrapper


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1):
    """Initialize the distributed runtime (reference ``comm.py:604``).

    On TPU this (a) optionally runs MPI/env rank discovery (reference
    :650-658 ``mpi_discovery``), (b) initializes ``jax.distributed`` when a
    coordinator is configured, and (c) installs the global ``cdb`` backend.
    Collectives themselves need no process groups — they compile into step
    programs over the mesh.
    """
    global cdb
    if cdb is not None and cdb.is_initialized():
        return cdb

    if auto_mpi_discovery and not _env_ranks_present() and _in_mpi_environment():
        mpi_discovery(distributed_port=distributed_port, verbose=verbose)

    cdb = XlaBackend(init_method=init_method, rank=rank, world_size=world_size)
    if verbose:
        log_dist(f"initialized comm backend '{dist_backend}' rank={cdb.get_rank()} "
                 f"world_size={cdb.get_world_size()}", ranks=[0])
    if config is not None:
        configure(config)
    return cdb


def _env_ranks_present():
    return all(v in os.environ for v in ("RANK", "WORLD_SIZE"))


def _in_mpi_environment():
    return any(v in os.environ for v in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"))


def mpi_discovery(distributed_port=29500, verbose=True):
    """Rank discovery from MPI/SLURM env (reference ``comm.py:673-771``)."""
    if "OMPI_COMM_WORLD_RANK" in os.environ:
        rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
        world_size = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        local_rank = int(os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", 0))
    elif "SLURM_PROCID" in os.environ:
        rank = int(os.environ["SLURM_PROCID"])
        world_size = int(os.environ.get("SLURM_NTASKS", 1))
        local_rank = int(os.environ.get("SLURM_LOCALID", 0))
    else:
        rank = int(os.environ.get("PMI_RANK", 0))
        world_size = int(os.environ.get("PMI_SIZE", 1))
        local_rank = 0
    os.environ.setdefault("RANK", str(rank))
    os.environ.setdefault("WORLD_SIZE", str(world_size))
    os.environ.setdefault("LOCAL_RANK", str(local_rank))
    from ..launcher.constants import ENV_COORDINATOR_ADDRESS

    if "MASTER_ADDR" in os.environ and ENV_COORDINATOR_ADDRESS not in os.environ:
        os.environ[ENV_COORDINATOR_ADDRESS] = f"{os.environ['MASTER_ADDR']}:{distributed_port}"
    if verbose:
        logger.info(f"mpi_discovery: rank={rank} world_size={world_size} local_rank={local_rank}")


def is_initialized():
    return cdb is not None and cdb.is_initialized()


def _ensure():
    global cdb
    if cdb is None:
        init_distributed()
    return cdb


def get_rank(group=None):
    return _ensure().get_rank()


def get_world_size(group=None):
    return _ensure().get_world_size()


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", 0))


@timed_op
def barrier(group=None):
    _ensure().barrier()


def broadcast_object_list(object_list, src=0, group=None):
    out = _ensure().broadcast_host(object_list, src=src)
    object_list[:] = list(out) if not isinstance(out, list) else out
    return object_list


def broadcast_host(value, src=0):
    return _ensure().broadcast_host(value, src=src)


def all_gather_host(value):
    return _ensure().all_gather_host(value)


def new_group(ranks=None):
    """Groups are mesh axes on TPU; host-plane subgroup creation is a no-op
    returning the rank list for API compatibility (reference ``comm.py:181``)."""
    return tuple(ranks) if ranks is not None else None


def destroy_process_group(group=None):
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None


def configure(config=None, deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    cfg = config or deepspeed_config
    if cfg is not None and getattr(cfg, "comms_config", None) is not None:
        comms_logger.configure(cfg.comms_config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def log_summary(show_straggler=False):
    """Print the comms profile (reference ``comm.py:422``)."""
    return comms_logger.log_all(print_log=(get_rank() == 0), show_straggler=show_straggler)


# ---------------------------------------------------------------------------
# reference comm.py surface parity — host-level introspection & environment
# ---------------------------------------------------------------------------
def is_available() -> bool:
    """Reference ``is_available``: the XLA backend ships with jax."""
    return True


def get_world_group():
    """Reference ``get_world_group``: None IS the world group in this API
    (every op treats group=None as all processes)."""
    return None


def get_global_rank(group=None, group_rank: int = 0) -> int:
    """Reference ``get_global_rank``: groups here are mesh-axis names whose
    members enumerate in world order, so a group-local rank maps through the
    group's rank list."""
    ranks = get_all_ranks_from_group(group)
    return ranks[group_rank]


def get_all_ranks_from_group(group=None):
    """Reference helper of the same name. For a mesh-axis-name group the
    ranks are DEVICE ids (one process owns many devices here): the group is
    the set of devices varying along that axis with this process's first
    addressable device's other coordinates held fixed — the device-level
    analog of "the subgroup containing my rank"."""
    if group is None:
        return list(range(get_world_size()))
    if isinstance(group, (list, tuple)) and all(isinstance(r, int) for r in group):
        return list(group)
    if isinstance(group, str):
        from ..parallel import groups as pgroups

        if pgroups.is_initialized():
            import jax
            import numpy as np

            mesh = pgroups.get_mesh()
            if group in mesh.axis_names:
                ids = np.vectorize(lambda d: d.id)(mesh.devices)
                ax = mesh.axis_names.index(group)
                my = jax.local_devices()[0].id
                pos = np.argwhere(ids == my)
                if pos.size:
                    idx = list(pos[0])
                    idx[ax] = slice(None)
                    return sorted(int(x) for x in np.ravel(ids[tuple(idx)]))
    return list(range(get_world_size()))


def monitored_barrier(group=None, timeout=None, wait_all_ranks: bool = False):
    """Reference ``monitored_barrier``: barrier + a log line (the jax
    coordination service already detects/reports stragglers by timeout)."""
    from ..utils.logging import logger

    t0 = time.time()
    barrier(group)
    dt = time.time() - t0
    if timeout is not None and dt > float(timeout):
        logger.warning(f"monitored_barrier took {dt:.1f}s (> {timeout})")
    return None


def set_backend(backend_name: str = "xla"):
    """Reference ``set_backend``: only the XLA backend exists here."""
    if backend_name not in ("xla", "hccl", "nccl", "ccl"):
        raise ValueError(f"unknown backend {backend_name!r}")
    return None


def init_deepspeed_backend(ds_backend=None, timeout=None, init_method=None, rank=-1, world_size=-1):
    """Reference ``init_deepspeed_backend``: folded into init_distributed."""
    return None


def in_aml() -> bool:
    """Azure ML env detection (reference comm.py)."""
    return "AZUREML_EXPERIMENT_ID" in os.environ


def in_aws_sm() -> bool:
    return "SM_TRAINING_ENV" in os.environ


def in_dlts() -> bool:
    return "DLTS_JOB_ID" in os.environ
