"""In-program (traced) collectives.

The hot-path half of the comm backend (SURVEY.md §2.5 "TPU equivalent"): these
run *inside* ``jit``/``shard_map`` over mesh axis names and lower to XLA
collectives on ICI/DCN. They carry the same names as the reference
``deepspeed/comm/comm.py`` API (``all_reduce:482``, ``all_gather:227``,
``reduce_scatter_tensor:279``, ``all_to_all_single:330``…) so code reading the
reference maps 1:1, but the ``group=`` argument is a mesh axis name (or tuple
of names) rather than a torch process group.
"""

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = Union[str, Sequence[str]]


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


def _axis(group: AxisNames):
    if isinstance(group, (list, tuple)) and len(group) == 1:
        return group[0]
    return group


def all_reduce(tensor, op=ReduceOp.SUM, group: AxisNames = "data"):
    axis = _axis(group)
    if op == ReduceOp.SUM:
        return lax.psum(tensor, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(tensor, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(tensor), axis))
    raise ValueError(f"unsupported reduce op {op}")


def inference_all_reduce(tensor, group: AxisNames = "model"):
    """TP partial-sum combine on the inference path (reference comm.py:499)."""
    return lax.psum(tensor, _axis(group))


def all_gather(tensor, group: AxisNames = "data", axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis``. ``tiled=True`` concatenates (the
    ``all_gather_into_tensor`` layout); ``tiled=False`` stacks a new dim."""
    return lax.all_gather(tensor, _axis(group), axis=axis, tiled=tiled)


all_gather_into_tensor = all_gather


def reduce_scatter(tensor, op=ReduceOp.SUM, group: AxisNames = "data", scatter_dimension: int = 0):
    axis = _axis(group)
    out = lax.psum_scatter(tensor, axis, scatter_dimension=scatter_dimension, tiled=True)
    if op == ReduceOp.AVG:
        out = out / lax.psum(1, axis)
    return out


reduce_scatter_tensor = reduce_scatter


def all_to_all_single(tensor, group: AxisNames = "seq", split_axis: int = 0, concat_axis: int = 0):
    """Split along ``split_axis`` across the group and concat received chunks
    along ``concat_axis`` (reference comm.py:330). This is the Ulysses /
    MoE-dispatch primitive."""
    return lax.all_to_all(tensor, _axis(group), split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src_index: int = 0, group: AxisNames = "data"):
    """Broadcast the ``src_index`` shard to all members of the group."""
    axis = _axis(group)
    full = lax.all_gather(tensor, axis, axis=0, tiled=False)
    return jax.tree_util.tree_map(lambda x: x[src_index], full)


def ppermute(tensor, perm, group: AxisNames = "pipe"):
    """Neighbor exchange — the pipeline p2p primitive (reference
    ``runtime/pipe/p2p.py`` send/recv pairs become a single collective)."""
    return lax.ppermute(tensor, _axis(group), perm=perm)


def send_recv_next(tensor, group: AxisNames = "pipe", size: int = None):
    """Shift +1 along the ring: stage i's value arrives at stage i+1."""
    axis = _axis(group)
    n = size if size is not None else lax.psum(1, axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(tensor, axis, perm=perm)


def send_recv_prev(tensor, group: AxisNames = "pipe", size: int = None):
    axis = _axis(group)
    n = size if size is not None else lax.psum(1, axis)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return lax.ppermute(tensor, axis, perm=perm)


def axis_index(group: AxisNames):
    return lax.axis_index(_axis(group))


def axis_size(group: AxisNames):
    return lax.psum(1, _axis(group))


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group: AxisNames = "data"):
    """Rooted reduce (reference ``comm.py`` reduce): every member
    participates; only ``dst`` keeps the reduced value, others get zeros
    (SPMD has no rank-divergent returns — masking is the traced analog of
    'result only materializes on dst')."""
    red = all_reduce(tensor, op=op, group=group)
    keep = lax.axis_index(_axis(group)) == dst
    return jnp.where(keep, red, jnp.zeros_like(red))


def gather(tensor, dst: int = 0, group: AxisNames = "data", axis: int = 0):
    """Rooted gather: the concatenated result on ``dst``, zeros elsewhere."""
    full = all_gather(tensor, group=group, axis=axis, tiled=True)
    keep = lax.axis_index(_axis(group)) == dst
    return jnp.where(keep, full, jnp.zeros_like(full))


def scatter(tensor, src: int = 0, group: AxisNames = "data", axis: int = 0):
    """Rooted scatter: ``src``'s tensor is split along ``axis``; member i
    receives chunk i (reference comm.py scatter)."""
    ax = _axis(group)
    src_full = broadcast(tensor, src_index=src, group=group)
    n = lax.axis_size(ax)  # static at trace time: chunk shapes must be static
    assert tensor.shape[axis] % n == 0, (
        f"scatter: dim {axis} ({tensor.shape[axis]}) not divisible by group size {n} — "
        "the reference errors on unequal chunks rather than silently dropping the tail")
    chunk = tensor.shape[axis] // n
    idx = lax.axis_index(ax)
    return lax.dynamic_slice_in_dim(src_full, idx * chunk, chunk, axis=axis)


def send(tensor, dst: int, src: int = None, group: AxisNames = "pipe"):
    """Point-to-point transfer ``src`` → ``dst`` (reference p2p send/recv
    pairs). XLA has no one-sided p2p, so ALL group members trace this one
    collective and BOTH endpoints must be named — an SPMD program cannot
    infer "the calling rank" the way the reference's per-process send can.
    ``src`` defaults to the ring predecessor ``(dst-1) % n``; for pipeline
    schedules prefer ``send_recv_next``/``send_recv_prev``. Non-``dst``
    members receive zeros."""
    n = lax.axis_size(_axis(group))
    if src is None:
        src = (dst - 1) % n
    return lax.ppermute(tensor, _axis(group), perm=[(src % n, dst % n)])


def recv(tensor, src: int, dst: int = None, group: AxisNames = "pipe"):
    """The matching end of :func:`send` — the same single permutation,
    spelled from the receiver's side. ``dst`` defaults to the ring successor
    ``(src+1) % n``."""
    n = lax.axis_size(_axis(group))
    if dst is None:
        dst = (src + 1) % n
    return lax.ppermute(tensor, _axis(group), perm=[(src % n, dst % n)])


def all_reduce_coalesced(tensors, op=ReduceOp.SUM, group: AxisNames = "data"):
    """Reduce a LIST of tensors in one traced region (reference
    ``all_reduce_coalesced``); XLA's combiner fuses the collectives, which
    is the whole point of the torch coalescing manager."""
    return [all_reduce(t, op=op, group=group) for t in tensors]


def all_gather_coalesced(tensors, group: AxisNames = "data", axis: int = 0):
    return [all_gather(t, group=group, axis=axis) for t in tensors]


# capability probes (reference comm.py has_* surface): the XLA backend
# always has the tensor variants, and coalescing is the compiler's job
def has_all_gather_into_tensor():
    return True


def has_reduce_scatter_tensor():
    return True


def has_all_reduce_coalesced():
    return True


def has_coalescing_manager():
    return True


def allgather_fn(output_tensor, input_tensor, group: AxisNames = "data", async_op: bool = False):
    """Reference helper of the same name: dispatches to the tensor variant
    (the output buffer argument is meaningless in a functional API — the
    gathered array IS the return)."""
    return all_gather(input_tensor, group=group)


def reduce_scatter_fn(output_tensor, input_tensor, group: AxisNames = "data", async_op: bool = False):
    return reduce_scatter(input_tensor, group=group)
