"""In-program (traced) collectives.

The hot-path half of the comm backend (SURVEY.md §2.5 "TPU equivalent"): these
run *inside* ``jit``/``shard_map`` over mesh axis names and lower to XLA
collectives on ICI/DCN. They carry the same names as the reference
``deepspeed/comm/comm.py`` API (``all_reduce:482``, ``all_gather:227``,
``reduce_scatter_tensor:279``, ``all_to_all_single:330``…) so code reading the
reference maps 1:1, but the ``group=`` argument is a mesh axis name (or tuple
of names) rather than a torch process group.
"""

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = Union[str, Sequence[str]]


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


def _axis(group: AxisNames):
    if isinstance(group, (list, tuple)) and len(group) == 1:
        return group[0]
    return group


def all_reduce(tensor, op=ReduceOp.SUM, group: AxisNames = "data"):
    axis = _axis(group)
    if op == ReduceOp.SUM:
        return lax.psum(tensor, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(tensor, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(tensor), axis))
    raise ValueError(f"unsupported reduce op {op}")


def inference_all_reduce(tensor, group: AxisNames = "model"):
    """TP partial-sum combine on the inference path (reference comm.py:499)."""
    return lax.psum(tensor, _axis(group))


def all_gather(tensor, group: AxisNames = "data", axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis``. ``tiled=True`` concatenates (the
    ``all_gather_into_tensor`` layout); ``tiled=False`` stacks a new dim."""
    return lax.all_gather(tensor, _axis(group), axis=axis, tiled=tiled)


all_gather_into_tensor = all_gather


def reduce_scatter(tensor, op=ReduceOp.SUM, group: AxisNames = "data", scatter_dimension: int = 0):
    axis = _axis(group)
    out = lax.psum_scatter(tensor, axis, scatter_dimension=scatter_dimension, tiled=True)
    if op == ReduceOp.AVG:
        out = out / lax.psum(1, axis)
    return out


reduce_scatter_tensor = reduce_scatter


def all_to_all_single(tensor, group: AxisNames = "seq", split_axis: int = 0, concat_axis: int = 0):
    """Split along ``split_axis`` across the group and concat received chunks
    along ``concat_axis`` (reference comm.py:330). This is the Ulysses /
    MoE-dispatch primitive."""
    return lax.all_to_all(tensor, _axis(group), split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src_index: int = 0, group: AxisNames = "data"):
    """Broadcast the ``src_index`` shard to all members of the group."""
    axis = _axis(group)
    full = lax.all_gather(tensor, axis, axis=0, tiled=False)
    return jax.tree_util.tree_map(lambda x: x[src_index], full)


def ppermute(tensor, perm, group: AxisNames = "pipe"):
    """Neighbor exchange — the pipeline p2p primitive (reference
    ``runtime/pipe/p2p.py`` send/recv pairs become a single collective)."""
    return lax.ppermute(tensor, _axis(group), perm=perm)


def send_recv_next(tensor, group: AxisNames = "pipe", size: int = None):
    """Shift +1 along the ring: stage i's value arrives at stage i+1."""
    axis = _axis(group)
    n = size if size is not None else lax.psum(1, axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(tensor, axis, perm=perm)


def send_recv_prev(tensor, group: AxisNames = "pipe", size: int = None):
    axis = _axis(group)
    n = size if size is not None else lax.psum(1, axis)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return lax.ppermute(tensor, axis, perm=perm)


def axis_index(group: AxisNames):
    return lax.axis_index(_axis(group))


def axis_size(group: AxisNames):
    return lax.psum(1, _axis(group))
