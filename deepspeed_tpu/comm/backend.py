"""Communication backend objects.

Analog of the reference ``deepspeed/comm/backend.py:25`` (``Backend`` base) and
``comm/torch.py:99`` (``TorchBackend``). The TPU backend has two planes:

  - the traced plane (``comm/functional.py``) — collectives that compile into
    the step program and ride ICI/DCN; and
  - this host control plane — process bootstrap (``jax.distributed``),
    barriers, small host-value broadcasts, used outside ``jit`` the way the
    reference uses a gloo/TCP store for KVS bootstrap (``comm/ccl.py:45-57``).
"""

import os

import numpy as np

from ..utils.logging import logger


class Backend:

    def __init__(self, name="backend", rank=0, size=1):
        self.name = name
        self.world_group = None
        self.world_size = size
        self.world_rank = rank
        self.initialized = False

    def is_initialized(self):
        return self.initialized

    def new_group(self, ranks):
        # On TPU sub-groups are mesh axes; host-plane groups are not needed.
        raise NotImplementedError()

    def init_process_group(self):
        self.initialized = True


class XlaBackend(Backend):
    """Host control plane over the JAX runtime.

    ``communication_backend_name() == 'xla'`` selects this backend the same way
    'hccl' selects Habana's (reference ``deepspeed/__init__.py:134``).
    """

    def __init__(self, init_method=None, rank=-1, world_size=-1, name="xla", timeout=None):
        super().__init__(name=name)
        self._multiprocess = False
        self._maybe_init_jax_distributed(init_method, rank, world_size)
        import jax

        self.world_rank = jax.process_index()
        self.world_size = jax.process_count()
        self.initialized = True

    def _maybe_init_jax_distributed(self, init_method, rank, world_size):
        import jax

        from ..launcher.constants import (ENV_COORDINATOR_ADDRESS, ENV_NUM_PROCESSES,
                                          ENV_PROCESS_ID)

        coord = (os.environ.get(ENV_COORDINATOR_ADDRESS)
                 or os.environ.get("JAX_COORDINATOR_ADDRESS"))
        n_proc = int(os.environ.get(ENV_NUM_PROCESSES, os.environ.get("WORLD_SIZE", world_size)) or -1)
        proc_id = int(os.environ.get(ENV_PROCESS_ID, os.environ.get("RANK", rank)) or -1)
        if coord is not None and n_proc > 1:
            try:
                jax.distributed.initialize(coordinator_address=coord, num_processes=n_proc, process_id=proc_id)
                self._multiprocess = True
            except Exception as e:  # already initialized or single-host
                logger.warning(f"jax.distributed.initialize skipped: {e}")

    # ---- host-plane ops ----
    def get_rank(self):
        return self.world_rank

    def get_world_size(self):
        return self.world_size

    def barrier(self):
        if self.world_size > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("deepspeed_tpu.barrier")

    def broadcast_host(self, value, src=0):
        """Broadcast a small host pytree from process ``src`` (control plane)."""
        if self.world_size == 1:
            return value
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(value, is_source=(self.world_rank == src))

    def all_gather_host(self, value):
        if self.world_size == 1:
            return [value]
        from jax.experimental import multihost_utils

        arr = multihost_utils.process_allgather(np.asarray(value))
        return list(arr)

    def destroy_process_group(self):
        self.initialized = False
