from .comm import *  # noqa: F401,F403
from .comm import cdb, init_distributed, get_rank, get_world_size, get_local_rank, barrier, is_initialized
from . import functional
