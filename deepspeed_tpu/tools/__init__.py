"""Fork tools (reference ``deepspeed/tools/``): tensor_logger for
cross-backend accuracy diffing; pg_sim's role is filled by the virtual
multi-device CPU mesh (tests/conftest.py)."""

from .tensor_logger import TensorLogger, compare_logs
