"""Tensor logger — per-iteration tensor capture for accuracy diffing.

Analog of the fork's ``deepspeed/tools/tensor_logger/tensor_logger.py``
(fwd/bwd/grad tensor dumps used to diff HPU-vs-GPU numerics). Under jit
there are no module hooks, so capture happens at the step boundary: params,
gradients, and metrics snapshot per optimizer step, either as full ``.npz``
tensors or compact statistics (mean/std/absmax/norm) in ``.jsonl`` —
enough to bisect a cross-backend divergence to the first drifting step and
tensor.
"""

import contextlib
import json
import os
from typing import Optional

import numpy as np

import jax


def _stats(x: np.ndarray) -> dict:
    x64 = np.asarray(x, np.float64).ravel()
    return {
        "shape": list(np.shape(x)),
        "mean": float(x64.mean()) if x64.size else 0.0,
        "std": float(x64.std()) if x64.size else 0.0,
        "absmax": float(np.abs(x64).max()) if x64.size else 0.0,
        "l2": float(np.linalg.norm(x64)),
        "finite": bool(np.isfinite(x64).all()),
    }


class TensorLogger:
    """Capture per-step tensors (reference class of the same name).

    mode='stats' writes one JSON line per step with per-tensor statistics;
    mode='full' additionally writes ``step_<N>.npz`` with the raw arrays.
    """

    def __init__(self, save_dir: str, start_iteration: int = 0, end_iteration: int = 10**9,
                 mode: str = "stats", include_grads: bool = True):
        assert mode in ("stats", "full")
        self.save_dir = save_dir
        self.start = start_iteration
        self.end = end_iteration
        self.mode = mode
        self.include_grads = include_grads
        os.makedirs(save_dir, exist_ok=True)
        self._fh = open(os.path.join(save_dir, "tensor_log.jsonl"), "a")

    def log_step(self, step: int, params, grads=None, metrics: Optional[dict] = None):
        if not (self.start <= step < self.end):
            return
        from ..runtime.zero.partition import path_str

        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        tensors = {("param/" + path_str(kp)): np.asarray(jax.device_get(v)) for kp, v in flat}
        if grads is not None and self.include_grads:
            gflat, _ = jax.tree_util.tree_flatten_with_path(grads)
            tensors.update({("grad/" + path_str(kp)): np.asarray(jax.device_get(v))
                            for kp, v in gflat})
        rec = {"step": int(step), "tensors": {k: _stats(v) for k, v in tensors.items()}}
        if metrics:
            rec["metrics"] = {k: float(v) for k, v in metrics.items()}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        if self.mode == "full":
            np.savez(os.path.join(self.save_dir, f"step_{step}.npz"), **tensors)

    def close(self):
        self._fh.close()

    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def attach(self, engine):
        """Wrap ``engine.train_batch`` to log every step automatically."""
        orig = engine.train_batch

        def wrapped(*a, **kw):
            loss = orig(*a, **kw)
            self.log_step(engine.global_steps, engine.state["params"],
                          metrics={"loss": float(loss),
                                   **({"grad_norm": float(engine._step_metrics["grad_norm"])}
                                      if "grad_norm" in engine._step_metrics else {})})
            return loss

        engine.train_batch = wrapped
        try:
            yield self
        finally:
            engine.train_batch = orig


def compare_logs(dir_a: str, dir_b: str, rtol: float = 1e-3) -> list:
    """Diff two stats logs; returns [(step, tensor, field, a, b), ...] for
    the first divergences (the cross-backend accuracy-diff workflow)."""
    out = []
    fa = os.path.join(dir_a, "tensor_log.jsonl")
    fb = os.path.join(dir_b, "tensor_log.jsonl")
    with open(fa) as a, open(fb) as b:
        for la, lb in zip(a, b):
            ra, rb = json.loads(la), json.loads(lb)
            for name in ra["tensors"]:
                if name not in rb["tensors"]:
                    out.append((ra["step"], name, "missing", None, None))
                    continue
                for field in ("mean", "std", "l2"):
                    va, vb = ra["tensors"][name][field], rb["tensors"][name][field]
                    if abs(va - vb) > rtol * max(abs(va), abs(vb), 1e-12):
                        out.append((ra["step"], name, field, va, vb))
            if out:
                break
    return out
