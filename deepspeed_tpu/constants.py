"""Top-level constants (reference ``deepspeed/constants.py``)."""

import os
from datetime import timedelta

TORCH_DISTRIBUTED_DEFAULT_PORT = 29500  # name kept for config compatibility

# coordination-service timeout knob (reference default_pg_timeout semantics;
# jax.distributed uses its own heartbeat but the env var is honored for
# launcher-level waits)
default_pg_timeout = timedelta(minutes=int(os.getenv("DEEPSPEED_TIMEOUT", default=30)))

INFERENCE_GENERIC_MODE = "generic"
INFERENCE_SPECIALIZED_MODE = "specialized"
