"""Version info (reference ``deepspeed/git_version_info.py``; that file is
generated at build time — this one is static, with the op compatibility
report derived from the live registry)."""

version = "0.12.4+tpu"
git_hash = "unknown"
git_branch = "main"
installed_ops = {}
compatible_ops = {}


def _populate():
    try:
        from .ops import op_registry

        for name, builder in op_registry.items():
            ok = builder.is_compatible()
            installed_ops[builder.NAME] = ok
            compatible_ops[builder.NAME] = ok
    except Exception:
        pass


_populate()
