"""Offline reconstruction of full fp32 weights from a sharded checkpoint.

Reference ``deepspeed/utils/zero_to_fp32.py`` (592 LoC,
``convert_zero_checkpoint_to_fp32_state_dict``): the reference must stitch
``bf16_zero_pp_rank_*`` flat shards back into parameter tensors; on TPU the
checkpoint is a tensorstore layout that restores to full arrays directly —
this module provides the same offline CLI/API surface (no engine, no mesh
required) over that layout.

Usage (same as the reference script dropped into checkpoint dirs):
    python -m deepspeed_tpu.checkpoint.zero_to_fp32 <ckpt_dir> <output_file>
"""

import argparse
import os
import pickle

import numpy as np

from ..utils.logging import logger

LATEST_FILE = "latest"


def _resolve_tag(checkpoint_dir, tag):
    if tag is None:
        latest = os.path.join(checkpoint_dir, LATEST_FILE)
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
        else:
            raise ValueError(f"no 'latest' file in {checkpoint_dir}; pass tag explicitly")
    path = os.path.join(checkpoint_dir, str(tag))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint path {path} does not exist")
    return path


def _restore_arrays(path):
    import orbax.checkpoint as ocp

    arrays_path = os.path.join(path, "arrays")
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(arrays_path)
    return tree


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None, exclude_frozen_parameters=False):
    """Full fp32 params as a flat {path: np.ndarray} dict (reference function
    of the same name)."""
    import jax

    path = _resolve_tag(checkpoint_dir, tag)
    tree = _restore_arrays(path)
    module = tree.get("module", tree)
    flat = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(module)[0]:
        from ..runtime.zero.partition import path_str

        flat[path_str(kp)] = np.asarray(jax.device_get(leaf), dtype=np.float32)
    return flat


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file, tag=None,
                                               exclude_frozen_parameters=False):
    """Write the consolidated fp32 state dict to ``output_file`` (pickle)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag, exclude_frozen_parameters)
    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    with open(output_file, "wb") as f:
        pickle.dump(sd, f)
    total = sum(v.size for v in sd.values())
    logger.info(f"wrote {len(sd)} tensors ({total/1e6:.2f}M params) to {output_file}")
    return sd


def load_state_dict_from_zero_checkpoint(model_params, checkpoint_dir, tag=None):
    """Overlay checkpoint weights onto a param pytree (reference
    ``load_state_dict_from_zero_checkpoint`` updates a torch module)."""
    import jax
    from ..runtime.zero.partition import path_str

    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)

    def replace(kp, leaf):
        key = path_str(kp)
        if key in sd:
            return np.asarray(sd[key], dtype=np.asarray(leaf).dtype).reshape(np.shape(leaf))
        logger.warning(f"checkpoint missing param {key}; keeping existing value")
        return leaf

    return jax.tree_util.tree_map_with_path(replace, model_params)


def main():
    parser = argparse.ArgumentParser(description="Reconstruct full fp32 weights from a checkpoint")
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("-t", "--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, tag=args.tag)


if __name__ == "__main__":
    main()
