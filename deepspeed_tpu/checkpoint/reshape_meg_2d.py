"""Megatron 2-D (pp × tp) checkpoint rank-map reshaping.

Analog of the reference ``deepspeed/checkpoint/reshape_meg_2d.py``
(``meg_2d_parallel_map:9``, ``reshape_meg_2d_parallel:80``,
``get_mpu_ranks:107``) — the bookkeeping that says, for a topology change,
which OLD ranks' checkpoint shards each NEW (pipeline, tensor) partition
must read. On TPU the byte movement itself is subsumed by resharding
(arrays are global, see ``ds_to_universal``/``universal_checkpoint``), but
offline tooling converting legacy Megatron-DeepSpeed checkpoints still
needs the rank-map math, re-derived here from the Megatron rank order
(tp fastest, then dp, then pp).
"""

from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger


class meg_2d_parallel_map:
    """(pp_index, tp_index) → list of payloads (global ranks, usually)."""

    def __init__(self, pp_degree: int, tp_degree: int):
        self.pp_degree = pp_degree
        self.tp_degree = tp_degree
        self.map: Dict[Tuple[int, int], List] = {}

    def simple_init(self):
        """Identity map: cell (p, t) owns global rank p * tp + t (the
        Megatron enumeration with dp folded out)."""
        for p in range(self.pp_degree):
            for t in range(self.tp_degree):
                self.map[(p, t)] = [p * self.tp_degree + t]
        return self

    def add_data(self, pp_index: int, tp_index: int, data: List):
        assert 0 <= pp_index < self.pp_degree and 0 <= tp_index < self.tp_degree
        self.map.setdefault((pp_index, tp_index), []).extend(data)

    def get_data(self, pp_index: Optional[int] = None, tp_index: Optional[int] = None) -> List:
        """Collect payloads; None wildcards a dimension."""
        pps = range(self.pp_degree) if pp_index is None else [pp_index]
        tps = range(self.tp_degree) if tp_index is None else [tp_index]
        out: List = []
        for p in pps:
            for t in tps:
                out.extend(self.map.get((p, t), []))
        return out

    def print_data(self, tag: str = ""):
        for key in sorted(self.map):
            logger.info(f"{tag} {key} -> {self.map[key]}")


def _merge_tp(old: meg_2d_parallel_map, new_tp: int) -> meg_2d_parallel_map:
    assert old.tp_degree % new_tp == 0, \
        f"tp reshape needs integer merge factor: {old.tp_degree} -> {new_tp}"
    factor = old.tp_degree // new_tp
    out = meg_2d_parallel_map(old.pp_degree, new_tp)
    for p in range(old.pp_degree):
        for t in range(new_tp):
            for f in range(factor):
                out.add_data(p, t, old.map[(p, t * factor + f)])
    return out


def _merge_pp(old: meg_2d_parallel_map, new_pp: int) -> meg_2d_parallel_map:
    assert old.pp_degree % new_pp == 0, \
        f"pp reshape needs integer merge factor: {old.pp_degree} -> {new_pp}"
    factor = old.pp_degree // new_pp
    out = meg_2d_parallel_map(new_pp, old.tp_degree)
    for p in range(new_pp):
        for t in range(old.tp_degree):
            for f in range(factor):
                out.add_data(p, t, old.map[(p * factor + f, t)])
    return out


def reshape_meg_2d_parallel(old_pp_degree: int, old_tp_degree: int, new_pp_degree: int,
                            new_tp_degree: int, verbose: bool = False) -> meg_2d_parallel_map:
    """Each new (pp, tp) cell lists the OLD global ranks whose shards feed
    it. Degrees may only shrink by integer factors (shard merging); growing
    goes through the universal layout instead."""
    old = meg_2d_parallel_map(old_pp_degree, old_tp_degree).simple_init()
    if verbose:
        old.print_data("old:")
    mid = _merge_tp(old, new_tp_degree)
    new = _merge_pp(mid, new_pp_degree)
    if verbose:
        new.print_data("new:")
    return new


def get_mpu_ranks(tp_size: int = 1, pp_size: int = 1, dp_size: int = 1,
                  virtual_pp_size=None):
    """Group rank lists for a (tp, dp, pp) world in Megatron order
    (global rank = pp * dp * tp + dp * tp_size... tp fastest):
    returns (tp_groups, dp_groups, pp_groups)."""
    world = tp_size * dp_size * pp_size
    tp_groups = [list(range(start, start + tp_size)) for start in range(0, world, tp_size)]
    dp_groups = []
    for p in range(pp_size):
        base = p * dp_size * tp_size
        for t in range(tp_size):
            dp_groups.append([base + d * tp_size + t for d in range(dp_size)])
    pp_groups = []
    per_stage = dp_size * tp_size
    for i in range(per_stage):
        pp_groups.append([i + p * per_stage for p in range(pp_size)])
    return tp_groups, dp_groups, pp_groups
