"""Universal checkpoint loading.

Reference ``deepspeed/checkpoint/universal_checkpoint.py``
(``load_hp_checkpoint_state:117``): each rank loads its slice of the
per-parameter fp32 weights + moments from the universal layout, whatever the
new DP/TP/PP topology. On TPU the "slice for this rank" is expressed by
device_put into the engine's NamedShardings — XLA distributes the full host
array to exactly the shards each device owns.
"""

import os
import pickle

import numpy as np

from ..utils.logging import logger


def read_universal_checkpoint(universal_dir):
    """Load the universal layout into ({path: {fp32, exp_avg?, exp_avg_sq?}}, meta)."""
    meta_path = os.path.join(universal_dir, "universal_meta.pkl")
    with open(meta_path, "rb") as f:
        meta = pickle.load(f)
    out = {}
    zero_dir = os.path.join(universal_dir, "zero")
    for key in meta["param_paths"]:
        pdir = os.path.join(zero_dir, key.replace("/", "."))
        entry = {"fp32": np.load(os.path.join(pdir, "fp32.npy"))}
        for name in ("exp_avg", "exp_avg_sq"):
            p = os.path.join(pdir, f"{name}.npy")
            if os.path.exists(p):
                entry[name] = np.load(p)
        out[key] = entry
    return out, meta


def load_hp_checkpoint_state(param_path, universal_dir):
    """Reference function of the same name: the hp (high-precision) states of
    one parameter."""
    sd, _ = read_universal_checkpoint(universal_dir)
    return sd[param_path]


def load_universal_checkpoint(engine, universal_dir, load_optimizer_states=True):
    """Restore an engine from a universal checkpoint under any topology.

    Weights are device_put into the engine's current shardings; Adam moments
    are written back into the optax chain state when the layouts line up
    (reference reshards the flat shards; XLA resharding does it here).
    """
    sd, meta = read_universal_checkpoint(universal_dir)
    meta = apply_universal_state(engine, sd, meta, load_optimizer_states=load_optimizer_states)
    logger.info(f"loaded universal checkpoint from {universal_dir} "
                f"(step={meta.get('step')}, optimizer={meta.get('has_optimizer')})")
    return meta


def apply_universal_state(engine, sd, meta, load_optimizer_states=True):
    """The in-memory half of :func:`load_universal_checkpoint`: overlay an
    already-materialized universal state (``{path: {fp32, exp_avg?,
    exp_avg_sq?}}``, ``meta``) onto ``engine`` under its CURRENT mesh. The
    elastic live remesh (``elasticity/remesh.py``) calls this directly with
    a host snapshot, skipping disk entirely; the disk loader reads the npy
    layout and resolves through the same code."""
    import jax

    from ..runtime.zero.partition import path_str

    def pick(kp, leaf):
        key = path_str(kp)
        if key not in sd:
            logger.warning(f"universal checkpoint missing {key}; keeping current value")
            return leaf
        return np.asarray(sd[key]["fp32"], dtype=leaf.dtype).reshape(leaf.shape)

    host_params = jax.tree_util.tree_map_with_path(pick, jax.device_get(engine.state["params"]))
    engine.state["params"] = jax.device_put(host_params, engine._state_shardings["params"])

    if load_optimizer_states and meta.get("has_optimizer") and engine.state["opt_state"]:
        flat = jax.tree_util.tree_flatten_with_path(host_params)[0]
        keys = [path_str(kp) for kp, _ in flat]
        mu = [np.asarray(sd[k]["exp_avg"], np.float32) for k in keys if k in sd and "exp_avg" in sd[k]]
        nu = [np.asarray(sd[k]["exp_avg_sq"], np.float32) for k in keys if k in sd and "exp_avg_sq" in sd[k]]
        if len(mu) == len(keys):
            engine.state["opt_state"] = _overlay_adam_moments(engine, mu, nu)
            # scalar chain leaves (adam `count` et al.) restore by flat
            # index: the optax chain structure is a function of the
            # optimizer config, not the mesh, so indices line up across
            # topologies. Without this the restored adam re-runs
            # bias-correction warmup and the first post-restore step
            # diverges from a native resume. ONLY alongside a successful
            # moments restore — a restored count over fresh zero moments
            # would be worse than a clean warmup. Per-leaf replacement (no
            # whole-tree host round trip: the moments above are 2x param
            # bytes, and device_get of non-addressable multi-host shards
            # would raise); each scalar keeps its live leaf's sharding so
            # the compiled step's signature is unchanged.
            scalar_leaves = meta.get("optimizer_scalar_leaves") or {}
            if scalar_leaves:
                import jax.numpy as jnp

                leaves, treedef = jax.tree_util.tree_flatten(engine.state["opt_state"])
                overlaid = 0
                for idx_str, val in scalar_leaves.items():
                    i = int(idx_str)
                    if 0 <= i < len(leaves) and np.ndim(leaves[i]) == 0:
                        old = leaves[i]
                        new = jnp.asarray(val, getattr(old, "dtype", None))
                        if isinstance(old, jax.Array):
                            new = jax.device_put(new, old.sharding)
                        leaves[i] = new
                        overlaid += 1
                    else:
                        logger.warning(f"universal checkpoint scalar opt leaf {i} does "
                                       f"not line up with this optimizer chain; skipped")
                if overlaid:
                    engine.state["opt_state"] = jax.tree_util.tree_unflatten(treedef, leaves)
        else:
            logger.warning("universal checkpoint moments incomplete; optimizer state not restored")

    if engine.host_optimizer is not None:
        engine.host_optimizer.reset_masters(engine.state["params"])
        if load_optimizer_states and meta.get("has_optimizer"):
            hsd = engine.host_optimizer.state_dict()
            for k in engine.host_optimizer.keys:
                if k in sd and "exp_avg" in sd[k]:
                    hsd["exp_avg"][k] = sd[k]["exp_avg"].reshape(-1)
                    hsd["exp_avg_sq"][k] = sd[k]["exp_avg_sq"].reshape(-1)
                if k in sd:
                    hsd["masters"][k] = sd[k]["fp32"].reshape(-1)
            engine.host_optimizer.load_state_dict(hsd)

    # scalars are device_put with the live leaf's OWN sharding: an unsharded
    # jnp scalar here changes the compiled step's input signature and costs
    # a silent recompile on the first post-restore step — exactly the warm
    # time a live remesh exists to save
    import jax.numpy as jnp

    for k in ("step", "good_steps"):
        if k in meta:
            engine.state[k] = jax.device_put(
                jnp.asarray(meta[k], engine.state[k].dtype), engine.state[k].sharding)
    if "loss_scale" in meta:
        engine.state["loss_scale"] = jax.device_put(
            jnp.asarray(meta["loss_scale"], jnp.float32), engine.state["loss_scale"].sharding)
    engine.global_steps = int(meta.get("global_steps", engine.global_steps))
    if meta.get("lr_scheduler") and getattr(engine, "lr_scheduler", None) is not None:
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    # data-efficiency scheduler state (ROADMAP 5c): a warm remesh must
    # resume curriculum difficulty / random-ltd sequence budget exactly
    # where the snapshot left them — without this a data-efficiency run
    # restarting onto a new topology silently re-ran its schedule from
    # step 0 while the optimizer continued from the restored step
    if meta.get("curriculum_scheduler") and getattr(engine, "curriculum_scheduler",
                                                    None) is not None:
        engine.curriculum_scheduler.load_state_dict(meta["curriculum_scheduler"])
    if meta.get("random_ltd_scheduler") and getattr(engine, "random_ltd_scheduler",
                                                    None) is not None:
        engine.random_ltd_scheduler.load_state_dict(meta["random_ltd_scheduler"])
    return meta


def _overlay_adam_moments(engine, mu_leaves, nu_leaves):
    """Write mu/nu leaf lists back into the optax chain state at the position
    where adam's ScaleByAdamState lives (matched by shape-run, the inverse of
    ds_to_universal._extract_adam_moments)."""
    import jax

    opt_state = jax.device_get(engine.state["opt_state"])
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    param_shapes = [np.shape(l) for l in jax.tree_util.tree_leaves(jax.device_get(engine.state["params"]))]
    n = len(param_shapes)
    for start in range(len(leaves) - 2 * n + 1):
        if all(np.shape(a) == s for a, s in zip(leaves[start:start + n], param_shapes)) and \
           all(np.shape(a) == s for a, s in zip(leaves[start + n:start + 2 * n], param_shapes)):
            for i in range(n):
                leaves[start + i] = mu_leaves[i].reshape(param_shapes[i])
                leaves[start + n + i] = nu_leaves[i].reshape(param_shapes[i])
            break
    else:
        logger.warning("could not locate adam moments in optimizer state; not restored")
        return engine.state["opt_state"]
    new_state = jax.tree_util.tree_unflatten(treedef, leaves)
    return jax.device_put(new_state, engine._state_shardings["opt_state"])
