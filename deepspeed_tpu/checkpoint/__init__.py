from .ds_to_universal import ds_to_universal, universal_state_from_tree, UNIVERSAL_LAYOUT_VERSION
from .universal_checkpoint import (apply_universal_state, load_universal_checkpoint,
                                   read_universal_checkpoint, load_hp_checkpoint_state)
from .zero_to_fp32 import (get_fp32_state_dict_from_zero_checkpoint,
                           convert_zero_checkpoint_to_fp32_state_dict, load_state_dict_from_zero_checkpoint)
from .reshape_meg_2d import get_mpu_ranks, meg_2d_parallel_map, reshape_meg_2d_parallel
from .reshape_utils import merge_tp_param, split_tp_param, reshard_state_dict
