"""Tensor-parallel checkpoint resharding.

Reference ``runtime/state_dict_factory.py`` (434 LoC ``SDLoaderFactory`` —
MP-degree resharding of inference checkpoints with qkv split/merge) +
``deepspeed/checkpoint/reshape_meg_2d.py``. On TPU a running engine reshards
through NamedShardings, so these utilities serve the *offline* path: take a
state dict saved at TP degree N and produce degree M (merge shards → split).
"""

from typing import Dict, List, Sequence

import numpy as np

from ..utils.logging import logger


def merge_tp_param(shards: Sequence[np.ndarray], axis: int) -> np.ndarray:
    """Concatenate TP shards of one parameter (reference merge path:
    qkv/mlp columns along their sharded axis)."""
    return np.concatenate([np.asarray(s) for s in shards], axis=axis)


def split_tp_param(full: np.ndarray, degree: int, axis: int) -> List[np.ndarray]:
    """Evenly split one parameter for a TP degree (reference split path)."""
    assert full.shape[axis] % degree == 0, \
        f"dim {axis} of shape {full.shape} not divisible by tp degree {degree}"
    return [np.ascontiguousarray(s) for s in np.split(full, degree, axis=axis)]


def reshard_state_dict(shard_dicts: Sequence[Dict[str, np.ndarray]],
                       tp_axis_map: Dict[str, int],
                       target_degree: int) -> List[Dict[str, np.ndarray]]:
    """Reshard a list of per-rank state dicts (source TP degree = len(list))
    to ``target_degree`` ranks.

    ``tp_axis_map``: {param_path: axis} for params sharded over TP; params
    absent from the map are treated as replicated (checked identical across
    shards, then copied to every target rank).
    """
    src_degree = len(shard_dicts)
    keys = list(shard_dicts[0].keys())
    out = [dict() for _ in range(target_degree)]
    for key in keys:
        parts = [sd[key] for sd in shard_dicts]
        if key in tp_axis_map:
            axis = tp_axis_map[key]
            full = merge_tp_param(parts, axis)
            splits = split_tp_param(full, target_degree, axis)
            for r in range(target_degree):
                out[r][key] = splits[r]
        else:
            base = np.asarray(parts[0])
            for p in parts[1:]:
                if not np.array_equal(base, np.asarray(p)):
                    logger.warning(f"replicated param {key} differs across source ranks; using rank0")
                    break
            for r in range(target_degree):
                out[r][key] = base
    return out
