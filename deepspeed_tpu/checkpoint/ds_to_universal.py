"""Engine checkpoint → universal checkpoint conversion.

Reference ``deepspeed/checkpoint/ds_to_universal.py`` (335 LoC): flattened
ZeRO shards are stitched into one folder per parameter holding ``fp32.pt``
plus optimizer moments, so a job with a different DP/TP/PP topology can
re-partition on load. The TPU layout needs no stitching (tensorstore restores
full arrays), so conversion = consolidate to fp32 + extract the Adam moments
from the optax chain state into the same per-parameter layout:

    <out>/zero/<param_path>/fp32.npy
    <out>/zero/<param_path>/exp_avg.npy        (when Adam state exists)
    <out>/zero/<param_path>/exp_avg_sq.npy
    <out>/universal_meta.pkl                   (step/loss-scale/version)
"""

import os
import pickle

import numpy as np

from ..utils.logging import logger
from .zero_to_fp32 import _resolve_tag, _restore_arrays

UNIVERSAL_LAYOUT_VERSION = 1


def _flat_paths(tree):
    import jax
    from ..runtime.zero.partition import path_str

    return [(path_str(kp), leaf) for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]


def _extract_adam_moments(opt_leaves_dict, params_tree):
    """Find the (mu, nu) trees of a ScaleByAdamState inside the serialized
    optax chain leaves. The engine checkpoints opt_state as numbered flat
    leaves; an adam/adamw chain stores [count, mu..., nu...] where mu/nu each
    mirror the params tree — match by leaf count and shapes."""
    import jax

    param_leaves = jax.tree_util.tree_leaves(params_tree)
    n = len(param_leaves)
    leaves = [opt_leaves_dict[str(i)] for i in range(len(opt_leaves_dict))]
    shapes = [np.shape(l) for l in param_leaves]
    # scan for two consecutive runs of leaves whose shapes match the params
    for start in range(len(leaves) - 2 * n + 1):
        run1 = leaves[start:start + n]
        run2 = leaves[start + n:start + 2 * n]
        if all(np.shape(a) == s for a, s in zip(run1, shapes)) and \
           all(np.shape(a) == s for a, s in zip(run2, shapes)):
            return run1, run2
    return None, None


def ds_to_universal(checkpoint_dir, output_dir, tag=None):
    """Convert; returns the number of parameters written (reference main)."""
    import jax

    path = _resolve_tag(checkpoint_dir, tag)
    tree = _restore_arrays(path)
    module = tree["module"]
    zero_dir = os.path.join(output_dir, "zero")
    os.makedirs(zero_dir, exist_ok=True)

    flat = _flat_paths(module)
    mu_leaves = nu_leaves = None
    masters = {}
    if "optimizer" in tree and tree["optimizer"]:
        mu_leaves, nu_leaves = _extract_adam_moments(tree["optimizer"], module)
        if mu_leaves is None:
            logger.warning("optimizer state present but not adam-shaped; universal ckpt will carry weights only")
    if mu_leaves is None and tree.get("host_optimizer"):
        # ZeRO-Offload: the device-side optimizer state is empty; the Adam
        # moments (and fp32 masters) live in the host_optimizer subtree
        # (engine.py save_checkpoint), keyed by '::'-escaped param paths.
        host = tree["host_optimizer"]
        try:
            mu_leaves, nu_leaves, masters = [], [], {}
            for key, leaf in flat:
                ek = key.replace("/", "::")
                shape = np.shape(leaf)
                mu_leaves.append(np.asarray(host["exp_avg"][ek], np.float32).reshape(shape))
                nu_leaves.append(np.asarray(host["exp_avg_sq"][ek], np.float32).reshape(shape))
                masters[key] = np.asarray(host["masters"][ek], np.float32).reshape(shape)
            logger.info("using host_optimizer (ZeRO-Offload) state for universal checkpoint")
        except KeyError as e:
            logger.warning(f"host_optimizer subtree incomplete ({e}); universal ckpt will carry weights only")
            mu_leaves = nu_leaves = None
            masters = {}

    for i, (key, leaf) in enumerate(flat):
        pdir = os.path.join(zero_dir, key.replace("/", "."))
        os.makedirs(pdir, exist_ok=True)
        fp32 = masters[key] if key in masters else np.asarray(jax.device_get(leaf), np.float32)
        np.save(os.path.join(pdir, "fp32.npy"), fp32)
        if mu_leaves is not None:
            np.save(os.path.join(pdir, "exp_avg.npy"), np.asarray(jax.device_get(mu_leaves[i]), np.float32))
            np.save(os.path.join(pdir, "exp_avg_sq.npy"), np.asarray(jax.device_get(nu_leaves[i]), np.float32))

    meta = {
        "universal_layout_version": UNIVERSAL_LAYOUT_VERSION,
        "param_paths": [k for k, _ in flat],
        "has_optimizer": mu_leaves is not None,
    }
    scalars = tree.get("scalars", {})
    for k in ("step", "loss_scale", "good_steps"):
        if k in scalars:
            meta[k] = np.asarray(jax.device_get(scalars[k])).item()
    # carry non-array sidecar meta (global_steps etc.) from the source ckpt
    src_meta = os.path.join(path, "meta.pkl")
    if os.path.exists(src_meta):
        with open(src_meta, "rb") as f:
            side = pickle.load(f)
        for k in ("global_steps", "global_samples", "skipped_steps", "lr_scheduler", "ds_version"):
            if k in side:
                meta[k] = side[k]
    with open(os.path.join(output_dir, "universal_meta.pkl"), "wb") as f:
        pickle.dump(meta, f)
    logger.info(f"universal checkpoint: {len(flat)} params -> {output_dir} "
                f"(optimizer={'yes' if mu_leaves is not None else 'no'})")
    return len(flat)


def main():
    import argparse

    p = argparse.ArgumentParser(description="Convert an engine checkpoint to universal layout")
    p.add_argument("--input_folder", required=True)
    p.add_argument("--output_folder", required=True)
    p.add_argument("--tag", default=None)
    args = p.parse_args()
    ds_to_universal(args.input_folder, args.output_folder, tag=args.tag)


if __name__ == "__main__":
    main()
