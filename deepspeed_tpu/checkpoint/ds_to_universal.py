"""Engine checkpoint → universal checkpoint conversion.

Reference ``deepspeed/checkpoint/ds_to_universal.py`` (335 LoC): flattened
ZeRO shards are stitched into one folder per parameter holding ``fp32.pt``
plus optimizer moments, so a job with a different DP/TP/PP topology can
re-partition on load. The TPU layout needs no stitching (tensorstore restores
full arrays), so conversion = consolidate to fp32 + extract the Adam moments
from the optax chain state into the same per-parameter layout:

    <out>/zero/<param_path>/fp32.npy
    <out>/zero/<param_path>/exp_avg.npy        (when Adam state exists)
    <out>/zero/<param_path>/exp_avg_sq.npy
    <out>/universal_meta.pkl                   (step/loss-scale/version)
"""

import os
import pickle

import numpy as np

from ..utils.logging import logger
from .zero_to_fp32 import _resolve_tag, _restore_arrays

UNIVERSAL_LAYOUT_VERSION = 1


def _flat_paths(tree):
    import jax
    from ..runtime.zero.partition import path_str

    return [(path_str(kp), leaf) for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]


def _extract_adam_moments(opt_leaves_dict, params_tree):
    """Find the (mu, nu) trees of a ScaleByAdamState inside the serialized
    optax chain leaves. The engine checkpoints opt_state as numbered flat
    leaves; an adam/adamw chain stores [count, mu..., nu...] where mu/nu each
    mirror the params tree — match by leaf count and shapes."""
    import jax

    param_leaves = jax.tree_util.tree_leaves(params_tree)
    n = len(param_leaves)
    leaves = [opt_leaves_dict[str(i)] for i in range(len(opt_leaves_dict))]
    shapes = [np.shape(l) for l in param_leaves]
    # scan for two consecutive runs of leaves whose shapes match the params
    for start in range(len(leaves) - 2 * n + 1):
        run1 = leaves[start:start + n]
        run2 = leaves[start + n:start + 2 * n]
        if all(np.shape(a) == s for a, s in zip(run1, shapes)) and \
           all(np.shape(a) == s for a, s in zip(run2, shapes)):
            return run1, run2
    return None, None


def universal_state_from_tree(tree):
    """The in-memory core of the conversion: a checkpoint-state tree (the
    exact shape ``engine._ckpt_state`` produces — ``module`` params,
    numbered ``optimizer`` leaves, optional ``host_optimizer`` subtree,
    ``scalars``, sidecar counters) to the per-parameter universal layout

        ``({param_path: {"fp32", "exp_avg"?, "exp_avg_sq"?}}, meta)``

    This is the reshape math the disk converter (:func:`ds_to_universal`)
    and the elastic live remesh (``elasticity/remesh.py`` — snapshot a
    LIVE engine, re-shard onto a new topology without touching disk) both
    resolve through, so warm-remesh parity is pinned to the same code the
    pp2×tp2 → pp1×tp4 bit-exactness test already proves.
    """
    import jax

    module = tree["module"]
    flat = _flat_paths(module)
    # Per-key Adam moments may come from TWO sources: the host_optimizer
    # subtree (ZeRO-Offload — full offload owns every key; twin-flow
    # `offload_optimizer.ratio` < 1 owns only its slice) and the device
    # optax state (normal training, or twin-flow's device slice). Merge:
    # host keys first, then match the device state against the REMAINING
    # leaves (engine.py twin-flow keeps the device opt over the pruned tree).
    mu_by_key, nu_by_key, masters = {}, {}, {}
    host = tree.get("host_optimizer") or {}
    if host:
        for key, leaf in flat:
            ek = key.replace("/", "::")
            # all three subtrees must carry the key (a partially-written
            # host save degrades that key to the device source / weights-only
            # instead of crashing the conversion)
            if all(ek in host.get(f, {}) for f in ("exp_avg", "exp_avg_sq", "masters")):
                shape = np.shape(leaf)
                mu_by_key[key] = np.asarray(host["exp_avg"][ek], np.float32).reshape(shape)
                nu_by_key[key] = np.asarray(host["exp_avg_sq"][ek], np.float32).reshape(shape)
                masters[key] = np.asarray(host["masters"][ek], np.float32).reshape(shape)
        if mu_by_key:
            logger.info(f"host_optimizer (ZeRO-Offload) state covers {len(mu_by_key)}/{len(flat)} params")
    remaining = [(key, leaf) for key, leaf in flat if key not in mu_by_key]
    if remaining and tree.get("optimizer"):
        mu, nu = _extract_adam_moments(tree["optimizer"], [leaf for _, leaf in remaining])
        if mu is not None:
            for (key, _), m, v in zip(remaining, mu, nu):
                mu_by_key[key] = np.asarray(jax.device_get(m), np.float32)
                nu_by_key[key] = np.asarray(jax.device_get(v), np.float32)
        else:
            logger.warning("device optimizer state present but not adam-shaped for the "
                           f"{len(remaining)} non-host params")
    has_optimizer = len(mu_by_key) == len(flat)
    if not has_optimizer:
        logger.warning(f"optimizer moments found for {len(mu_by_key)}/{len(flat)} params; "
                       "universal ckpt will carry weights only")

    sd = {}
    for key, leaf in flat:
        entry = {"fp32": masters[key] if key in masters
                 else np.asarray(jax.device_get(leaf), np.float32)}
        if has_optimizer:
            entry["exp_avg"] = mu_by_key[key]
            entry["exp_avg_sq"] = nu_by_key[key]
        sd[key] = entry

    meta = {
        "universal_layout_version": UNIVERSAL_LAYOUT_VERSION,
        "param_paths": [k for k, _ in flat],
        "has_optimizer": has_optimizer,
    }
    # scalar optax-chain leaves (adam's bias-correction `count`, loss-scale
    # internals) are topology-free but NOT per-parameter: carry them by flat
    # index so a restore is bit-exact against a native resume — without the
    # count, a restored adam re-runs warmup bias correction and the first
    # post-restore step silently diverges
    opt = tree.get("optimizer") or {}
    scalar_leaves = {}
    for idx in sorted(opt, key=lambda s: int(s) if str(s).isdigit() else -1):
        leaf = opt[idx]
        if leaf is not None and np.ndim(leaf) == 0:
            scalar_leaves[str(idx)] = np.asarray(jax.device_get(leaf))
    if scalar_leaves:
        meta["optimizer_scalar_leaves"] = scalar_leaves
    scalars = tree.get("scalars", {})
    for k in ("step", "loss_scale", "good_steps"):
        if k in scalars:
            meta[k] = np.asarray(jax.device_get(scalars[k])).item()
    # non-array sidecar counters when present in the tree (a live
    # ``_ckpt_state`` tree carries them inline; the disk path merges the
    # meta.pkl sidecar in before calling here). curriculum / random-ltd
    # scheduler state rides along: a warm remesh of a data-efficiency run
    # must resume at the restored step's difficulty / sequence budget, not
    # restart the schedule from scratch (silent divergence from native
    # resume otherwise — the lr_scheduler lesson repeated)
    for k in ("global_steps", "global_samples", "skipped_steps", "lr_scheduler",
              "curriculum_scheduler", "random_ltd_scheduler", "ds_version"):
        if tree.get(k) is not None:
            meta[k] = tree[k]
    return sd, meta


def ds_to_universal(checkpoint_dir, output_dir, tag=None):
    """Convert; returns the number of parameters written (reference main)."""
    path = _resolve_tag(checkpoint_dir, tag)
    tree = _restore_arrays(path)
    # carry non-array sidecar meta (global_steps etc.) from the source ckpt
    src_meta = os.path.join(path, "meta.pkl")
    if os.path.exists(src_meta):
        with open(src_meta, "rb") as f:
            side = pickle.load(f)
        tree = dict(tree)
        for k in ("global_steps", "global_samples", "skipped_steps", "lr_scheduler",
                  "curriculum_scheduler", "random_ltd_scheduler", "ds_version"):
            if k in side and tree.get(k) is None:
                tree[k] = side[k]

    sd, meta = universal_state_from_tree(tree)
    zero_dir = os.path.join(output_dir, "zero")
    os.makedirs(zero_dir, exist_ok=True)
    for key, entry in sd.items():
        pdir = os.path.join(zero_dir, key.replace("/", "."))
        os.makedirs(pdir, exist_ok=True)
        for field in ("fp32", "exp_avg", "exp_avg_sq"):
            if field in entry:
                np.save(os.path.join(pdir, f"{field}.npy"), entry[field])
    with open(os.path.join(output_dir, "universal_meta.pkl"), "wb") as f:
        pickle.dump(meta, f)
    logger.info(f"universal checkpoint: {len(sd)} params -> {output_dir} "
                f"(optimizer={'yes' if meta['has_optimizer'] else 'no'})")
    return len(sd)


def main():
    import argparse

    p = argparse.ArgumentParser(description="Convert an engine checkpoint to universal layout")
    p.add_argument("--input_folder", required=True)
    p.add_argument("--output_folder", required=True)
    p.add_argument("--tag", default=None)
    args = p.parse_args()
    ds_to_universal(args.input_folder, args.output_folder, tag=args.tag)


if __name__ == "__main__":
    main()
