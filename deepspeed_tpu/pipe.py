"""Top-level pipe namespace (reference ``deepspeed/pipe/__init__.py``:
re-exports the pipeline container types)."""

from .runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec  # noqa: F401
