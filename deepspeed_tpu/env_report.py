"""Environment / op-compatibility report (reference ``deepspeed/env_report.py``
— the ``ds_report`` CLI: versions + a matrix of which native ops are
installed/compatible)."""

import importlib
import shutil
import subprocess
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"


def op_report():
    """Rows of (op name, available) for every registered op builder."""
    from .ops import op_registry

    rows = []
    for name, builder in sorted(op_registry.items()):
        rows.append((name, builder.is_compatible()))
    # native toolchain entries (the reference reports nvcc/torch cuda here)
    from .ops.native import is_available as native_ok

    rows.append(("native toolchain (g++)", native_ok()))
    return rows


def version_report():
    rows = []
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = importlib.import_module(mod)
            rows.append((mod, getattr(m, "__version__", "?")))
        except ImportError:
            rows.append((mod, None))
    return rows


def device_report():
    import jax

    try:
        devs = jax.devices()
        return {
            "platform": devs[0].platform if devs else "none",
            "device_count": len(devs),
            "process_count": jax.process_count(),
            "devices": [str(d) for d in devs[:8]],
        }
    except Exception as e:  # no backend available
        return {"platform": f"unavailable ({e})", "device_count": 0, "process_count": 0, "devices": []}


def main(hide_operator_status=False, hide_errors_and_warnings=False):
    import deepspeed_tpu

    print("-" * 64)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 64)
    if not hide_operator_status:
        for name, ok in op_report():
            print(f"{name:.<40} {OKAY if ok else NO}")
    print("-" * 64)
    print("DeepSpeed-TPU general environment info:")
    for mod, ver in version_report():
        print(f"{mod:.<40} {ver if ver else NO}")
    print(f"{'deepspeed_tpu':.<40} {deepspeed_tpu.__version__}")
    dev = device_report()
    print(f"{'platform':.<40} {dev['platform']}")
    print(f"{'device_count':.<40} {dev['device_count']}")
    print(f"{'process_count':.<40} {dev['process_count']}")
    print("-" * 64)


def cli_main():
    main()


if __name__ == "__main__":
    main()
