"""Cross-replica KV-block handoff ledger: the transport half of
disaggregated prefill/decode serving (``serving/disagg.py``).

A handoff moves one request's prefix KV from the prefill replica that
computed it to the decode replica that will finish it, THROUGH the host
tier: the source driver exports functional D2H snapshots of the sequence's
full blocks (``InferenceEngineV2.export_sequence_kv`` — the exact
``read_block`` path tiered demotion rides), this ledger checksums every
block into a manifest, and the destination adopts the payloads as
host-resident radix nodes (``install_prefix_kv``) that the resume's
admission promotes H2D through the standard lookahead promotion pipeline.

The ledger is the never-lose-a-request contract:

  * **at-most-once**: one entry per request id, ever — a second ``begin``
    for the same rid is refused, so a retried or raced handoff can never
    resume one request on two decode replicas;
  * **checksummed**: every block payload is crc32'd at export
    (``record_manifest``) and re-verified before install (``verify``); a
    mismatch — chaos-injected corruption included — fails the handoff
    BEFORE the destination sees a byte of wrong KV;
  * **fallback is terminal and safe**: any failure before the resume
    enqueue leaves the request decoding in place on its prefill replica;
    the ledger records the fallback + reason and the request is never lost
    (zero-unreported, chaos-drilled in ``tests/test_disagg.py``).

State machine (one direction, no retries — retrying would need a second
ledger entry, which at-most-once refuses by design)::

    started ──> exported ──> installed ──> resumed
       │            │             │
       └────────────┴─────────────┴──────> fallback(reason)
"""

import threading
import time
import zlib

import numpy as np

from ..monitor.metrics import get_metrics

__all__ = ["HandoffError", "HandoffLedger"]


class HandoffError(RuntimeError):
    """A handoff step failed — the coordinator falls back to decoding in
    place on the source replica (the request is never lost)."""


def _payload_crc(payload) -> int:
    crc = 0
    for arr in payload:
        if arr is not None:
            crc = zlib.crc32(np.ascontiguousarray(arr).view(np.uint8), crc)
    return crc & 0xFFFFFFFF


class HandoffLedger:
    """Gateway-brokered bookkeeping for every prefill→decode migration.

    Entries are kept for the gateway's lifetime (one small dict per
    migrated request) — that retention IS the at-most-once mechanism, and
    the ``/v1/pools`` endpoint serves the recent ones for operators.
    """

    STATES = ("started", "exported", "installed", "resumed", "fallback")

    def __init__(self, clock=time.perf_counter, keep_entries: int = 256):
        self._clock = clock
        self._lock = threading.Lock()
        self._entries = {}
        self._keep = max(1, int(keep_entries))
        self._lat_s = []  # completed handoff latencies, bounded like entries
        self.stats = {"started": 0, "resumed": 0, "fallbacks": 0,
                      "refused": 0, "blocks_moved": 0, "bytes_moved": 0,
                      "checksum_failures": 0}

    # -- state machine -----------------------------------------------------
    def begin(self, rid: str, src: str, dst) -> bool:
        """Open a handoff. False = REFUSED: this rid has a ledger entry
        already (at-most-once — the request must keep decoding wherever it
        currently lives, no second migration attempt)."""
        with self._lock:
            if rid in self._entries:
                self.stats["refused"] += 1
                return False
            self._entries[rid] = {"state": "started", "src": str(src),
                                  "dst": None if dst is None else str(dst),
                                  "t0": self._clock(), "blocks": 0,
                                  "bytes": 0, "crcs": [], "reason": None}
            self.stats["started"] += 1
        return True

    def record_manifest(self, rid: str, token_chunks, payloads) -> None:
        """Checksum the exported blocks into the entry's manifest."""
        crcs = [_payload_crc(p) for p in payloads]
        nbytes = sum(a.nbytes for p in payloads for a in p if a is not None)
        with self._lock:
            ent = self._entries[rid]
            ent.update(state="exported", blocks=len(payloads), bytes=nbytes,
                       crcs=crcs, n_chunks=len(token_chunks))

    def verify(self, rid: str, payloads) -> bool:
        """Re-checksum ``payloads`` against the manifest — the integrity
        gate between export and install. Any mismatch (corruption in the
        broker's hands) or count drift fails the whole handoff."""
        with self._lock:
            want = list(self._entries[rid]["crcs"])
        ok = (len(payloads) == len(want)
              and all(_payload_crc(p) == c for p, c in zip(payloads, want)))
        if not ok:
            with self._lock:
                self.stats["checksum_failures"] += 1
            get_metrics().counter("handoff/checksum_failures_total").inc()
        return ok

    def mark_installed(self, rid: str, n_blocks: int) -> None:
        with self._lock:
            self._entries[rid].update(state="installed",
                                      installed_blocks=int(n_blocks))

    def mark_resumed(self, rid: str) -> None:
        """The point past no-return succeeded: the request now lives on the
        decode replica. Books the migration's latency + moved volume."""
        with self._lock:
            ent = self._entries[rid]
            dt = self._clock() - ent["t0"]
            ent.update(state="resumed", latency_s=round(dt, 6))
            self.stats["resumed"] += 1
            self.stats["blocks_moved"] += ent["blocks"]
            self.stats["bytes_moved"] += ent["bytes"]
            self._lat_s.append(dt)
            if len(self._lat_s) > self._keep:
                del self._lat_s[:-self._keep]
            blocks = ent["blocks"]
        m = get_metrics()
        m.counter("handoff/completed_total").inc()
        m.counter("handoff/blocks_moved_total").inc(blocks)

    def fail(self, rid: str, reason: str) -> None:
        """Terminal fallback: the request decodes in place on its source
        replica. Idempotent-safe for a rid that never opened (refused
        begin) — that path records nothing."""
        with self._lock:
            ent = self._entries.get(rid)
            if ent is None or ent["state"] in ("resumed", "fallback"):
                return
            ent.update(state="fallback", reason=str(reason)[:200])
            self.stats["fallbacks"] += 1
        get_metrics().counter("handoff/fallback_total").inc()

    # -- queries -----------------------------------------------------------
    def entry(self, rid: str):
        with self._lock:
            ent = self._entries.get(rid)
            return dict(ent) if ent is not None else None

    @property
    def p50_ms(self):
        with self._lock:
            if not self._lat_s:
                return None
            return round(float(np.percentile(np.asarray(self._lat_s), 50)) * 1e3, 3)

    @property
    def fallback_rate(self) -> float:
        with self._lock:
            return self.stats["fallbacks"] / max(1, self.stats["started"])

    def state(self) -> dict:
        with self._lock:
            recent = dict(sorted(self._entries.items())[-self._keep:])
            recent = {rid: {k: v for k, v in e.items() if k != "crcs"}
                      for rid, e in recent.items()}
            stats = dict(self.stats)
            lat = list(self._lat_s)
        out = {**stats, "inflight": sum(1 for e in recent.values()
                                        if e["state"] not in ("resumed",
                                                              "fallback")),
               "handoff_p50_ms": (round(float(np.percentile(
                   np.asarray(lat), 50)) * 1e3, 3) if lat else None),
               "handoff_p99_ms": (round(float(np.percentile(
                   np.asarray(lat), 99)) * 1e3, 3) if lat else None),
               "handoff_fallback_rate": round(
                   stats["fallbacks"] / max(1, stats["started"]), 4),
               "recent": recent}
        return out

    def gauge_rows(self):
        """Labelled rows for the health exporter's ``/metrics`` scrape."""
        rows = [("handoff/started_total", {}, float(self.stats["started"])),
                ("handoff/fallback_rate", {}, float(self.fallback_rate)),
                ("handoff/bytes_moved_total", {},
                 float(self.stats["bytes_moved"]))]
        p50 = self.p50_ms
        if p50 is not None:
            rows.append(("handoff/p50_ms", {}, float(p50)))
        return rows
