"""Causal timeline collector: the serving-side join of every sensor plane.

``monitor/timeline.py`` owns the pure assembly model (segments, overlays,
verdicts); this module owns the LIVE half — collecting, per gateway, the
overlay events no single request carries on itself, and assembling one
:class:`RequestTimeline` dict for every terminal request the moment
reqtrace finalizes it:

  * **stage stamps** ride the request objects themselves (``RequestContext``
    + the ``GatewayRequest`` handoff/resume stamps), all perf_counter;
  * **driver stall gaps** arrive via :meth:`on_stall` from the replica
    drivers (the same measured gap the goodput ledger books as
    ``stalled``);
  * **recompile events** are joined from the recompile sentinel's recent
    ring by request id / engine uid;
  * **chaos fires** are joined from a passive ``chaos.observe`` listener
    (armed only while the gateway runs) by the fire ctx's request id;
  * **control actuations** are joined from the decision log through the
    ``inflight_rids`` roster each decision records at actuation time —
    never by timestamp (decisions stamp ``time.time``; requests stamp
    ``time.perf_counter``; the roster is the one clock-free join key).

The collector is deliberately passive: no thread, no timers — assembly
runs synchronously on whichever driver/handler thread finalizes the
request, bounded by the ring size, and never raises into the driver.
Retention is tail-aware like the request log: beyond the last-N ring, the
worst ``exemplar_slots`` requests by TTFT and by TPOT are ALWAYS retained
(the p99 exemplar a regression hunt needs is exactly the one a ring
forgets first). Zero overhead with the config block absent: the gateway
holds no collector, replicas carry a None, reqtrace's terminal path stays
one attribute check.
"""

import threading
import time
from collections import OrderedDict, deque

from ..monitor.goodput import get_goodput
from ..monitor.timeline import assemble_timeline
from ..runtime.resilience import chaos

__all__ = ["TimelineCollector"]

# stalls are bounded per replica (a wedged drill can fire repeatedly);
# chaos fires bounded fleet-wide — both are JOIN sources, not archives
_STALLS_PER_REPLICA = 64
_CHAOS_RING = 128


class TimelineCollector:
    """Per-gateway assembler state. One instance when the
    ``serving.gateway.timeline`` block is present; replicas get it via
    ``set_timeline`` and reqtrace holds it for terminal assembly."""

    def __init__(self, config):
        self.config = config
        self._lock = threading.Lock()
        self._ring = OrderedDict()  # rid -> timeline, bounded config.last_n
        # kind -> {rid: (value_ms, timeline)}: the always-retained tail
        self._exemplars = {"ttft": {}, "tpot": {}}
        self._stalls = {}  # replica name -> deque[(t0, t1)] perf_counter
        self._chaos = deque(maxlen=_CHAOS_RING)
        self._decisions_provider = None
        self._chaos_handle = None
        self.stats = {"assembled": 0, "coverage_failures": 0, "errors": 0}

    # -- wiring (gateway start/stop) ------------------------------------
    def set_decisions_provider(self, fn):
        """``fn() -> recent decision records`` (the control plane's ring);
        None with the control block absent — actuation joins just no-op."""
        self._decisions_provider = fn

    def arm(self):
        """Install the passive chaos-fire listener (gateway start)."""
        if self._chaos_handle is None:
            self._chaos_handle = chaos.observe(self._on_chaos_fire)

    def disarm(self):
        """Remove the listener + drop join state (gateway stop)."""
        if self._chaos_handle is not None:
            self._chaos_handle.remove()
            self._chaos_handle = None
        with self._lock:
            self._stalls.clear()
            self._chaos.clear()

    # -- overlay event feeds --------------------------------------------
    def on_stall(self, replica_name, t0, gap_s):
        """One measured driver stall gap (replica driver thread, same
        detection the goodput ledger books as ``stalled``)."""
        with self._lock:
            dq = self._stalls.get(replica_name)
            if dq is None:
                dq = self._stalls[replica_name] = deque(maxlen=_STALLS_PER_REPLICA)
            dq.append((t0, t0 + gap_s))

    def _on_chaos_fire(self, point, ctx):
        """chaos.observe listener — runs on the firing thread BEFORE the
        hooks, so even a kill fire lands in the join ring."""
        rid = None
        if isinstance(ctx, dict):
            rid = ctx.get("request_id") or ctx.get("rid")
        with self._lock:
            self._chaos.append({"point": str(point), "t": time.perf_counter(),
                                "request_id": rid})

    # -- joins -----------------------------------------------------------
    def _join_stalls(self, replicas, t_recv, t_done):
        out = []
        with self._lock:
            for name in replicas:
                for (s0, s1) in self._stalls.get(name, ()):
                    if s1 >= t_recv and s0 <= t_done:
                        out.append((s0, s1))
        return out

    def _join_recompiles(self, rid, uid, t_recv, t_done):
        out = []
        for sc in get_goodput().sentinel.report().values():
            for ev in sc.get("recent", ()):
                if not (t_recv <= float(ev.get("t", 0.0)) <= t_done):
                    continue
                if rid in (ev.get("rids") or ()) or uid in (ev.get("uids") or ()):
                    out.append(ev)
        return out

    def _join_chaos(self, rid, t_recv, t_done):
        with self._lock:
            fires = list(self._chaos)
        return [{"point": f["point"],
                 "t_ms": round((f["t"] - t_recv) * 1e3, 3)}
                for f in fires
                if f["request_id"] == rid and t_recv <= f["t"] <= t_done]

    def _join_actuations(self, rid):
        provider = self._decisions_provider
        if provider is None:
            return []
        # join key: the in-flight roster the controller stamped at
        # actuation time — decisions live on the time.time clock, so a
        # timestamp window against perf_counter stamps would be garbage
        return [d for d in provider()
                if d.get("applied") and rid in (d.get("inflight_rids") or ())]

    # -- assembly (reqtrace terminal path) -------------------------------
    def assemble(self, req, record):
        """Assemble + retain the timeline of one ADMITTED terminal request.
        Runs on the finalizing thread (driver/handler/stop path) — never
        raises into it."""
        try:
            ctx = req.ctx
            stamps = {
                "t_recv": ctx.t_recv, "t_admitted": ctx.t_admitted,
                "t_dequeued": ctx.t_dequeued,
                "t_first_token": ctx.t_first_token,
                "t_last_token": ctx.t_last_token, "t_done": ctx.t_done,
                "t_handoff_start": req.t_handoff_start,
                "t_handoff_export": req.t_handoff_export,
                "t_handoff_verify": req.t_handoff_verify,
                "t_handoff_done": req.t_handoff_done,
                "t_resume_enqueued": req.t_resume_enqueued,
                "t_resume_submitted": req.t_resume_submitted,
            }
            if ctx.t_recv is None or ctx.t_done is None:
                return
            replicas = {n for n in (record.get("replica"), ctx.route_choice)
                        if n is not None}
            tl = assemble_timeline(
                stamps, record=record,
                stalls=self._join_stalls(replicas, ctx.t_recv, ctx.t_done),
                recompiles=self._join_recompiles(ctx.rid, req.uid,
                                                 ctx.t_recv, ctx.t_done),
                chaos_fires=self._join_chaos(ctx.rid, ctx.t_recv, ctx.t_done),
                actuations=self._join_actuations(ctx.rid),
                tolerance=self.config.tolerance)
            self._store(tl, record)
        except Exception:  # noqa: BLE001 — assembly is forensics: it must
            # cost the timeline, never the driver loop behind it
            self.stats["errors"] += 1

    def assemble_rejected(self, ctx, record):
        """Refused-before-admission terminal (400/429/503): the timeline is
        the ingress/queue stub — still assembled, so 'every terminal
        request has one' holds for the shed tail too."""
        try:
            if ctx.t_recv is None or ctx.t_done is None:
                return
            stamps = {"t_recv": ctx.t_recv, "t_admitted": ctx.t_admitted,
                      "t_dequeued": ctx.t_dequeued,
                      "t_first_token": ctx.t_first_token,
                      "t_last_token": ctx.t_last_token, "t_done": ctx.t_done}
            tl = assemble_timeline(stamps, record=record,
                                   actuations=self._join_actuations(ctx.rid),
                                   tolerance=self.config.tolerance)
            self._store(tl, record)
        except Exception:  # noqa: BLE001
            self.stats["errors"] += 1

    def _store(self, tl, record):
        with self._lock:
            self.stats["assembled"] += 1
            if not tl["coverage_ok"]:
                self.stats["coverage_failures"] += 1
            rid = tl.get("request_id")
            if rid is not None:
                self._ring[rid] = tl
                self._ring.move_to_end(rid)
                while len(self._ring) > self.config.last_n:
                    self._ring.popitem(last=False)
            slots = int(self.config.exemplar_slots)
            if slots > 0 and rid is not None:
                for kind in ("ttft", "tpot"):
                    v = record.get(f"{kind}_ms")
                    if v is None:
                        continue
                    pool = self._exemplars[kind]
                    if rid in pool or len(pool) < slots:
                        pool[rid] = (float(v), tl)
                        continue
                    worst_floor = min(pool, key=lambda r: pool[r][0])
                    if float(v) > pool[worst_floor][0]:
                        del pool[worst_floor]
                        pool[rid] = (float(v), tl)

    # -- read side -------------------------------------------------------
    def get(self, rid):
        """One assembled timeline by request id: the ring first, then the
        always-retained tail exemplars (a p99 request must stay
        addressable after the ring forgot it)."""
        with self._lock:
            tl = self._ring.get(rid)
            if tl is not None:
                return tl
            for pool in self._exemplars.values():
                hit = pool.get(rid)
                if hit is not None:
                    return hit[1]
        return None

    def recent(self, n=None):
        """Newest-last assembled timelines from the ring."""
        with self._lock:
            out = list(self._ring.values())
        return out[-int(n):] if n else out

    def exemplars(self):
        """The retained tail, worst-first per kind."""
        with self._lock:
            return {kind: [{"request_id": rid, "value_ms": v,
                            "timeline": tl}
                           for rid, (v, tl) in sorted(pool.items(),
                                                      key=lambda kv: -kv[1][0])]
                    for kind, pool in self._exemplars.items()}

    def state(self) -> dict:
        with self._lock:
            return {**self.stats, "ring": len(self._ring),
                    "last_n": self.config.last_n,
                    "tolerance": self.config.tolerance,
                    "exemplars": {k: len(p) for k, p in self._exemplars.items()},
                    "chaos_observer_armed": self._chaos_handle is not None}

    def gauge_rows(self):
        """Labelled rows for the health exporter's ``/metrics`` scrape."""
        with self._lock:
            return [("timeline/assembled_total", {},
                     float(self.stats["assembled"])),
                    ("timeline/coverage_failures_total", {},
                     float(self.stats["coverage_failures"])),
                    ("timeline/errors_total", {}, float(self.stats["errors"])),
                    ("timeline/ring_size", {}, float(len(self._ring)))]
