"""Prefix-aware multi-replica router.

Placement policy for N engine replicas, in preference order:

  * ``prefix`` — score each LIVE replica by how many prompt tokens its
    radix tree could serve, using the pure read-only
    ``PrefixKVCache.match`` (via ``EngineReplica.prefix_overlap``) as the
    routing oracle: the walk takes no references, touches no LRU clock,
    and bumps no stats, so routing N candidates costs N tree walks and
    ZERO cache mutations. Highest overlap wins; ties (including the
    all-zero cold-start case) fall back to least-loaded.
  * ``least_loaded`` — smallest (scheduler-inflight + queued) count.
  * ``random`` — uniform over live replicas (the A/B control the bench
    measures the prefix policy against).

Liveness comes from the PR 5 health plane: a replica whose
``serving:<name>`` heartbeat tripped the stall watchdog (or whose driver
thread died) is excluded from placement until a fresh beat re-arms it —
so a wedged replica sheds to its siblings instead of black-holing
requests.

Disaggregated pools (``serving/disagg.py``): when any replica carries a
non-``mixed`` role, NEW requests place only onto prefill-capable replicas
(``prefill``/``mixed``) — decode replicas receive work through the KV
handoff, not the front door. The prefix oracle still scores the WHOLE
live fleet: the host tier is fleet-shared state (a handed-off chain is
promotable from any replica's host pool after adoption), so a hit
anywhere counts as ``fleet_prefix_hits`` even when placement is
restricted to the prefill pool.
"""

from typing import List, Optional

import numpy as np


class ReplicaRouter:

    def __init__(self, replicas: List, policy: str = "prefix", seed: int = 0):
        if policy not in ("prefix", "least_loaded", "random"):
            raise ValueError(f"unknown router policy {policy!r}: "
                             "'prefix' | 'least_loaded' | 'random'")
        self.replicas = list(replicas)
        self.policy = policy
        self._rng = np.random.default_rng(seed)
        self.stats = {"routed": 0, "prefix_hits": 0, "fallback_least_loaded": 0,
                      "no_live_replica": 0, "fleet_prefix_hits": 0,
                      "pool_restricted": 0}

    def live(self) -> List:
        return [r for r in self.replicas if r.alive]

    def _placement_pool(self, live: List) -> List:
        """Role-restricted placement candidates: with disaggregated pools,
        new requests go to prefill-capable replicas only. Every live
        replica mixed (or no role attr at all) = the full live set; an
        all-decode live fleet also falls back to the full set — degraded
        placement beats a 503. Control-plane-drained replicas are skipped
        while any un-draining candidate exists (a lone drained fleet still
        takes placements — degraded beats a 503 here too; the queue then
        holds the work the controller's un-drain will release)."""
        if not all(getattr(r, "role", "mixed") == "mixed" for r in live):
            pool = [r for r in live
                    if getattr(r, "role", "mixed") in ("prefill", "mixed")]
            if pool and len(pool) < len(live):
                self.stats["pool_restricted"] += 1
            live = pool or live
        undrained = [r for r in live if not getattr(r, "draining", False)]
        return undrained or live

    def select(self, prompt_tokens, ctx=None) -> Optional[object]:
        """Pick the replica for a prompt; None when no replica is live.
        With a request-tracing ``ctx``, the candidate scores that justified
        the placement are recorded on it (the gateway emits them as the
        router-decision instant) — pure bookkeeping, no tracer calls here."""
        live = self.live()
        if not live:
            self.stats["no_live_replica"] += 1
            return None
        self.stats["routed"] += 1
        cands = self._placement_pool(live)
        if self.policy == "random":
            chosen = cands[int(self._rng.integers(len(cands)))]
            if ctx is not None:
                ctx.route_policy, ctx.route_scores = self.policy, {}
            return chosen
        if self.policy == "prefix":
            # score the WHOLE live fleet (the fleet-wide radix oracle over
            # shared host-tier state), place within the candidate pool
            scores = {r.name: r.prefix_overlap(prompt_tokens) for r in live}
            if ctx is not None:
                ctx.route_policy = self.policy
                ctx.route_scores = {n: int(s) for n, s in scores.items()}
            if max(scores.values()) > 0:
                self.stats["fleet_prefix_hits"] += 1
            best = max(scores[r.name] for r in cands)
            if best > 0:
                self.stats["prefix_hits"] += 1
                # ties on overlap (two replicas both hold the hot prefix)
                # break by load, so affinity never builds a hotspot
                tied = [r for r in cands if scores[r.name] == best]
                return min(tied, key=lambda r: r.load)
            self.stats["fallback_least_loaded"] += 1
        if ctx is not None and ctx.route_policy is None:
            ctx.route_policy = self.policy
            ctx.route_scores = {r.name: int(r.load) for r in cands}
        return min(cands, key=lambda r: r.load)

    def state(self) -> dict:
        return {"policy": self.policy,
                "replicas": [r.name for r in self.replicas],
                "live": [r.name for r in self.live()],
                "roles": {r.name: getattr(r, "role", "mixed")
                          for r in self.replicas},
                **self.stats}
