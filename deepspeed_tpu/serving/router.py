"""Prefix-aware multi-replica router.

Placement policy for N engine replicas, in preference order:

  * ``prefix`` — score each LIVE replica by how many prompt tokens its
    radix tree could serve, using the pure read-only
    ``PrefixKVCache.match`` (via ``EngineReplica.prefix_overlap``) as the
    routing oracle: the walk takes no references, touches no LRU clock,
    and bumps no stats, so routing N candidates costs N tree walks and
    ZERO cache mutations. Highest overlap wins; ties (including the
    all-zero cold-start case) fall back to least-loaded.
  * ``least_loaded`` — smallest (scheduler-inflight + queued) count.
  * ``random`` — uniform over live replicas (the A/B control the bench
    measures the prefix policy against).

Liveness comes from the PR 5 health plane: a replica whose
``serving:<name>`` heartbeat tripped the stall watchdog (or whose driver
thread died) is excluded from placement until a fresh beat re-arms it —
so a wedged replica sheds to its siblings instead of black-holing
requests.
"""

from typing import List, Optional

import numpy as np


class ReplicaRouter:

    def __init__(self, replicas: List, policy: str = "prefix", seed: int = 0):
        if policy not in ("prefix", "least_loaded", "random"):
            raise ValueError(f"unknown router policy {policy!r}: "
                             "'prefix' | 'least_loaded' | 'random'")
        self.replicas = list(replicas)
        self.policy = policy
        self._rng = np.random.default_rng(seed)
        self.stats = {"routed": 0, "prefix_hits": 0, "fallback_least_loaded": 0,
                      "no_live_replica": 0}

    def live(self) -> List:
        return [r for r in self.replicas if r.alive]

    def select(self, prompt_tokens, ctx=None) -> Optional[object]:
        """Pick the replica for a prompt; None when no replica is live.
        With a request-tracing ``ctx``, the candidate scores that justified
        the placement are recorded on it (the gateway emits them as the
        router-decision instant) — pure bookkeeping, no tracer calls here."""
        live = self.live()
        if not live:
            self.stats["no_live_replica"] += 1
            return None
        self.stats["routed"] += 1
        if self.policy == "random":
            chosen = live[int(self._rng.integers(len(live)))]
            if ctx is not None:
                ctx.route_policy, ctx.route_scores = self.policy, {}
            return chosen
        if self.policy == "prefix":
            scores = [r.prefix_overlap(prompt_tokens) for r in live]
            if ctx is not None:
                ctx.route_policy = self.policy
                ctx.route_scores = {r.name: int(s) for r, s in zip(live, scores)}
            best = max(scores)
            if best > 0:
                self.stats["prefix_hits"] += 1
                # ties on overlap (two replicas both hold the hot prefix)
                # break by load, so affinity never builds a hotspot
                cands = [r for r, s in zip(live, scores) if s == best]
                return min(cands, key=lambda r: r.load)
            self.stats["fallback_least_loaded"] += 1
        if ctx is not None and ctx.route_policy is None:
            ctx.route_policy = self.policy
            ctx.route_scores = {r.name: int(r.load) for r in live}
        return min(live, key=lambda r: r.load)

    def state(self) -> dict:
        return {"policy": self.policy,
                "replicas": [r.name for r in self.replicas],
                "live": [r.name for r in self.live()],
                **self.stats}
