"""Disaggregated prefill/decode serving: role-typed replica pools with
cross-replica KV handoff through the host tier.

Prefill is compute-bound and bursty; decode is bandwidth-bound and steady —
co-locating them forces SplitFuse to arbitrate, and one long prefill still
inflates decode TPOT tails (PR 7's p99 stage attribution). This module
splits the fleet: replicas carry a ``role`` (``prefill`` / ``decode`` /
``mixed``, ``serving/config.py``'s ``disagg`` block), the router places new
requests on the prefill pool, and once a request's prefill completes (its
first token proves it) the :class:`DisaggCoordinator` migrates the
request's KV to a decode replica and resumes it there — the DeepSpeed-
FastGen/MII successor architecture (SURVEY.md § inference v2).

The migration rides PR 17's tiered store as transport: the source driver
D2H-exports the sequence's full blocks (``engine.export_sequence_kv``),
the :class:`~deepspeed_tpu.serving.handoff.HandoffLedger` checksums and
brokers ownership (at-most-once, fallback-in-place — never a lost
request), the destination adopts them as host-tier radix nodes
(``engine.install_prefix_kv``), and the resume's admission promotes H2D
through the standard lookahead promotion pipeline. Because the adopted
chain is ordinary fleet-visible radix state, every decode replica also
gains the migrated prefix for FUTURE requests — cross-replica prefix
sharing falls out of the same mechanism.

Threading: ``try_handoff`` runs on the SOURCE replica's driver thread
(from ``_fanout``), which is what makes ``export_sequence_kv`` (a device
op) and ``detach_request`` (scheduler surgery) legal without extra locks.
Everything that touches the DESTINATION is host-memory-only
(``install_prefix_kv`` under the dest tree lock; ``enqueue_resume`` is a
list append) — the decode replica's driver is never blocked by a
migration. Chaos point ``serving/handoff`` sits between export and
verify: a hook can raise (transport loss) or swap a corrupted payload into
the manifest's list (the checksum gate must catch it); either way the
request falls back to decoding in place on the source.
"""

import time

import numpy as np

from ..monitor.trace import get_tracer, observe_latency
from ..runtime.resilience import chaos
from .handoff import HandoffError, HandoffLedger

__all__ = ["DisaggCoordinator", "ROLES"]

ROLES = ("prefill", "decode", "mixed")


class DisaggCoordinator:
    """Gateway-owned broker for the prefill→decode migrations of one fleet.

    One instance per gateway when ``serving.gateway.disagg`` is present;
    replicas get it via ``set_disagg`` and call :meth:`try_handoff` from
    their drivers. Stateless beyond the ledger — destination choice is
    least-loaded at migration time, no sticky assignment.
    """

    def __init__(self, replicas, config, ledger=None):
        self.replicas = list(replicas)
        self.config = config
        self.ledger = ledger if ledger is not None else HandoffLedger()
        self.stats = {"attempted": 0, "migrated": 0, "fallbacks": 0}

    # -- pool topology -----------------------------------------------------
    def roles(self):
        return {r.name: r.role for r in self.replicas}

    def pools(self):
        out = {role: [] for role in ROLES}
        for r in self.replicas:
            out.setdefault(r.role, []).append(r.name)
        return {role: names for role, names in out.items() if names}

    @property
    def handoff_after_tokens(self) -> int:
        return max(1, int(getattr(self.config, "handoff_after_tokens", 1)))

    def wants_handoff(self, replica) -> bool:
        """Only dedicated prefill replicas push work away; mixed replicas
        keep their requests (they ARE the co-located baseline)."""
        return replica.role == "prefill"

    def pick_decode_replica(self, src):
        """Least-loaded live decode-capable replica, EXCLUDING saturated
        and control-drained ones. Saturation reads the decode pool's own
        back-pressure signal (``load`` = scheduler-inflight + admission
        queue depth, against ``max_inflight``): a decode replica already
        at capacity would queue the migrated request behind a backlog,
        which is strictly worse than decoding in place on the source —
        an all-saturated pool therefore returns None and the caller's
        fallback-in-place path takes over."""
        cands = [r for r in self.replicas
                 if r is not src and r.alive and r.role in ("decode", "mixed")
                 and not getattr(r, "draining", False)
                 and r.load < getattr(r, "max_inflight", float("inf"))]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.load, r.name))

    # -- the migration -----------------------------------------------------
    def try_handoff(self, src, req, generated) -> bool:
        """Migrate one request whose prefill just completed on ``src``.
        Runs on ``src``'s driver thread. True = the request now lives on a
        decode replica (the caller must NOT touch it again); False = the
        handoff fell back and the request keeps decoding in place on
        ``src`` — every failure path lands here, never a lost request."""
        rid = req.rid or f"uid-{req.uid}"
        t0 = time.perf_counter()
        # timeline stage stamps (one perf_counter clock, stored on the
        # request): the handoff window decomposes into export -> verify ->
        # install so the assembler can name WHICH broker stage ate a
        # migrated request's gap instead of hiding it inside decode
        req.t_handoff_start = t0
        self.stats["attempted"] += 1
        dst = self.pick_decode_replica(src)
        if not self.ledger.begin(rid, src.name, dst.name if dst else None):
            # at-most-once refusal: this rid already has a ledger entry
            # (an earlier attempt got somewhere) — decode wherever it is.
            # No broker work happened, so no handoff interval to attribute.
            req.t_handoff_start = None
            return False
        try:
            if dst is None:
                raise HandoffError("no_live_decode_replica")
            tokens = np.concatenate([
                np.asarray(req.prompt, np.int32).reshape(-1),
                np.asarray(generated, np.int32).reshape(-1)])
            chunks, payloads = src.engine.export_sequence_kv(req.uid, tokens)
            self.ledger.record_manifest(rid, chunks, payloads)
            req.t_handoff_export = time.perf_counter()
            get_tracer().complete(
                "serving/handoff_export", t0, req.t_handoff_export - t0,
                tid="serving", args={"request_id": rid, "src": src.name,
                                     "blocks": len(payloads)})
            # chaos drill: a hook here can raise (transport loss) or swap a
            # corrupted payload into the list (the verify gate must catch it)
            chaos.fire("serving/handoff", {"rid": rid, "request_id": rid,
                                           "src": src.name, "dst": dst.name,
                                           "payloads": payloads})
            if not self.ledger.verify(rid, payloads):
                raise HandoffError("checksum_mismatch")
            req.t_handoff_verify = time.perf_counter()
            get_tracer().complete(
                "serving/broker_verify", req.t_handoff_export,
                req.t_handoff_verify - req.t_handoff_export, tid="serving",
                args={"request_id": rid, "src": src.name, "dst": dst.name})
            installed = dst.engine.install_prefix_kv(chunks, payloads,
                                                     tenant=req.tenant)
            self.ledger.mark_installed(rid, installed)
            remaining = int(req.max_new_tokens) - int(len(generated))
            # ---- point of no return: detach is driver-thread-local (we
            # ARE src's driver) and the enqueue is an infallible append —
            # past here the request lives on dst, exactly once
            src.detach_request(req.uid)
            dst.enqueue_resume(req, tokens, remaining)
            self.ledger.mark_resumed(rid)
            self.stats["migrated"] += 1
            dt = observe_latency(t0, "serving/handoff",
                                 hist_name="handoff/latency_ms",
                                 span_args={"request_id": rid, "src": src.name,
                                            "dst": dst.name,
                                            "blocks": len(payloads)})
            # summary-record visibility (the PR 18 residual): the broker's
            # whole wall cost rides the request without the plane armed
            req.handoff_ms = dt * 1e3
            src.book_handoff(dt)
            return True
        except Exception as e:  # noqa: BLE001 — every failure = fallback
            # ledger.fail owns the handoff/fallback_total counter
            self.ledger.fail(rid, f"{type(e).__name__}: {e}")
            self.stats["fallbacks"] += 1
            dt = time.perf_counter() - t0
            # the fallback's decode-in-place resumes HERE: the timeline's
            # decode_fallback segment opens at the failed broker's exit
            req.t_handoff_done = t0 + dt
            req.handoff_ms = dt * 1e3
            src.book_handoff(dt)
            return False

    def state(self) -> dict:
        return {"pools": self.pools(), "roles": self.roles(),
                **self.stats, "handoff": self.ledger.state()}
