"""Replica driver: one background thread per engine running the SplitFuse
put/decode loop and fanning generated tokens out to per-request streams.

The driver owns the ONLY thread that touches its engine (JAX dispatch,
scheduler state): the HTTP handlers and the admission path never call into
the engine's forward — they enqueue work and read from
:class:`TokenStream`s. A slow (or absent) stream consumer therefore cannot
stall the decode loop: ``TokenStream.push`` never blocks, and the stream's
buffer is bounded by the request's own ``max_new_tokens`` (which admission
capped), so a stalled client costs one bounded buffer, not batch progress.

Liveness rides the PR 5 health plane: while a replica has work its driver
beats the instance-qualified ``serving:<name>`` source every loop (the
family deadline ``monitor.health.deadline_serving_s`` applies via the
prefix fallback), and the engine's own ``put``/``decode`` begin/end the
``serving`` source around each forward — a wedged device call or a wedged
driver both trip the stall watchdog with a full forensic dump.
"""

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..monitor.flight import get_flight_recorder
from ..monitor.goodput import get_goodput
from ..monitor.health import get_health
from ..monitor.metrics import get_metrics
from ..inference.v2 import DynamicSplitFuseScheduler
from ..runtime.resilience import chaos


class TokenStream:
    """Bounded single-producer / single-consumer token queue for ONE request.

    The replica driver pushes token batches (never blocking — overflow past
    ``capacity`` is counted and dropped, though with ``capacity ==
    max_new_tokens`` it is unreachable); the HTTP handler drains at the
    client's pace. ``finish`` latches the terminal state exactly once.
    """

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._tokens: List[int] = []   # produced tokens, in order
        self._cursor = 0               # consumer read position
        self._cond = threading.Condition()
        self.done = False
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.dropped = 0
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None

    @property
    def produced(self) -> int:
        return len(self._tokens)

    def push(self, tokens) -> int:
        """Append ``tokens`` (non-blocking). Returns how many were kept.
        A finished stream drops everything — ``finish`` latches the
        terminal state, so a late producer cannot make the final frame's
        ``n_tokens`` disagree with the token list a reader collects."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            return 0
        now = time.perf_counter()
        with self._cond:
            if self.done:
                self.dropped += len(tokens)
                return 0
            space = self.capacity - len(self._tokens)
            kept = tokens[:max(0, space)]
            self.dropped += len(tokens) - len(kept)
            if kept:
                if self.first_token_t is None:
                    self.first_token_t = now
                self.last_token_t = now
                self._tokens.extend(kept)
                self._cond.notify_all()
        return len(kept)

    def finish(self, reason: str = "length", error: Optional[str] = None):
        with self._cond:
            if self.done:
                return
            self.done = True
            self.finish_reason = reason
            self.error = error
            self._cond.notify_all()

    def get(self, timeout: Optional[float] = None):
        """Drain everything available (blocking up to ``timeout`` for the
        first new token). Returns ``(tokens, done)`` — ``([], done)`` on
        timeout, so the caller can distinguish 'no progress' from 'over'."""
        with self._cond:
            if self._cursor >= len(self._tokens) and not self.done:
                self._cond.wait(timeout)
            out = self._tokens[self._cursor:]
            self._cursor += len(out)
            return out, self.done and self._cursor >= len(self._tokens)

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._cond:
            while not self.done:
                rem = None if deadline is None else deadline - time.perf_counter()
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(rem)
            return True

    def all_tokens(self) -> List[int]:
        with self._cond:
            return list(self._tokens)


class GatewayRequest:
    """One admitted request's lifecycle record (admission -> stream)."""

    __slots__ = ("uid", "prompt", "max_new_tokens", "slo_class", "eos_token_id",
                 "stream", "replica_name", "t_admitted", "cached_tokens",
                 "uncached_tokens", "ttft_ms", "tpot_ms", "rid", "ctx", "sampling",
                 "tenant", "resume_base", "handoff_state",
                 "t_handoff_start", "t_handoff_export", "t_handoff_verify",
                 "t_handoff_done", "t_resume_enqueued", "t_resume_submitted",
                 "handoff_ms", "resume_wait_ms")

    def __init__(self, uid, prompt, max_new_tokens, slo_class, eos_token_id=None,
                 rid=None, ctx=None, sampling=None, tenant=None):
        self.uid = int(uid)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.slo_class = str(slo_class)
        self.eos_token_id = eos_token_id
        self.sampling = sampling  # SamplingParams | None (= greedy)
        self.stream = TokenStream(capacity=self.max_new_tokens)
        self.replica_name = None
        self.t_admitted = None
        self.cached_tokens = 0    # prefix-cache credit measured at admission
        self.uncached_tokens = 0  # what admission actually charged
        self.ttft_ms = None
        self.tpot_ms = None
        # request id: always present (echoed on the X-Request-Id response
        # header + SSE meta); ctx only when request tracing is configured
        self.rid = rid
        self.ctx = ctx
        # sanitized tenant identity (X-Tenant-Id, DEFAULT_TENANT when
        # absent): always carried so the request log and SSE meta can name
        # the owner; the METER only exists when the config block asks
        self.tenant = tenant
        # disaggregated-serving migration state (serving/disagg.py):
        # resume_base = tokens the stream already held when this request
        # resumed on a decode replica (its scheduler counts from 0 again);
        # handoff_state latches the one migration attempt — None (never
        # tried) | 'migrated' | 'fallback' (failed, decoding in place)
        self.resume_base = 0
        self.handoff_state = None
        # migration stage stamps, all on perf_counter (the one-clock rule
        # the timeline assembler's segments-sum acceptance rests on):
        # broker boundaries stamped by DisaggCoordinator.try_handoff,
        # resume boundaries by the DESTINATION replica. Plain float slots,
        # always stamped when a migration runs — handoff_ms/resume_wait_ms
        # reach the summary record and SSE final frame WITHOUT the timeline
        # plane armed (the PR 18 residual)
        self.t_handoff_start = None
        self.t_handoff_export = None
        self.t_handoff_verify = None
        self.t_handoff_done = None   # failure path only (fallback-in-place)
        self.t_resume_enqueued = None
        self.t_resume_submitted = None
        self.handoff_ms = None
        self.resume_wait_ms = None


class EngineReplica:
    """Driver thread + SplitFuse scheduler over ONE ``InferenceEngineV2``."""

    # bounded idle wait between wake polls: purely a backstop — submit()
    # sets the wake event, so admit latency does not ride this; short
    # enough that pause()/stop() stay responsive, long enough that an idle
    # fleet of replicas is not spinning on the admission lock
    IDLE_WAIT_S = 0.05

    def __init__(self, name, engine, admission, config, reqtrace=None, meter=None,
                 role="mixed"):
        self.name = str(name)
        self.engine = engine
        self.config = config
        # disaggregated pool role (serving/disagg.py): "prefill" replicas
        # push completed prefills to the decode pool through the KV handoff;
        # "mixed" (the default) is the co-located baseline and never migrates
        self.role = str(role)
        self._disagg = None  # DisaggCoordinator, wired by the gateway
        self._timeline = None  # TimelineCollector, wired by the gateway
        self._resume_lock = threading.Lock()
        self._resumes = []  # (req, tokens, remaining) adopted migrations
        self._admission = admission
        self._reqtrace = reqtrace
        # tenant metering plane (serving/metering.py): compute-seconds via
        # the step observer, queue-seconds at dequeue, terminal accounting
        # at close-out. None keeps every site at one attribute check and
        # attaches NOTHING to the engine (the zero-overhead-off contract).
        self._meter = meter
        if meter is not None:
            # per-block owner stamps + prefix-hit attribution ride the
            # engine's own lifecycle hooks — wired through the ONE public
            # entry (the check_gateway_api contract keeps the request
            # plane out of engine internals)
            engine.set_tenant_meter(meter)
        self._scheduler = DynamicSplitFuseScheduler(
            engine, token_budget=config.token_budget or None)
        if reqtrace is not None or meter is not None:
            # per-chunk prefill attribution + per-tenant compute-second
            # apportionment ride the scheduler's step observer (None by
            # default — the un-traced, un-metered path is untouched)
            self._scheduler.step_observer = self._on_sched_step
        self._max_inflight = (config.max_inflight_per_replica
                              or engine.max_concurrent_sequences)
        # total KV blocks a lone request may reserve: measured on the idle
        # engine (free + evictable = the whole usable pool), so validation
        # can refuse requests the scheduler could NEVER admit (they would
        # otherwise sit in the pending queue forever)
        self.pool_blocks = engine.available_blocks
        self._streams: Dict[int, GatewayRequest] = {}
        self._inflight = 0  # requests submitted to the scheduler, not finished
        self._cancel_lock = threading.Lock()
        self._cancelled = []  # uids handed back by timed-out/gone clients
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self.paused = False
        # controller-driven drain: distinct from ``paused`` (tests and the
        # gateway drain pause replicas that must KEEP receiving placements
        # so queues build); the router skips draining replicas whenever an
        # un-draining live alternative exists
        self.draining = False
        self.started = False
        self.warmed = False
        self.steps = 0
        # goodput ledger (attached post-warmup in start(); None = one check
        # per loop iteration, the PR 5 zero-overhead contract)
        self._goodput = None
        self._gp_death_t = None

    # -- public surface the gateway/router/tests drive ---------------------
    @property
    def alive(self) -> bool:
        if not (self.started and self._thread is not None and self._thread.is_alive()):
            return False
        hb = get_health()
        if hb.enabled:
            entry = hb.heartbeats().get(self.heartbeat_source)
            if entry is not None and entry["tripped"]:
                return False
        return True

    @property
    def heartbeat_source(self) -> str:
        return f"serving:{self.name}"

    @property
    def load(self) -> int:
        """Scheduler-inflight + class-queued requests bound for this replica
        (the router's least-loaded signal)."""
        return self._inflight + self._admission.depth(replica=self.name)

    @property
    def max_inflight(self) -> int:
        """Concurrent-request capacity (the saturation denominator the
        disagg coordinator and control plane compare ``load`` against)."""
        return self._max_inflight

    def spec_params(self):
        """Live speculative knobs (``{"k", "tree_width"}``) or None when
        this replica is not speculating — the control plane's read side."""
        return self._scheduler.spec_params()

    def set_spec_params(self, k=None, tree_width=None):
        """Control-plane actuator: retarget speculative K / tree width for
        future draft rounds (scheduler forwarder — the request plane stays
        out of scheduler internals per the check_gateway_api contract).
        Returns the applied params, or None when not speculating."""
        return self._scheduler.set_spec_params(k=k, tree_width=tree_width)

    def prefix_overlap(self, prompt_tokens) -> int:
        """Routing oracle: tokens of ``prompt_tokens`` this replica's radix
        tree could serve, via the PURE read-only ``PrefixKVCache.match`` —
        no references taken, no LRU touch, no stats."""
        pc = self.engine.prefix_cache
        if pc is None:
            return 0
        return int(pc.match(np.asarray(prompt_tokens, np.int32).reshape(-1)).n_cached_tokens)

    def inflight_summaries(self):
        """Last-resort forensics: one summary row per request this replica
        is currently serving (queued-to-scheduler or decoding) — the rows a
        stall dump needs to NAME the requests on a wedged replica."""
        now = time.perf_counter()
        out = []
        for uid, req in list(self._streams.items()):
            row = {"request_id": req.rid, "uid": uid, "replica": self.name,
                   "tenant": req.tenant, "slo_class": req.slo_class,
                   "prompt_tokens": int(req.prompt.size),
                   "max_new_tokens": req.max_new_tokens,
                   "produced": req.stream.produced,
                   "age_ms": (round((now - req.t_admitted) * 1e3, 1)
                              if req.t_admitted else None)}
            if req.ctx is not None:
                row.update({"prefix_hit_tokens": req.ctx.prefix_hit_tokens,
                            "prefill_chunks": req.ctx.prefill_chunks})
            out.append(row)
        return out

    def _on_sched_step(self, uids, chunk_sizes, t0, dur, kind="put"):
        """Scheduler step observer: apportion one engine forward's wall
        time across the requests whose chunks composed it, by token share.
        Two consumers ride the same apportionment:

          * request tracing — per-chunk prefill spans for ``put`` steps
            (a request still pre-first-token is by definition prefilling);
          * tenant metering — compute-seconds charged to each request's
            tenant, bucketed prefill/decode/spec_verify so the per-tenant
            sum reconciles with the goodput ledger's serving active
            categories (the conservation acceptance bar).
        """
        total = sum(chunk_sizes) or 1
        meter = self._meter
        for uid, n in zip(uids, chunk_sizes):
            req = self._streams.get(uid)
            if req is None:
                continue
            share = dur * (n / total)
            if kind == "put" and req.ctx is not None \
                    and req.stream.first_token_t is None:
                self._reqtrace.on_prefill_chunk(req, n, t0, share)
            if meter is not None:
                if kind == "put":
                    bucket = "prefill" if n > 1 else "decode"
                else:
                    bucket = kind  # "decode" | "spec_verify"
                # pool=<role> feeds the per-pool compute split the purity
                # acceptance bar measures (zero decode-seconds on a prefill
                # pool is what proves disaggregation actually disaggregated)
                meter.on_compute(req.tenant, bucket, share, tokens=n,
                                 pool=self.role)

    def set_disagg(self, coordinator):
        """Arm the disaggregation coordinator (gateway wiring, pre-start):
        prefill-role replicas begin offering completed prefills to it."""
        self._disagg = coordinator

    def set_timeline(self, collector):
        """Arm the timeline collector (gateway wiring, pre-start): the
        driver loop starts reporting measured chaos-fire stall gaps to it
        (the assembler's `stall` overlay source). None keeps the loop at
        the same one-check cost as the un-timelined path."""
        self._timeline = collector

    def detach_request(self, uid: int):
        """Surgically remove ``uid`` from this replica WITHOUT terminal
        accounting — the request is migrating, not finishing (the decode
        replica close-out runs exactly once, over the full token count).
        Driver-thread only. The scheduler cancel flushes the engine
        sequence, which publishes its full blocks into this replica's OWN
        radix tree first — the migrated prefix stays locally reusable, so
        prefix sharing flows both directions of the handoff."""
        req = self._streams.pop(int(uid), None)
        if req is None:
            return
        if self._scheduler.cancel(int(uid)):
            self._scheduler.discard_result(int(uid))
        self._inflight -= 1

    def enqueue_resume(self, req, tokens, remaining):
        """Adopt a migrated request (called from the SOURCE replica's driver
        via the coordinator): an infallible list append — the scheduler
        submit happens on THIS replica's own driver at its next loop
        iteration (the single-threaded-scheduler contract). ``tokens`` is
        prompt + everything generated so far; ``remaining`` is the new-token
        budget left."""
        # resume_wait starts HERE (the source driver's enqueue): everything
        # until this replica's driver submits is destination adoption-queue
        # time — the dst half of the handoff gap PR 18 left unattributed
        req.t_resume_enqueued = time.perf_counter()
        with self._resume_lock:
            self._resumes.append((req,
                                  np.asarray(tokens, np.int32).reshape(-1),
                                  max(1, int(remaining))))
        self.wake()

    def book_handoff(self, seconds: float):
        """Goodput booking for handoff broker wall time: driver seconds
        spent migrating (or failing to migrate) a request are neither
        prefill nor decode — they get their own serving category."""
        if self._goodput is not None:
            self._goodput.book("handoff", max(0.0, float(seconds)))

    def cancel(self, uid: int):
        """Request abort of ``uid`` (client timed out / disconnected). The
        actual teardown runs on the DRIVER thread at its next loop — the
        scheduler is single-threaded by contract. An abandoned request
        would otherwise decode to max_new_tokens holding its KV reservation
        and an inflight slot against live traffic."""
        with self._cancel_lock:
            self._cancelled.append(int(uid))
        self.wake()

    def pause(self):
        self.paused = True

    def resume(self):
        self.paused = False
        self.wake()

    def drain(self):
        """Control-plane actuator: stop pulling queued work AND steer the
        router away (new placements go to un-draining replicas while any
        exist). In-flight requests finish; the replica stays alive and
        warmed for an instant undrain."""
        self.draining = True
        self.paused = True

    def undrain(self):
        self.draining = False
        self.paused = False
        self.wake()

    def wake(self):
        self._wake.set()

    def start(self):
        if self.started:
            return self
        seq_warmed = []
        if self.config.warmup:
            for bucket, steps in self.config.warmup:
                # boundary declared once after the WHOLE sequence — a
                # per-call declaration would flag entries 2..N's own
                # warmup compiles as steady-state recompiles
                self.engine.warmup([int(bucket)], int(steps),
                                   declare_warmed=False)
                seq_warmed.append(int(bucket))
        if self.config.warmup_token_buckets:
            # prefill put buckets — also honored WITHOUT decode warmup
            # entries (falls back to the smallest engine seq bucket). The
            # sentinel boundary below makes any bucket missed here a
            # flagged steady-state recompile.
            self.engine.warmup(seq_warmed or [1], [],
                               token_buckets=self.config.warmup_token_buckets,
                               declare_warmed=False)
        if self.config.warmup or self.config.warmup_token_buckets:
            self.engine.declare_gp_warmed()
        self.warmed = True
        gp = get_goodput()
        if gp.enabled and self._goodput is None:
            # ledger wall-clock origin is HERE, after warmup: the serving
            # taxonomy has no compile bucket — warmed-engine serving time is
            # what the ledger attributes (warmup compiles ride the trace bus
            # + sentinel's expected count instead)
            self._goodput = gp.serving_ledger(self.name)
            self.engine.goodput_ledger = self._goodput
        elif self._goodput is not None:
            # stop() -> start() on the same replica: the frozen interval was
            # a deliberate drain, not a failure — book it as draining and
            # un-freeze (no-op if the clock is already running)
            self._goodput.resume("draining")
        if self._goodput is not None:
            # (re-)register the uid -> request-id join; stop() clears it so
            # a dead replica never pins itself on the process-global plane
            self.engine.gp_rid_resolver = self._rid_of
            gp.sentinel.set_uid_resolver(self.name, self._rid_of)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name=f"dstpu-serving-{self.name}", daemon=True)
        self.started = True
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self.started = False
        self._fail_active("replica_stopped")
        if self._goodput is not None:
            self._goodput.stop()  # freeze wall clock: reports stay stable
            # drop the sentinel's strong reference to this replica (the
            # plane is process-global; a stopped replica must be
            # collectable). restart()/start() re-register.
            get_goodput().sentinel.set_uid_resolver(self.name, None)

    def _rid_of(self, uid):
        """uid -> request id for the sentinel's compile-tail attribution
        (None once the request left this replica)."""
        req = self._streams.get(int(uid))
        return req.rid if req is not None else None

    def restart(self):
        """Bring a dead replica back into rotation (chaos drill / operator
        recovery): only valid once the previous driver thread has exited —
        a live driver is left alone. Active state was already failed on the
        way down (crash handler or :meth:`stop`); the engine and scheduler
        are reused, warmup is not repeated, and the first fresh heartbeat
        re-arms liveness for the router."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._fail_active("replica_stopped")  # belt-and-braces: crash paths
        gl = self._goodput
        if gl is not None:
            # down-time books as `recovering`: crash (death stamp) -> now,
            # CLAMPED to any stop() freeze — resume() books the frozen
            # interval itself, so booking past the freeze would double-count
            if self._gp_death_t is not None:
                end = gl.stopped_at if gl.stopped_at is not None \
                    else time.perf_counter()
                gl.book("recovering", end - self._gp_death_t)
                self._gp_death_t = None
            gl.resume("recovering")
            get_goodput().sentinel.set_uid_resolver(self.name, self._rid_of)
        self._stop.clear()
        self._wake.clear()
        self.paused = False
        self.draining = False
        self._thread = threading.Thread(target=self._run,
                                        name=f"dstpu-serving-{self.name}", daemon=True)
        self.started = True
        self._thread.start()
        get_metrics().counter("gateway/replica_restarts_total").inc()
        return self

    # -- driver loop --------------------------------------------------------
    def _run(self):
        hb = get_health()
        src = self.heartbeat_source
        gl = self._goodput
        tl = self._timeline
        stall_gap = get_goodput().stall_gap_s
        try:
            while not self._stop.is_set():
                # chaos injection point: a storm's replica kill lands here,
                # between scheduler steps (no-op-when-unhooked fire())
                t_fire = time.perf_counter() if (gl is not None
                                                 or tl is not None) else 0.0
                chaos.fire("serving/driver", {"replica": self.name})
                if gl is not None or tl is not None:
                    gap = time.perf_counter() - t_fire
                    if gap >= stall_gap:
                        # a fire hook wedged the driver — the same gap the
                        # serving watchdog trips on. Booked as `stalled`,
                        # NOT idle: the replica had (or was denied) work.
                        if gl is not None:
                            gl.book("stalled", gap)
                        if tl is not None:
                            # the measured interval, not a flag: the
                            # assembler re-attributes exactly the overlap
                            # with each in-flight request's segments
                            tl.on_stall(self.name, t_fire, gap)
                busy = False
                self._process_cancellations()
                if not self.paused:
                    busy = self._pull_resumes() or busy
                    busy = self._pull_admitted() or busy
                    if self._scheduler.has_work:
                        if hb.enabled:
                            # armed exactly while work is in flight: a wedged
                            # step (or a dead driver) goes stale and trips the
                            # serving-family deadline
                            hb.beat(src)
                        busy = self._step() or busy
                if not busy:
                    if hb.enabled:
                        hb.disarm(src)
                    t_wait = time.perf_counter() if gl is not None else 0.0
                    self._wake.wait(self.IDLE_WAIT_S)
                    self._wake.clear()
                    if gl is not None:
                        gl.book("draining" if self.paused else "idle",
                                time.perf_counter() - t_wait)
        except BaseException:  # noqa: BLE001 — driver death is a replica
            # failure, distinct from shed in the metrics: the counter is what
            # lets an operator tell "queue full" from "replica died" on a
            # dashboard. Every request this driver was actively serving is
            # failed HERE (the loop-level crash window the _step handler
            # cannot see), so no admitted request goes unreported.
            get_metrics().counter("gateway/replica_failures_total").inc()
            get_flight_recorder().record("serving", "replica_driver_death",
                                         replica=self.name)
            if gl is not None:
                # recovery clock starts at the death site; restart() books it
                self._gp_death_t = time.perf_counter()
            self._fail_active("replica_stopped")
            raise
        finally:
            # the driver is the ONLY consumer of this replica's admission
            # queues: on the way out (clean stop or crash) fail whatever is
            # still queued, so waiting clients get an immediate error instead
            # of the full request timeout, and a stranded full queue cannot
            # pin gateway readiness to False
            self._admission.fail_for(self.name, "replica_stopped")
            if hb.enabled:
                hb.release(src)

    def _fail_active(self, error):
        """Fail every request currently on the scheduler (driver death /
        stop): cancel its engine sequence so the KV reservation frees,
        finish its stream so the waiting client gets an immediate terminal
        frame, and finalize its trace record."""
        for uid, req in list(self._streams.items()):
            try:
                if self._scheduler.cancel(uid):
                    self._scheduler.discard_result(uid)
            except Exception as e:  # noqa: BLE001 — a poisoned engine must
                # not keep the remaining streams from being failed/reported
                get_flight_recorder().record("serving", "cancel_error",
                                             replica=self.name, uid=uid,
                                             error=repr(e))
            req.stream.finish(reason="error", error=error)
            if self._reqtrace is not None:
                self._reqtrace.finalize(req)
        self._streams.clear()
        self._inflight = 0
        # adopted migrations still queued for submit die with the driver
        # too — the never-lose-a-request contract covers the resume queue
        with self._resume_lock:
            resumes, self._resumes = self._resumes, []
        for req, _tokens, _remaining in resumes:
            req.stream.finish(reason="error", error=error)
            if self._reqtrace is not None:
                self._reqtrace.finalize(req)

    def _process_cancellations(self):
        with self._cancel_lock:
            uids, self._cancelled = self._cancelled, []
        for uid in uids:
            req = self._streams.pop(uid, None)
            if req is None:
                continue  # already finished (or never reached this replica)
            spec = self._scheduler.spec_summary(uid)  # read before discard drops it
            if self._scheduler.cancel(uid):
                self._scheduler.discard_result(uid)
            self._inflight -= 1
            req.stream.finish(reason="error", error="cancelled")
            get_metrics().counter(f"gateway/cancelled_{req.slo_class}_total").inc()
            if self._meter is not None:
                self._meter.on_terminal(req.tenant, req.rid, req.slo_class,
                                        "cancelled", req.stream.produced,
                                        cancelled=True)
            if self._reqtrace is not None:
                # the stream latched its REAL terminal first (timeout /
                # disconnect / explicit cancel) — finalize reads it
                self._reqtrace.finalize(req, spec=spec)

    def _pull_admitted(self) -> bool:
        pulled = False
        while self._inflight < self._max_inflight:
            req = self._admission.pop_for(self.name)
            if req is None:
                break
            try:
                self._scheduler.submit(req.uid, req.prompt,
                                       max_new_tokens=req.max_new_tokens,
                                       eos_token_id=req.eos_token_id,
                                       sampling=req.sampling,
                                       tenant=req.tenant)
            except Exception as e:  # validation said yes, scheduler said no
                req.stream.finish(reason="error", error=f"{type(e).__name__}: {e}")
                if self._reqtrace is not None:
                    self._reqtrace.finalize(req)
                continue
            if self._reqtrace is not None and req.ctx is not None:
                self._reqtrace.on_dequeue(req)
            if self._meter is not None and req.t_admitted is not None:
                # queue-seconds per SLO class, stamped at the replica pull
                # (the same admitted->dequeued interval the tracing stage
                # breakdown measures) — also feeds the starvation detector
                self._meter.on_queue_wait(
                    req.tenant, req.slo_class,
                    time.perf_counter() - req.t_admitted, rid=req.rid)
            self._streams[req.uid] = req
            self._inflight += 1
            pulled = True
        return pulled

    def _pull_resumes(self) -> bool:
        """Driver-side half of a handoff adoption: submit each migrated
        request's full stream (prompt + produced) with its remaining token
        budget. The host chain the handoff installed makes the submit's
        prefix acquisition a hierarchy hit — only the un-exported tail
        re-prefills before decode continues. Bypasses ``_max_inflight``
        (the request already holds a fleet-wide slot, counted on its source
        at admission) and never raises: a failed submit finishes the stream
        with the error, so migrated requests are never silently lost."""
        with self._resume_lock:
            if not self._resumes:
                return False
            items, self._resumes = self._resumes, []
        for req, tokens, remaining in items:
            try:
                self._scheduler.submit(req.uid, tokens,
                                       max_new_tokens=remaining,
                                       eos_token_id=req.eos_token_id,
                                       sampling=req.sampling,
                                       tenant=req.tenant)
            except Exception as e:  # noqa: BLE001 — report, never lose
                req.stream.finish(reason="error",
                                  error=f"{type(e).__name__}: {e}")
                if self._reqtrace is not None:
                    self._reqtrace.finalize(req)
                continue
            req.resume_base = req.stream.produced
            req.replica_name = self.name
            req.t_resume_submitted = time.perf_counter()
            if req.t_resume_enqueued is not None:
                req.resume_wait_ms = (req.t_resume_submitted
                                      - req.t_resume_enqueued) * 1e3
                if self._reqtrace is not None and req.ctx is not None:
                    self._reqtrace.on_resume_wait(req)
            self._streams[req.uid] = req
            self._inflight += 1
            get_metrics().counter("gateway/resumed_requests_total").inc()
        return True

    def _step(self) -> bool:
        try:
            n = self._scheduler.step()
        except Exception as e:  # noqa: BLE001 — one poisoned batch must not
            # silently wedge every queued request: fail the active streams
            # loudly and drop the driver's view of them
            get_flight_recorder().record("serving", "replica_step_error",
                                         replica=self.name, error=repr(e))
            for req in list(self._streams.values()):
                req.stream.finish(reason="error", error=f"{type(e).__name__}: {e}")
                if self._reqtrace is not None:
                    self._reqtrace.finalize(req)
            self._streams.clear()
            self._inflight = 0
            raise
        self.steps += 1
        self._fanout()
        return n > 0

    def _fanout(self):
        """Push newly generated tokens to each request's stream; close out
        finished requests with TTFT/TPOT accounting. Reads only each
        stream's TAIL (``new_tokens``) — snapshotting ``results`` here
        would re-copy every active generation whole on every step."""
        finished = self._scheduler.finished
        reg = get_metrics()
        for uid, req in list(self._streams.items()):
            st = req.stream
            # resume_base: tokens the stream already held when a migrated
            # request resumed HERE — this scheduler's generation restarts at
            # zero, so the stream cursor is offset by what the source made
            new = self._scheduler.new_tokens(uid, st.produced - req.resume_base)
            if new:
                pushed = st.push(new)
                if pushed:
                    reg.counter("gateway/tokens_streamed_total").inc(pushed)
                    if req.ttft_ms is None and st.first_token_t is not None:
                        req.ttft_ms = (st.first_token_t - req.t_admitted) * 1e3
                        reg.histogram(f"gateway/ttft_ms_{req.slo_class}").observe(req.ttft_ms)
                        if self._reqtrace is not None and req.ctx is not None:
                            self._reqtrace.on_first_token(req, req.ttft_ms)
            if (self._disagg is not None and uid not in finished
                    and req.handoff_state is None and req.resume_base == 0
                    and req.sampling is None  # greedy-parity contract only
                    and self._disagg.wants_handoff(self)
                    and st.produced >= self._disagg.handoff_after_tokens
                    and st.produced < req.max_new_tokens):
                # prefill is proven done (first tokens exist) and decode
                # remains — migrate to the decode pool. try_handoff runs the
                # whole pipeline on THIS driver thread; True means detach
                # already removed the request from our maps.
                if self._disagg.try_handoff(self, req, st.all_tokens()):
                    req.handoff_state = "migrated"
                    continue
                # terminal fallback: decode in place, never re-attempted
                # (the ledger refused-or-failed entry pins at-most-once)
                req.handoff_state = "fallback"
            if uid in finished:  # once: the stream entry is removed with it
                self._inflight -= 1
                del self._streams[uid]
                self._close_out(req)
                # the stream holds the full generation; dropping the
                # scheduler's copy keeps a long-lived replica's results dict
                # (and each per-step `results` snapshot) from growing with
                # every request ever served
                self._scheduler.discard_result(uid)

    def _close_out(self, req: GatewayRequest):
        st = req.stream
        n = st.produced
        toks = st.all_tokens()
        reason = ("eos" if (req.eos_token_id is not None and toks
                            and toks[-1] == req.eos_token_id) else "length")
        if (n > 1 and st.first_token_t is not None and st.last_token_t is not None
                and st.last_token_t > st.first_token_t):
            req.tpot_ms = (st.last_token_t - st.first_token_t) / (n - 1) * 1e3
            get_metrics().histogram(f"gateway/tpot_ms_{req.slo_class}").observe(req.tpot_ms)
        cls = self.config.slo_classes.get(req.slo_class)
        if cls is not None:
            if cls.ttft_target_ms > 0 and (req.ttft_ms or 0) > cls.ttft_target_ms:
                get_metrics().counter(f"gateway/slo_ttft_miss_{req.slo_class}_total").inc()
            if cls.tpot_target_ms > 0 and (req.tpot_ms or 0) > cls.tpot_target_ms:
                get_metrics().counter(f"gateway/slo_tpot_miss_{req.slo_class}_total").inc()
        get_metrics().counter(f"gateway/completed_{req.slo_class}_total").inc()
        if self._meter is not None:
            self._meter.on_terminal(req.tenant, req.rid, req.slo_class,
                                    reason, n)
        if self._reqtrace is not None:
            # finalize BEFORE the stream latches done: the HTTP handler
            # wakes on finish and may read the request log immediately —
            # the summary record must already be durable by then.
            # spec_summary is None unless the scheduler actually speculated
            # for this request (ragged.speculative present) — the summary
            # record then carries the per-request acceptance rate
            self._reqtrace.finalize(req, finish_reason=reason, n_tokens=n,
                                    spec=self._scheduler.spec_summary(req.uid))
        st.finish(reason=reason)

    # -- introspection -------------------------------------------------------
    def state(self) -> dict:
        out = {"name": self.name, "alive": self.alive, "paused": self.paused,
               "draining": self.draining,
               "warmed": self.warmed, "role": self.role,
               "inflight": self._inflight,
               "queued": self._admission.depth(replica=self.name),
               "steps": self.steps,
               "available_blocks": self.engine.available_blocks}
        if self._scheduler.speculating:
            sp = self._scheduler.spec_stats
            out["speculative"] = dict(sp, accept_rate=round(
                sp["accepted"] / max(1, sp["drafted"]), 3),
                **(self._scheduler.spec_params() or {}))
        return out
