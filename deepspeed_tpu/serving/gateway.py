"""HTTP/SSE serving gateway over one-or-N ``InferenceEngineV2`` replicas.

Endpoints (stdlib ``ThreadingHTTPServer``, the ``monitor/export.py``
pattern — one daemon accept thread, one handler thread per connection):

  * ``POST /v1/generate`` — body ``{"prompt": [token ids],
    "max_new_tokens": N, "slo_class": "interactive", "stream": true,
    "eos_token_id": null}``. With ``stream`` (the default) the response is
    ``text/event-stream``: one ``meta`` frame (uid, routed replica,
    prefix-cache credit), one frame per generated token, and a terminal
    ``done`` frame carrying finish_reason + TTFT/TPOT. With
    ``stream: false`` the handler blocks and returns one JSON object with
    the full token list. Admission failures map to HTTP statuses: 400
    (invalid request), 429 (class queue past its shed depth — back off),
    503 (draining / no live replica — go elsewhere).
  * ``GET /healthz`` — liveness + the full gateway state (replicas,
    queues, router), always 200 while the process serves.
  * ``GET /readyz`` — readiness for LB rotation: 200 while ``ready``
    (started, not draining, replicas warmed + live, every bounded class
    queue below its shed depth), 503 otherwise — so a drained replica
    leaves rotation without being killed.
  * ``GET /v1/usage`` — the per-tenant metering ledger (top-K tenants by
    spend + aggregated ``other``, fairness index, starvation count); 404
    when the ``serving.gateway.metering`` block is absent.
  * ``GET /v1/pools`` — disaggregated-serving topology + handoff ledger
    (pool membership, per-pool roles, migration stats, recent handoff
    entries with their state-machine position); 404 when the
    ``serving.gateway.disagg`` block is absent.
  * ``POST /v1/profile`` — on-demand deep profiling of LIVE traffic: body
    ``{"duration_s": 2.0}`` (optional) brackets ``jax.profiler``
    start/stop around whatever the replicas are serving and returns the
    atomically-renamed XPlane artifact directory. Bounded duration
    (clamped to ``profiling.max_duration_s``), 409 while another capture
    is in flight (the profiler is process-global), 404 when the
    ``serving.gateway.profiling`` block is absent.

SSE frame format (``sse_frame``/``parse_sse`` are the canonical pair; the
load generator and the tests share them):

    data: {"token": 1234, "index": 0}\\n\\n          # one per token
    data: {"done": true, "n_tokens": 8, "finish_reason": "length", ...}

The handler thread is the stream CONSUMER: it drains the request's bounded
``TokenStream`` at the client's pace, so a slow reader backs up its own
socket, never the replica decode loop.
"""

import json
import threading
import time
from typing import Optional

from ..monitor.health import get_health
from ..monitor.metrics import get_metrics
from ..monitor.roofline import CaptureBusyError, get_capture_manager
from .admission import AdmissionController
from .config import GatewayConfig
from .disagg import DisaggCoordinator
from .metering import TenantMeter, sanitize_tenant_id
from .replica import EngineReplica, GatewayRequest
from .reqtrace import (RequestTracing, extract_request_id, new_request_id,
                       sanitize_request_id)
from .router import ReplicaRouter


def sse_frame(obj) -> bytes:
    """One server-sent-event frame carrying a JSON payload."""
    return b"data: " + json.dumps(obj).encode("utf-8") + b"\n\n"


def parse_sse(text):
    """Parse an SSE body (bytes or str) back into its JSON payloads —
    the exact inverse of :func:`sse_frame` (round-trip asserted in
    ``tests/test_gateway.py``). Multi-``data:``-line events are joined per
    the SSE spec; non-JSON payloads raise (the gateway never emits them)."""
    if isinstance(text, bytes):
        text = text.decode("utf-8")
    events = []
    for block in text.split("\n\n"):
        datas = [ln[5:].lstrip() for ln in block.split("\n") if ln.startswith("data:")]
        if datas:
            events.append(json.loads("\n".join(datas)))
    return events


class ServingGateway:
    """Request plane over ``engines`` (one :class:`EngineReplica` each)."""

    def __init__(self, engines, config: Optional[GatewayConfig] = None):
        self.config = config or GatewayConfig()
        # causal timeline plane: exists ONLY when the timeline block asked
        # for it — with it absent no collector, no chaos observer, no
        # per-request assembly (zero-overhead-off like every plane here).
        # It rides reqtrace's terminal path, so tracing is a hard
        # prerequisite (from_dict enforces the same; this covers direct
        # GatewayConfig construction)
        self.timeline = None
        if self.config.timeline.enabled:
            if not self.config.tracing.enabled:
                raise ValueError("serving.gateway.timeline requires the "
                                 "tracing block: the assembler joins the "
                                 "stage stamps request tracing owns")
            from .timeline import TimelineCollector
            self.timeline = TimelineCollector(self.config.timeline)
        # request-scoped tracing plane: exists ONLY when the config block
        # asked for it — with it absent the request path allocates no
        # contexts, opens no log, and emits nothing (the PR 1/5 bar)
        self.reqtrace = (RequestTracing(self.config.tracing,
                                        slo_classes=self.config.slo_classes,
                                        timeline=self.timeline)
                         if self.config.tracing.enabled else None)
        # tenant metering plane: exists ONLY when the metering block asked
        # for it — with it absent no meter, no per-engine views, no stamp
        # arrays, and every hook stays one `is not None` check (the same
        # zero-overhead contract as the tracing plane above)
        self.meter = (TenantMeter(self.config.metering,
                                  slo_classes=self.config.slo_classes)
                      if self.config.metering.enabled else None)
        self.admission = AdmissionController(self.config, reqtrace=self.reqtrace,
                                             meter=self.meter)
        # disaggregated pools: roles come from the config block by replica
        # index, padded with "mixed" — an absent block means every replica
        # is mixed, no coordinator, no ledger (zero-overhead-off)
        dcfg = self.config.disagg
        roles = [str(dcfg.roles[i]) if dcfg.enabled and i < len(dcfg.roles)
                 else "mixed" for i in range(len(engines))]
        self.replicas = [EngineReplica(str(i), eng, self.admission, self.config,
                                       reqtrace=self.reqtrace, meter=self.meter,
                                       role=roles[i])
                         for i, eng in enumerate(engines)]
        self.disagg = None
        if dcfg.enabled:
            self.disagg = DisaggCoordinator(self.replicas, dcfg)
            for r in self.replicas:
                r.set_disagg(self.disagg)
            self.admission.set_roles({r.name: r.role for r in self.replicas})
        # feedback control plane: exists ONLY when the control block asked
        # for it — with it absent no controller object, no decision log, no
        # thread (the same zero-overhead contract as the planes above)
        self.controller = None
        if self.config.control.enabled:
            from .control import ServingController
            self.controller = ServingController(self, self.config.control)
        if self.timeline is not None:
            for r in self.replicas:
                r.set_timeline(self.timeline)
            if self.controller is not None:
                # actuation join source: decisions carry inflight_rids, the
                # roster-based (clock-free) decision -> request join key
                self.timeline.set_decisions_provider(
                    self.controller.decisions.recent)
        self.router = ReplicaRouter(self.replicas, policy=self.config.router)
        self._uid_lock = threading.Lock()
        self._next_uid = 1
        self._httpd = None
        self._http_thread = None
        self._registered_ready = None
        self._registered_state = None
        self._registered_gauges = None
        self._registered_dump = None
        self._registered_tenant_gauges = None
        self._registered_tenant_dump = None
        self._registered_handoff_gauges = None
        self._registered_timeline_gauges = None
        self.started = False
        self.draining = False

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Start every replica driver + the HTTP front end; registers the
        gateway's readiness + state with the health plane so the PR 5
        exporter's ``/healthz``/``/readyz`` reflect this gateway."""
        if self.started:
            return self
        if not self.config.enabled:
            # the knob is live, not documentation: a deployment driven by a
            # ds_config without a serving.gateway block must not serve
            raise ValueError("serving gateway disabled by config — set "
                             "serving.gateway.enabled (or GatewayConfig(enabled=True)) "
                             "before start()")
        get_metrics().enable()  # gateway metrics ride the registry
        if self.meter is not None:
            # (re-)attach the engine-side views: stop() detaches them, so a
            # stop() -> start() cycle on one gateway keeps metering live
            for r in self.replicas:
                r.engine.set_tenant_meter(self.meter)
        for r in self.replicas:
            r.start()
        self._start_http()
        self.started = True
        health = get_health()
        # bound methods are fresh objects per access: keep THE registered
        # objects so stop() can remove exactly what this gateway installed
        self._registered_ready = self._readiness
        self._registered_state = self.state
        health.set_ready_provider(self._registered_ready)
        health.set_state_provider("gateway", self._registered_state)
        # scrapeable admission state: per-(replica, class) queue depth +
        # per-class shed rate ride /metrics as labelled gauges, and stall
        # dumps get the in-flight request roster (which requests were ON
        # the wedged replica) — both ownership-checked like ready/state
        self._registered_gauges = self.admission.gauge_rows
        self._registered_dump = self.inflight_request_summaries
        health.set_gauge_provider("gateway", self._registered_gauges)
        health.set_dump_provider("inflight_requests", self._registered_dump)
        if self.meter is not None:
            # tenant-labelled rows on /metrics (top-K + `other`, the only
            # sanctioned source of a `tenant` label) and tenant rows in
            # forensic stall dumps — ownership-checked like the rest
            self._registered_tenant_gauges = self.meter.gauge_rows
            self._registered_tenant_dump = self.meter.dump_rows
            health.set_gauge_provider("tenant_meter", self._registered_tenant_gauges)
            health.set_dump_provider("tenants", self._registered_tenant_dump)
        if self.disagg is not None:
            # handoff ledger rows on /metrics (started/fallback-rate/volume
            # + p50 once any migration completed) — ownership-checked too
            self._registered_handoff_gauges = self.disagg.ledger.gauge_rows
            health.set_gauge_provider("handoff", self._registered_handoff_gauges)
        if self.timeline is not None:
            # arm the chaos-fire listener + assembly counters on /metrics
            # BEFORE the controller starts: its first actuation must
            # already be joinable
            self.timeline.arm()
            self._registered_timeline_gauges = self.timeline.gauge_rows
            health.set_gauge_provider("timeline", self._registered_timeline_gauges)
        if self.controller is not None:
            # the controller registers its own health providers and starts
            # its decision thread LAST — every sensor it reads is live
            self.controller.start()
        return self

    def stop(self, timeout: float = 10.0):
        if self.controller is not None:
            # FIRST: a live controller must not actuate against a gateway
            # that is tearing down under it
            self.controller.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        for r in self.replicas:
            r.stop(timeout=timeout)
        self.admission.fail_all("gateway_shutdown")
        if self.started:
            # ownership-checked: a newer gateway's registration survives an
            # old instance's shutdown (in-process rollover)
            health = get_health()
            health.clear_ready_provider(self._registered_ready)
            health.clear_state_provider("gateway", self._registered_state)
            health.clear_gauge_provider("gateway", self._registered_gauges)
            health.clear_dump_provider("inflight_requests", self._registered_dump)
            if self.meter is not None:
                health.clear_gauge_provider("tenant_meter",
                                            self._registered_tenant_gauges)
                health.clear_dump_provider("tenants", self._registered_tenant_dump)
            if self.disagg is not None:
                health.clear_gauge_provider("handoff",
                                            self._registered_handoff_gauges)
            if self.timeline is not None:
                health.clear_gauge_provider("timeline",
                                            self._registered_timeline_gauges)
        if self.timeline is not None:
            self.timeline.disarm()
        if self.reqtrace is not None:
            self.reqtrace.close()
        if self.meter is not None:
            # detach the engine-side views: a reused engine must not keep
            # feeding a dead gateway's meter (and a later unmetered gateway
            # must find the hooks disarmed)
            for r in self.replicas:
                r.engine.set_tenant_meter(None)
            self.meter.close()
        self.started = False

    def drain(self, on: bool = True):
        """Stop admitting (503 + not ready) while in-flight work finishes —
        the LB-facing half of a graceful rollout."""
        self.draining = bool(on)

    def _readiness(self) -> bool:
        return self.ready

    @property
    def ready(self) -> bool:
        """Distinct from liveness: serving AND able to take traffic —
        replicas warmed + at least one live, not draining, and every
        bounded class queue below its shed depth."""
        return (self.started and not self.draining
                and all(r.warmed for r in self.replicas)
                and bool(self.router.live())
                and self.admission.below_shed_threshold())

    @property
    def engines(self):
        return [r.engine for r in self.replicas]

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self):
        return f"http://{self.config.host}:{self.port}" if self._httpd else None

    # -- programmatic entry (what the HTTP handler calls) ---------------------
    def submit(self, prompt, max_new_tokens: int = 16, slo_class: Optional[str] = None,
               eos_token_id=None, rid: Optional[str] = None,
               traceparent: Optional[str] = None, temperature=None, top_p=None,
               seed=None, tenant: Optional[str] = None):
        """Validate -> route -> admit. Returns ``(200, GatewayRequest)`` or
        ``(status, error_dict)`` with status 400/429/503. ``rid`` is the
        (already-sanitized) client request id — generated when absent, so
        every refusal carries one too.

        ``temperature``/``top_p``/``seed``: per-request sampling
        (``SamplingParams``) — absent/temperature-0 keeps the greedy fast
        path; out-of-range values are a 400 at the door, never a replica
        error.

        ``tenant``: the request's owner identity (``X-Tenant-Id`` at the
        HTTP door) — sanitized with the request-id charset discipline and
        defaulted, so every request is charged to SOME tenant; the meter
        itself only exists when ``serving.gateway.metering`` is present."""
        rt = self.reqtrace
        rid = sanitize_request_id(rid) or new_request_id()
        tenant = sanitize_tenant_id(tenant)
        cls = slo_class or self.config.default_slo_class
        ctx = rt.open(rid, traceparent=traceparent, slo_class=cls, tenant=tenant) \
            if rt is not None else None

        def refuse(status, payload, replica=None):
            payload["request_id"] = rid
            if rt is not None:
                rt.finalize_rejected(ctx, status,
                                     payload.get("reason") or payload.get("error"),
                                     replica=replica.name if replica else None)
            return status, payload

        if not self.started or self.draining:
            return refuse(503, {"error": "not_ready",
                                "detail": "draining" if self.draining else "not started"})
        if cls not in self.config.slo_classes:
            return refuse(400, {"error": "unknown_slo_class", "slo_class": cls,
                                "known": sorted(self.config.slo_classes)})
        sampling = None
        if temperature is not None or top_p is not None or seed is not None:
            from ..inference.v2.sampling import SamplingParams

            try:
                sampling = SamplingParams(
                    temperature=float(temperature) if temperature is not None else 0.0,
                    top_p=float(top_p) if top_p is not None else 1.0,
                    seed=int(seed) if seed is not None else None).validate()
            except (TypeError, ValueError) as e:
                return refuse(400, {"error": "invalid_sampling", "detail": str(e)})
        try:
            max_new_tokens = int(max_new_tokens)
            with self._uid_lock:
                uid = self._next_uid
                self._next_uid += 1
            req = GatewayRequest(uid, prompt, max_new_tokens, cls,
                                 eos_token_id=eos_token_id, rid=rid, ctx=ctx,
                                 sampling=sampling, tenant=tenant)
            if ctx is not None:
                # stamped here (not at admission) so too_large/shed records
                # — exactly the always-retained tail — carry the real size
                ctx.prompt_tokens = int(req.prompt.size)
        except (TypeError, ValueError, OverflowError) as e:
            # OverflowError: a token id outside int32 range from np.asarray
            return refuse(400, {"error": "invalid_request", "detail": str(e)})
        if req.prompt.size == 0:
            return refuse(400, {"error": "invalid_request", "detail": "empty prompt"})
        if req.max_new_tokens <= 0:
            return refuse(400, {"error": "invalid_request",
                                "detail": "max_new_tokens must be positive"})
        cap = self.config.max_new_tokens_cap
        if cap and req.max_new_tokens > cap:
            return refuse(400, {"error": "invalid_request",
                                "detail": f"max_new_tokens {req.max_new_tokens} > cap {cap}"})
        replica = self.router.select(req.prompt, ctx=ctx)
        if replica is None:
            get_metrics().counter("gateway/rejected_total").inc()
            return refuse(503, {"error": "no_live_replica"})
        if rt is not None:
            # the decision instant carries what justified the placement:
            # per-candidate prefix-overlap tokens AND whole blocks (the
            # unit the radix tree actually shares)
            bs = replica.engine.config.kv_block_size
            rt.on_route(ctx, replica.name, ctx.route_policy, ctx.route_scores,
                        overlap_blocks=({n: s // bs
                                         for n, s in (ctx.route_scores or {}).items()}
                                        if ctx.route_policy == "prefix" else None))
        total = req.prompt.size + req.max_new_tokens
        if total > replica.engine.max_context:
            return refuse(400, {"error": "too_large",
                                "detail": f"prompt {req.prompt.size} + max_new_tokens "
                                          f"{req.max_new_tokens} exceeds max_context "
                                          f"{replica.engine.max_context}"}, replica)
        blocks = -(-total // replica.engine.config.kv_block_size)
        if blocks > replica.pool_blocks:
            # the scheduler could NEVER admit this (its lifetime reservation
            # exceeds the whole pool) — refuse now instead of queueing forever
            return refuse(400, {"error": "too_large",
                                "detail": f"request needs {blocks} KV blocks, pool has "
                                          f"{replica.pool_blocks}"}, replica)
        ok, reason = self.admission.try_admit(req, replica)
        if not ok:
            return refuse(429, {"error": "shed", "reason": reason, "slo_class": cls,
                                "replica": replica.name}, replica)
        if rt is not None:
            rt.on_admitted(req)
        replica.wake()
        return 200, req

    def cancel_request(self, req: GatewayRequest) -> bool:
        """Abandon an admitted request (client timeout / disconnect):
        removed from its admission queue if still waiting, else handed to
        its replica's driver for teardown (engine sequence flushed, KV
        reservation released) at the next loop. Without this an abandoned
        request keeps decoding to max_new_tokens against live traffic."""
        if self.admission.cancel(req):
            req.stream.finish(reason="error", error="cancelled")
            if self.reqtrace is not None:
                # still queued: the driver never saw it, finalize here (the
                # stream latched the real cause — timeout/disconnect — first)
                self.reqtrace.finalize(req)
            return True
        for r in self.replicas:
            if r.name == req.replica_name:
                r.cancel(req.uid)
                return True
        return False

    # -- on-demand profiling ---------------------------------------------------
    def capture_profile(self, duration_s=None):
        """One bounded XPlane capture of live traffic (``POST /v1/profile``).
        Returns ``(status, body)`` exactly like :meth:`submit`: 404 when the
        ``profiling`` block is absent, 400 on a bad duration, 409 while
        another capture holds the process-global profiler, 200 with the
        final (atomically-renamed) artifact directory. The handler thread
        blocks here for the capture window — live traffic on the replica
        threads is exactly what lands in the trace."""
        cfg = self.config.profiling
        if not cfg.enabled:
            return 404, {"error": "profiling_disabled"}
        if duration_s is None:
            duration_s = cfg.default_duration_s
        try:
            duration_s = float(duration_s)
        except (TypeError, ValueError):
            return 400, {"error": "bad_duration",
                         "detail": f"duration_s must be a number, got {duration_s!r}"}
        if duration_s <= 0:
            return 400, {"error": "bad_duration",
                         "detail": f"duration_s must be > 0, got {duration_s}"}
        duration_s = min(duration_s, cfg.max_duration_s)
        try:
            artifact = get_capture_manager().capture(
                duration_s, cfg.artifact_dir, label="gateway",
                max_s=cfg.max_duration_s)
        except CaptureBusyError:
            return 409, {"error": "capture_in_flight"}
        except Exception as e:  # noqa: BLE001 — profiling must never 500-loop
            return 500, {"error": "capture_failed",
                         "detail": f"{type(e).__name__}: {e}"}
        return 200, {"artifact_dir": artifact, "duration_s": duration_s}

    # -- introspection --------------------------------------------------------
    def state(self) -> dict:
        out = {"ready": self.ready, "draining": self.draining,
               "replicas": [r.state() for r in self.replicas],
               "admission": self.admission.state(),
               "router": self.router.state()}
        if self.reqtrace is not None:
            out["tracing"] = self.reqtrace.state()
        if self.meter is not None:
            out["metering"] = self.meter.state()
        if self.disagg is not None:
            out["disagg"] = self.disagg.state()
        if self.controller is not None:
            out["control"] = self.controller.state()
        if self.timeline is not None:
            out["timeline"] = self.timeline.state()
        return out

    def inflight_request_summaries(self) -> dict:
        """Dump-provider payload for the health plane's forensic bundles:
        every request currently on a replica (the roster a stall dump needs
        to NAME who was on the wedged replica) plus the most recent
        terminal summaries when request tracing is on."""
        return {"inflight": [row for r in self.replicas
                             for row in r.inflight_summaries()],
                "recent": (self.reqtrace.last_summaries(16)
                           if self.reqtrace is not None else [])}

    # -- HTTP front end --------------------------------------------------------
    def _start_http(self):
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # HTTP/1.0: every response closes its connection, so SSE bodies
            # need no chunked framing — clients read until EOF (exactly the
            # contract stdlib http.client implements)
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):  # no stderr chatter per request
                pass

            # -- the ONE response entry point: EVERY response this gateway
            # writes — success, 400/404/429/503/504, the catch-all 500, the
            # GET endpoints, SSE headers — attaches `X-Request-Id` here.
            # Structurally enforced: `tools/check_request_tracing.py`
            # asserts no send_response/send_header/end_headers call exists
            # outside this helper, so an error path added later cannot
            # silently lose the id.
            def _respond(self, code, ctype, body=None, rid=None, extra=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("X-Request-Id", rid or new_request_id())
                for k, v in extra:
                    self.send_header(k, v)
                if body is not None:
                    self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body is not None:
                    self.wfile.write(body)

            def _json(self, code, obj, rid=None):
                # every retryable refusal carries Retry-After: 429 = back
                # off and retry HERE, 503 = this instance is going away /
                # has no live replica — retry ELSEWHERE (the LB sees the
                # same signal via /readyz)
                extra = ((("Retry-After", str(outer.config.retry_after_s)), )
                         if code in (429, 503) else ())
                self._respond(code, "application/json",
                              json.dumps(obj).encode("utf-8"), rid=rid, extra=extra)

            def do_GET(self):
                rid, _tp = extract_request_id(self.headers)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._json(200, {"live": True, **outer.state()}, rid=rid)
                    elif path == "/readyz":
                        ready = outer.ready
                        self._json(200 if ready else 503,
                                   {"ready": ready, "draining": outer.draining},
                                   rid=rid)
                    elif path == "/v1/usage":
                        # the per-tenant ledger: top-K + aggregated `other`,
                        # fairness index, starvation count — 404 when the
                        # metering block is absent (there IS no ledger)
                        if outer.meter is None:
                            self._json(404, {"error": "metering_disabled"},
                                       rid=rid)
                        else:
                            self._json(200, outer.meter.usage_report(), rid=rid)
                    elif path == "/v1/pools":
                        # disaggregation topology + the handoff ledger —
                        # 404 when the disagg block is absent (there ARE
                        # no pools, only the mixed fleet)
                        if outer.disagg is None:
                            self._json(404, {"error": "disagg_disabled"},
                                       rid=rid)
                        else:
                            self._json(200, outer.disagg.state(), rid=rid)
                    elif path == "/v1/control":
                        # the feedback controller: armed policies, actuation
                        # stats, depth overrides, recent decisions — 404
                        # when the control block is absent (there IS no
                        # controller)
                        if outer.controller is None:
                            self._json(404, {"error": "control_disabled"},
                                       rid=rid)
                        else:
                            self._json(200, outer.controller.state(), rid=rid)
                    elif path == "/v1/timeline" or path.startswith("/v1/timeline/"):
                        # assembled causal timelines: the collection view
                        # (collector stats + retained tail exemplars) or
                        # one request's full timeline by id — 404 when the
                        # timeline block is absent (nothing was assembled)
                        if outer.timeline is None:
                            self._json(404, {"error": "timeline_disabled"},
                                       rid=rid)
                        elif path == "/v1/timeline":
                            self._json(200, {**outer.timeline.state(),
                                             "exemplars":
                                                 outer.timeline.exemplars()},
                                       rid=rid)
                        else:
                            want = sanitize_request_id(
                                path[len("/v1/timeline/"):])
                            tl = (outer.timeline.get(want)
                                  if want is not None else None)
                            if tl is None:
                                self._json(404, {"error": "unknown_request_id",
                                                 "request_id": want}, rid=rid)
                            else:
                                self._json(200, tl, rid=rid)
                    else:
                        self._json(404, {"error": "not_found",
                                         "paths": ["/v1/generate", "/v1/usage",
                                                   "/v1/pools", "/v1/control",
                                                   "/v1/timeline",
                                                   "/v1/profile",
                                                   "/healthz", "/readyz"]},
                                   rid=rid)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):
                # id resolution FIRST (sanitize client id / adopt traceparent
                # / generate) so even the catch-all 500 echoes it
                rid, traceparent = extract_request_id(self.headers)
                path = self.path.split("?", 1)[0]
                try:
                    if path not in ("/v1/generate", "/v1/profile"):
                        self._json(404, {"error": "not_found"}, rid=rid)
                        return
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("body must be a JSON object")
                    except (ValueError, json.JSONDecodeError) as e:
                        self._json(400, {"error": "bad_json", "detail": str(e),
                                         "request_id": rid}, rid=rid)
                        return
                    if path == "/v1/profile":
                        # on-demand XPlane capture of live traffic; the
                        # request-id echo rides _respond like every response
                        # (normalized here so body and X-Request-Id agree
                        # even when the client sent none)
                        rid = sanitize_request_id(rid) or new_request_id()
                        status, result = outer.capture_profile(
                            body.get("duration_s"))
                        if status == 200:
                            result = {**result, "request_id": rid}
                        self._json(status, result, rid=rid)
                        return
                    status, result = outer.submit(
                        body.get("prompt"),
                        max_new_tokens=body.get("max_new_tokens", 16),
                        slo_class=body.get("slo_class"),
                        eos_token_id=body.get("eos_token_id"),
                        rid=rid, traceparent=traceparent,
                        temperature=body.get("temperature"),
                        top_p=body.get("top_p"),
                        seed=body.get("seed"),
                        tenant=self.headers.get("X-Tenant-Id"))
                    if status != 200:
                        self._json(status, result, rid=rid)
                        return
                    if body.get("stream", True):
                        self._stream_response(result)
                    else:
                        self._blocking_response(result)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-response
                except Exception as e:  # noqa: BLE001 — a malformed request
                    # must come back as a status, never kill the handler
                    # without a response (the client would see a bare reset)
                    try:
                        self._json(500, {"error": "internal",
                                         "detail": f"{type(e).__name__}: {e}",
                                         "request_id": rid}, rid=rid)
                    except (BrokenPipeError, ConnectionResetError):
                        pass

            def _final_frame(self, req: GatewayRequest) -> dict:
                st = req.stream
                out = {"done": True, "uid": req.uid, "request_id": req.rid,
                       "n_tokens": st.produced,
                       "finish_reason": st.finish_reason, "error": st.error,
                       "ttft_ms": round(req.ttft_ms, 3) if req.ttft_ms else None,
                       "tpot_ms": round(req.tpot_ms, 3) if req.tpot_ms else None,
                       "cached_tokens": req.cached_tokens, "dropped": st.dropped}
                if req.handoff_state is not None:
                    # migrated/fallback requests disclose the broker cost
                    # to the CLIENT, not just the operator surfaces
                    out["handoff_state"] = req.handoff_state
                    out["handoff_ms"] = (round(req.handoff_ms, 3)
                                         if req.handoff_ms is not None else None)
                    out["resume_wait_ms"] = (round(req.resume_wait_ms, 3)
                                             if req.resume_wait_ms is not None
                                             else None)
                return out

            def _stream_response(self, req: GatewayRequest):
                self._respond(200, "text/event-stream", rid=req.rid,
                              extra=(("Cache-Control", "no-cache"),))
                st = req.stream
                try:
                    self.wfile.write(sse_frame({"meta": True, "uid": req.uid,
                                                "request_id": req.rid,
                                                "tenant": req.tenant,
                                                "slo_class": req.slo_class,
                                                "replica": req.replica_name,
                                                "cached_tokens": req.cached_tokens}))
                    self.wfile.flush()
                    deadline = time.perf_counter() + outer.config.request_timeout_s
                    index = 0
                    while True:
                        toks, done = st.get(timeout=0.1)
                        for t in toks:
                            self.wfile.write(sse_frame({"token": t, "index": index}))
                            index += 1
                        if toks:
                            self.wfile.flush()
                        if done:
                            break
                        if time.perf_counter() > deadline:
                            st.finish(reason="error", error="request_timeout")
                            outer.cancel_request(req)  # stop decoding for nobody
                            break
                    self.wfile.write(sse_frame(self._final_frame(req)))
                    self.wfile.flush()
                    if outer.reqtrace is not None and req.ctx is not None:
                        outer.reqtrace.on_respond(req.ctx, 200)
                except (BrokenPipeError, ConnectionResetError):
                    # the client is gone: release its engine-side resources
                    st.finish(reason="error", error="client_disconnected")
                    outer.cancel_request(req)
                    raise

            @staticmethod
            def _error_status(error):
                """Status contract: 503 = retry elsewhere (this instance is
                going away), 504 = the request timed out here, 500 = it
                failed here."""
                if error is None:
                    return 200
                if error in ("replica_stopped", "gateway_shutdown"):
                    return 503
                if error == "request_timeout":
                    return 504
                return 500

            def _blocking_response(self, req: GatewayRequest):
                finished = req.stream.wait_done(timeout=outer.config.request_timeout_s)
                if not finished:
                    req.stream.finish(reason="error", error="request_timeout")
                    outer.cancel_request(req)
                out = self._final_frame(req)
                out.pop("done")
                out["tokens"] = req.stream.all_tokens()
                out["slo_class"] = req.slo_class
                out["replica"] = req.replica_name
                status = self._error_status(out["error"])
                self._json(status, out, rid=req.rid)
                if outer.reqtrace is not None and req.ctx is not None:
                    outer.reqtrace.on_respond(req.ctx, status)

        self._httpd = http.server.ThreadingHTTPServer(
            (self.config.host, int(self.config.port)), Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(target=self._httpd.serve_forever,
                                             name="dstpu-gateway-http", daemon=True)
        self._http_thread.start()
