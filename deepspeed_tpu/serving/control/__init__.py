"""Self-driving serving: the feedback control plane.

The sensor planes PRs 11-18 built (goodput ledger, reqtrace stage
histograms, admission gauges, speculative accept stats, recompile
sentinel) become ACTUATION inputs here: a single controller thread reads
them, windowed, and drives four narrow public setters —

  * SLO-aware admission depth overrides (``AdmissionController``);
  * replica drain/undrain/restart (``EngineReplica``);
  * background kernel re-tuning sweeps (``KernelAutotuner`` persisted
    through the ``KernelConfigRegistry``);
  * per-replica speculative K / tree-width (``set_spec_params``).

Layering: ``decisions.py`` (the decision log every actuation goes
through) <- ``policies.py`` (sensors in, proposals out) <-
``controller.py`` (the loop, the flap budget, the ONLY sanctioned
actuator call sites). Configured by ``serving.gateway.control``; absent
block = none of these objects exist (the zero-overhead-off contract).
"""

from .controller import ServingController
from .decisions import DecisionLog
from .policies import (AdmissionPolicy, RetunePolicy, ScalingPolicy,
                       SpeculationPolicy, build_policies)

__all__ = ["ServingController", "DecisionLog", "AdmissionPolicy",
           "ScalingPolicy", "RetunePolicy", "SpeculationPolicy",
           "build_policies"]
