"""The serving feedback controller: sensors -> policies -> actuators.

One :class:`ServingController` per gateway when ``serving.gateway.control``
is present. A single daemon thread ticks every ``interval_s``: it takes a
raw sensor sample (counters, admission state, replica state, goodput
ledgers, sentinel buckets — READ-ONLY, through the public surfaces the
earlier PRs built), diffs it against the trailing ``window_s`` of samples,
hands the windowed snapshot to each armed policy, and applies the
proposals through the ``_apply_*`` helpers — the ONLY sanctioned actuator
call sites in the tree (``tools/check_control_actuators.py``).

Flap-proofing is layered so the loop provably cannot oscillate under a
chaos storm:

  * policies act on hysteresis BANDS and require ``sustain_ticks``
    consecutive over-threshold samples (``policies.py``);
  * an applied actuation puts its policy on ``cooldown_s``;
  * a global budget of ``max_actuations_per_window`` applied actuations
    per ``window_s`` caps the whole loop — proposals past it are logged
    as DEFERRED decisions, never applied. The chaos drill's bound is
    exactly this arithmetic: applied <= budget x ceil(elapsed / window).

Every applied AND deferred decision goes through the
:class:`~deepspeed_tpu.serving.control.decisions.DecisionLog` with the
sensor readings that justified it.
"""

import logging
import threading
import time
from collections import deque
from typing import Dict, Optional

from ...monitor.goodput import get_goodput
from ...monitor.health import get_health
from ...monitor.metrics import get_metrics
from .decisions import DecisionLog
from .policies import build_policies

logger = logging.getLogger(__name__)

__all__ = ["ServingController"]


class ServingController:
    """Feedback control loop over one gateway's sensor planes."""

    def __init__(self, gateway, config):
        self.gateway = gateway
        self.config = config
        self.decisions = DecisionLog(config)
        self.policies = build_policies(config)
        self.stats = {"ticks": 0, "applied": 0, "deferred": 0, "errors": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # perf_counter stamps of APPLIED actuations inside the flap window
        self._actuation_t = deque()
        self._cooldown_until: Dict[str, float] = {}
        # trailing raw samples the windowed deltas diff against
        self._samples = deque()
        self._last_snap: dict = {}
        # EWMA state for the idle_frac sensor (ewma_alpha > 0): smooths
        # bursty arrival dips so a momentary busy spike can't reset a
        # drain proposal's sustain counter
        self._idle_ewma: Optional[float] = None
        # injected by tests / built lazily on the first retune actuation
        self._tuner = None
        self._registered_gauges = None
        self._registered_state = None
        self._registered_dump = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        health = get_health()
        self._registered_gauges = self.gauge_rows
        self._registered_state = self.state
        self._registered_dump = self.decision_dump
        health.set_gauge_provider("control", self._registered_gauges)
        health.set_state_provider("control", self._registered_state)
        health.set_dump_provider("control_decisions", self._registered_dump)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="dstpu-control",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        health = get_health()
        if self._registered_gauges is not None:
            health.clear_gauge_provider("control", self._registered_gauges)
            health.clear_state_provider("control", self._registered_state)
            health.clear_dump_provider("control_decisions", self._registered_dump)
            self._registered_gauges = None
            self._registered_state = None
            self._registered_dump = None
        self.decisions.close()

    def _run(self):
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self.stats["errors"] += 1
                get_metrics().counter("control/errors_total").inc()
                logger.warning(f"control tick failed: {type(e).__name__}: "
                               f"{str(e)[:200]}")

    # -- the decision pass (public so tests drive it deterministically) ------
    def tick(self, now: Optional[float] = None) -> None:
        now = time.perf_counter() if now is None else float(now)
        snap = self._sense(now)
        for pol in self.policies:
            if now < self._cooldown_until.get(pol.name, 0.0):
                continue
            try:
                proposals = pol.propose(snap)
            except Exception as e:  # noqa: BLE001 — one policy never kills a tick
                self.stats["errors"] += 1
                get_metrics().counter("control/errors_total").inc()
                logger.warning(f"control policy {pol.name} failed: "
                               f"{type(e).__name__}: {str(e)[:200]}")
                continue
            for prop in proposals:
                self._actuate(pol, prop, now)
        self.stats["ticks"] += 1

    # -- sensors (read-only, public surfaces only) ---------------------------
    def _raw_sample(self, now: float) -> dict:
        reg = get_metrics()
        classes = {}
        for cls in self.gateway.config.slo_classes:
            classes[cls] = {
                "completed": reg.counter(f"gateway/completed_{cls}_total").value,
                "ttft_miss": reg.counter(f"gateway/slo_ttft_miss_{cls}_total").value,
                "tpot_miss": reg.counter(f"gateway/slo_tpot_miss_{cls}_total").value,
            }
        spec = {}
        for r in self.gateway.replicas:
            st = r.state().get("speculative")
            if st:
                spec[r.name] = {"drafted": st.get("drafted", 0),
                                "accepted": st.get("accepted", 0)}
        sample = {"t": now, "classes": classes, "spec": spec}
        gp = get_goodput()
        if gp.enabled:
            idle = wall = 0.0
            for rep in (gp.report().get("serving") or {}).values():
                idle += rep.get("categories", {}).get("idle", 0.0)
                wall += rep.get("wall_s", 0.0)
            sample["goodput"] = {"idle_s": idle, "wall_s": wall}
        return sample

    def _sense(self, now: float) -> dict:
        cur = self._raw_sample(now)
        horizon = now - self.config.window_s
        while len(self._samples) > 1 and self._samples[1]["t"] <= horizon:
            self._samples.popleft()
        base = self._samples[0] if self._samples else cur
        self._samples.append(cur)
        adm = self.gateway.admission
        classes = {}
        for cls, c in cur["classes"].items():
            b = base["classes"].get(cls, c)
            d_done = c["completed"] - b["completed"]
            d_miss = (c["ttft_miss"] - b["ttft_miss"]) \
                + (c["tpot_miss"] - b["tpot_miss"])
            limits = adm.effective_limits(cls)
            overrides = adm.state().get("depth_overrides", {})
            classes[cls] = {"d_completed": d_done, "d_miss": d_miss,
                            "queue_depth": adm.depth(slo_class=cls),
                            "admitted_rate": adm.admitted_rate(cls),
                            "effective_depth": limits["max_queue_depth"],
                            "override_active": cls in overrides,
                            "priority": int(getattr(
                                self.gateway.config.slo_classes[cls],
                                "priority", 0))}
        replicas = []
        for r in self.gateway.replicas:
            row = {"name": r.name, "alive": r.alive, "paused": r.paused,
                   "draining": r.draining, "load": r.load, "spec": None}
            sp_cur = cur["spec"].get(r.name)
            if sp_cur is not None:
                sp_base = base["spec"].get(r.name, sp_cur)
                params = r.spec_params() or {}
                row["spec"] = {
                    "d_drafted": sp_cur["drafted"] - sp_base["drafted"],
                    "d_accepted": sp_cur["accepted"] - sp_base["accepted"],
                    "k": params.get("k", 0),
                    "tree_width": params.get("tree_width", 1)}
            replicas.append(row)
        idle_frac = None
        idle_frac_raw = None
        if "goodput" in cur and "goodput" in base:
            d_wall = cur["goodput"]["wall_s"] - base["goodput"]["wall_s"]
            if d_wall > 1e-6:
                idle_frac_raw = max(0.0, min(1.0, (cur["goodput"]["idle_s"]
                                                   - base["goodput"]["idle_s"]) / d_wall))
                idle_frac = idle_frac_raw
                alpha = self.config.ewma_alpha
                if alpha > 0.0:
                    # optional EWMA (control.ewma_alpha, default off): one
                    # bursty sub-window dip below the drain band otherwise
                    # resets the policy's sustain counter every burst, so a
                    # genuinely idle fleet never drains
                    self._idle_ewma = (idle_frac_raw if self._idle_ewma is None
                                       else alpha * idle_frac_raw
                                       + (1.0 - alpha) * self._idle_ewma)
                    idle_frac = self._idle_ewma
        buckets = {}
        gp = get_goodput()
        for src in gp.sentinel.report().values():
            for bucket, count in (src.get("by_bucket") or {}).items():
                buckets[bucket] = buckets.get(bucket, 0) + int(count)
        snap = {"now": now, "window_s": now - base["t"], "classes": classes,
                "replicas": replicas, "depth_total": adm.depth(),
                "idle_frac": idle_frac, "idle_frac_raw": idle_frac_raw,
                "compile_buckets": buckets}
        self._last_snap = snap
        return snap

    def _inflight_rids(self, cap: int = 64):
        """Request ids in flight across the fleet AT actuation time — the
        decision record's join key to the timeline plane (decisions stamp
        ``time.time``; requests stamp ``perf_counter``; the roster is the
        one clock-free 'this actuation overlapped that request' join).
        Bounded: a decision record must stay one log line."""
        rids = []
        for r in self.gateway.replicas:
            for row in r.inflight_summaries():
                rid = row.get("request_id")
                if rid:
                    rids.append(rid)
                    if len(rids) >= cap:
                        return rids
        return rids

    # -- actuation (the ONLY sanctioned actuator call sites) -----------------
    def _actuate(self, policy, prop, now: float) -> None:
        horizon = now - self.config.window_s
        while self._actuation_t and self._actuation_t[0] <= horizon:
            self._actuation_t.popleft()
        if len(self._actuation_t) >= self.config.max_actuations_per_window:
            self.decisions.emit(policy=policy.name, action=prop["action"],
                                applied=False,
                                reason="deferred: actuation budget exhausted "
                                       f"({self.config.max_actuations_per_window}"
                                       f"/{self.config.window_s}s)",
                                sensors=prop["sensors"],
                                inflight_rids=self._inflight_rids())
            self.stats["deferred"] += 1
            return
        apply_fn = getattr(self, f"_apply_{prop['kind']}")
        if apply_fn(policy, prop):
            self._actuation_t.append(now)
            self._cooldown_until[policy.name] = now + self.config.cooldown_s
            self.stats["applied"] += 1
        else:
            self.stats["deferred"] += 1

    def _apply_admission(self, policy, prop) -> bool:
        args = prop["args"]
        adm = self.gateway.admission
        if args.get("clear"):
            adm.clear_depth_override(args["slo_class"])
            result = {"cleared": True}
        else:
            result = adm.set_depth_override(
                args["slo_class"],
                max_queue_depth=args.get("max_queue_depth"),
                max_queue_uncached_tokens=args.get("max_queue_uncached_tokens"))
        self.decisions.emit(policy=policy.name, action=prop["action"],
                            applied=True, reason=prop["reason"],
                            sensors=prop["sensors"], result=result,
                            inflight_rids=self._inflight_rids())
        return True

    def _apply_scale(self, policy, prop) -> bool:
        args = prop["args"]
        rep = next((r for r in self.gateway.replicas
                    if r.name == args["replica"]), None)
        if rep is None:
            self.decisions.emit(policy=policy.name, action=prop["action"],
                                applied=False, reason="replica gone",
                                sensors=prop["sensors"],
                                inflight_rids=self._inflight_rids())
            return False
        op = args["op"]
        if op == "drain":
            rep.drain()
        elif op == "undrain":
            rep.undrain()
        else:  # "restart"
            rep.restart()
        self.decisions.emit(policy=policy.name, action=prop["action"],
                            applied=True, reason=prop["reason"],
                            sensors=prop["sensors"],
                            result={"replica": rep.name, "op": op},
                            inflight_rids=self._inflight_rids())
        return True

    def _apply_retune(self, policy, prop) -> bool:
        args = prop["args"]
        tuner = self._get_tuner()
        best, error = None, None
        try:
            if args["sweep"] == "paged":
                best = tuner.tune_paged(T=args["T"])
            else:
                best = tuner.tune_paged_decode()
            tuner.registry.save()
        except Exception as e:  # noqa: BLE001 — a failed sweep never kills the loop
            error = f"{type(e).__name__}: {str(e)[:200]}"
        applied = error is None and best is not None
        self.decisions.emit(policy=policy.name, action=prop["action"],
                            applied=applied, reason=prop["reason"],
                            sensors=prop["sensors"],
                            result={"bucket": args["bucket"], "best": best,
                                    "error": error},
                            inflight_rids=self._inflight_rids())
        return applied

    def _apply_spec(self, policy, prop) -> bool:
        args = prop["args"]
        rep = next((r for r in self.gateway.replicas
                    if r.name == args["replica"]), None)
        result = None
        if rep is not None:
            result = rep.set_spec_params(k=args.get("k"),
                                         tree_width=args.get("tree_width"))
        applied = result is not None
        self.decisions.emit(policy=policy.name, action=prop["action"],
                            applied=applied,
                            reason=prop["reason"] if applied
                            else "replica gone or not speculating",
                            sensors=prop["sensors"], result=result,
                            inflight_rids=self._inflight_rids())
        return applied

    def _get_tuner(self):
        if self._tuner is None:
            from ...autotuning.kernel_config import (KernelAutotuner,
                                                     get_kernel_registry)
            self._tuner = KernelAutotuner(self.config.retune_artifact_dir,
                                          registry=get_kernel_registry())
        return self._tuner

    # -- export surfaces -----------------------------------------------------
    def gauge_rows(self):
        rows = [("control/actuations", {}, float(self.stats["applied"])),
                ("control/deferred", {}, float(self.stats["deferred"]))]
        for cls, w in (self._last_snap.get("classes") or {}).items():
            done = w.get("d_completed", 0)
            if done:
                rows.append(("control/slo_miss_rate", {"slo_class": cls},
                             round(w.get("d_miss", 0) / done, 4)))
        return rows

    def state(self) -> dict:
        return {"policies": [p.name for p in self.policies],
                "interval_s": self.config.interval_s,
                "window_s": self.config.window_s,
                "max_actuations_per_window": self.config.max_actuations_per_window,
                **self.stats,
                "overrides": self.gateway.admission.state().get("depth_overrides", {}),
                "decisions": self.decisions.state(),
                "recent_decisions": self.decisions.recent(10)}

    def decision_dump(self) -> dict:
        """Forensic stall-dump provider: the full in-memory decision ring —
        what the controller did (and declined to do) leading into a wedge."""
        return {"decisions": self.decisions.recent(),
                "snapshot": self._last_snap, **self.stats}
