"""First-class decision log for the serving control plane.

Every actuation the controller applies — and every proposal it defers
past the flap budget — becomes ONE structured decision record carrying
the sensor readings that justified it. The record fans out to every
forensic surface the repo already has:

  * a bounded, atomically-rotated JSONL file (the reqtrace
    ``RequestLog`` chassis — the log can never grow unbounded);
  * an in-memory ring (``GET /v1/control`` + the health plane's stall
    dump provider read it without touching the file);
  * ``control/*`` Prometheus counters (``tools/perf_sentinel.py``
    audits the controller through these);
  * a ``control/decision`` tracer instant + flight-recorder breadcrumb
    (the decision lands in the same timeline as the requests it
    affected).

The emit method is deliberately named ``emit`` (not ``record``/``write``)
so ``tools/check_control_actuators.py`` can gate on the literal call name
without colliding with the registry/flight-recorder verbs.
"""

import threading
import time
from collections import deque
from typing import Optional

from ...monitor.flight import get_flight_recorder
from ...monitor.metrics import get_metrics
from ...monitor.trace import get_tracer
from ..reqtrace import RequestLog

__all__ = ["DecisionLog"]


class DecisionLog:
    """Bounded JSONL + in-memory ring of controller decisions."""

    def __init__(self, config):
        self.config = config
        self._lock = threading.Lock()
        self._ring = deque(maxlen=max(1, int(config.last_n)))
        self._log: Optional[RequestLog] = None
        if config.decision_log_path:
            self._log = RequestLog(config.decision_log_path,
                                   max_bytes=config.decision_log_max_bytes,
                                   max_files=config.decision_log_max_files)
        self.stats = {"applied": 0, "deferred": 0}

    def emit(self, policy: str, action: str, applied: bool, reason: str,
             sensors: dict, **fields) -> dict:
        """Log one decision. ``applied=False`` = the proposal was DEFERRED
        (flap budget / cooldown) — it still gets a full record, because an
        un-applied decision is exactly what a flapping-loop post-mortem
        needs to see. Returns the record."""
        rec = {"t": round(time.time(), 3), "policy": str(policy),
               "action": str(action), "applied": bool(applied),
               "reason": str(reason), "sensors": dict(sensors or {}), **fields}
        reg = get_metrics()
        if applied:
            reg.counter("control/actuations_total").inc()
            reg.counter(f"control/actuations_{policy}_total").inc()
        else:
            reg.counter("control/deferred_total").inc()
        with self._lock:
            self.stats["applied" if applied else "deferred"] += 1
            self._ring.append(rec)
            if self._log is not None:
                self._log.write(rec)
        # request_id=None: a controller decision is fleet-scoped, not
        # request-scoped (the sensors dict names the classes/replicas it
        # read) — the keyword is still required by check_request_tracing
        get_tracer().instant("control/decision", tid="serving",
                             request_id=None,
                             policy=rec["policy"], action=rec["action"],
                             applied=rec["applied"], reason=rec["reason"])
        get_flight_recorder().record("control", rec["action"],
                                     policy=rec["policy"],
                                     applied=rec["applied"],
                                     reason=rec["reason"])
        return rec

    def recent(self, n: Optional[int] = None):
        """Newest-last decision records from the in-memory ring."""
        with self._lock:
            rows = list(self._ring)
        return rows if n is None else rows[-int(n):]

    def state(self) -> dict:
        with self._lock:
            return {"path": self.config.decision_log_path or None,
                    "ring": len(self._ring), **self.stats}

    def close(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.close()
