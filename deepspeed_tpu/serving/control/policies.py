"""Pluggable control policies: sensors in, proposals out.

Each policy is a small stateful object with one method —
``propose(snap) -> [proposal, ...]`` — where ``snap`` is the controller's
windowed sensor snapshot (see ``ServingController._sense``). A proposal
is a plain dict::

    {"kind": "admission" | "scale" | "retune" | "spec",
     "action": <short verb string>,
     "reason": <why, one line>,
     "sensors": <the readings that justified it>,
     "args": <kwargs for the controller's _apply_* helper>}

Policies NEVER touch an actuator: the controller's ``_apply_*`` helpers
are the only sanctioned mutation sites (``tools/check_control_actuators.py``
enforces this with an AST gate), and the controller owns the global flap
budget and per-policy cooldowns. A policy's only job is to read the
window and say what it wants.

Hysteresis lives here: every policy acts on a BAND (tighten threshold
strictly above relax threshold) and requires its condition to hold for
``sustain_ticks`` consecutive ticks — one noisy sample never actuates,
and the act/undo thresholds never chase each other.
"""

import re
from typing import Dict, List

__all__ = ["AdmissionPolicy", "ScalingPolicy", "RetunePolicy",
           "SpeculationPolicy", "build_policies"]


class _Sustain:
    """Consecutive-tick counter: ``hit(key, cond)`` returns True only once
    ``cond`` has been True for ``need`` consecutive calls on ``key``."""

    def __init__(self, need: int):
        self.need = max(1, int(need))
        self._runs: Dict[str, int] = {}

    def hit(self, key: str, cond: bool) -> bool:
        run = self._runs.get(key, 0) + 1 if cond else 0
        self._runs[key] = run
        return run >= self.need


class AdmissionPolicy:
    """(a) SLO-aware admission: a class's windowed TTFT/TPOT miss rate
    drives queue-depth overrides on its VICTIMS — the lower-priority
    classes sharing the fleet. Shedding the class that is missing its own
    SLO only thins the traffic the SLO exists to protect (measured: the
    first cut of this policy did exactly that and made the control_ab
    WORSE); shedding the background behind it removes the prefill work the
    misses are queued behind. Past the tighten threshold the
    lowest-priority victim's depth halves (never under ``min_queue_depth``);
    under the relax threshold overrides restore in reverse — doubling back
    toward (and finally clearing to) the configured bound. A class with no
    lower-priority victim left to shed falls back to self-shedding, the
    last resort that at least bounds its own queue."""

    name = "admission"

    def __init__(self, config):
        self.config = config
        self._sustain = _Sustain(config.sustain_ticks)
        # overridden class -> effective depth when first tightened (the
        # relax/clear target)
        self._entry_depth: Dict[str, int] = {}

    def _tighten_for(self, cls, victim, classes, miss_rate, sensors):
        """Halve ``victim``'s depth on behalf of missing class ``cls``;
        None when the victim is already at the floor."""
        cfg = self.config
        vw = classes[victim]
        depth = vw.get("effective_depth", 0)
        base = depth if depth > 0 else max(4 * cfg.min_queue_depth,
                                           2 * vw.get("queue_depth", 0), 8)
        new_depth = max(cfg.min_queue_depth, base // 2)
        if new_depth >= base and vw.get("override_active"):
            return None  # already floored — nothing left to shed here
        if victim not in self._entry_depth:
            self._entry_depth[victim] = base
        verb = "shed" if victim != cls else "self-shed"
        return {"kind": "admission", "action": "tighten_depth",
                "reason": f"{verb} {victim} to protect {cls}: miss_rate "
                          f"{miss_rate:.2f} >= {cfg.slo_miss_tighten}",
                "sensors": {**sensors, "victim": victim,
                            "victim_depth": depth},
                "args": {"slo_class": victim, "max_queue_depth": new_depth}}

    def propose(self, snap) -> List[dict]:
        cfg = self.config
        classes = snap.get("classes", {})
        out = []
        for cls, w in classes.items():
            done = w.get("d_completed", 0)
            if done < cfg.min_window_completions:
                self._sustain.hit(f"tighten/{cls}", False)
                self._sustain.hit(f"relax/{cls}", False)
                continue
            miss_rate = w.get("d_miss", 0) / done
            prio = w.get("priority", 0)
            # victims: strictly lower-priority classes, least important first
            victims = sorted((v for v, vw in classes.items()
                              if vw.get("priority", 0) > prio),
                             key=lambda v: (-classes[v].get("priority", 0), v))
            # restorable: own override first (it sheds protected traffic —
            # most harmful), then victims in reverse shed order
            restorable = ([cls] if w.get("override_active") else []) \
                + [v for v in reversed(victims)
                   if classes[v].get("override_active")]
            sensors = {"slo_class": cls, "miss_rate": round(miss_rate, 4),
                       "window_completions": done,
                       "window_misses": w.get("d_miss", 0),
                       "queue_depth": w.get("queue_depth", 0),
                       "admitted_rate": w.get("admitted_rate", 0.0),
                       "effective_depth": w.get("effective_depth", 0)}
            tighten = self._sustain.hit(f"tighten/{cls}",
                                        miss_rate >= cfg.slo_miss_tighten)
            relax = self._sustain.hit(
                f"relax/{cls}",
                bool(restorable) and miss_rate <= cfg.slo_miss_relax)
            if tighten:
                for victim in victims + [cls]:
                    prop = self._tighten_for(cls, victim, classes, miss_rate,
                                             sensors)
                    if prop is not None:
                        out.append(prop)
                        break
            elif relax:
                victim = restorable[0]
                depth = classes[victim].get("effective_depth", 0)
                entry = self._entry_depth.get(victim, 0)
                new_depth = max(1, depth) * 2
                reason = (f"restore {victim}: {cls} miss_rate "
                          f"{miss_rate:.2f} <= {cfg.slo_miss_relax}")
                if entry and new_depth >= entry:
                    self._entry_depth.pop(victim, None)
                    out.append({"kind": "admission", "action": "clear_depth",
                                "reason": reason,
                                "sensors": {**sensors, "victim": victim},
                                "args": {"slo_class": victim, "clear": True}})
                else:
                    out.append({"kind": "admission", "action": "relax_depth",
                                "reason": reason,
                                "sensors": {**sensors, "victim": victim},
                                "args": {"slo_class": victim,
                                         "max_queue_depth": new_depth}})
        return out


class ScalingPolicy:
    """(b) Replica scaling/draining: sustained fleet idle drains ONE
    un-draining replica (the router steers around it, in-flight work
    finishes); sustained queue pressure un-drains one (or restarts a dead
    one — the stronger form of "bring capacity back"). The hysteresis is
    structural: the drain signal (idle) and the un-drain signal (queued
    work) cannot both hold, and ``min_active_replicas`` floors the fleet."""

    name = "scaling"

    def __init__(self, config):
        self.config = config
        self._sustain = _Sustain(config.sustain_ticks)

    def propose(self, snap) -> List[dict]:
        cfg = self.config
        reps = snap.get("replicas", [])
        depth_total = snap.get("depth_total", 0)
        live = [r for r in reps if r["alive"]]
        active = [r for r in live if not r["draining"]]
        idle_frac = snap.get("idle_frac")
        fleet_idle = (idle_frac >= cfg.idle_frac_drain) if idle_frac is not None \
            else (depth_total == 0 and all(r["load"] == 0 for r in active))
        sensors = {"depth_total": depth_total, "idle_frac": idle_frac,
                   "live": len(live), "active": len(active),
                   "draining": len(live) - len(active),
                   "dead": len(reps) - len(live)}
        out = []
        pressure = self._sustain.hit("undrain",
                                     depth_total >= cfg.queue_depth_undrain)
        idle = self._sustain.hit("drain",
                                 fleet_idle and len(active) > cfg.min_active_replicas)
        if pressure:
            dead = [r for r in reps if not r["alive"]]
            drained = [r for r in live if r["draining"]]
            if dead:
                out.append({"kind": "scale", "action": "restart_replica",
                            "reason": f"queued {depth_total} >= "
                                      f"{cfg.queue_depth_undrain} with a dead replica",
                            "sensors": sensors,
                            "args": {"replica": dead[0]["name"], "op": "restart"}})
            elif drained:
                out.append({"kind": "scale", "action": "undrain_replica",
                            "reason": f"queued {depth_total} >= "
                                      f"{cfg.queue_depth_undrain}",
                            "sensors": sensors,
                            "args": {"replica": drained[0]["name"], "op": "undrain"}})
        elif idle:
            # drain the least-loaded active replica (ties by name for
            # deterministic drills)
            victim = min(active, key=lambda r: (r["load"], r["name"]))
            out.append({"kind": "scale", "action": "drain_replica",
                        "reason": "sustained idle "
                                  + (f"(idle_frac {idle_frac:.2f})"
                                     if idle_frac is not None else "(zero load)"),
                        "sensors": sensors,
                        "args": {"replica": victim["name"], "op": "drain"}})
        return out


class RetunePolicy:
    """(c) Online kernel re-tuning: the recompile sentinel's hot
    steady-state compile buckets nominate background ``KernelAutotuner``
    sweeps, persisted through the ``KernelConfigRegistry``. Each bucket is
    nominated AT MOST ONCE per controller lifetime and the total sweep
    budget is bounded — a sweep is minutes of device time, so the policy
    is a nomination filter, not a loop."""

    name = "retune"

    _PUT = re.compile(r"^put/t(\d+)")
    _DECODE = re.compile(r"^decode/")

    def __init__(self, config):
        self.config = config
        self._nominated = set()
        self._launched = 0

    def propose(self, snap) -> List[dict]:
        cfg = self.config
        out = []
        for bucket, count in sorted(snap.get("compile_buckets", {}).items(),
                                    key=lambda kv: (-kv[1], kv[0])):
            if self._launched >= cfg.retune_max_sweeps:
                break
            if bucket in self._nominated or count < cfg.retune_min_bucket_count:
                continue
            sensors = {"bucket": bucket, "unexpected_compiles": count}
            m = self._PUT.match(bucket)
            if m:
                self._nominated.add(bucket)
                self._launched += 1
                out.append({"kind": "retune", "action": "tune_paged",
                            "reason": f"hot untuned bucket {bucket} "
                                      f"({count} steady-state compiles)",
                            "sensors": sensors,
                            "args": {"bucket": bucket, "sweep": "paged",
                                     "T": int(m.group(1))}})
            elif self._DECODE.match(bucket):
                self._nominated.add(bucket)
                self._launched += 1
                out.append({"kind": "retune", "action": "tune_paged_decode",
                            "reason": f"hot untuned bucket {bucket} "
                                      f"({count} steady-state compiles)",
                            "sensors": sensors,
                            "args": {"bucket": bucket, "sweep": "paged_decode"}})
            else:
                # verify/... and unknown shapes have no sweep mapping yet;
                # mark them handled so they don't re-propose every tick
                self._nominated.add(bucket)
        return out


class SpeculationPolicy:
    """(d) Per-replica speculative adaptation: the windowed draft accept
    rate tunes K within ``[spec_k_min, spec_k_max]`` (and optionally tree
    width up to ``spec_tree_width_max``). High acceptance = the drafter is
    under-asked, raise K; low acceptance = verify tokens are being burned,
    lower K (the PR 13 per-uid backoff stays as the degenerate in-round
    case)."""

    name = "speculation"

    def __init__(self, config):
        self.config = config
        self._sustain = _Sustain(config.sustain_ticks)

    def propose(self, snap) -> List[dict]:
        cfg = self.config
        out = []
        for r in snap.get("replicas", []):
            sp = r.get("spec")
            if not sp or not r["alive"]:
                continue
            drafted = sp.get("d_drafted", 0)
            if drafted < cfg.spec_min_window_drafted:
                self._sustain.hit(f"up/{r['name']}", False)
                self._sustain.hit(f"down/{r['name']}", False)
                continue
            accept = sp.get("d_accepted", 0) / drafted
            k = sp.get("k", 0)
            sensors = {"replica": r["name"], "accept_rate": round(accept, 4),
                       "window_drafted": drafted,
                       "window_accepted": sp.get("d_accepted", 0), "k": k}
            up = self._sustain.hit(f"up/{r['name']}",
                                   accept >= cfg.spec_accept_high
                                   and k < cfg.spec_k_max)
            down = self._sustain.hit(f"down/{r['name']}",
                                     accept <= cfg.spec_accept_low
                                     and k > cfg.spec_k_min)
            if up:
                args = {"replica": r["name"], "k": min(cfg.spec_k_max, k + 1)}
                if cfg.spec_tree_width_max > 0:
                    args["tree_width"] = min(cfg.spec_tree_width_max,
                                             sp.get("tree_width", 1) + 1)
                out.append({"kind": "spec", "action": "raise_k",
                            "reason": f"accept_rate {accept:.2f} >= "
                                      f"{cfg.spec_accept_high}",
                            "sensors": sensors, "args": args})
            elif down:
                out.append({"kind": "spec", "action": "lower_k",
                            "reason": f"accept_rate {accept:.2f} <= "
                                      f"{cfg.spec_accept_low}",
                            "sensors": sensors,
                            "args": {"replica": r["name"],
                                     "k": max(cfg.spec_k_min, k - 1)}})
        return out


_BUILDERS = {"admission": AdmissionPolicy, "scaling": ScalingPolicy,
             "retune": RetunePolicy, "speculation": SpeculationPolicy}


def build_policies(config) -> List[object]:
    """Instantiate the armed policies in config order (config validation
    already rejected unknown names)."""
    return [_BUILDERS[name](config) for name in config.policies]
