"""SLO-aware admission: per-class bounded queues with 429/503 shedding.

Admission is where the gateway stops over-promising: each (replica, SLO
class) pair has a bounded queue, and the cost charged for a request is its
**uncached** prompt tokens — the prefix cache is consulted through the same
pure probe the ``DynamicSplitFuseScheduler`` admission path uses
(``engine.probe_prefix``: no references taken, no LRU touch, no stats), so
a shed request leaves the radix tree untouched and a hot shared prefix
makes its followers cheap at the door, not just at the prefill.

Shedding contract (the HTTP layer maps these straight to status codes):

  * ``429`` — the class queue for the routed replica is past its
    configured depth (requests or uncached tokens): the client should back
    off and retry; the gateway is alive and draining work;
  * ``503`` — no live replica / gateway draining: retry against another
    instance (the LB sees the same signal via ``/readyz``).

Queues are plain bounded deques under one lock; replicas pull in class
priority order (``SLOClassConfig.priority``), so an interactive request
admitted after a pile of batch work still reaches the scheduler first.
"""

import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from ..monitor.metrics import get_metrics


class AdmissionController:
    """Bounded per-(replica, class) queues + uncached-token accounting."""

    def __init__(self, config, reqtrace=None, meter=None):
        self.config = config
        self.reqtrace = reqtrace
        # tenant metering plane (serving/metering.py): None (the default)
        # keeps every hook below at one attribute check — the shed path
        # then stays byte-identical to the pre-metering controller
        self.meter = meter
        self._lock = threading.Lock()
        self._queues: Dict[Tuple[str, str], deque] = {}
        self._queued_uncached: Dict[Tuple[str, str], int] = {}
        self._order = config.class_order()
        # disaggregated pool roles by replica name (serving/disagg.py):
        # empty when disagg is off — gauge rows then carry no pool label,
        # byte-identical to the pre-disagg scrape
        self._roles: Dict[str, str] = {}
        self.stats = {"admitted": 0, "shed": 0,
                      "uncached_tokens_admitted": 0, "cached_tokens_admitted": 0}
        # per-SLO-class admitted/shed counts behind the scrapeable shed-rate
        # gauge (gauge_rows) — the aggregate stats above can't give per-class
        self.class_stats: Dict[str, Dict[str, int]] = {}
        # control-plane depth overrides, keyed by class: the ONE sanctioned
        # mutation point for live admission limits (check_control_actuators
        # keeps the setter reachable only from serving/control/). Empty dict
        # = configured limits apply untouched.
        self._depth_overrides: Dict[str, Dict[str, int]] = {}
        # per-class admit timestamps behind the admitted-rate gauge — a
        # bounded deque per class, pruned to the rate window on read
        self._admit_times: Dict[str, deque] = {}

    ADMIT_RATE_WINDOW_S = 30.0

    # -- control-plane actuators (serving/control/ only) ---------------------
    def set_depth_override(self, slo_class: str,
                           max_queue_depth: Optional[int] = None,
                           max_queue_uncached_tokens: Optional[int] = None) -> dict:
        """Override a class's configured queue bounds at runtime (the
        admission actuator). ``None`` leaves that bound at its configured
        value; the override is consulted by ``try_admit`` and
        ``below_shed_threshold`` in place of the static config."""
        ov = {}
        if max_queue_depth is not None:
            ov["max_queue_depth"] = max(0, int(max_queue_depth))
        if max_queue_uncached_tokens is not None:
            ov["max_queue_uncached_tokens"] = max(0, int(max_queue_uncached_tokens))
        with self._lock:
            self._depth_overrides[slo_class] = ov
        return dict(ov)

    def clear_depth_override(self, slo_class: str) -> None:
        with self._lock:
            self._depth_overrides.pop(slo_class, None)

    def effective_limits(self, slo_class: str) -> Dict[str, int]:
        """The bounds ``try_admit`` would enforce for ``slo_class`` right
        now — configured values with any control override applied."""
        with self._lock:
            return dict(zip(("max_queue_depth", "max_queue_uncached_tokens"),
                            self._limits_locked(slo_class)))

    def _limits_locked(self, slo_class: str) -> Tuple[int, int]:
        cls = self.config.slo_classes.get(slo_class)
        depth = cls.max_queue_depth if cls is not None else 0
        tokens = cls.max_queue_uncached_tokens if cls is not None else 0
        ov = self._depth_overrides.get(slo_class)
        if ov:
            depth = ov.get("max_queue_depth", depth)
            tokens = ov.get("max_queue_uncached_tokens", tokens)
        return int(depth), int(tokens)

    def admitted_rate(self, slo_class: str) -> float:
        """Admits/s for ``slo_class`` over the trailing rate window."""
        with self._lock:
            return self._admitted_rate_locked(slo_class)

    def _admitted_rate_locked(self, slo_class: str) -> float:
        times = self._admit_times.get(slo_class)
        if not times:
            return 0.0
        horizon = time.perf_counter() - self.ADMIT_RATE_WINDOW_S
        while times and times[0] < horizon:
            times.popleft()
        if not times:
            return 0.0
        span = max(1e-3, min(self.ADMIT_RATE_WINDOW_S,
                             time.perf_counter() - times[0]))
        return len(times) / span

    def set_roles(self, roles: Dict[str, str]) -> None:
        """Arm the disaggregation role map (gateway wiring): queue-depth
        gauge rows gain a ``pool`` label so a dashboard can see which POOL
        a backlog is building in, not just which replica."""
        self._roles = dict(roles)

    # -- depth introspection -------------------------------------------------
    def depth(self, replica: Optional[str] = None, slo_class: Optional[str] = None) -> int:
        with self._lock:
            return sum(len(q) for (r, c), q in self._queues.items()
                       if (replica is None or r == replica)
                       and (slo_class is None or c == slo_class))

    def below_shed_threshold(self) -> bool:
        """True while every bounded class queue has headroom — the
        readiness half of /healthz ``ready`` (an LB drains the instance
        when admission is already refusing work)."""
        with self._lock:
            for (r, c), q in self._queues.items():
                depth, _ = self._limits_locked(c)
                if depth > 0 and len(q) >= depth:
                    return False
        return True

    # -- admission -----------------------------------------------------------
    def try_admit(self, req, replica) -> Tuple[bool, Optional[str]]:
        """Admit ``req`` onto ``replica``'s class queue, charging its
        uncached prompt tokens. Returns ``(True, None)`` or
        ``(False, reason)`` — a refusal mutates nothing (probe is pure)."""
        self.config.slo_classes[req.slo_class]  # KeyError on unknown class
        # the probe runs OUTSIDE the queue lock (it walks the radix tree);
        # single-writer per tree (only the replica driver mutates it), so
        # the credit is a floor — concurrent publishes only raise it
        n_cached, _shared, _tree_only, _match = replica.engine.probe_prefix(req.prompt)
        uncached = int(req.prompt.size - n_cached)
        if req.ctx is not None:
            # the probe already ran: a SHED record should still say how much
            # of the refused prompt the cache could have served
            req.ctx.prefix_hit_tokens = int(n_cached)
        key = (replica.name, req.slo_class)
        reg = get_metrics()
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
                self._queued_uncached[key] = 0
            cs = self.class_stats.setdefault(req.slo_class,
                                             {"admitted": 0, "shed": 0})
            max_depth, max_tokens = self._limits_locked(req.slo_class)
            if max_depth > 0 and len(q) >= max_depth:
                reason = "queue_depth"
            elif (max_tokens > 0
                  and self._queued_uncached[key] + uncached > max_tokens):
                reason = "queue_tokens"
            else:
                reason = None
            if reason is not None:
                self.stats["shed"] += 1
                cs["shed"] += 1
                reg.counter(f"gateway/shed_{req.slo_class}_total").inc()
                if self.meter is not None:
                    # shed split BY TENANT (bounded by the meter's top-K
                    # aggregator): one tenant's burst filling a class queue
                    # is attributable, instead of reading as systemic
                    # overload on the aggregate per-class counter above
                    self.meter.on_shed(req.tenant, req.slo_class, reason)
                return False, reason
            req.cached_tokens = int(n_cached)
            req.uncached_tokens = uncached
            req.replica_name = replica.name
            req.t_admitted = time.perf_counter()
            if req.ctx is not None:
                # stamped BEFORE the request is published to the queue: the
                # replica driver can dequeue (and even finish) it the moment
                # it lands, racing any later stamp — pure field write here,
                # span emission stays outside the lock
                req.ctx.t_admitted = req.t_admitted
            q.append(req)
            self._queued_uncached[key] += uncached
            self.stats["admitted"] += 1
            cs["admitted"] += 1
            self._admit_times.setdefault(req.slo_class,
                                         deque(maxlen=4096)).append(req.t_admitted)
            self.stats["uncached_tokens_admitted"] += uncached
            self.stats["cached_tokens_admitted"] += int(n_cached)
        reg.counter(f"gateway/requests_{req.slo_class}_total").inc()
        reg.counter("gateway/admitted_uncached_tokens_total").inc(uncached)
        reg.counter("gateway/admitted_cached_tokens_total").inc(int(n_cached))
        if self.meter is not None:
            # the admission charge IS the token meter: uncached prompt
            # tokens billed, prefix-cache tokens credited as savings
            self.meter.on_admitted(req.tenant, uncached, int(n_cached))
        reg.gauge(f"gateway/queue_depth_{req.slo_class}").set(self.depth(slo_class=req.slo_class))
        return True, None

    def pop_for(self, replica_name: str):
        """Next queued request for ``replica_name`` in class priority order
        (FIFO within a class). None when nothing is queued."""
        with self._lock:
            for c in self._order:
                q = self._queues.get((replica_name, c))
                if q:
                    req = q.popleft()
                    self._queued_uncached[(replica_name, c)] -= req.uncached_tokens
                    depth = sum(len(qq) for (r, cc), qq in self._queues.items()
                                if cc == c)
                    get_metrics().gauge(f"gateway/queue_depth_{c}").set(depth)
                    return req
        return None

    def fail_all(self, reason: str):
        """Drain every queue, failing the waiting streams (gateway stop)."""
        with self._lock:
            reqs = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._queued_uncached.clear()
        for req in reqs:
            req.stream.finish(reason="error", error=reason)
            if self.reqtrace is not None:
                self.reqtrace.finalize(req)

    def cancel(self, req) -> bool:
        """Remove a still-queued request (client gave up before a replica
        pulled it). False when it already left the queue — the caller then
        routes the cancel to the replica driver instead."""
        key = (req.replica_name, req.slo_class)
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                return False
            try:
                q.remove(req)
            except ValueError:
                return False
            self._queued_uncached[key] -= req.uncached_tokens
        return True

    def fail_for(self, replica_name: str, reason: str) -> int:
        """Drain ONE replica's queues, failing the waiting streams — the
        driver's exit path (crash or stop). Without this, requests admitted
        onto a replica whose driver died would wait out the full client
        timeout, and a stranded full queue would pin readiness to False for
        the whole gateway. Failures here are counted under
        ``gateway/replica_failed_requests_total`` — DISTINCT from the shed
        counters, so a dashboard can tell "replica died under its queue"
        from "queue full, client backed off"."""
        reqs = []
        with self._lock:
            for (r, c), q in self._queues.items():
                if r == replica_name:
                    reqs.extend(q)
                    q.clear()
                    self._queued_uncached[(r, c)] = 0
        if reqs:
            get_metrics().counter("gateway/replica_failed_requests_total").inc(len(reqs))
        for req in reqs:
            req.stream.finish(reason="error", error=reason)
            if self.reqtrace is not None:
                self.reqtrace.finalize(req)
        return len(reqs)

    def gauge_rows(self):
        """Admission state as labelled Prometheus gauge rows for the
        ``monitor/export.py`` ``extra_gauges`` path — per-(replica, class)
        queue depth + queued uncached tokens, and per-class shed rate.
        Before this, queue state was reachable only via the /healthz JSON,
        invisible to an actual Prometheus scraper."""
        rows = []
        with self._lock:
            for (r, c), q in self._queues.items():
                labels = {"replica": r, "slo_class": c}
                if self._roles:
                    labels["pool"] = self._roles.get(r, "mixed")
                rows.append(("gateway/queue_depth", labels, float(len(q))))
                rows.append(("gateway/queued_uncached_tokens", labels,
                             float(self._queued_uncached.get((r, c), 0))))
            for c, cs in self.class_stats.items():
                total = cs["admitted"] + cs["shed"]
                rows.append(("gateway/shed_rate", {"slo_class": c},
                             (cs["shed"] / total) if total else 0.0))
                rows.append(("gateway/admitted_rate", {"slo_class": c},
                             round(self._admitted_rate_locked(c), 4)))
        return rows

    def state(self) -> dict:
        with self._lock:
            queues = {f"{r}/{c}": len(q) for (r, c), q in self._queues.items() if q}
            per_class = {c: dict(cs) for c, cs in self.class_stats.items()}
            overrides = {c: dict(ov) for c, ov in self._depth_overrides.items()}
        return {"queues": queues, "per_class": per_class,
                "depth_overrides": overrides, **self.stats}
