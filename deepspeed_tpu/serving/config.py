"""``serving.gateway`` configuration block.

Plain dataclasses (the gateway is a standalone serving entry point, not a
training-engine subsystem, so it does not ride the pydantic runtime config):
:meth:`GatewayConfig.from_dict` accepts the ds_config-style nested dict

.. code-block:: python

    {"serving": {"gateway": {
        "enabled": true,
        "port": 8100,
        "router": "prefix",
        "slo_classes": {
            "interactive": {"max_queue_depth": 32, "ttft_target_ms": 250},
            "batch": {"priority": 1, "max_queue_depth": 256},
        },
    }}}

via :meth:`GatewayConfig.from_ds_config`. EVERY knob defaults to off:
``enabled=False``, depth limits 0 (= unbounded, no shedding), SLO targets 0
(= no conformance counters), ``port=0`` (= ephemeral), warmup empty.
"""

from dataclasses import dataclass, field, fields
from typing import Dict, Tuple


@dataclass
class SLOClassConfig:
    """One TTFT/TPOT service class. ``priority`` orders replica pull
    (lower = served first); depth limits of 0 disable shedding for the
    class; targets of 0 disable the SLO-miss conformance counters."""

    priority: int = 0
    # admission sheds (HTTP 429) once this many requests are queued for one
    # replica in this class; 0 = unbounded
    max_queue_depth: int = 0
    # admission sheds once the queued UNCACHED prompt tokens (the real
    # prefill cost after prefix-cache credit) exceed this; 0 = unbounded
    max_queue_uncached_tokens: int = 0
    # advisory SLO targets: a completed request past the target bumps
    # gateway/slo_{ttft,tpot}_miss_<class>_total; 0 = untracked
    ttft_target_ms: float = 0.0
    tpot_target_ms: float = 0.0


def _default_classes() -> Dict[str, SLOClassConfig]:
    # two conventional classes so an empty block is usable out of the box;
    # both unbounded/untracked until the operator sets depths/targets
    return {"interactive": SLOClassConfig(priority=0),
            "batch": SLOClassConfig(priority=1)}


@dataclass
class RequestTraceConfig:
    """``serving.gateway.tracing`` block — request-scoped tracing and the
    per-request summary log (``serving/reqtrace.py``). Presence-enables
    (the ``trace``/``health`` contract): an absent block costs the request
    path zero allocations and zero threads (test-enforced); a present one
    turns on request contexts, request-id-carrying spans on the Tracer/
    FlightRecorder, per-stage Prometheus histograms, and the JSONL summary
    log with tail-aware sampling."""

    enabled: bool = False
    # per-request summary records (JSONL, one line per terminal request);
    # "" = in-memory ring only, no file
    log_path: str = ""
    # atomic rotation: past this size the log rotates to .1/.2/... and the
    # oldest retained file is dropped — the log is bounded, never unbounded
    log_max_bytes: int = 16 << 20
    log_max_files: int = 2
    # head-sampling rate for HEALTHY requests (deterministic on request id).
    # SLO-miss / shed / error / cancelled records are ALWAYS retained
    # regardless — tails are the records the log exists for.
    sample_rate: float = 1.0
    # terminal-summary ring retained in memory (flight-dump forensics +
    # programmatic reads without touching the file)
    last_n: int = 64


@dataclass
class MeteringConfig:
    """``serving.gateway.metering`` block — tenant-scoped resource metering
    & fairness observability (``serving/metering.py``). Presence-enables
    (the ``tracing``/``health`` contract): an absent block costs the
    request path zero allocations and zero threads — no meter object, no
    engine views, no per-block stamp arrays (test-enforced in
    ``tests/test_tenant_metering.py``)."""

    enabled: bool = False
    # tenants exported individually on /metrics and /v1/usage; everything
    # past the cut aggregates into ONE `other` row — the scrape never
    # carries more than top_k + 1 distinct tenant label values
    top_k: int = 8
    # distinct in-memory ledgers; past this bound new tenant ids fold into
    # the `other` ledger (a hostile client inventing ids cannot grow memory)
    max_tracked_tenants: int = 256
    # atomically-rotated usage JSONL (the reqtrace RequestLog pattern):
    # one record per terminal request + periodic full-ledger snapshots;
    # "" = in-memory only, no file
    usage_log_path: str = ""
    usage_log_max_bytes: int = 16 << 20
    usage_log_max_files: int = 2
    # a full per-tenant ledger snapshot line every N terminal requests
    # (0 = per-request records only)
    ledger_snapshot_every: int = 64
    # starvation detector: a tenant's windowed p99 queue wait must exceed
    # BOTH `starvation_factor` x the global p99 AND the absolute floor
    # before the latched starvation instant fires
    starvation_factor: float = 4.0
    starvation_min_wait_s: float = 0.05
    # per-tenant sliding queue-wait window the p99s are computed over
    starvation_window: int = 64


@dataclass
class ProfilingConfig:
    """``serving.gateway.profiling`` block — the on-demand ``POST
    /v1/profile`` XPlane capture endpoint (``monitor/roofline.py``'s
    :class:`CaptureManager` bracketing ``jax.profiler`` around live
    traffic). Presence-enables (the ``tracing``/``metering`` contract): an
    absent block keeps the route returning 404 and allocates nothing."""

    enabled: bool = False
    # artifact root; each capture lands as an atomically-renamed
    # subdirectory (a visible dir is always a whole, loadable artifact)
    artifact_dir: str = "/tmp/dstpu_xplane"
    # capture length when the request body names none
    default_duration_s: float = 2.0
    # hard bound: requested durations clamp here (a typo'd duration must
    # not hold the process-global profiler for an hour)
    max_duration_s: float = 60.0


@dataclass
class DisaggConfig:
    """``serving.gateway.disagg`` block — disaggregated prefill/decode
    serving (``serving/disagg.py`` + ``serving/handoff.py``). Presence-
    enables (the ``tracing``/``metering``/``profiling`` contract): an
    absent block means every replica stays ``mixed``, the router ignores
    roles, and no coordinator/ledger objects exist."""

    enabled: bool = False
    # per-replica role by LIST INDEX ('prefill' | 'decode' | 'mixed');
    # shorter than the replica list pads the tail with 'mixed'. New
    # requests place onto prefill/mixed replicas; completed prefills hand
    # off to decode/mixed replicas through the host tier.
    roles: Tuple = ()
    # generated tokens a prefill replica waits for before handing off —
    # the first token proves prefill really completed (and is the TTFT the
    # client already saw); raising it delays migration
    handoff_after_tokens: int = 1


@dataclass
class TimelineConfig:
    """``serving.gateway.timeline`` block — the causal timeline plane
    (``serving/timeline.py`` + ``monitor/timeline.py``). Presence-enables
    (the ``tracing``/``metering``/``disagg``/``control`` contract): an
    absent block means no collector object, no per-request assembly, no
    chaos observer, no thread (test-enforced). Requires the ``tracing``
    block: the assembler joins the stage stamps request tracing owns."""

    enabled: bool = False
    # assembled timelines retained in the bounded ring (newest win);
    # tail exemplars below survive past ring eviction
    last_n: int = 256
    # always-retained tail exemplars: the top-K requests by TTFT and by
    # TPOT keep their COMPLETE assembled timelines regardless of ring age
    # — the PR 7 tail-retention discipline applied to whole timelines
    exemplar_slots: int = 8
    # segments-sum acceptance tolerance as a fraction of client e2e
    # (2 ms absolute floor) — PR 7's budget extended to migrated requests
    tolerance: float = 0.10


@dataclass
class ControlConfig:
    """``serving.gateway.control`` block — the feedback control plane
    (``serving/control/``). Presence-enables (the ``tracing``/``metering``/
    ``profiling``/``disagg`` contract): an absent block means no controller
    object, no thread, zero overhead on every request path (test-enforced).

    The controller ticks every ``interval_s``, computes windowed sensor
    deltas over the trailing ``window_s``, and lets each armed policy
    propose actuations. Flap-proofing is three-layered: per-policy
    hysteresis bands (the tighten threshold strictly above the relax
    threshold), a per-policy ``cooldown_s`` after any applied actuation,
    and a global ``max_actuations_per_window`` budget — a proposal past
    the budget is logged as a DEFERRED decision, never applied."""

    enabled: bool = False
    # decision-loop tick period
    interval_s: float = 0.25
    # armed policies: 'admission' | 'scaling' | 'retune' | 'speculation'
    policies: Tuple = ("admission", "scaling", "speculation")
    # trailing sensor window the rates/deltas are computed over
    window_s: float = 5.0
    # global actuation budget per window — the provable flap bound
    max_actuations_per_window: int = 4
    # per-policy quiet period after an applied actuation
    cooldown_s: float = 1.0
    # consecutive ticks a condition must hold before a policy may act
    # (one noisy sample never actuates)
    sustain_ticks: int = 2
    # bounded decision JSONL (the reqtrace RequestLog pattern);
    # "" = in-memory ring only, no file
    decision_log_path: str = ""
    decision_log_max_bytes: int = 4 << 20
    decision_log_max_files: int = 2
    # in-memory decision ring (forensic dumps + GET /v1/control)
    last_n: int = 128
    # -- (a) admission policy: windowed SLO-miss-rate hysteresis band ------
    # tighten the class's queue bound when the windowed miss rate crosses
    # the high threshold; relax/clear once it falls under the low one
    slo_miss_tighten: float = 0.5
    slo_miss_relax: float = 0.1
    # tightening halves the effective depth, never below this floor
    min_queue_depth: int = 2
    # windowed completions required before a miss rate is trusted
    min_window_completions: int = 4
    # -- (b) scaling policy: drain on sustained idle, un-drain on queue ----
    # drain one replica when the fleet idles (goodput idle fraction at or
    # past this, or zero load without a ledger) for the sustain window
    idle_frac_drain: float = 0.9
    # optional EWMA smoothing over the windowed idle fraction (0 = off,
    # raw signal). Bursty traffic dips the raw signal below the drain band
    # for single ticks, resetting the sustain counter and under-triggering
    # drains; alpha in (0, 1] blends alpha*raw + (1-alpha)*prev so a brief
    # burst stops masking a genuinely idle fleet (smaller = smoother)
    ewma_alpha: float = 0.0
    # un-drain (or restart a dead replica) when total queued requests
    # reach this for the sustain window
    queue_depth_undrain: int = 1
    # never drain below this many un-draining live replicas
    min_active_replicas: int = 1
    # -- (c) retune policy: sentinel buckets nominate autotuner sweeps -----
    # unexpected steady-state compiles a bucket needs before nomination
    retune_min_bucket_count: int = 3
    # sweeps launched per controller lifetime (each sweep is minutes of
    # device time — the budget is deliberately small)
    retune_max_sweeps: int = 2
    # autotuner artifact root (the registry JSON the sweeps persist into,
    # unless a process-global registry is already configured)
    retune_artifact_dir: str = "/tmp/dstpu_control_retune"
    # -- (d) speculation policy: accept-rate band retunes K ----------------
    spec_accept_high: float = 0.8
    spec_accept_low: float = 0.4
    spec_k_min: int = 1
    spec_k_max: int = 8
    # 0 = never touch tree_width; otherwise K raises may widen up to this
    spec_tree_width_max: int = 0
    # windowed drafted tokens required before an accept rate is trusted
    spec_min_window_drafted: int = 16


KNOWN_POLICIES = ("admission", "scaling", "retune", "speculation")


@dataclass
class GatewayConfig:
    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral (ServingGateway.port reports the real one)
    # replica placement policy: 'prefix' (radix-overlap oracle, least-loaded
    # fallback) | 'least_loaded' | 'random'
    router: str = "prefix"
    default_slo_class: str = "interactive"
    slo_classes: Dict[str, SLOClassConfig] = field(default_factory=_default_classes)
    # per-forward token budget handed to each replica's SplitFuse scheduler;
    # 0 = the scheduler default (the engine's max_ragged_batch_size)
    token_budget: int = 0
    # requests handed to a replica's scheduler at once (admitted requests
    # beyond this wait in the class queues, preserving SLO priority);
    # 0 = the engine's max_ragged_sequence_count
    max_inflight_per_replica: int = 0
    # hard cap on a request's max_new_tokens; 0 = engine max_context only
    max_new_tokens_cap: int = 0
    # HTTP handler wait bound for one request end-to-end, seconds
    request_timeout_s: float = 120.0
    # Retry-After seconds advertised on every 429/503 (shed, draining, dead
    # replica): the client-visible half of "this failure is retryable here
    # (429) or elsewhere (503)" — load balancers and well-behaved clients
    # key their backoff on it
    retry_after_s: int = 1
    # (seq_bucket, decode_steps) pairs pre-compiled per replica at start()
    # via engine.warmup; empty = no warmup
    warmup: Tuple = ()
    # prefill token buckets ALSO pre-compiled (against the warmup seq
    # buckets) so the recompile sentinel's warmup boundary covers the put
    # path — without these, the first real request per (token, seq) bucket
    # compiles post-boundary and is flagged as a steady-state recompile
    warmup_token_buckets: Tuple = ()
    # request-scoped tracing + per-request summary log; off by default
    tracing: RequestTraceConfig = field(default_factory=RequestTraceConfig)
    # tenant-scoped resource metering + fairness observability; off by
    # default with the same zero-overhead-absent contract
    metering: MeteringConfig = field(default_factory=MeteringConfig)
    # on-demand XPlane capture endpoint (POST /v1/profile); off by default —
    # the route 404s and no capture manager is created
    profiling: ProfilingConfig = field(default_factory=ProfilingConfig)
    # disaggregated prefill/decode replica pools + KV handoff; off by
    # default with the same zero-overhead-absent contract
    disagg: DisaggConfig = field(default_factory=DisaggConfig)
    # feedback control plane (serving/control/); off by default with the
    # same zero-overhead-absent contract
    control: ControlConfig = field(default_factory=ControlConfig)
    # causal timeline plane (serving/timeline.py); off by default with the
    # same zero-overhead-absent contract; requires the tracing block
    timeline: TimelineConfig = field(default_factory=TimelineConfig)

    @classmethod
    def from_dict(cls, d) -> "GatewayConfig":
        d = dict(d or {})
        classes = d.pop("slo_classes", None)
        tracing = d.pop("tracing", None)
        metering = d.pop("metering", None)
        profiling = d.pop("profiling", None)
        disagg = d.pop("disagg", None)
        control = d.pop("control", None)
        timeline = d.pop("timeline", None)
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"serving.gateway: unknown keys {sorted(unknown)}")
        cfg = cls(**d)
        if tracing is not None:
            if isinstance(tracing, RequestTraceConfig):
                cfg.tracing = tracing
            else:
                body = dict(tracing)
                tr_known = {f.name for f in fields(RequestTraceConfig)}
                bad = set(body) - tr_known
                if bad:
                    raise ValueError(f"serving.gateway.tracing: unknown keys {sorted(bad)}")
                if "enabled" not in body:  # presence-enables
                    body["enabled"] = True
                cfg.tracing = RequestTraceConfig(**body)
            if not 0.0 <= cfg.tracing.sample_rate <= 1.0:
                raise ValueError("serving.gateway.tracing: sample_rate must be in [0, 1], "
                                 f"got {cfg.tracing.sample_rate}")
        if metering is not None:
            if isinstance(metering, MeteringConfig):
                cfg.metering = metering
            else:
                body = dict(metering)
                mt_known = {f.name for f in fields(MeteringConfig)}
                bad = set(body) - mt_known
                if bad:
                    raise ValueError(f"serving.gateway.metering: unknown keys {sorted(bad)}")
                if "enabled" not in body:  # presence-enables
                    body["enabled"] = True
                cfg.metering = MeteringConfig(**body)
            if cfg.metering.top_k < 1:
                raise ValueError("serving.gateway.metering: top_k must be >= 1, "
                                 f"got {cfg.metering.top_k}")
            if cfg.metering.max_tracked_tenants < cfg.metering.top_k:
                raise ValueError("serving.gateway.metering: max_tracked_tenants "
                                 f"({cfg.metering.max_tracked_tenants}) must cover "
                                 f"top_k ({cfg.metering.top_k})")
        if profiling is not None:
            if isinstance(profiling, ProfilingConfig):
                cfg.profiling = profiling
            else:
                body = dict(profiling)
                pf_known = {f.name for f in fields(ProfilingConfig)}
                bad = set(body) - pf_known
                if bad:
                    raise ValueError(f"serving.gateway.profiling: unknown keys {sorted(bad)}")
                if "enabled" not in body:  # presence-enables
                    body["enabled"] = True
                cfg.profiling = ProfilingConfig(**body)
            if cfg.profiling.max_duration_s <= 0 or cfg.profiling.default_duration_s <= 0:
                raise ValueError("serving.gateway.profiling: durations must be > 0, got "
                                 f"default={cfg.profiling.default_duration_s} "
                                 f"max={cfg.profiling.max_duration_s}")
        if disagg is not None:
            if isinstance(disagg, DisaggConfig):
                cfg.disagg = disagg
            else:
                body = dict(disagg)
                dg_known = {f.name for f in fields(DisaggConfig)}
                bad = set(body) - dg_known
                if bad:
                    raise ValueError(f"serving.gateway.disagg: unknown keys {sorted(bad)}")
                if "enabled" not in body:  # presence-enables
                    body["enabled"] = True
                cfg.disagg = DisaggConfig(**body)
            cfg.disagg.roles = tuple(str(r) for r in cfg.disagg.roles)
            bad_roles = [r for r in cfg.disagg.roles
                         if r not in ("prefill", "decode", "mixed")]
            if bad_roles:
                raise ValueError(f"serving.gateway.disagg: unknown roles {bad_roles}: "
                                 "'prefill' | 'decode' | 'mixed'")
            if cfg.disagg.handoff_after_tokens < 1:
                raise ValueError("serving.gateway.disagg: handoff_after_tokens must "
                                 f"be >= 1, got {cfg.disagg.handoff_after_tokens}")
        if control is not None:
            if isinstance(control, ControlConfig):
                cfg.control = control
            else:
                body = dict(control)
                ct_known = {f.name for f in fields(ControlConfig)}
                bad = set(body) - ct_known
                if bad:
                    raise ValueError(f"serving.gateway.control: unknown keys {sorted(bad)}")
                if "enabled" not in body:  # presence-enables
                    body["enabled"] = True
                cfg.control = ControlConfig(**body)
            ct = cfg.control
            ct.policies = tuple(str(p) for p in ct.policies)
            bad_pols = [p for p in ct.policies if p not in KNOWN_POLICIES]
            if bad_pols:
                raise ValueError(f"serving.gateway.control: unknown policies "
                                 f"{bad_pols}: {' | '.join(KNOWN_POLICIES)}")
            if ct.interval_s <= 0 or ct.window_s <= 0:
                raise ValueError("serving.gateway.control: interval_s and window_s "
                                 f"must be > 0, got interval={ct.interval_s} "
                                 f"window={ct.window_s}")
            if ct.max_actuations_per_window < 1:
                raise ValueError("serving.gateway.control: max_actuations_per_window "
                                 f"must be >= 1, got {ct.max_actuations_per_window}")
            if ct.cooldown_s < 0:
                raise ValueError("serving.gateway.control: cooldown_s must be >= 0, "
                                 f"got {ct.cooldown_s}")
            if ct.sustain_ticks < 1:
                raise ValueError("serving.gateway.control: sustain_ticks must be "
                                 f">= 1, got {ct.sustain_ticks}")
            if not ct.slo_miss_tighten > ct.slo_miss_relax >= 0:
                raise ValueError("serving.gateway.control: the admission hysteresis "
                                 "band needs slo_miss_tighten > slo_miss_relax >= 0, "
                                 f"got tighten={ct.slo_miss_tighten} "
                                 f"relax={ct.slo_miss_relax}")
            if not ct.spec_accept_high > ct.spec_accept_low >= 0:
                raise ValueError("serving.gateway.control: the speculation band "
                                 "needs spec_accept_high > spec_accept_low >= 0, "
                                 f"got high={ct.spec_accept_high} "
                                 f"low={ct.spec_accept_low}")
            if not 1 <= ct.spec_k_min <= ct.spec_k_max:
                raise ValueError("serving.gateway.control: need 1 <= spec_k_min <= "
                                 f"spec_k_max, got min={ct.spec_k_min} "
                                 f"max={ct.spec_k_max}")
            if ct.min_active_replicas < 1:
                raise ValueError("serving.gateway.control: min_active_replicas must "
                                 f"be >= 1, got {ct.min_active_replicas}")
            if not 0.0 <= ct.ewma_alpha <= 1.0:
                raise ValueError("serving.gateway.control: ewma_alpha must be "
                                 f"in [0, 1] (0 = off), got {ct.ewma_alpha}")
        if timeline is not None:
            if isinstance(timeline, TimelineConfig):
                cfg.timeline = timeline
            else:
                body = dict(timeline)
                tl_known = {f.name for f in fields(TimelineConfig)}
                bad = set(body) - tl_known
                if bad:
                    raise ValueError(f"serving.gateway.timeline: unknown keys {sorted(bad)}")
                if "enabled" not in body:  # presence-enables
                    body["enabled"] = True
                cfg.timeline = TimelineConfig(**body)
            tl = cfg.timeline
            if tl.last_n < 1:
                raise ValueError("serving.gateway.timeline: last_n must be >= 1, "
                                 f"got {tl.last_n}")
            if tl.exemplar_slots < 0:
                raise ValueError("serving.gateway.timeline: exemplar_slots must "
                                 f"be >= 0, got {tl.exemplar_slots}")
            if not 0.0 < tl.tolerance <= 1.0:
                raise ValueError("serving.gateway.timeline: tolerance must be in "
                                 f"(0, 1], got {tl.tolerance}")
            if tl.enabled and not cfg.tracing.enabled:
                raise ValueError("serving.gateway.timeline requires the tracing "
                                 "block: the assembler joins the stage stamps "
                                 "request tracing owns")
        if classes is not None:
            slo_known = {f.name for f in fields(SLOClassConfig)}
            parsed = {}
            for name, body in dict(classes).items():
                bad = set(body) - slo_known
                if bad:
                    raise ValueError(f"serving.gateway.slo_classes[{name!r}]: "
                                     f"unknown keys {sorted(bad)}")
                parsed[str(name)] = SLOClassConfig(**body)
            cfg.slo_classes = parsed
        if cfg.default_slo_class not in cfg.slo_classes:
            raise ValueError(f"serving.gateway: default_slo_class "
                             f"{cfg.default_slo_class!r} not in slo_classes "
                             f"{sorted(cfg.slo_classes)}")
        if cfg.router not in ("prefix", "least_loaded", "random"):
            raise ValueError(f"serving.gateway: unknown router {cfg.router!r}: "
                             "'prefix' | 'least_loaded' | 'random'")
        return cfg

    @classmethod
    def from_ds_config(cls, param_dict) -> "GatewayConfig":
        """Parse the ``serving.gateway`` block out of a full ds_config dict.
        An absent block yields the all-off defaults; a present-but-empty
        block enables the gateway with defaults (the presence-enables
        contract of the ``trace``/``health`` blocks)."""
        block = dict((param_dict or {}).get("serving", {}).get("gateway", {}))
        present = "gateway" in (param_dict or {}).get("serving", {})
        if present and "enabled" not in block:
            block["enabled"] = True
        return cls.from_dict(block)

    def class_order(self):
        """Class names in pull order: priority ascending, then name (a
        deterministic tiebreak so replica pull order is reproducible)."""
        return sorted(self.slo_classes, key=lambda n: (self.slo_classes[n].priority, n))
