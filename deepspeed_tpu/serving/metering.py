"""Tenant-scoped resource metering & fairness observability.

Every observability plane so far explains WHAT the fleet did (goodput
categories, cache lifecycles, request stages) — none of them knows WHO
consumed the resource. ROADMAP item 4 ("millions of users" = many tenants
on one fleet, with quotas, weighted-fair queueing and tenant-scoped
caches) cannot land without that attribution: a hot tenant starving the
rest is invisible until users complain. This module is the attribution
plane, in two halves:

  * :class:`TenantMeter` — one per gateway. Accumulates per-tenant
    resource-time integrals fed by narrow hooks on the existing
    measurement points:

      - **tokens**: uncached prefill tokens charged vs prefix-cache
        tokens saved (admission's probe numbers), generated tokens, and
        per-tenant hit ATTRIBUTION — hits split into self-hits vs
        cross-tenant hits via tenant-stamped published radix-tree blocks,
        with the publishing tenant credited ``served_tokens`` (the
        cross-subsidy ledger item 4's tenant-prefixed radix keys need);
      - **KV-block-seconds**: per-block allocate→physical-free intervals
        charged to the block's stamped owner (the same allocator
        lifecycle surface ``CacheTelemetry`` rides), so the sum over
        tenants equals the pool's occupancy integral by construction —
        test-enforced against cache telemetry's independent integral;
      - **compute-seconds**: the scheduler's step-observer apportionment
        (PR 7) extended to decode/verify bursts — each engine forward's
        wall clock split across its batch by token share, bucketed
        prefill/decode/spec_verify so the tenant sum reconciles with the
        PR 14 goodput ledger's serving active categories (test-enforced
        within 5%);
      - **queue-seconds** per SLO class, stamped at replica dequeue;
      - **shed/429 accounting per tenant** (the admission satellite): a
        shed caused by one tenant's burst is now distinguishable from
        systemic overload.

    On top of the ledgers: per-tenant share-of-capacity gauges, a
    dominant-resource-fairness index (Jain's index over each tenant's
    dominant resource share — 1.0 = perfectly fair), and STARVATION
    instants: when a tenant's windowed p99 queue wait detaches from the
    global p99 (factor + floor, latched per tenant), a
    ``serving/tenant_starvation`` trace instant + counter fires naming
    the tenant.

  * :class:`EngineMeterView` — the per-engine adapter (one per replica;
    block ids are engine-local). Owns the per-block owner/alloc-time
    stamp arrays and forwards tenant-level prefix-cache events up to the
    gateway's meter. Engines reach it only through
    ``InferenceEngineV2.set_tenant_meter`` — the request plane itself
    never touches engine internals (the ``check_gateway_api`` contract).

Cardinality is BOUNDED everywhere: at most ``max_tracked_tenants``
ledgers exist (overflow folds into the ``other`` ledger), and the export
side (``gauge_rows`` → labelled Prometheus rows ``serving/tenant_*``)
emits the top-K tenants by spend plus one aggregated ``other`` row —
``/metrics`` never carries more than K+1 distinct ``tenant`` label values
regardless of how many tenants exist (``tools/check_tenant_labels.py``
gates any tenant-labelled registration outside this module, and the bound
is test-enforced). The per-tenant ledger is served by ``GET /v1/usage``
and mirrored into an atomically-rotated usage JSONL (the reqtrace
``RequestLog`` pattern) plus tenant rows in forensic stall dumps.

Zero overhead when the ``serving.gateway.metering`` block is absent: no
meter object, no engine views, no stamp arrays, no threads, no
per-request allocations — every hook site is one ``is not None`` check
(test-enforced, the PR 5 contract).
"""

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..monitor.flight import get_flight_recorder
from ..monitor.metrics import get_metrics
from ..monitor.trace import get_tracer
from .reqtrace import RequestLog, sanitize_request_id

# the identity every request carries when the client sent none: metering
# still charges SOMEONE, and an all-anonymous fleet degrades to exactly
# the pre-metering aggregate view
DEFAULT_TENANT = "default"

# export-row name for everything past the top-K cut and for ledgers folded
# at the max_tracked_tenants bound
OTHER_TENANT = "other"

# owner bucket for blocks allocated outside any tenanted request (engine
# warmup, direct scheduler use): disclosed, never silently dropped — the
# KV conservation check needs every occupied block-second attributed
UNTENANTED = "untenanted"

_COMPUTE_KINDS = ("prefill", "decode", "spec_verify")


# names the meter itself emits: a client must not be able to collide with
# the aggregate bucket (duplicate Prometheus series) or the disclosed
# residual (silent overwrite in kv_block_seconds)
_RESERVED_TENANTS = (OTHER_TENANT, UNTENANTED)


def sanitize_tenant_id(raw) -> str:
    """Fold a client-supplied ``X-Tenant-Id`` into the request-id charset
    and length bound (header-safe, label-safe, log-safe — the exact
    ``sanitize_request_id`` discipline). Absent/empty/hostile-only input
    yields :data:`DEFAULT_TENANT`, never None: every request is charged to
    SOME tenant. The meter's own sentinel names (``other``,
    ``untenanted``) are escaped with an ``x-`` prefix so a client can
    never impersonate the aggregate bucket or the disclosed residual."""
    rid = sanitize_request_id(raw) or DEFAULT_TENANT
    if rid in _RESERVED_TENANTS:
        return "x-" + rid
    return rid


class _TenantLedger:
    """Accumulators for ONE tenant. Plain slots, mutated under the owning
    meter's lock; ``snapshot`` is the JSON-able read side."""

    __slots__ = ("name", "requests", "completed", "cancelled", "shed",
                 "uncached_tokens", "cached_tokens", "generated_tokens",
                 "computed_tokens", "hit_tokens_self", "hit_tokens_cross",
                 "served_tokens", "published_blocks", "evicted_blocks",
                 "kv_block_s", "host_kv_s", "compute_s", "queue_s",
                 "starvations", "waits", "starved", "shed_reasons")

    def __init__(self, name, wait_window=64):
        self.name = name
        self.requests = 0
        self.completed = 0
        self.cancelled = 0
        self.shed = 0
        self.uncached_tokens = 0
        self.cached_tokens = 0
        self.generated_tokens = 0
        self.computed_tokens = 0
        self.hit_tokens_self = 0      # hits on blocks this tenant published
        self.hit_tokens_cross = 0     # hits on another tenant's blocks
        self.served_tokens = 0        # producer credit: others hit OUR blocks
        self.published_blocks = 0
        self.evicted_blocks = 0       # eviction pressure: OUR blocks evicted
        self.kv_block_s = 0.0
        self.host_kv_s = 0.0          # tiered host-pool residency (own resource)
        self.compute_s = {k: 0.0 for k in _COMPUTE_KINDS}
        self.queue_s: Dict[str, float] = {}
        self.starvations = 0
        self.waits = deque(maxlen=max(8, int(wait_window)))
        self.starved = False          # starvation latch (one instant per episode)
        self.shed_reasons: Dict[str, int] = {}

    @property
    def compute_total_s(self) -> float:
        return sum(self.compute_s.values())

    @property
    def queue_total_s(self) -> float:
        return sum(self.queue_s.values())

    def spend(self) -> float:
        """The top-K ranking key: resource-time actually consumed."""
        return self.compute_total_s + self.kv_block_s

    def merge_into(self, other: "_TenantLedger") -> None:
        """Fold this ledger into ``other`` (the export-side aggregation of
        everything past the top-K cut)."""
        other.requests += self.requests
        other.completed += self.completed
        other.cancelled += self.cancelled
        other.shed += self.shed
        other.uncached_tokens += self.uncached_tokens
        other.cached_tokens += self.cached_tokens
        other.generated_tokens += self.generated_tokens
        other.computed_tokens += self.computed_tokens
        other.hit_tokens_self += self.hit_tokens_self
        other.hit_tokens_cross += self.hit_tokens_cross
        other.served_tokens += self.served_tokens
        other.published_blocks += self.published_blocks
        other.evicted_blocks += self.evicted_blocks
        other.kv_block_s += self.kv_block_s
        other.host_kv_s += self.host_kv_s
        for k, v in self.compute_s.items():
            other.compute_s[k] += v
        for c, v in self.queue_s.items():
            other.queue_s[c] = other.queue_s.get(c, 0.0) + v
        for r, v in self.shed_reasons.items():
            other.shed_reasons[r] = other.shed_reasons.get(r, 0) + v
        other.starvations += self.starvations

    def snapshot(self) -> dict:
        return {
            "requests": self.requests, "completed": self.completed,
            "cancelled": self.cancelled, "shed": self.shed,
            "shed_reasons": dict(self.shed_reasons),
            "uncached_tokens": self.uncached_tokens,
            "cached_tokens": self.cached_tokens,
            "generated_tokens": self.generated_tokens,
            "computed_tokens": self.computed_tokens,
            "hit_tokens_self": self.hit_tokens_self,
            "hit_tokens_cross": self.hit_tokens_cross,
            "served_tokens": self.served_tokens,
            "published_blocks": self.published_blocks,
            "evicted_blocks": self.evicted_blocks,
            "kv_block_s": round(self.kv_block_s, 6),
            "host_kv_s": round(self.host_kv_s, 6),
            "compute_s": {k: round(v, 6) for k, v in self.compute_s.items()},
            "compute_total_s": round(self.compute_total_s, 6),
            "queue_s": {c: round(v, 6) for c, v in self.queue_s.items()},
            "starvations": self.starvations,
        }


class EngineMeterView:
    """Per-engine block-lifecycle adapter for one :class:`TenantMeter`.

    Block ids are engine-local, so owner/alloc-time stamp arrays live here
    (pre-allocated to the pool size — the CacheTelemetry discipline: no
    per-block dict entries). ``on_allocate``/``on_free`` ride the SAME
    allocator lifecycle hooks CacheTelemetry does; ``stamp`` associates an
    owner when the tenanted layer (state manager / prefix cache) knows
    one. Physical free charges the block's whole resident interval to its
    owner, so summed tenant KV-block-seconds equal the pool's occupancy
    integral by construction (unfreed blocks contribute their partial
    interval at report time via :meth:`inflight_kv_s`).
    """

    def __init__(self, meter: "TenantMeter", num_blocks: int,
                 clock=time.perf_counter):
        self.meter = meter
        self.num_blocks = int(num_blocks)
        self._clock = clock
        self._alloc_t = np.zeros(self.num_blocks, np.float64)
        self._allocated = np.zeros(self.num_blocks, bool)
        self._owner: List[Optional[str]] = [None] * self.num_blocks

    # -- allocator lifecycle hooks (the CacheTelemetry surface) ---------
    def on_allocate(self, blocks) -> None:
        now = self._clock()
        for b in blocks:
            b = int(b)
            self._alloc_t[b] = now
            self._allocated[b] = True
            self._owner[b] = None

    def on_free(self, blocks) -> None:
        now = self._clock()
        for b in blocks:
            b = int(b)
            if not self._allocated[b]:
                continue
            self.meter.charge_kv(self._owner[b], now - self._alloc_t[b])
            self._allocated[b] = False
            self._owner[b] = None

    def stamp(self, blocks, tenant: Optional[str]) -> None:
        """Associate an owner with live blocks (state-manager growth, COW
        copies). A re-stamp overwrites — the last tenanted holder to
        materialize content owns the residency."""
        if tenant is None:
            return
        for b in blocks:
            self._owner[int(b)] = tenant

    def owner_of(self, block: int) -> Optional[str]:
        return self._owner[int(block)]

    def inflight_kv_s(self) -> Dict[str, float]:
        """Partial block-second charges for blocks still resident, per
        owner (``UNTENANTED`` for unstamped) — the report-time complement
        of the free-time charges."""
        now = self._clock()
        out: Dict[str, float] = {}
        for b in np.nonzero(self._allocated)[0]:
            t = self._owner[int(b)] or UNTENANTED
            out[t] = out.get(t, 0.0) + float(now - self._alloc_t[int(b)])
        return out

    def retire(self) -> Dict[str, float]:
        """Detach-time settlement: return the in-flight residency charges
        and clear every allocated bit — a retired view contributes nothing
        further (it can never see ``on_free`` again)."""
        settled = self.inflight_kv_s()
        self._allocated[:] = False
        return settled

    # -- prefix-cache forwards (tenant-level, engine-agnostic) ----------
    def on_prefix_hit(self, tenant, owners, tokens_by_owner) -> None:
        self.meter.on_prefix_hit(tenant, owners, tokens_by_owner)

    def on_publish(self, tenant, n_blocks) -> None:
        self.meter.on_publish(tenant, n_blocks)

    def on_evict(self, owner) -> None:
        self.meter.on_evict(owner)

    def charge_host_kv(self, owner, seconds) -> None:
        """Tiered host-pool residency charge (the tier calls this when a
        demoted block leaves the host pool) — HBM stamps survive demotion,
        so the same owner pays for the host tier as its own resource."""
        self.meter.charge_host_kv(owner, seconds)


class TenantMeter:
    """The gateway's tenant attribution plane (see module docstring).

    Thread-safety: hooks arrive from HTTP handler threads (admission),
    every replica driver (compute/queue/terminal), and engine internals
    (block lifecycle via the views) — all accumulation serializes on one
    lock; reads (:meth:`usage_report`, :meth:`gauge_rows`) snapshot under
    the same lock. No hook ever calls back into the serving plane while
    holding it."""

    def __init__(self, config, slo_classes=None, clock=time.perf_counter):
        self.config = config
        self.slo_classes = dict(slo_classes or {})
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantLedger] = {}
        self._other = _TenantLedger(OTHER_TENANT, config.starvation_window)
        self._untenanted_kv_s = 0.0
        self._untenanted_host_kv_s = 0.0
        self._views: List[EngineMeterView] = []
        self._global_waits = deque(maxlen=max(16, int(config.starvation_window) * 4))
        self._t0 = time.time()
        self._terminals = 0
        # per-pool compute split (serving/disagg.py): {role: {kind: s}} —
        # fleet-level, not per-tenant, because pool purity is a topology
        # property (did the prefill pool do decode work?), not a billing one
        self._pool_compute: Dict[str, Dict[str, float]] = {}
        self.stats = {"tenants_seen": 0, "folded_other": 0, "starvations": 0,
                      "usage_records": 0}
        self.usage_log = (RequestLog(config.usage_log_path,
                                     config.usage_log_max_bytes,
                                     config.usage_log_max_files)
                          if config.usage_log_path else None)

    # -- engine attachment ----------------------------------------------
    def engine_view(self, num_blocks: int) -> EngineMeterView:
        """A per-engine block-lifecycle adapter (replicas each get one;
        block ids are engine-local). Kept for report-time in-flight
        charges; the engine returns it via :meth:`drop_view` on detach."""
        view = EngineMeterView(self, num_blocks, clock=self._clock)
        with self._lock:
            self._views.append(view)
        return view

    def drop_view(self, view) -> None:
        """Retire a detached engine view (gateway ``stop()``): the view's
        in-flight residency charges are SETTLED into the ledgers first —
        blocks still resident at detach paid for their interval so far —
        then the view stops contributing. Without this, a stopped
        gateway's view would keep its allocated bits forever (it can no
        longer see ``on_free``) and accrue phantom KV-block-seconds that
        grow with wall clock."""
        settled = view.retire()
        for t, s in settled.items():
            self.charge_kv(None if t == UNTENANTED else t, s)
        with self._lock:
            if view in self._views:
                self._views.remove(view)

    # -- ledger plumbing -------------------------------------------------
    def _ledger(self, tenant: Optional[str]) -> _TenantLedger:
        """Get-or-create under the caller's lock. Past
        ``max_tracked_tenants`` distinct tenants, new ones fold into the
        ``other`` ledger — the meter's memory is bounded no matter how
        many tenant ids a hostile client invents."""
        led = self._tenants.get(tenant)
        if led is not None:
            return led
        if tenant is None:
            return self._other
        if len(self._tenants) >= self.config.max_tracked_tenants:
            self.stats["folded_other"] += 1
            return self._other
        led = self._tenants[tenant] = _TenantLedger(
            tenant, self.config.starvation_window)
        self.stats["tenants_seen"] += 1
        return led

    # -- admission hooks -------------------------------------------------
    def on_admitted(self, tenant, uncached_tokens, cached_tokens) -> None:
        with self._lock:
            led = self._ledger(tenant)
            led.requests += 1
            led.uncached_tokens += int(uncached_tokens)
            led.cached_tokens += int(cached_tokens)

    def on_shed(self, tenant, slo_class, reason) -> None:
        """The admission satellite: shed/429 accounting split by tenant —
        ``Retry-After`` pressure caused by one tenant's burst is now
        attributable instead of reading as systemic overload."""
        with self._lock:
            led = self._ledger(tenant)
            led.shed += 1
            led.shed_reasons[str(reason)] = led.shed_reasons.get(str(reason), 0) + 1

    # -- replica hooks ----------------------------------------------------
    def on_queue_wait(self, tenant, slo_class, wait_s, rid=None) -> None:
        """Queue-seconds per SLO class + the starvation detector: when this
        tenant's windowed p99 queue wait detaches from the GLOBAL MEDIAN
        wait (``starvation_factor`` above it AND past the absolute floor),
        one latched ``serving/tenant_starvation`` instant fires — re-armed
        when the tenant's p99 re-attaches. The comparison baseline is the
        global p50, not the global p99: a starving tenant IS the global
        tail, so its own waits would contaminate a p99 baseline and mask
        exactly the detachment being detected."""
        wait_s = max(0.0, float(wait_s))
        starved_now = None
        with self._lock:
            led = self._ledger(tenant)
            led.queue_s[slo_class] = led.queue_s.get(slo_class, 0.0) + wait_s
            led.waits.append(wait_s)
            self._global_waits.append(wait_s)
            if len(led.waits) >= 8 and len(self._global_waits) >= 16:
                t_p99 = float(np.percentile(np.asarray(led.waits), 99))
                g_p50 = float(np.percentile(np.asarray(self._global_waits), 50))
                detached = (t_p99 >= self.config.starvation_min_wait_s
                            and t_p99 > self.config.starvation_factor * g_p50)
                if detached and not led.starved:
                    led.starved = True
                    led.starvations += 1
                    self.stats["starvations"] += 1
                    starved_now = (led.name, t_p99, g_p50)
                elif not detached:
                    led.starved = False
        if starved_now is not None:
            name, t_p99, g_p50 = starved_now
            get_metrics().counter("serving/tenant_starvation_total").inc()
            get_tracer().instant("serving/tenant_starvation", tid="serving",
                                 request_id=rid, tenant=name,
                                 tenant_p99_wait_ms=round(t_p99 * 1e3, 3),
                                 global_p50_wait_ms=round(g_p50 * 1e3, 3))
            get_flight_recorder().record("serving", "tenant_starvation",
                                         tenant=name, request_id=rid,
                                         tenant_p99_wait_ms=round(t_p99 * 1e3, 3))

    def on_compute(self, tenant, kind, seconds, tokens=0, pool=None) -> None:
        """One request's share of one engine forward's wall clock (the
        scheduler step-observer apportionment), bucketed
        prefill/decode/spec_verify. ``pool`` is the serving replica's
        disaggregation role — the fleet-level per-pool split it feeds is
        what the pool-purity acceptance test measures."""
        if seconds <= 0.0 and not tokens:
            return
        with self._lock:
            led = self._ledger(tenant)
            led.compute_s[kind] += max(0.0, float(seconds))
            led.computed_tokens += int(tokens)
            if pool is not None:
                by_kind = self._pool_compute.setdefault(str(pool), {})
                by_kind[kind] = by_kind.get(kind, 0.0) + max(0.0, float(seconds))

    def on_terminal(self, tenant, rid, slo_class, finish_reason,
                    generated_tokens, cancelled=False) -> None:
        """Terminal accounting + the usage JSONL: one per-request record,
        and every ``ledger_snapshot_every`` terminals a full per-tenant
        ledger snapshot line (both via the atomically-rotated
        ``RequestLog``)."""
        with self._lock:
            led = self._ledger(tenant)
            led.generated_tokens += int(generated_tokens)
            if cancelled:
                led.cancelled += 1
            else:
                led.completed += 1
            self._terminals += 1
            write_ledger = (self.usage_log is not None
                            and self.config.ledger_snapshot_every > 0
                            and self._terminals % self.config.ledger_snapshot_every == 0)
        if self.usage_log is None:
            return
        try:
            self.usage_log.write({
                "kind": "request", "t_unix": time.time(), "tenant": tenant,
                "request_id": rid, "slo_class": slo_class,
                "finish_reason": finish_reason,
                "generated_tokens": int(generated_tokens)})
            if write_ledger:
                self.usage_log.write({"kind": "ledger", **self.usage_report()})
            self.stats["usage_records"] += 1
        except Exception as e:  # noqa: BLE001 — metering runs on the replica
            # driver thread: a full disk costs the record, never the loop
            self.stats["log_errors"] = self.stats.get("log_errors", 0) + 1
            self._log().error(f"usage log write failed: {e!r}")

    # -- KV / prefix-cache hooks (via EngineMeterView) --------------------
    def charge_kv(self, tenant, seconds) -> None:
        seconds = max(0.0, float(seconds))
        with self._lock:
            if tenant is None:
                self._untenanted_kv_s += seconds
            else:
                self._ledger(tenant).kv_block_s += seconds

    def charge_host_kv(self, tenant, seconds) -> None:
        """Host-tier block-seconds: a demoted block's residency in the
        pinned host pool, charged to the owner its HBM stamp carried at
        demotion time — the tier's own resource, never folded into
        ``kv_block_s`` (HBM and host capacity are separate budgets)."""
        seconds = max(0.0, float(seconds))
        with self._lock:
            if tenant is None:
                self._untenanted_host_kv_s += seconds
            else:
                self._ledger(tenant).host_kv_s += seconds

    def on_prefix_hit(self, tenant, owners, tokens_by_owner) -> None:
        """Hit attribution via tenant-stamped published blocks: the
        consumer splits saved tokens into self vs cross-tenant, and each
        publishing tenant is credited ``served_tokens`` — the
        cross-subsidy ledger."""
        with self._lock:
            led = self._ledger(tenant)
            for owner, tokens in zip(owners, tokens_by_owner):
                tokens = int(tokens)
                if owner == tenant:
                    led.hit_tokens_self += tokens
                else:
                    led.hit_tokens_cross += tokens
                if owner is not None:
                    self._ledger(owner).served_tokens += tokens

    def on_publish(self, tenant, n_blocks) -> None:
        if tenant is None:
            return
        with self._lock:
            self._ledger(tenant).published_blocks += int(n_blocks)

    def on_evict(self, owner) -> None:
        """Eviction pressure attributed to the evicted block's publisher —
        the direct precursor of item 4's per-tenant cache namespaces."""
        if owner is None:
            return
        with self._lock:
            self._ledger(owner).evicted_blocks += 1

    # -- read side ---------------------------------------------------------
    def _kv_with_inflight_locked(self):
        """(per-tenant kv_block_s incl. in-flight partials, untenanted
        total) — charged intervals plus each live view's resident blocks."""
        per = {name: led.kv_block_s for name, led in self._tenants.items()}
        if self._other.kv_block_s:
            per[OTHER_TENANT] = per.get(OTHER_TENANT, 0.0) + self._other.kv_block_s
        unt = self._untenanted_kv_s
        for view in self._views:
            for t, s in view.inflight_kv_s().items():
                if t == UNTENANTED:
                    unt += s
                else:
                    per[t] = per.get(t, 0.0) + s
        return per, unt

    def kv_block_seconds(self) -> Dict[str, float]:
        """Per-tenant KV-block-seconds including in-flight partials, with
        the ``untenanted`` residual disclosed — the conservation test sums
        this against cache telemetry's occupancy integral."""
        with self._lock:
            per, unt = self._kv_with_inflight_locked()
        per[UNTENANTED] = unt
        return per

    def host_kv_block_seconds(self) -> Dict[str, float]:
        """Per-tenant host-tier block-seconds (charged at host release;
        blocks still host-resident are not yet included — the conservation
        test drains the tier before comparing against the telemetry host
        occupancy integral)."""
        with self._lock:
            per = {name: led.host_kv_s for name, led in self._tenants.items()
                   if led.host_kv_s}
            if self._other.host_kv_s:
                per[OTHER_TENANT] = (per.get(OTHER_TENANT, 0.0)
                                     + self._other.host_kv_s)
            per[UNTENANTED] = self._untenanted_host_kv_s
        return per

    def _fairness_locked(self, per_kv) -> Optional[float]:
        """Jain's index over each tenant's DOMINANT resource share
        (compute-seconds, KV-block-seconds, uncached tokens — the DRF
        dominant share): 1.0 = perfectly fair, 1/N = one tenant holds
        everything. None before any consumption. Caller holds the lock
        and passes the per-tenant KV it already computed, so one report
        reads one consistent snapshot (and pays one view scan, not two)."""
        rows = [(led.compute_total_s, per_kv.get(name, 0.0),
                 float(led.uncached_tokens))
                for name, led in self._tenants.items()]
        if not rows:
            return None
        totals = [sum(r[i] for r in rows) for i in range(3)]
        dom = []
        for r in rows:
            shares = [r[i] / totals[i] for i in range(3) if totals[i] > 0]
            if shares:
                dom.append(max(shares))
        if not dom or sum(dom) <= 0:
            return None
        return float(sum(dom) ** 2 / (len(dom) * sum(x * x for x in dom)))

    def fairness_index(self) -> Optional[float]:
        with self._lock:
            per_kv, _unt = self._kv_with_inflight_locked()
            return self._fairness_locked(per_kv)

    def _top_k_locked(self):
        """(top-K ledgers by spend, aggregated-rest ledger-or-None)."""
        ranked = sorted(self._tenants.values(),
                        key=lambda led: (led.spend(), led.uncached_tokens,
                                         led.name),
                        reverse=True)
        top = ranked[:max(1, self.config.top_k)]
        rest = ranked[len(top):]
        other = None
        if rest or self._other.requests or self._other.shed \
                or self._other.spend() > 0:
            other = _TenantLedger(OTHER_TENANT, 8)
            self._other.merge_into(other)
            for led in rest:
                led.merge_into(other)
        return top, other

    def usage_report(self) -> dict:
        """The ``GET /v1/usage`` payload: the top-K per-tenant ledgers +
        the aggregated ``other`` bucket, fairness, and the disclosed
        untenanted KV residual. In-flight KV partials are included so the
        report is current, not free-lagged."""
        with self._lock:
            per_kv, unt = self._kv_with_inflight_locked()
            top, other = self._top_k_locked()
            tot_kv = sum(per_kv.values())
            top_kv = 0.0
            snaps = {}
            for led in top:
                s = led.snapshot()
                kv_s = per_kv.get(led.name, 0.0)
                top_kv += kv_s
                s["kv_block_s"] = round(kv_s, 6)
                snaps[led.name] = s
            other_snap = other.snapshot() if other is not None else None
            if other_snap is not None:
                # everything per_kv holds beyond the top-K (folded ledgers
                # AND the rest tenants' charges + in-flight partials) — the
                # merged ledger alone misses the rest's live partials
                other_snap["kv_block_s"] = round(max(0.0, tot_kv - top_kv), 6)
            fi = self._fairness_locked(per_kv)
            n_seen = self.stats["tenants_seen"]
            # disaggregated-pool compute split (empty dict when the fleet
            # is all-mixed or disagg is off): {role: {kind: seconds}}
            pools = {role: {k: round(v, 6) for k, v in by_kind.items()}
                     for role, by_kind in self._pool_compute.items()}
        return {
            "since_unix": self._t0,
            "wall_s": round(time.time() - self._t0, 3),
            "tenants_seen": n_seen,
            "top_k": self.config.top_k,
            "fairness_index": fi,
            "tenants": snaps,
            "other": other_snap,
            "pools": pools,
            "untenanted_kv_block_s": round(unt, 6),
            "starvations": self.stats["starvations"],
        }

    def gauge_rows(self):
        """Labelled Prometheus rows for the health exporter — the ONLY
        sanctioned source of ``tenant``-labelled metric rows
        (``tools/check_tenant_labels.py`` gates every other site). Top-K
        tenants + one aggregated ``other`` row per family: the scrape
        carries at most K+1 distinct tenant label values."""
        with self._lock:
            per_kv, _unt = self._kv_with_inflight_locked()
            top, other = self._top_k_locked()
            rows = []
            ledgers = [(led.name, led) for led in top]
            tot_kv = sum(per_kv.values()) or 0.0
            top_kv = {led.name: per_kv.get(led.name, 0.0) for led in top}
            if other is not None:
                # the aggregate row's KV is everything beyond the top-K
                # (folded + rest tenants incl. their in-flight partials),
                # so the exported family still sums to the pool total
                top_kv[OTHER_TENANT] = max(0.0, tot_kv - sum(top_kv.values()))
                ledgers.append((OTHER_TENANT, other))
            tot_compute = sum(led.compute_total_s for _, led in ledgers) or 0.0
            for name, led in ledgers:
                labels = {"tenant": name}
                kv_s = top_kv[name]
                rows.append(("serving/tenant_uncached_tokens_total", labels,
                             float(led.uncached_tokens)))
                rows.append(("serving/tenant_cached_tokens_total", labels,
                             float(led.cached_tokens)))
                rows.append(("serving/tenant_generated_tokens_total", labels,
                             float(led.generated_tokens)))
                rows.append(("serving/tenant_compute_seconds_total", labels,
                             led.compute_total_s))
                rows.append(("serving/tenant_kv_block_seconds_total", labels, kv_s))
                if led.host_kv_s:
                    rows.append(("serving/tenant_host_kv_block_seconds_total",
                                 labels, led.host_kv_s))
                rows.append(("serving/tenant_queue_seconds_total", labels,
                             led.queue_total_s))
                rows.append(("serving/tenant_shed_total", labels, float(led.shed)))
                rows.append(("serving/tenant_served_tokens_total", labels,
                             float(led.served_tokens)))
                rows.append(("serving/tenant_evicted_blocks_total", labels,
                             float(led.evicted_blocks)))
                rows.append(("serving/tenant_starvations_total", labels,
                             float(led.starvations)))
                if tot_compute > 0:
                    rows.append(("serving/tenant_share",
                                 {"tenant": name, "resource": "compute"},
                                 led.compute_total_s / tot_compute))
                if tot_kv > 0:
                    rows.append(("serving/tenant_share",
                                 {"tenant": name, "resource": "kv_blocks"},
                                 kv_s / tot_kv))
            n_seen = self.stats["tenants_seen"]
            fi = self._fairness_locked(per_kv)
        if fi is not None:
            rows.append(("serving/tenant_fairness_index", {}, fi))
        rows.append(("serving/tenants_tracked", {}, float(n_seen)))
        return rows

    def dump_rows(self) -> dict:
        """Forensic stall-dump section: the usage report, so a wedged
        replica's dump names which tenants held the fleet's resources."""
        return self.usage_report()

    def state(self) -> dict:
        with self._lock:
            n = len(self._tenants)
        return {**self.stats, "tracked": n,
                "fairness_index": self.fairness_index(),
                "usage_log_path": self.config.usage_log_path or None,
                "usage_log_written": self.usage_log.written if self.usage_log else 0}

    def close(self) -> None:
        if self.usage_log is not None:
            self.usage_log.close()

    @staticmethod
    def _log():
        from ..utils.logging import logger  # lazy: keep module import-light

        return logger
