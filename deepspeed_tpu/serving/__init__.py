"""Production serving gateway: the HTTP/SSE request plane over one-or-N
:class:`~deepspeed_tpu.inference.v2.InferenceEngineV2` replicas.

Layering (request -> token):

  * :mod:`gateway`   — stdlib ``ThreadingHTTPServer`` front end
    (``POST /v1/generate`` with SSE token streaming or a blocking JSON
    mode, ``GET /healthz``), request validation, replica selection,
    readiness/drain for load balancers;
  * :mod:`admission` — per-SLO-class bounded queues with 429/503 shedding;
    the cost of a request is its *uncached* prompt tokens, consulting the
    prefix cache exactly the way ``DynamicSplitFuseScheduler`` admission
    does (pure probe, no tree mutation);
  * :mod:`router`    — places each request across replicas by radix-tree
    prefix overlap (the pure ``PrefixKVCache.match`` as the routing
    oracle), falling back to least-loaded; liveness comes from the
    PR 5 heartbeat state;
  * :mod:`replica`   — one driver thread per engine running the
    SplitFuse put/decode loop and fanning generated tokens out to
    bounded per-request stream queues;
  * :mod:`metering`  — tenant-scoped resource metering & fairness
    observability (``serving.gateway.metering`` block): sanitized
    ``X-Tenant-Id`` identity charged per-tenant token/KV-block-second/
    compute-second integrals, DRF fairness index, starvation instants,
    bounded top-K Prometheus export, ``GET /v1/usage``;
  * :mod:`disagg` / :mod:`handoff` — disaggregated prefill/decode pools
    (``serving.gateway.disagg`` block): role-typed replicas, new requests
    placed on the prefill pool, and completed prefills migrated to the
    decode pool by a gateway-brokered cross-replica KV handoff through
    the host tier (checksummed manifests, at-most-once, fallback-in-place
    — never a lost request), ``GET /v1/pools``;
  * :mod:`timeline`  — the causal timeline plane
    (``serving.gateway.timeline`` block, requires ``tracing``): assembles,
    for every terminal request, one cross-replica RequestTimeline joining
    the stage stamps, handoff broker sub-stages, measured driver stalls,
    recompile-sentinel events, chaos fires and overlapping control
    actuations on one clock — segments sum to client e2e (within
    tolerance, migrated requests included), critical path + dominant-cause
    verdict, always-retained p99 TTFT/TPOT exemplars,
    ``GET /v1/timeline/<request_id>``;
  * :mod:`control`   — the feedback control plane
    (``serving.gateway.control`` block): one decision thread reading the
    sensor planes (goodput windows, SLO-miss counters, admission gauges,
    spec accept rates, recompile-sentinel buckets) and driving admission
    depths, replica drain/undrain/restart, background kernel re-tunes
    and speculative K through narrow public setters, every decision
    logged with its sensor justification, ``GET /v1/control``.

Everything defaults OFF: importing this package starts no threads, and a
constructed-but-never-started gateway allocates no queues' worth of
background machinery (asserted by ``tests/test_gateway.py``).

The request plane talks to the engine ONLY through its public API
(``put``/``decode`` via the scheduler, ``probe_prefix``, ``prefix_cache``,
``available_blocks``, ``max_context``, ``warmup``) — enforced structurally
by the ``tools/check_gateway_api.py`` AST gate, run from tier-1.
"""

from .config import (ControlConfig, DisaggConfig, GatewayConfig,
                     MeteringConfig, RequestTraceConfig, SLOClassConfig,
                     TimelineConfig)
from .admission import AdmissionController
from .router import ReplicaRouter
from .replica import EngineReplica, GatewayRequest, TokenStream
from .disagg import DisaggCoordinator
from .handoff import HandoffError, HandoffLedger
from .reqtrace import (RequestContext, RequestLog, RequestTracing,
                       extract_request_id, new_request_id, parse_traceparent,
                       sanitize_request_id)
from .metering import (DEFAULT_TENANT, EngineMeterView, TenantMeter,
                       sanitize_tenant_id)
from .control import DecisionLog, ServingController
from .timeline import TimelineCollector
from .gateway import ServingGateway, parse_sse, sse_frame
