"""Request-scoped tracing: the per-REQUEST observability plane.

PR 1/5 made the serving plane explain *steps* (``serving/prefill`` spans,
TTFT histograms, stall dumps); this module makes it explain *requests* —
the unit users and SLOs care about. One :class:`RequestContext` rides each
request end-to-end (gateway parse -> admission queue -> router decision ->
per-chunk prefill -> first token -> decode tail -> terminal), stamping
stage boundaries on one clock so the breakdown SUMS to the request's
end-to-end latency (acceptance: within 10%, test-enforced). Three outputs:

  * **spans** on the existing Tracer/FlightRecorder bus, every one carrying
    a ``request_id`` field (structurally enforced by
    ``tools/check_request_tracing.py``): ``serving/queue_wait`` and
    ``serving/decode_tail`` durations, ``serving/route`` /
    ``serving/first_token`` / terminal instants, ``serving/prefill_chunk``
    per scheduler chunk (step wall time apportioned by chunk tokens);
  * **per-stage Prometheus histograms** (``gateway/stage_{ingress,queue,
    prefill,decode}_ms`` + ``gateway/prefill_cache_miss_tokens``) so p99
    TTFT decomposes into queue vs route vs prefill vs cache-miss straight
    off ``/metrics``;
  * a **bounded JSONL request log** (atomic rotation, tail-aware sampling:
    SLO-miss/shed/error/cancelled records always retained, healthy ones
    head-sampled deterministically on the request id) — one summary line
    per terminal request with the full stage breakdown
    ``{queue_ms, route_choice, prefix_hit_tokens, prefill_ms, ttft_ms,
    tpot_ms, finish_reason, slo_verdict}``.

Request ids: a client-supplied ``X-Request-Id`` (or the trace-id of a W3C
``traceparent``) is sanitized (charset/length) and propagated — echoed on
the ``X-Request-Id`` response header of EVERY gateway response path, in the
SSE meta frame, in every span, and in the log record — else one is
generated. Zero overhead with the config block absent: the gateway holds
no plane object, no context is allocated, no thread exists (the log writer
is synchronous under its own lock), mirroring the PR 1/5 contract.
"""

import json
import os
import re
import threading
import time
import uuid
import zlib
from collections import deque
from typing import Optional

from ..monitor.flight import get_flight_recorder
from ..monitor.metrics import get_metrics
from ..monitor.trace import get_tracer

# client-supplied id charset (header-safe, label-safe, log-safe) and bound
_RID_OK = re.compile(r"[^A-Za-z0-9._\-]")
RID_MAX_LEN = 64

# W3C traceparent: version "00", 32-hex trace-id, 16-hex parent-id, 2-hex flags
_TRACEPARENT = re.compile(r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def sanitize_request_id(raw) -> Optional[str]:
    """Fold a client-supplied id into the safe charset, bounded length.
    Returns None when nothing usable remains (caller generates instead) —
    a hostile header can never smuggle bytes into responses, spans, or
    Prometheus labels."""
    if raw is None:
        return None
    rid = _RID_OK.sub("", str(raw).strip())[:RID_MAX_LEN]
    return rid or None


def parse_traceparent(raw) -> Optional[str]:
    """The trace-id of a well-formed W3C ``traceparent`` header (lowercased),
    None for anything malformed — never a partial parse."""
    if not raw:
        return None
    m = _TRACEPARENT.match(str(raw).strip().lower())
    if m is None or m.group(2) == "0" * 32:
        return None
    return m.group(2)


def extract_request_id(headers):
    """(rid, traceparent_trace_id) from an HTTP header mapping: a sanitized
    ``X-Request-Id`` wins, else the ``traceparent`` trace-id, else a fresh
    id — every request leaves with SOME id attached."""
    tp = parse_traceparent(headers.get("traceparent") if headers else None)
    rid = sanitize_request_id(headers.get("X-Request-Id") if headers else None)
    return rid or tp or new_request_id(), tp


class RequestContext:
    """Stage timestamps + routing facts for ONE request, all on the
    ``time.perf_counter`` clock so stage durations and end-to-end latency
    subtract exactly (no cross-clock skew in the breakdown)."""

    __slots__ = ("rid", "traceparent", "slo_class", "tenant", "sampled", "closed",
                 "t_recv", "t_admitted", "t_dequeued", "t_first_token",
                 "t_last_token", "t_done",
                 "route_choice", "route_policy", "route_scores",
                 "prefix_hit_tokens", "prompt_tokens",
                 "prefill_chunks", "prefill_compute_ms")

    def __init__(self, rid, traceparent=None, slo_class=None, sampled=True,
                 tenant=None):
        self.rid = rid
        self.traceparent = traceparent
        self.slo_class = slo_class
        self.tenant = tenant
        self.sampled = sampled
        self.closed = False
        self.t_recv = time.perf_counter()
        self.t_admitted = None
        self.t_dequeued = None
        self.t_first_token = None
        self.t_last_token = None
        self.t_done = None
        self.route_choice = None
        self.route_policy = None
        self.route_scores = None
        self.prefix_hit_tokens = 0
        self.prompt_tokens = 0
        self.prefill_chunks = 0
        self.prefill_compute_ms = 0.0

    @staticmethod
    def _ms(a, b):
        return None if (a is None or b is None) else max(0.0, (b - a) * 1e3)

    def stages(self) -> dict:
        """The stage breakdown. Stages partition [t_recv, t_last_token] on
        one clock — ``ingress + queue + prefill + decode`` reconstructs
        end-to-end latency up to the (sub-ms) close-out residual:

          ingress  — parse/validate/route (recv -> admitted)
          queue    — admission class-queue wait (admitted -> replica pull)
          prefill  — scheduler pickup -> first generated token
          decode   — first -> last generated token (the decode tail)
        """
        return {"ingress_ms": self._ms(self.t_recv, self.t_admitted),
                "queue_ms": self._ms(self.t_admitted, self.t_dequeued),
                "prefill_ms": self._ms(self.t_dequeued, self.t_first_token),
                "decode_ms": self._ms(self.t_first_token, self.t_last_token),
                "e2e_ms": self._ms(self.t_recv, self.t_done)}


class RequestLog:
    """Bounded JSONL writer with atomic rotation. Synchronous (no thread:
    one short lock-held write per terminal request — terminal rate, not
    token rate) and bounded: past ``max_bytes`` the live file rotates to
    ``path.1`` (older shift up, oldest dropped past ``max_files``) via
    ``os.replace``, so a reader never sees a torn or unbounded file."""

    def __init__(self, path, max_bytes=16 << 20, max_files=2):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.max_files = max(1, int(max_files))
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self.written = 0   # records written (post-sampling)
        self.rotations = 0

    def write(self, record: dict):
        line = json.dumps(record, default=repr) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            if self._fh is None:
                d = os.path.dirname(os.path.abspath(self.path))
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fh = open(self.path, "a")
                self._size = self._fh.tell()
            if self._size + len(data) > self.max_bytes and self._size > 0:
                self._rotate_locked()
            self._fh.write(line)
            self._fh.flush()
            self._size += len(data)
            self.written += 1

    def _rotate_locked(self):
        self._fh.close()
        self._fh = None
        # shift path.(n-1) -> path.n, ..., path -> path.1; each shift is one
        # atomic os.replace, and the oldest file simply gets overwritten
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")
        self._fh = open(self.path, "w")
        self._size = 0
        self.rotations += 1

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class RequestTracing:
    """The per-gateway request-tracing plane: context factory, span
    emission (every event carries ``request_id``), the summary log, and the
    last-N terminal ring. One instance per ServingGateway, shared (by
    reference) with its admission controller and replicas."""

    def __init__(self, config, slo_classes=None, timeline=None):
        self.config = config
        self.slo_classes = dict(slo_classes or {})
        # causal timeline collector (serving/timeline.py): finalize hands
        # it every terminal request for assembly. None (the default, and
        # whenever serving.gateway.timeline is absent) keeps the terminal
        # path at one attribute check — no assembly, no allocations.
        self._timeline = timeline
        self.log = (RequestLog(config.log_path, config.log_max_bytes,
                               config.log_max_files) if config.log_path else None)
        self._lock = threading.Lock()
        self._recent = deque(maxlen=max(1, int(config.last_n)))
        self.stats = {"opened": 0, "finalized": 0, "retained": 0, "head_sampled_out": 0}

    # -- sampling -------------------------------------------------------
    def head_sample(self, rid: str) -> bool:
        """Deterministic head-sampling on the request id: the same request
        samples the same way on every replica/retry, and tests can pick
        ids on either side of the line."""
        rate = self.config.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        return (zlib.crc32(rid.encode("utf-8")) % 10_000) < rate * 10_000

    # -- lifecycle ------------------------------------------------------
    def open(self, rid, traceparent=None, slo_class=None,
             tenant=None) -> RequestContext:
        ctx = RequestContext(rid, traceparent=traceparent, slo_class=slo_class,
                             sampled=self.head_sample(rid), tenant=tenant)
        self.stats["opened"] += 1
        return ctx

    def on_admitted(self, req):
        """Admission success — emission only: the ctx admission stamp and
        prompt/prefix facts were written by ``AdmissionController.try_admit``
        UNDER its lock, before the request was published to the queue (the
        driver can dequeue and even finish a request the instant it lands,
        so any post-publish ctx mutation would race finalize)."""
        ctx = req.ctx
        get_tracer().instant("serving/admitted", tid="serving",
                             request_id=ctx.rid, replica=req.replica_name,
                             slo_class=ctx.slo_class,
                             prefix_hit_tokens=int(req.cached_tokens))

    def on_route(self, ctx: RequestContext, chosen, policy, scores,
                 overlap_blocks=None):
        """Router-decision instant: the candidate scores + prefix-overlap
        blocks that justified the placement (the forensic answer to 'why
        was this p99 request cold-routed')."""
        ctx.route_choice = chosen
        ctx.route_policy = policy
        ctx.route_scores = dict(scores or {})
        get_tracer().instant("serving/route", tid="serving",
                             request_id=ctx.rid, chosen=chosen, policy=policy,
                             scores=dict(scores or {}),
                             overlap_blocks=dict(overlap_blocks or {}))

    def on_dequeue(self, req):
        """Replica pulled the request off its class queue: stamp + emit the
        per-class queue-wait duration span."""
        ctx = req.ctx
        ctx.t_dequeued = time.perf_counter()
        if ctx.t_admitted is not None:
            wait = ctx.t_dequeued - ctx.t_admitted
            get_tracer().complete(
                "serving/queue_wait", ctx.t_admitted, wait, tid="serving",
                args={"request_id": ctx.rid, "slo_class": ctx.slo_class,
                      "replica": req.replica_name,
                      "queue_ms": round(wait * 1e3, 3)})
            get_metrics().histogram("gateway/stage_queue_ms").observe(wait * 1e3)

    def on_prefill_chunk(self, req, n_tokens, t0, dur):
        """One scheduler prefill chunk for this request: the composed step's
        wall time apportioned by this chunk's share of the step's tokens
        (chunks of one composed forward are not separately timeable)."""
        ctx = req.ctx
        ctx.prefill_chunks += 1
        ctx.prefill_compute_ms += dur * 1e3
        get_tracer().complete(
            "serving/prefill_chunk", t0, dur, tid="serving",
            args={"request_id": ctx.rid, "tokens": int(n_tokens),
                  "chunk_index": ctx.prefill_chunks,
                  "replica": req.replica_name})

    def on_first_token(self, req, ttft_ms):
        ctx = req.ctx
        ctx.t_first_token = req.stream.first_token_t
        get_tracer().instant("serving/first_token", tid="serving",
                             request_id=ctx.rid, ttft_ms=round(ttft_ms, 3),
                             slo_class=ctx.slo_class, replica=req.replica_name)

    def on_resume_wait(self, req):
        """A migrated request's adoption gap: source-driver enqueue on the
        decode replica -> that replica's own scheduler submit (the dst
        half of the handoff window PR 18 left unattributed). Emitted by
        the DESTINATION driver from ``_pull_resumes``; both stamps are
        perf_counter, so the span composes with the broker stages."""
        ctx = req.ctx
        wait = max(0.0, req.t_resume_submitted - req.t_resume_enqueued)
        get_tracer().complete(
            "serving/resume_wait", req.t_resume_enqueued, wait, tid="serving",
            args={"request_id": ctx.rid, "replica": req.replica_name,
                  "resume_wait_ms": round(wait * 1e3, 3)})
        get_metrics().histogram("gateway/resume_wait_ms").observe(wait * 1e3)

    def on_respond(self, ctx: RequestContext, status):
        """Gateway parse/respond span: the HTTP handler's own walltime for
        this request (recv -> response written), emitted by the handler
        thread after the terminal frame/body went out."""
        now = time.perf_counter()
        get_tracer().complete("serving/gateway_respond", ctx.t_recv,
                              now - ctx.t_recv, tid="serving",
                              args={"request_id": ctx.rid, "status": int(status)})

    # -- terminal -------------------------------------------------------
    def _close(self, ctx) -> bool:
        """Latch terminal exactly once (handler timeout, driver close-out,
        and gateway-stop fail paths can race to finalize)."""
        with self._lock:
            if ctx.closed:
                return False
            ctx.closed = True
            return True

    def slo_verdict(self, slo_class, ttft_ms, tpot_ms) -> str:
        cls = self.slo_classes.get(slo_class)
        if cls is None or (cls.ttft_target_ms <= 0 and cls.tpot_target_ms <= 0):
            return "ok"  # untargeted class: completion is conformance
        miss = []
        if cls.ttft_target_ms > 0 and (ttft_ms or 0) > cls.ttft_target_ms:
            miss.append("ttft_miss")
        if cls.tpot_target_ms > 0 and (tpot_ms or 0) > cls.tpot_target_ms:
            miss.append("tpot_miss")
        return "+".join(miss) or "ok"

    def finalize(self, req, finish_reason=None, error=None, n_tokens=None, spec=None):
        """Terminal path for an ADMITTED request (completed, cancelled,
        timed out, errored, failed by a dying replica): stamp the tail,
        derive the verdict, emit the terminal instant + decode-tail span,
        feed the stage histograms, and write the summary record (tail-aware
        sampling). Exactly-once per request. ``spec`` — the scheduler's
        per-request speculation summary (``{"drafted", "accepted"}``; None
        when the request never speculated): the record then carries the
        request's own draft acceptance rate."""
        ctx = req.ctx
        if ctx is None or not self._close(ctx):
            return
        st = req.stream
        now = time.perf_counter()
        ctx.t_last_token = st.last_token_t or ctx.t_first_token
        ctx.t_done = now
        error = error if error is not None else st.error
        if finish_reason is None:
            if error is not None:
                finish_reason = {"request_timeout": "timeout",
                                 "cancelled": "cancelled",
                                 "client_disconnected": "disconnect"}.get(error, "error")
            else:
                finish_reason = st.finish_reason or "length"
        healthy = error is None and finish_reason in ("length", "eos")
        verdict = (self.slo_verdict(ctx.slo_class, req.ttft_ms, req.tpot_ms)
                   if healthy else "n/a")
        stages = ctx.stages()
        reg = get_metrics()
        if healthy:
            for key, hist in (("ingress_ms", "gateway/stage_ingress_ms"),
                              ("prefill_ms", "gateway/stage_prefill_ms"),
                              ("decode_ms", "gateway/stage_decode_ms")):
                if stages[key] is not None:
                    reg.histogram(hist).observe(stages[key])
            reg.histogram("gateway/prefill_cache_miss_tokens").observe(
                max(0, ctx.prompt_tokens - ctx.prefix_hit_tokens))
        if ctx.t_first_token is not None and ctx.t_last_token is not None \
                and ctx.t_last_token > ctx.t_first_token:
            get_tracer().complete(
                "serving/decode_tail", ctx.t_first_token,
                ctx.t_last_token - ctx.t_first_token, tid="serving",
                args={"request_id": ctx.rid,
                      "tokens": int(n_tokens if n_tokens is not None else st.produced),
                      "tpot_ms": round(req.tpot_ms, 3) if req.tpot_ms else None})
        record = {
            "request_id": ctx.rid, "uid": req.uid,
            "traceparent": ctx.traceparent, "tenant": ctx.tenant,
            "slo_class": ctx.slo_class, "replica": req.replica_name,
            "finish_reason": finish_reason, "error": error,
            "slo_verdict": verdict, "t_unix": time.time(),
            "n_tokens": int(n_tokens if n_tokens is not None else st.produced),
            "prompt_tokens": ctx.prompt_tokens,
            "prefix_hit_tokens": ctx.prefix_hit_tokens,
            "route_choice": ctx.route_choice, "route_policy": ctx.route_policy,
            "route_scores": ctx.route_scores,
            "prefill_chunks": ctx.prefill_chunks,
            "prefill_compute_ms": round(ctx.prefill_compute_ms, 3),
            "ttft_ms": round(req.ttft_ms, 3) if req.ttft_ms else None,
            "tpot_ms": round(req.tpot_ms, 3) if req.tpot_ms else None,
            "sampled": ctx.sampled,
        }
        if spec is not None and spec.get("drafted"):
            record["spec_drafted_tokens"] = int(spec["drafted"])
            record["spec_accepted_tokens"] = int(spec["accepted"])
            record["spec_accept_rate"] = round(spec["accepted"] / spec["drafted"], 3)
        if req.handoff_state is not None:
            # migrated/fallback requests carry the broker cost in their own
            # summary record (and SSE final frame) — the PR 18 residual:
            # previously the handoff window hid inside decode_ms
            record["handoff_state"] = req.handoff_state
            record["handoff_ms"] = (round(req.handoff_ms, 3)
                                    if req.handoff_ms is not None else None)
            record["resume_wait_ms"] = (round(req.resume_wait_ms, 3)
                                        if req.resume_wait_ms is not None else None)
        record.update({k: (round(v, 3) if v is not None else None)
                       for k, v in stages.items()})
        get_tracer().instant("serving/request_done", tid="serving",
                             request_id=ctx.rid, finish_reason=finish_reason,
                             slo_verdict=verdict, error=error,
                             e2e_ms=record["e2e_ms"])
        get_flight_recorder().record("serving", "request_done",
                                     request_id=ctx.rid,
                                     finish_reason=finish_reason,
                                     slo_verdict=verdict, error=error)
        self._record_terminal(record, healthy and verdict == "ok")
        if self._timeline is not None:
            self._timeline.assemble(req, record)

    def finalize_rejected(self, ctx: RequestContext, status, reason,
                          replica=None):
        """Terminal path for a request refused BEFORE admission (400/429/503)
        — shed and rejection records are always retained (they ARE the
        tail), and the shed instant names the queue that refused."""
        if ctx is None or not self._close(ctx):
            return
        ctx.t_done = time.perf_counter()
        finish = "shed" if status == 429 else "rejected"
        get_tracer().instant("serving/request_shed" if status == 429
                             else "serving/request_rejected", tid="serving",
                             request_id=ctx.rid, status=int(status),
                             reason=str(reason), slo_class=ctx.slo_class,
                             replica=replica)
        get_flight_recorder().record("serving", f"request_{finish}",
                                     request_id=ctx.rid, status=int(status),
                                     reason=str(reason))
        record = {
            "request_id": ctx.rid, "traceparent": ctx.traceparent,
            "tenant": ctx.tenant, "slo_class": ctx.slo_class, "replica": replica,
            "finish_reason": finish, "error": str(reason),
            "slo_verdict": "n/a", "t_unix": time.time(), "status": int(status),
            "n_tokens": 0, "prompt_tokens": ctx.prompt_tokens,
            "prefix_hit_tokens": ctx.prefix_hit_tokens,
            "route_choice": ctx.route_choice, "route_policy": ctx.route_policy,
            "route_scores": ctx.route_scores,
            "ttft_ms": None, "tpot_ms": None, "sampled": ctx.sampled,
        }
        record.update({k: (round(v, 3) if v is not None else None)
                       for k, v in ctx.stages().items()})
        self._record_terminal(record, healthy=False)
        if self._timeline is not None:
            self._timeline.assemble_rejected(ctx, record)

    def _record_terminal(self, record, healthy):
        """Tail-aware retention: unhealthy terminals (SLO miss, shed,
        rejection, cancel, timeout, error) are ALWAYS written; healthy ones
        only when head-sampled. The in-memory ring keeps every terminal
        (bounded) for dump forensics either way."""
        self.stats["finalized"] += 1
        with self._lock:
            self._recent.append(record)
        if healthy and not record["sampled"]:
            self.stats["head_sampled_out"] += 1
            return
        self.stats["retained"] += 1
        if self.log is not None:
            try:
                self.log.write(record)
            except Exception as e:  # noqa: BLE001 — finalize runs on the
                # replica DRIVER thread: a full disk (ENOSPC) or revoked
                # permission must cost the record, never the driver loop
                # and every in-flight stream behind it
                self.stats["log_errors"] = self.stats.get("log_errors", 0) + 1
                self._log().error(f"request log write failed: {e!r}")

    @staticmethod
    def _log():
        from ..utils.logging import logger  # lazy: keep module import-light

        return logger

    # -- read side ------------------------------------------------------
    def last_summaries(self, n=None):
        with self._lock:
            out = list(self._recent)
        return out[-int(n):] if n else out

    def state(self) -> dict:
        return {**self.stats,
                "log_path": self.config.log_path or None,
                "log_written": self.log.written if self.log else 0,
                "log_rotations": self.log.rotations if self.log else 0,
                "sample_rate": self.config.sample_rate}

    def close(self):
        if self.log is not None:
            self.log.close()
