"""Shared enums (reference ``utils/types.py``)."""

from enum import IntEnum


class ActivationFuncType(IntEnum):
    UNKNOWN = 0
    GELU = 1
    ReLU = 2
    GATED_GELU = 3
    GATED_SILU = 4


class NormType(IntEnum):
    UNKNOWN = 0
    LayerNorm = 1
    GroupNorm = 2
    RMSNorm = 3
