"""Debug access to sharded parameters and optimizer state.

Analog of the reference ``deepspeed/utils/tensor_fragment.py``
(``safe_get_full_fp32_param``, ``safe_get_full_optimizer_state``,
``safe_set_full_fp32_param``, ``safe_set_full_optimizer_state``,
``safe_get_local_*``) — the public debugging surface HF Trainer integrations
rely on. The reference maps flat ZeRO partitions back to params; here a
param is addressed by its pytree path (e.g. ``"blocks/wq"``) and the
"gather" is a device-side reshard to the replicated layout (allgather on
demand), so the APIs work identically under ZeRO-1/2/3, MiCS, and ZeRO++.

Optimizer-state names follow the reference's Adam vocabulary: ``exp_avg``
is the first param-shaped subtree of the optax state (Adam's mu),
``exp_avg_sq`` the second (nu); other optax chains expose their
param-shaped subtrees positionally.
"""

from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

_STATE_INDEX = {"exp_avg": 0, "exp_avg_sq": 1}


def _walk(tree, path: str):
    node = tree
    for part in path.split("/"):
        if isinstance(node, (list, tuple)):
            node = node[int(part)]
        else:
            node = node[part]
    return node


def _set_by_path(tree, path: str, value):
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node[int(part)] if isinstance(node, (list, tuple)) else node[part]
    last = parts[-1]
    if isinstance(node, (list, tuple)):
        raise ValueError(f"cannot assign into an immutable sequence at {path}")
    node[last] = value


def _param_shaped_subtrees(opt_state, params_treedef):
    """All subtrees of ``opt_state`` whose structure matches the param tree
    (mu/nu/... in optax states), in deterministic traversal order."""
    found = []

    def is_match(x):
        try:
            return jax.tree_util.tree_structure(x) == params_treedef
        except Exception:
            return False

    def visit(node):
        if is_match(node):
            found.append(node)
            return
        if isinstance(node, dict):
            for v in node.values():
                visit(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                visit(v)
        elif hasattr(node, "_fields"):  # NamedTuple state
            for v in node:
                visit(v)

    visit(opt_state)
    return found


def _gather_full(leaf) -> np.ndarray:
    """Replicate a (possibly sharded) array and fetch it to host."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None or getattr(sharding, "is_fully_replicated", True):
        return np.asarray(jax.device_get(leaf))
    mesh = sharding.mesh
    rep = jax.device_put(leaf, NamedSharding(mesh, P()))
    return np.asarray(jax.device_get(rep))


def _scatter_full(leaf, value) -> jax.Array:
    """Place a full host array back into ``leaf``'s sharding/dtype."""
    value = np.asarray(value)
    if value.shape != tuple(leaf.shape):
        raise ValueError(f"shape mismatch: param is {tuple(leaf.shape)}, value is {value.shape}")
    return jax.device_put(value.astype(leaf.dtype), leaf.sharding)


# ---------------------------------------------------------------------------
# public API (reference tensor_fragment.py surface)
# ---------------------------------------------------------------------------

def safe_get_full_fp32_param(engine, path: str) -> np.ndarray:
    """Full (gathered) fp32 master value of the parameter at ``path``
    (reference ``safe_get_full_fp32_param``). Works under every ZeRO stage —
    the gather is an on-demand device-side reshard."""
    return _gather_full(_walk(engine.state["params"], path)).astype(np.float32)


def safe_set_full_fp32_param(engine, path: str, value) -> None:
    """Overwrite the parameter at ``path`` from a full host array
    (reference ``safe_set_full_fp32_param``): re-sharded into the param's
    layout; host-offload masters follow so the next step can't resurrect
    the old value."""
    leaf = _walk(engine.state["params"], path)
    _set_by_path(engine.state["params"], path, _scatter_full(leaf, value))
    host_opt = getattr(engine, "host_optimizer", None)
    if host_opt is not None:
        host_opt.reset_masters(engine.state["params"])


def safe_get_full_optimizer_state(engine, path: str, state_key: str) -> Optional[np.ndarray]:
    """Full (gathered) optimizer state of the param at ``path``;
    ``state_key``: 'exp_avg' | 'exp_avg_sq' (reference
    ``safe_get_full_optimizer_state``). Returns None when the engine keeps
    no such state on device (e.g. host offload — read
    ``engine.host_optimizer`` instead)."""
    subtree = _find_state_subtree(engine, state_key)
    if subtree is None:
        return None
    return _gather_full(_walk(subtree, path)).astype(np.float32)


def safe_set_full_optimizer_state(engine, path: str, state_key: str, value) -> None:
    """Overwrite one optimizer-state tensor from a full host array
    (reference ``safe_set_full_optimizer_state``)."""
    subtree = _find_state_subtree(engine, state_key)
    if subtree is None:
        raise ValueError(f"engine has no on-device optimizer state '{state_key}' "
                         "(host offload keeps moments on the host)")
    leaf = _walk(subtree, path)
    _set_by_path(subtree, path, _scatter_full(leaf, value))


def safe_get_local_fp32_param(engine, path: str) -> np.ndarray:
    """THIS process's shard(s) of the param, concatenated flat (reference
    ``safe_get_local_fp32_param`` — the ZeRO-3 local view)."""
    leaf = _walk(engine.state["params"], path)
    seen = {}
    for s in leaf.addressable_shards:
        seen.setdefault(str(s.index), np.asarray(s.data))
    return np.concatenate([v.reshape(-1) for _, v in sorted(seen.items())]).astype(np.float32)


def safe_get_local_optimizer_state(engine, path: str, state_key: str) -> Optional[np.ndarray]:
    subtree = _find_state_subtree(engine, state_key)
    if subtree is None:
        return None
    leaf = _walk(subtree, path)
    seen = {}
    for s in leaf.addressable_shards:
        seen.setdefault(str(s.index), np.asarray(s.data))
    return np.concatenate([v.reshape(-1) for _, v in sorted(seen.items())]).astype(np.float32)


def _find_state_subtree(engine, state_key: str):
    if state_key not in _STATE_INDEX:
        raise ValueError(f"unknown optimizer state {state_key!r}: expected one of {sorted(_STATE_INDEX)}")
    opt_state = engine.state.get("opt_state")
    if not opt_state and opt_state != 0:
        return None
    params_treedef = jax.tree_util.tree_structure(engine.state["params"])
    subtrees = _param_shaped_subtrees(opt_state, params_treedef)
    idx = _STATE_INDEX[state_key]
    if idx >= len(subtrees):
        return None
    return subtrees[idx]
