"""Exception types (reference ``utils/exceptions.py``)."""


class DeprecatedException(Exception):
    pass
