"""Communication logging.

Analog of the reference ``deepspeed/utils/comms_logging.py`` (178 LoC:
``CommsLogger`` with per-op size/latency/busbw stats and ``log_summary``).
On TPU most collectives are compiled into the program, so per-op host timing
only applies to control-plane ops; traced collectives are recorded with their
message sizes at trace time and attributed latency from profiler runs.
"""

import math

from .logging import log_dist


def get_caller_func(frame=3):
    import sys

    return sys._getframe(frame).f_code.co_name


def calc_bw_log(comm_op, size, duration, n=None):
    """algbw/busbw math, mirroring the reference implementation. ``n`` is the
    collective's participant count (mesh-axis degree); callers that know the
    group pass it, legacy callers fall back to the historical placeholder."""
    if n is None or n < 1:
        n = 8  # mesh-degree placeholder when axis size unknown at log time
    duration = max(duration, 1e-9)
    if comm_op in ("all_to_all_single", "all_to_all"):
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter", "reduce_scatter_tensor"):
        size *= n
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n)
    elif comm_op == "all_reduce":
        tput = size * 2 / duration
        busbw = (size / duration) * (2 * (n - 1) / n)
    else:
        tput = size / duration
        busbw = tput
    tput /= 1e9
    busbw /= 1e9
    duration_ms = duration * 1e3
    return tput, busbw, duration_ms


class CommsLogger:

    def __init__(self, enabled=False, verbose=False, prof_all=True, debug=False, prof_ops=None):
        self.comms_dict = {}
        self.verbose = verbose
        self.debug = debug
        self.prof_ops = prof_ops or []
        self.prof_all = prof_all
        self.enabled = enabled

    def configure(self, comms_config):
        self.enabled = comms_config.comms_logger_enabled
        if self.enabled:
            self.verbose = comms_config.comms_logger.verbose
            self.debug = comms_config.comms_logger.debug
            self.prof_ops = comms_config.comms_logger.prof_ops
            self.prof_all = comms_config.comms_logger.prof_all

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def start_profiling_op(self, op_name_list):
        self.prof_ops = list(set(self.prof_ops) | set(op_name_list))

    def stop_profiling_op(self, op_name_list):
        self.prof_ops = [op for op in self.prof_ops if op not in op_name_list]

    def append(self, raw_name, record_name, latency, msg_size, n=None):
        algbw, busbw, duration_ms = calc_bw_log(raw_name, msg_size, latency, n=n)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                self.comms_dict[record_name][msg_size][0] += 1
                self.comms_dict[record_name][msg_size][1].append(duration_ms)
                self.comms_dict[record_name][msg_size][2].append(algbw)
                self.comms_dict[record_name][msg_size][3].append(busbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, [duration_ms], [algbw], [busbw]]
        else:
            self.comms_dict[record_name] = {msg_size: [1, [duration_ms], [algbw], [busbw]]}
        if self.verbose:
            log_dist(f"rank=0 | comm op: {record_name} | time (ms): {duration_ms:.2f} | "
                     f"msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw:.2f} | busbw (Gbps): {busbw:.2f}",
                     ranks=[0])

    def log_all(self, print_log=True, show_straggler=False):
        from .timer import trim_mean

        if print_log:
            print("{:<20} {:<20} {:<20} {:<20} {:<20} {:<20}".format("Comm. Op", "Message Size", "Count",
                                                                     "Total Latency(ms)", "Avg Latency(ms)",
                                                                     "tput_avg (Gbps)"))
        for record_name in self.comms_dict.keys():
            if print_log:
                print(record_name)
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count = vals[0]
                total_lat = sum(vals[1])
                avg_lat = trim_mean(list(vals[1]), 0.1)
                avg_algbw = trim_mean(list(vals[2]), 0.1)
                if print_log:
                    print("{:<20} {:<20} {:<20} {:<20} {:<20} {:<20}".format(
                        " ", convert_size(msg_size), count, f"{total_lat: .2f}", f"{avg_lat: .2f}",
                        f"{avg_algbw: .2f}"))
        return self.comms_dict

    def summary(self):
        """Aggregate view for machine consumers (bench JSON): per-op count,
        total bytes and trimmed-mean algo/bus bandwidth, plus grand totals."""
        from .timer import trim_mean

        ops = {}
        total_bytes = 0
        total_count = 0
        for record_name, by_size in self.comms_dict.items():
            count = sum(v[0] for v in by_size.values())
            op_bytes = sum(size * v[0] for size, v in by_size.items())
            lats = [x for v in by_size.values() for x in v[1]]
            algs = [x for v in by_size.values() for x in v[2]]
            buses = [x for v in by_size.values() for x in v[3]]
            ops[record_name] = {
                "count": count,
                "bytes": int(op_bytes),
                "avg_latency_ms": trim_mean(list(lats), 0.1),
                "avg_algbw_gbps": trim_mean(list(algs), 0.1),
                "avg_busbw_gbps": trim_mean(list(buses), 0.1),
            }
            total_bytes += op_bytes
            total_count += count
        return {"ops": ops, "total_bytes": int(total_bytes), "total_count": total_count}

    def reset(self):
        self.comms_dict = {}


def convert_size(size_bytes):
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return "%s %s" % (s, size_name[i])
