"""Profiler range annotation (reference ``utils/nvtx.py`` —
``instrument_w_nvtx`` wraps functions in NVTX ranges for nsight traces).

TPU analog: ``jax.profiler.TraceAnnotation`` — the annotated span shows up
named in the XLA/perfetto trace captured by ``jax.profiler``. Same decorator
contract, same name."""

import functools

import jax


def instrument_w_nvtx(func):
    """Decorate ``func`` so its host-side span is named in profiler traces."""

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(func.__qualname__):
            return func(*args, **kwargs)

    return wrapped


def range_push(name: str):
    """Manual range open (reference nvtx range_push); pair with range_pop."""
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    _stack.append(ann)


def range_pop():
    if _stack:
        _stack.pop().__exit__(None, None, None)


_stack = []
