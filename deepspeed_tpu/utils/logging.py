"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (``logger``,
``log_dist``): rank filtering is keyed on ``jax.process_index()`` instead of
torch.distributed ranks.
"""

import logging
import os
import sys
import functools

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:

    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    name="DeepSpeedTPU", level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info"), logging.INFO))


@functools.lru_cache(None)
def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the listed process ranks (``-1`` or None = all).

    Mirrors the contract of the reference ``utils/logging.py::log_dist``.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message):
    if _process_index() == 0:
        logger.info(message)


def warning_once(message, _seen=set()):
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)


def should_log_le(max_log_level_str):
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in LOG_LEVELS:
        raise ValueError(f"{max_log_level_str} is not one of the `logging` levels")
    return logger.getEffectiveLevel() <= LOG_LEVELS[max_log_level_str]
