"""OnDevice init context (reference ``utils/init_on_device.py`` —
``with OnDevice(dtype=..., device='meta')`` builds models without
materializing weights; with a real device, directly there).

JAX mapping: 'meta' is ``jax.eval_shape`` (abstract arrays — nothing
materializes; the engine's sharded ``_init_state`` with out_shardings is the
production form of this, never building an unsharded tree); a real device
is ``jax.default_device``. ``OnDevice.init(fn, *args)`` runs an init
function under the context's placement.
"""

from typing import Optional

import jax


class OnDevice:

    _active_dtype = None

    def __init__(self, dtype=None, device: Optional[str] = "meta", enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._ctx = None
        self._prev_dtype = None

    def __enter__(self):
        if self.enabled and self.device not in (None, "meta"):
            dev = jax.devices(self.device)[0] if isinstance(self.device, str) else self.device
            self._ctx = jax.default_device(dev)
            self._ctx.__enter__()
        self._prev_dtype = OnDevice._active_dtype  # nested contexts restore
        OnDevice._active_dtype = self.dtype
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        OnDevice._active_dtype = self._prev_dtype
        return False

    def _cast(self, tree):
        if self.dtype is None:
            return tree
        import jax.numpy as jnp

        def leaf(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                return (jax.ShapeDtypeStruct(x.shape, self.dtype)
                        if jnp.issubdtype(x.dtype, jnp.floating) else x)
            return x.astype(self.dtype) if jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating) else x

        return jax.tree_util.tree_map(leaf, tree)

    def init(self, init_fn, *args, **kwargs):
        """Run ``init_fn`` under this context's placement: 'meta' returns the
        ABSTRACT tree (jax.ShapeDtypeStruct leaves, zero bytes allocated);
        a real device materializes there. Floating leaves take the context's
        ``dtype`` (the reference casts module params the same way)."""
        if self.enabled and self.device == "meta":
            # close over the args: python scalars (sizes, configs) stay
            # concrete instead of becoming abstract tracers
            return self._cast(jax.eval_shape(lambda: init_fn(*args, **kwargs)))
        return self._cast(init_fn(*args, **kwargs))
