"""OnDevice init context (reference ``utils/init_on_device.py`` —
``with OnDevice(dtype=..., device='meta')`` builds models without
materializing weights; with a real device, directly there).

JAX mapping: 'meta' is ``jax.eval_shape`` (abstract arrays — nothing
materializes; the engine's sharded ``_init_state`` with out_shardings is the
production form of this, never building an unsharded tree); a real device
is ``jax.default_device``. ``OnDevice.init(fn, *args)`` runs an init
function under the context's placement.
"""

from typing import Optional

import jax


class OnDevice:

    _active_dtype = None

    def __init__(self, dtype=None, device: Optional[str] = "meta", enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._ctx = None

    def __enter__(self):
        if self.enabled and self.device not in (None, "meta"):
            dev = jax.devices(self.device)[0] if isinstance(self.device, str) else self.device
            self._ctx = jax.default_device(dev)
            self._ctx.__enter__()
        OnDevice._active_dtype = self.dtype
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        OnDevice._active_dtype = None
        return False

    def init(self, init_fn, *args, **kwargs):
        """Run ``init_fn`` under this context's placement: 'meta' returns the
        ABSTRACT tree (jax.ShapeDtypeStruct leaves, zero bytes allocated);
        a real device materializes there."""
        if self.enabled and self.device == "meta":
            # close over the args: python scalars (sizes, configs) stay
            # concrete instead of becoming abstract tracers
            return jax.eval_shape(lambda: init_fn(*args, **kwargs))
        return init_fn(*args, **kwargs)
