"""Reference import-path alias: ``deepspeed.utils.groups`` is where the
reference keeps the process-group registry; the TPU-native registry (mesh
axes) lives in ``parallel.groups`` and is re-exported here under the
reference path."""

from ..parallel.groups import *  # noqa: F401,F403
from ..parallel import groups as _impl


def __getattr__(name):  # anything not starred through (underscore helpers)
    return getattr(_impl, name)
