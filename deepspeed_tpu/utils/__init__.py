"""deepspeed_tpu.utils — logging, timers, comms logging, and the
tensor_fragment debug surface (reference ``deepspeed/utils/__init__.py``
re-exports)."""

from .logging import logger, log_dist, warning_once
from .tensor_fragment import (safe_get_full_fp32_param, safe_get_full_optimizer_state,
                              safe_get_local_fp32_param, safe_get_local_optimizer_state,
                              safe_set_full_fp32_param, safe_set_full_optimizer_state)
from . import exceptions, groups, init_on_device, nvtx, types
from .init_on_device import OnDevice
from .nvtx import instrument_w_nvtx
from .types import ActivationFuncType, NormType
