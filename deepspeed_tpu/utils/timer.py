"""Wall-clock and throughput timers.

TPU-native analog of the reference ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` :class via device events, ``ThroughputTimer``,
``NoopTimer``). On TPU there are no CUDA events; synchronization is achieved by
blocking on the most recent JAX async dispatch (``jax.block_until_ready`` /
``jax.effects_barrier``), which gives the same "device work up to here is done"
semantics the reference gets from ``get_accelerator().synchronize()``.
"""

import time

from .logging import log_dist

try:
    import psutil

    PSUTIL_AVAILABLE = True
except ImportError:  # pragma: no cover
    PSUTIL_AVAILABLE = False

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


def _device_sync():
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass


class CudaEventTimer:  # name kept for API familiarity; this is a host timer pair
    pass


class SynchronizedWallClockTimer:
    """Group of named timers, each synchronizing device work at start/stop."""

    class Timer:

        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.start_time = 0.0
            self.total_elapsed_ = 0.0

        def start(self):
            assert not self.started_, f"{self.name_} timer has already been started"
            _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False, record=False):
            assert self.started_, "timer is not started"
            _device_sync()
            elapsed = time.time() - self.start_time
            if reset:
                self.total_elapsed_ = elapsed
            else:
                self.total_elapsed_ += elapsed
            self.started_ = False

        def reset(self):
            self.started_ = False
            self.total_elapsed_ = 0.0

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop()
            elapsed = self.total_elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

        def mean(self):
            return self.elapsed(reset=False)

    def __init__(self):
        self.timers = {}

    def get_timers(self):
        return self.timers

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            alloc = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"Mem in use {alloc:.2f} GB | peak {peak:.2f} GB"
        except Exception:
            return "Mem stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        log_dist(string, ranks=ranks or [0])


class NoopTimer:

    class Timer:

        def start(self):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0

        def mean(self):
            return 0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def get_timers(self):
        return {}

    def log(self, names=None, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        ...


class ThroughputTimer:
    """Samples/sec + TFLOPS reporting, mirrors reference ``ThroughputTimer``."""

    def __init__(self, config, batch_size, start_step=2, steps_per_output=None, monitor_memory=False, logging_fn=None):
        self.config = config
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = batch_size or 1
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist
        self.initialized = False

    @property
    def enabled(self):
        return getattr(self.config, "enabled", True)

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _device_sync()
            self.start_time = time.time()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _device_sync()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration

            if global_step:
                if report_speed and self.steps_per_output and self.global_step_count % self.steps_per_output == 0:
                    self.logging("epoch={}/micro_step={}/global_step={}, RunningAvgSamplesPerSec={:.2f}, "
                                 "CurrSamplesPerSec={:.2f}".format(self.epoch_count, self.micro_step_count,
                                                                   self.global_step_count, self.avg_samples_per_sec(),
                                                                   self.batch_size / self.step_elapsed_time))
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step:
            samples_per_step = self.batch_size
            total_step_offset = self.global_step_count - self.start_step
            avg_time_per_step = self.total_elapsed_time / total_step_offset
            return samples_per_step / avg_time_per_step
        return float("-inf")


def trim_mean(data, trim_percent):
    """Compute the trimmed mean of a list (reference ``utils/timer.py::trim_mean``)."""
    assert 0.0 <= trim_percent <= 1.0
    n = len(data)
    if n == 0:
        return 0
    data.sort()
    k = int(round(n * trim_percent))
    return sum(data[k:n - k]) / max(1, n - 2 * k)
