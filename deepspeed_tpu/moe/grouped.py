"""Grouped-GEMM MoE dispatch: expert-sorted tokens through the Pallas
ragged matmul (``ops/pallas/grouped_matmul.py``).

Reference counterpart: the CUTLASS moe_gemm path
(``inference/v2/kernels/cutlass_ops/``) — gather each expert's tokens, run E
grouped GEMMs, scatter back. VERDICT r4 missing #5: the one-hot ``[S, E, C]``
dispatch/combine einsum (``sharded_moe.py``) is faithful to the reference's
training path but materializes capacity-padded buffers whose cost scales as
S*E*C — quadratic waste at E=64 with low capacity factors. Here the FFN work
scales with the ACTUAL routed tokens (plus at most one zero row-block per
expert for alignment).

Parity contract: assignments and weights are taken from the per-token
combine-weight matrix ``w_se`` (= ``combine.sum(capacity_axis)`` of the
capacity-based gate), so kept/dropped tokens and their gate weights are
IDENTICAL to the einsum path — only the dispatch mechanism changes.

Pipeline (all static shapes, jit-friendly):
  1. top-k over ``w_se`` → (expert id, weight) per token slot [S*k].
  2. stable-sort slots by expert; per-expert counts → BLOCK-ALIGNED group
     offsets (each group padded to a multiple of the row block, min one
     block, zero rows) → scatter tokens into ``x_sorted [T_pad, M]``.
  3. ``block_expert[i]`` = expert owning row block i (searchsorted over the
     padded starts) — the kernel's scalar-prefetch table.
  4. grouped_matmul chain (up [+ gate] → activation → down).
  5. gather back by slot destination, scale by gate weight, segment-sum the
     k slots per token.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _round_up(x, m):
    return (x + m - 1) // m * m


def block_align_dispatch(w_se, top_k: int, block_rows: int, top_idx=None, top_w=None,
                         num_experts: Optional[int] = None):
    """From per-token combine weights [S, E] — or precomputed routing
    ``top_idx``/``top_w`` [S, k] (+ ``num_experts``), skipping the top-k
    re-derivation: slot order, destinations and the block→expert table.
    Returns (flat_tok [S*k], flat_w [S*k], dest [S*k],
    block_expert [T_pad//block_rows], T_pad)."""
    if top_idx is not None:
        S = top_idx.shape[0]
        E = num_experts
        assert E is not None, "num_experts is required with precomputed top_idx"
        wvals, idx = top_w, top_idx
    else:
        S, E = w_se.shape
        wvals, idx = jax.lax.top_k(w_se, top_k)  # [S, k]
    flat_e = idx.reshape(-1)
    flat_w = wvals.reshape(-1)
    flat_tok = jnp.arange(S * top_k, dtype=jnp.int32) // top_k
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    sizes = jnp.bincount(flat_e, length=E)  # [E]
    # block-aligned groups, min one block each (tgmm needs every expert's
    # output block visited; zero rows contribute zero gradient)
    padded = jnp.maximum(block_rows, _round_up(sizes, block_rows))
    starts = jnp.concatenate([jnp.zeros(1, padded.dtype), jnp.cumsum(padded)])[:E]
    un_starts = jnp.concatenate([jnp.zeros(1, sizes.dtype), jnp.cumsum(sizes)])[:E]
    rank = jnp.arange(S * top_k) - un_starts[sorted_e]  # position within group
    dest = (starts[sorted_e] + rank).astype(jnp.int32)  # row in the padded buffer
    T_pad = _round_up(S * top_k, block_rows) + E * block_rows  # static bound
    block_expert = (jnp.searchsorted(starts, jnp.arange(T_pad // block_rows) * block_rows,
                                     side="right") - 1).astype(jnp.int32)
    return flat_tok[order], flat_w[order], dest, block_expert, T_pad


def grouped_moe_ffn(x, w_se, wi, wo, top_k: int, wg=None,
                    activation: Optional[Callable] = None,
                    block_rows: Optional[int] = None, interpret: Optional[bool] = None,
                    top_idx=None, top_w=None):
    """x: [S, M] tokens; w_se: [S, E] combine weights (nonzero = kept
    assignment, zero rows = dropped tokens) — or pass precomputed routing
    ``top_idx``/``top_w`` [S, k] (w_se then unused, may be None); wi:
    [E, M, F]; wg: optional swiglu gate weights [E, M, F]; wo: [E, F, M].
    ``activation(up, gate)`` (gate is None when wg is None); default
    silu(gate)*up / gelu(up).

    ``block_rows``/``interpret`` default by backend: 128/compiled on TPU,
    8/interpret elsewhere (one resolution point for every caller).

    Returns y [S, M] = sum over kept assignments of w * FFN_e(x) — the same
    quantity the einsum combine computes.
    """
    from ..ops.pallas.grouped_matmul import grouped_matmul

    on_tpu = jax.default_backend() == "tpu"
    if block_rows is None:
        block_rows = 128 if on_tpu else 8
    if interpret is None:
        interpret = not on_tpu
    S, M = x.shape
    if activation is None:
        activation = (lambda up, gate: jax.nn.silu(gate) * up) if wg is not None \
            else (lambda up, gate: jax.nn.gelu(up))
    tok, w_slot, dest, block_expert, T_pad = block_align_dispatch(
        w_se, top_k, block_rows, top_idx=top_idx, top_w=top_w,
        num_experts=wi.shape[0])
    x_sorted = jnp.zeros((T_pad, M), x.dtype).at[dest].set(x[tok])
    up = grouped_matmul(x_sorted, wi.astype(x.dtype), block_expert, block_t=block_rows,
                        interpret=interpret)
    gate = grouped_matmul(x_sorted, wg.astype(x.dtype), block_expert, block_t=block_rows,
                          interpret=interpret) if wg is not None else None
    mid = activation(up, gate)
    y_sorted = grouped_matmul(mid, wo.astype(x.dtype), block_expert, block_t=block_rows,
                              interpret=interpret)
    y_slots = y_sorted[dest] * w_slot[:, None].astype(x.dtype)
    return jax.ops.segment_sum(y_slots, tok, num_segments=S)
