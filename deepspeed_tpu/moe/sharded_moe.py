"""Sharded MoE: gating + expert-parallel dispatch.

TPU-native analog of the reference ``deepspeed/moe/sharded_moe.py``
(``TopKGate:348``, ``top1gating:184``, ``top2gating:282``, ``MOELayer:425``,
``_AllToAll:95``). Parity points kept exactly:

  * top-1 / top-2 gating with capacity factor, load-balancing aux loss
    (`l_aux`), optional random-token-priority (top-1) and second-expert
    normalization (top-2), min-capacity floor, token dropping at capacity.
  * dispatch/combine as einsums against a one-hot "dispatch mask" — the
    reference's own formulation (it einsums with ``sec`` masks), which on TPU
    lands directly on the MXU.
  * expert parallelism over the mesh: experts are sharded over the (data,
    seq) axes — ``lax.all_to_all`` moves token slots between expert shards,
    exactly the reference's ``_AllToAll`` over the EP process group.

Design difference (TPU-idiomatic): everything is fixed-shape — capacity is a
static int, dropped tokens contribute zeros — so the whole layer jits with no
dynamic shapes (the reference also uses fixed capacity; its CUDA path pads the
same way).
"""

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

uniform_map = {}
gumbel_map = {}
exp_selection_uniform_map = {}


def multiplicative_jitter(x, rng, epsilon=1e-2):
    """Reference ``multiplicative_jitter`` — uniform noise on gate inputs."""
    if epsilon == 0:
        return x
    uniform = jax.random.uniform(rng, x.shape, x.dtype, 1.0 - epsilon, 1.0 + epsilon)
    return x * uniform


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int) -> int:
    """Reference ``_capacity`` — tokens per expert buffer size (static)."""
    capacity = math.ceil(num_tokens / num_experts * capacity_factor)
    return max(capacity, min_capacity)


def _one_hot(indices, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(indices, num_classes, dtype=dtype)


def top1gating(logits: jax.Array,
               capacity_factor: float,
               min_capacity: int,
               used_token=None,
               noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None,
               drop_tokens: bool = True,
               use_rts: bool = True) -> Tuple[jax.Array, jax.Array, jax.Array, int]:
    """Reference ``top1gating:184``. logits: [S, E].

    Returns (l_aux, combine_weights [S, E, C], dispatch_mask [S, E, C], capacity).
    """
    S, E = logits.shape
    capacity = _capacity(S, E, capacity_factor, min_capacity)

    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        logits_w_noise = logits + jax.random.gumbel(sub, logits.shape, logits.dtype)
        indices1_s = jnp.argmax(logits_w_noise, axis=1)
    else:
        indices1_s = jnp.argmax(logits, axis=1)
    gates = jax.nn.softmax(logits, axis=1)
    mask1 = _one_hot(indices1_s, E)

    if used_token is not None:
        mask1 = mask1 * used_token[:, None]

    # load-balancing aux loss (reference: me*ce*E)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # random token priority (reference use_rts): random scores break position
    # bias when selecting which tokens win capacity slots
    if use_rts and rng is not None:
        rng, sub = jax.random.split(rng)
        mask1_rand = mask1 * jax.random.uniform(sub, mask1.shape, mask1.dtype)
    else:
        mask1_rand = mask1

    if drop_tokens:
        # rank tokens per expert by priority score (assigned tokens have
        # positive scores and sort first; argsort is stable). A token's rank
        # is its buffer slot; ranks >= capacity drop — fixed-shape
        # formulation of the reference's top-capacity selection.
        order = jnp.argsort(-mask1_rand, axis=0)  # [S, E]: rank -> token
        ranks = jnp.argsort(order, axis=0)  # [S, E]: token -> rank
        within_cap = (ranks < capacity) & (mask1 > 0)
        mask1 = jnp.where(within_cap, mask1, 0.0)
        locations1_s = jnp.sum(ranks * mask1, axis=1)
    else:
        locations1 = jnp.cumsum(mask1, axis=0) - 1
        locations1_s = jnp.sum(locations1 * mask1, axis=1)
        capacity = S  # no dropping: buffers must hold every token

    gates1_s = jnp.sum(gates * mask1, axis=1)  # gate value of kept tokens (0 if dropped)

    loc_oh = _one_hot(locations1_s.astype(jnp.int32), capacity)
    combine_weights = gates1_s[:, None, None] * mask1[:, :, None] * loc_oh[:, None, :]
    dispatch_mask = (combine_weights > 0).astype(logits.dtype)
    return l_aux, combine_weights, dispatch_mask, capacity


def top2gating(logits: jax.Array,
               capacity_factor: float,
               min_capacity: int,
               drop_tokens: bool = True,
               top2_2nd_expert_sampling: bool = True,
               rng: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array, jax.Array, int]:
    """Reference ``top2gating:282``. logits: [S, E]."""
    S, E = logits.shape
    gates = jax.nn.softmax(logits, axis=1)
    capacity = _capacity(S, E, capacity_factor * 2, min_capacity) if drop_tokens else S

    indices1_s = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1_s, E)

    if top2_2nd_expert_sampling and rng is not None:
        rng, sub = jax.random.split(rng)
        logits2 = logits + jax.random.gumbel(sub, logits.shape, logits.dtype)
    else:
        logits2 = logits
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits2)
    indices2_s = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2_s, E)

    # positions: expert-1 tokens first, expert-2 after (reference ordering)
    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1 + jnp.sum(mask1, axis=0, keepdims=True)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.mean(me * ce) * E * E

    if drop_tokens:
        mask1 = mask1 * (locations1 < capacity)
        mask2 = mask2 * (locations2 < capacity)

    locations1_s = jnp.sum(locations1 * mask1, axis=1)
    locations2_s = jnp.sum(locations2 * mask2, axis=1)

    # normalize kept gate values
    gates1_s = jnp.sum(gates * mask1, axis=1)
    gates2_s = jnp.sum(gates * mask2, axis=1)
    denom_s = jnp.clip(gates1_s + gates2_s, 1e-9, None)
    gates1_s = gates1_s / denom_s
    gates2_s = gates2_s / denom_s

    loc1_oh = _one_hot(locations1_s.astype(jnp.int32), capacity)
    loc2_oh = _one_hot(locations2_s.astype(jnp.int32), capacity)
    combine1 = gates1_s[:, None, None] * mask1[:, :, None] * loc1_oh[:, None, :]
    combine2 = gates2_s[:, None, None] * mask2[:, :, None] * loc2_oh[:, None, :]
    combine_weights = combine1 + combine2
    dispatch_mask = (combine_weights > 0).astype(logits.dtype)
    return l_aux, combine_weights, dispatch_mask, capacity


class TopKGate:
    """Reference ``TopKGate:348`` — linear gate + top-k routing."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 8, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True, top2_2nd_expert_sampling: bool = True):
        assert k in (1, 2), "Only top-1 and top-2 gatings are supported (reference behavior)"
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts
        self.top2_2nd_expert_sampling = top2_2nd_expert_sampling

    def init(self, rng):
        w = jax.random.normal(rng, (self.model_dim, self.num_experts), jnp.float32) / math.sqrt(self.model_dim)
        return {"wg": w}

    def __call__(self, params, x, rng=None, train=True):
        """x: [S, M] tokens. Returns (l_aux, combine [S,E,C], dispatch [S,E,C], capacity)."""
        inp = x.astype(jnp.float32)
        if self.noisy_gate_policy == "Jitter" and rng is not None and train:
            rng, sub = jax.random.split(rng)
            inp = multiplicative_jitter(inp, sub)
        logits = inp @ params["wg"].astype(jnp.float32)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity, noisy_gate_policy=self.noisy_gate_policy if train else
                              None, rng=rng, drop_tokens=self.drop_tokens, use_rts=self.use_rts and train)
        return top2gating(logits, cf, self.min_capacity, drop_tokens=self.drop_tokens,
                          top2_2nd_expert_sampling=self.top2_2nd_expert_sampling and train, rng=rng)


class MOELayer:
    """Reference ``MOELayer:425`` — dispatch → expert FFN → combine.

    Functional object: ``init(rng)`` makes params (gate + stacked expert FFN
    weights [E_local, ...]); ``__call__(params, x, ...)`` runs the layer.

    Expert parallelism: with ``ep_axis`` set (inside shard_map over a mesh
    whose (data×seq) axes carry ``ep_size`` shards), each shard holds
    ``num_local_experts = E / ep_size`` experts; dispatched slots move between
    shards by ``lax.all_to_all`` before and after the expert FFN — identical
    communication pattern to the reference's ``_AllToAll`` autograd function.
    """

    def __init__(self, gate: TopKGate, hidden_dim: int, ffn_dim: int, num_local_experts: int,
                 ep_axis: Optional[str] = None, ep_size: int = 1, activation: Callable = jax.nn.gelu,
                 moe_impl: str = "einsum"):
        if moe_impl not in ("einsum", "grouped"):
            raise ValueError(f"moe_impl must be 'einsum' or 'grouped', got {moe_impl!r}")
        if moe_impl == "grouped" and ep_axis is not None and ep_size > 1:
            # the grouped path replaces dispatch+combine entirely; the EP
            # a2a rides the capacity-slot layout, so the combination is not
            # implemented — reject loudly rather than silently fall back
            raise NotImplementedError(
                "moe_impl='grouped' does not compose with expert parallelism yet "
                "(the a2a exchanges fixed-capacity slots); use moe_impl='einsum' "
                "for EP-sharded layers")
        self.gate = gate
        self.hidden_dim = hidden_dim
        self.ffn_dim = ffn_dim
        self.num_local_experts = num_local_experts
        self.ep_axis = ep_axis
        self.ep_size = ep_size
        self.activation = activation
        self.moe_impl = moe_impl

    def init(self, rng):
        kg, k1, k2 = jax.random.split(rng, 3)
        E, M, F = self.num_local_experts, self.hidden_dim, self.ffn_dim
        return {
            "gate": self.gate.init(kg),
            "experts": {
                "wi": jax.random.normal(k1, (E, M, F), jnp.float32) / math.sqrt(M),
                "wo": jax.random.normal(k2, (E, F, M), jnp.float32) / math.sqrt(F),
            },
        }

    def _expert_ffn(self, eparams, x):
        """x: [E_local, n, C, M] → per-expert FFN via batched einsum (the
        TPU version of the reference's grouped expert GEMM / moe_gemm)."""
        h = jnp.einsum("encm,emf->encf", x, eparams["wi"].astype(x.dtype))
        h = self.activation(h)
        return jnp.einsum("encf,efm->encm", h, eparams["wo"].astype(x.dtype))

    def __call__(self, params, x, rng=None, train=True):
        """x: [S_local, M] (tokens of this shard). Returns (y [S_local, M], l_aux)."""
        S, M = x.shape
        E = self.gate.num_experts
        l_aux, combine, dispatch, capacity = self.gate(params["gate"], x, rng=rng, train=train)

        if self.moe_impl == "grouped":
            # megablocks-style path (ops/pallas/grouped_matmul.py): work
            # scales with routed tokens, not S*E*C — same kept set and gate
            # weights as the einsum path (w_se = combine collapsed over the
            # capacity axis), so numerics match the dispatch/combine einsums
            from .grouped import grouped_moe_ffn

            y = grouped_moe_ffn(
                x, combine.sum(axis=2), params["experts"]["wi"], params["experts"]["wo"],
                top_k=self.gate.k, activation=lambda up, gate: self.activation(up))
            return y, l_aux

        # dispatch: [S, E, C] x [S, M] → [E, C, M]
        dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(x.dtype), x)

        if self.ep_axis is not None and self.ep_size > 1:
            # [E, C, M] → [ep, E_local, C, M] slots; a2a swaps the ep dim with
            # the shard dim: every shard ends up with its local experts' slots
            # from ALL shards (reference _AllToAll:95)
            dispatched = dispatched.reshape(self.ep_size, self.num_local_experts, capacity, M)
            dispatched = lax.all_to_all(dispatched, self.ep_axis, split_axis=0, concat_axis=0, tiled=True)
            # now [ep * E_local, C, M] where axis 0 groups = peers' tokens
            dispatched = dispatched.reshape(self.ep_size, self.num_local_experts, capacity, M)
            dispatched = dispatched.transpose(1, 0, 2, 3)  # [E_local, ep, C, M]
            expert_out = self._expert_ffn(params["experts"], dispatched)
            expert_out = expert_out.transpose(1, 0, 2, 3).reshape(self.ep_size * self.num_local_experts, capacity, M)
            expert_out = lax.all_to_all(expert_out, self.ep_axis, split_axis=0, concat_axis=0, tiled=True)
            expert_out = expert_out.reshape(E, capacity, M)
        else:
            expert_out = self._expert_ffn(params["experts"], dispatched[:, None].reshape(
                self.num_local_experts, -1, capacity, M)).reshape(E, capacity, M)

        # combine: [S, E, C] x [E, C, M] → [S, M]
        y = jnp.einsum("sec,ecm->sm", combine.astype(x.dtype), expert_out)
        return y, l_aux
