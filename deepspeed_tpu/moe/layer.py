"""User-facing MoE layer.

Analog of the reference ``deepspeed/moe/layer.py:16`` (``MoE``): bundles a
TopKGate + MOELayer + expert FFN and declares the expert-parallel degree. On
TPU the "EP process group creation" (reference :85 via groups.py) amounts to
recording the ep axis name; communication comes from ``lax.all_to_all`` in
shard_map form or sharding constraints in GSPMD form.
"""

from typing import Callable, Optional

import jax

from .sharded_moe import MOELayer, TopKGate
from ..parallel import groups
from ..utils.logging import log_dist


class MoE:

    def __init__(self,
                 hidden_size: int,
                 expert=None,
                 num_experts: int = 1,
                 ep_size: int = 1,
                 k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 use_rts: bool = True,
                 use_tutel: bool = False,
                 enable_expert_tensor_parallelism: bool = False,
                 top2_2nd_expert_sampling: bool = True,
                 ffn_dim: Optional[int] = None,
                 activation: Callable = jax.nn.gelu):
        assert num_experts % ep_size == 0, f"Number of experts ({num_experts}) should be divisible by expert parallel size ({ep_size})"
        self.ep_size = ep_size
        self.num_experts = num_experts
        self.num_local_experts = num_experts // ep_size
        self.use_residual = use_residual
        ffn_dim = ffn_dim or 4 * hidden_size
        log_dist(f"Creating MoE layer with num_experts: {num_experts} | num_local_experts: "
                 f"{self.num_local_experts} | expert_parallel_size: {ep_size}", ranks=[0])
        gate = TopKGate(hidden_size, num_experts, k, capacity_factor, eval_capacity_factor, min_capacity,
                        noisy_gate_policy, drop_tokens, use_rts, top2_2nd_expert_sampling)
        ep_axis = None
        if ep_size > 1:
            ep_axis = groups.get_expert_parallel_group()
            ep_axis = ep_axis[0] if len(ep_axis) == 1 else ep_axis
        self.deepspeed_moe = MOELayer(gate, hidden_size, ffn_dim, self.num_local_experts, ep_axis=ep_axis,
                                      ep_size=ep_size, activation=activation)
        self.hidden_size = hidden_size

    def init(self, rng):
        rng, moe_rng = jax.random.split(rng)
        params = {"moe": self.deepspeed_moe.init(moe_rng)}
        if self.use_residual:
            import math
            import jax.numpy as jnp

            k1, k2, k3 = jax.random.split(rng, 3)
            F = self.deepspeed_moe.ffn_dim
            params["residual_mlp"] = {
                "wi": jax.random.normal(k1, (self.hidden_size, F), jnp.float32) / math.sqrt(self.hidden_size),
                "wo": jax.random.normal(k2, (F, self.hidden_size), jnp.float32) / math.sqrt(F),
            }
            params["coefficient"] = jax.random.normal(k3, (self.hidden_size, 2), jnp.float32) * 0.02
        return params

    def __call__(self, params, hidden_states, rng=None, train=True):
        """hidden_states: [S, M] (or [B*S, M] flattened). Returns
        (output, l_aux) — reference returns (output, l_aux, exp_counts)."""
        out, l_aux = self.deepspeed_moe(params["moe"], hidden_states, rng=rng, train=train)
        if self.use_residual:
            import jax.numpy as jnp

            mlp = jax.nn.gelu(hidden_states @ params["residual_mlp"]["wi"].astype(hidden_states.dtype))
            mlp = mlp @ params["residual_mlp"]["wo"].astype(hidden_states.dtype)
            coef = jax.nn.softmax(hidden_states @ params["coefficient"].astype(hidden_states.dtype), axis=-1)
            out = out * coef[..., 0:1] + mlp * coef[..., 1:2]
        return out, l_aux
