"""MoE ↔ tensor-parallel token mappings.

Analog of the reference ``deepspeed/moe/mappings.py`` (``_gather_tokens:28``
/ ``_drop_tokens:47`` with their autograd duals ``_GatherTokens:60`` /
``_DropTokens``): a TP-sharded transformer feeds its MoE layer tokens that
are REPLICATED across the model axis; the expert all-to-all wants each rank
to own a distinct token shard, so the MoE block drops to a 1/tp slice on
entry and gathers back on exit.

TPU form: inside ``shard_map`` the two mappings are one collective each —
``jax.lax.all_gather`` over the model axis (gather) and a static slice of
this rank's chunk (drop). They are exact transposes of each other, so
``jax.grad`` derives each one's backward as the other automatically — the
reference's hand-written autograd Function pair is subsumed by the functional
transform. Outside ``shard_map`` (GSPMD-auto code), use the
``*_constraint`` forms: a ``with_sharding_constraint`` re-annotation that
lets XLA insert the identical collective.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MODEL_AXIS


def gather_tokens(x, dim: int = 0, axis_name: str = MODEL_AXIS):
    """All-gather token shards along ``dim`` across the TP axis
    (reference ``_gather_tokens:28``). shard_map-traced form."""
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def drop_tokens(x, dim: int = 0, axis_name: str = MODEL_AXIS):
    """Keep this rank's 1/tp slice along ``dim`` (reference
    ``_drop_tokens:47``). shard_map-traced form."""
    tp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    n = x.shape[dim]
    assert n % tp == 0, (f"input dimension {dim} ({n}) is not divisible by "
                         f"tensor parallel world size ({tp})")
    chunk = n // tp
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


def gather_tokens_constraint(x, dim: int = 0, mesh=None, axis_name: str = MODEL_AXIS):
    """GSPMD-auto form of ``gather_tokens``: constrain ``dim`` replicated so
    XLA materializes the model-axis all-gather at this point. Every OTHER
    dim stays UNCONSTRAINED — a batch dim sharded over the data axis keeps
    its sharding instead of being collaterally all-gathered."""
    from ..parallel import groups

    mesh = mesh or groups.get_mesh()
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[dim] = None
    return jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, P(*spec)))


def drop_tokens_constraint(x, dim: int = 0, mesh=None, axis_name: str = MODEL_AXIS):
    """GSPMD-auto form of ``drop_tokens``: constrain ``dim`` sharded over the
    model axis so XLA slices each rank's chunk here; other dims stay
    UNCONSTRAINED (DP shardings compose untouched)."""
    from ..parallel import groups

    mesh = mesh or groups.get_mesh()
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[dim] = axis_name
    return jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, P(*spec)))
