from .grouped import block_align_dispatch, grouped_moe_ffn
from .layer import MoE
from .mappings import drop_tokens, drop_tokens_constraint, gather_tokens, gather_tokens_constraint
from .sharded_moe import MOELayer, TopKGate, top1gating, top2gating
