"""deepspeed_tpu — a TPU-native training & inference framework with the
capabilities of DeepSpeed (reference: schoi-habana/DeepSpeed v0.12.4).

Public API mirrors the reference ``deepspeed/__init__.py``:
``initialize`` (:64), ``init_inference`` (:273), ``add_config_arguments``
(:250) — with JAX-native semantics: the "module" is a model object exposing
``init``/``loss`` (see ``models.transformer.TransformerLM``), the optimizer is
an optax transformation, and all parallelism is carried by one device mesh.
"""

__version__ = "0.1.0"
__git_hash__ = None
__git_branch__ = None

from .accelerator import get_accelerator, set_accelerator
from . import comm as _comm_pkg
from .comm import comm as dist
from .comm.comm import init_distributed
from .runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from .runtime.engine import DeepSpeedEngine
from .runtime.hybrid_engine import DeepSpeedHybridEngine
from .runtime.pipe.module import PipelineModule
from .runtime import zero
from . import constants, git_version_info, model_implementations, nebula, pipe
from .runtime.activation_checkpointing import checkpointing
from .inference.engine import InferenceEngine
from .inference.config import DeepSpeedInferenceConfig
from .module_inject import replace_transformer_layer, revert_transformer_layer
from . import ops
from . import module_inject
from .parallel import MeshConfig, groups
from .utils.logging import logger, log_dist


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               example_batch=None):
    """Initialize the engine (reference ``deepspeed.initialize`` signature,
    ``deepspeed/__init__.py:64``). Returns (engine, optimizer, dataloader,
    lr_scheduler) like the reference.

    - ``model``: object with ``init(rng, example) -> params`` and
      ``loss(params, batch, rng) -> loss`` (e.g. ``models.llama2()``); or any
      callable ``(params, batch, rng) -> loss`` paired with
      ``model_parameters`` as initial params.
    - ``config``: dict or path to a DeepSpeed-style JSON config.
    - ``mesh``: optional pre-built ``jax.sharding.Mesh``; otherwise built from
      the config's ``tpu.mesh`` section over all visible devices.
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    if config is None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config:
        config = args.deepspeed_config
    assert config is not None, "DeepSpeed requires --deepspeed_config to specify configuration file"

    ds_config = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config, mesh=mesh, mpu=mpu)

    if callable(model) and not hasattr(model, "init"):
        model = _FunctionalModel(model, model_parameters)

    # engine-class dispatch (reference deepspeed/__init__.py:156-196:
    # DeepSpeedEngine / PipelineEngine / DeepSpeedHybridEngine)
    engine_cls = DeepSpeedEngine
    if ds_config.hybrid_engine_config.enabled:
        engine_cls = DeepSpeedHybridEngine
    engine = engine_cls(model=model,
                        config=ds_config,
                        optimizer=optimizer,
                        lr_scheduler=lr_scheduler,
                        mesh=mesh,
                        example_batch=example_batch,
                        training_data=training_data,
                        collate_fn=collate_fn)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


class _FunctionalModel:
    """Adapter: bare loss function + initial params → model protocol."""

    def __init__(self, loss_fn, init_params):
        self._loss_fn = loss_fn
        self._params = init_params

    def init(self, rng, example_batch=None):
        assert self._params is not None, "pass model_parameters with a bare loss function"
        return self._params

    def loss(self, params, batch, rng=None):
        try:
            return self._loss_fn(params, batch, rng)
        except TypeError:
            return self._loss_fn(params, batch)


def init_inference(model=None, config=None, **kwargs):
    """Reference ``deepspeed.init_inference`` (:273): build an InferenceEngine
    around a model with TP sharding and fused kernels."""
    if config is None:
        config = kwargs
    ds_config = config if isinstance(config, DeepSpeedInferenceConfig) else DeepSpeedInferenceConfig(**(config or {}))
    return InferenceEngine(model, ds_config)


def add_config_arguments(parser):
    """Reference ``deepspeed.add_config_arguments`` (:250)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no impact on DS itself)")
    group.add_argument("--deepspeed_config", default=None, type=str, help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale", default=False, action="store_true", help=argparse_dep("--deepspeed"))
    group.add_argument("--deepscale_config", default=None, type=str, help=argparse_dep("--deepspeed_config"))
    return parser


def argparse_dep(new):
    return f"Deprecated, use {new}"
