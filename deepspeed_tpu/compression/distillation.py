"""Knowledge distillation and layer reduction.

Analog of the reference compression suite's student-teacher path
(``compression/compress.py student_initialization`` + the
``layer_reduction`` block of ``compression/config.py``, used by the
DeepSpeed-Compression XTC/ZeroQuant recipes):

  * ``apply_layer_reduction`` — build a shallower student by SELECTING
    teacher layers. With scan-stacked params ([L, ...] arrays) this is one
    gather over the layer dim, vs the reference's module-tree surgery.
  * ``distillation_loss`` — soft-target KL (temperature-scaled) + optional
    hard CE mix, the standard KD objective the reference recipes train with.
  * ``compress_embedding`` — fake-quantized embedding with straight-through
    gradients (the reference ``Embedding_Compress`` layer).
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .basic_layer import quantize_weight


def apply_layer_reduction(params, keep_layers: Sequence[int]):
    """Student params keeping the given teacher layer indices (reference
    ``student_initialization``'s teacher_layer list). Works on any pytree
    whose 'blocks' subtree stacks layers on dim 0."""
    idx = jnp.asarray(list(keep_layers), jnp.int32)
    out = dict(params)
    out["blocks"] = jax.tree_util.tree_map(lambda x: x[idx], params["blocks"])
    return out


def distillation_loss(student_logits, teacher_logits, labels=None, temperature: float = 1.0,
                      alpha: float = 0.5, loss_mask=None):
    """KD objective: ``alpha * T^2 * KL(teacher_T || student_T) +
    (1-alpha) * CE(student, labels)`` (the reference recipes' kd loss).

    logits: [..., V]; labels: [...] int (optional; alpha=1 for pure soft)."""
    T = float(temperature)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / T, axis=-1)
    t = jax.nn.softmax(teacher_logits.astype(jnp.float32) / T, axis=-1)
    kl = jnp.sum(t * (jnp.log(jnp.maximum(t, 1e-20)) - s), axis=-1)
    if loss_mask is not None:
        m = loss_mask.astype(jnp.float32)
        soft = (kl * m).sum() / jnp.maximum(m.sum(), 1.0)
    else:
        soft = kl.mean()
    soft = (T * T) * soft
    if labels is None or alpha >= 1.0:
        return soft
    logp = jax.nn.log_softmax(student_logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        m = loss_mask.astype(jnp.float32)
        hard = -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)
    else:
        hard = -ll.mean()
    return alpha * soft + (1.0 - alpha) * hard


def compress_embedding(params, bits: int = 8, groups: int = 1):
    """Fake-quantize the token embedding with STE (reference
    ``Embedding_Compress``): training sees quantized values, gradients pass
    through to the fp32 master."""
    out = dict(params)
    emb = dict(out["embed"])
    w = emb["embedding"]
    qw = quantize_weight(w, bits=bits, groups=groups)
    emb["embedding"] = w + jax.lax.stop_gradient(qw - w)
    out["embed"] = emb
    return out
