"""Compression application (reference ``deepspeed/compression/compress.py``:
``init_compression`` rewrites nn modules into compressed variants;
``redundancy_clean`` permanently applies masks/quantization at export).

Functional TPU form: the "rewrite" is a transform over the param tree —
``init_compression`` builds a ``CompressionScheduler`` describing which param
paths get which technique; ``apply_compression(params, step)`` produces the
compressed view (used in the loss for QAT / mask-training), and
``redundancy_clean`` bakes the final masks/quantization into the stored
params for export."""

import fnmatch
import re
from typing import Dict

import jax
import jax.numpy as jnp

from . import basic_layer
from .config import DeepSpeedCompressionConfig, get_compression_config
from .scheduler import CompressionScheduler
from ..utils.logging import logger


def _match(path: str, patterns) -> bool:
    for pat in patterns:
        if fnmatch.fnmatch(path, pat):
            return True
        try:
            if re.search(pat, path):
                return True
        except re.error:
            pass  # pattern was glob-only (e.g. leading '*'), already tried
    return False


def _technique_plan(config: DeepSpeedCompressionConfig):
    """[(technique_name, group_name, params, modules_patterns, offset)]"""
    plan = []
    for tech_name in ("weight_quantization", "sparse_pruning", "row_pruning", "head_pruning",
                      "channel_pruning"):
        tech = getattr(config, tech_name)
        if not tech.enabled:
            continue
        offset = tech.schedule_offset
        shared = tech.shared_parameters
        for gname, group in tech.different_groups.items():
            plan.append((tech_name, gname, {**shared, **group.params}, group.modules, offset))
    if config.activation_quantization.enabled:
        # activation quantization operates on forward intermediates, not
        # weights — the model must call basic_layer.ste(asym_quantize, x, …)
        # at its activation sites (reference rewrites the module forward);
        # record it on the scheduler so models can query the config, and be
        # loud that a weight-tree transform alone cannot honor it
        logger.warning("activation_quantization enabled: apply it at model activation sites via "
                       "compression.basic_layer (ste + asym/sym_quantize); it is not a weight transform")
    return plan


def _apply_one(tech_name, params_cfg, w):
    if tech_name == "weight_quantization":
        bits = int(params_cfg.get("target_bits", 8))
        groups = int(params_cfg.get("quantization_groups", 1))
        qtype = params_cfg.get("quantization_type", "symmetric")
        return basic_layer.ste(basic_layer.quantize_weight, w, bits, groups, qtype) \
            if params_cfg.get("quantize_weight_in_forward", True) else \
            basic_layer.quantize_weight(w, bits, groups, qtype)
    dense = float(params_cfg.get("dense_ratio", 0.5))
    if tech_name == "sparse_pruning":
        mask = basic_layer.sparse_pruning_mask(w, dense, params_cfg.get("method", "l1"))
    elif tech_name == "row_pruning":
        mask = basic_layer.row_pruning_mask(w, dense)
    elif tech_name == "channel_pruning":
        mask = basic_layer.channel_pruning_mask(w, dense)
    elif tech_name == "head_pruning":
        mask = basic_layer.head_pruning_mask(w, dense, int(params_cfg.get("num_heads", 1)))
    else:
        return w
    return w * jax.lax.stop_gradient(mask)


def init_compression(params, deepspeed_config, teacher_model=None, mpu=None):
    """Build the compression scheduler for a param tree (reference
    ``init_compression`` returns the rewritten model; here: (params,
    scheduler) — params unchanged until apply/clean)."""
    cfg = deepspeed_config if isinstance(deepspeed_config, DeepSpeedCompressionConfig) else \
        get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict) else {})
    plan = _technique_plan(cfg)
    n_matched = 0
    from ..runtime.zero.partition import path_str

    matched: Dict[str, list] = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        path = path_str(kp)
        for tech_name, gname, pcfg, patterns, offset in plan:
            if jnp.ndim(leaf) >= 2 and _match(path, patterns):
                matched.setdefault(path, []).append((tech_name, pcfg, offset))
                n_matched += 1
    logger.info(f"init_compression: {len(plan)} technique groups, {n_matched} param matches")
    scheduler = CompressionScheduler(matched)
    scheduler.activation_quantization = cfg.activation_quantization  # model-side technique
    return scheduler


def apply_compression(params, scheduler: CompressionScheduler, step: int = 10**9):
    """Compressed view of the params for techniques past their schedule
    offset (QAT/mask-training forward)."""
    from ..runtime.zero.partition import path_str

    def transform(kp, leaf):
        path = path_str(kp)
        for tech_name, pcfg, offset in scheduler.matched.get(path, []):
            if step >= offset:
                leaf = _apply_one(tech_name, pcfg, leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(transform, params)


def redundancy_clean(params, deepspeed_config, scheduler: CompressionScheduler = None):
    """Bake compression into the stored params for export (reference
    ``redundancy_clean`` folds masks/quantization into the state dict)."""
    if scheduler is None:
        scheduler = init_compression(params, deepspeed_config)
    return apply_compression(params, scheduler)
