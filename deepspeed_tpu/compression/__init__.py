from .compress import init_compression, redundancy_clean, apply_compression
from .config import get_compression_config, DeepSpeedCompressionConfig
from .scheduler import CompressionScheduler
from . import basic_layer
from .distillation import apply_layer_reduction, compress_embedding, distillation_loss
