"""Compression scheduler (reference ``deepspeed/compression/scheduler.py``:
tracks which technique applies to which module from which step)."""

from typing import Dict, List, Tuple


class CompressionScheduler:

    def __init__(self, matched: Dict[str, List[Tuple[str, dict, int]]]):
        #: {param_path: [(technique, params, schedule_offset_step), ...]}
        self.matched = matched

    def active_techniques(self, step: int):
        out = {}
        for path, entries in self.matched.items():
            live = [(t, p) for t, p, offset in entries if step >= offset]
            if live:
                out[path] = live
        return out

    def check_sparse_pruning_before_backward(self, step: int):
        """Reference hook name; mask freshness is handled functionally in
        apply_compression so this is a no-op kept for API parity."""
        return self.active_techniques(step)
