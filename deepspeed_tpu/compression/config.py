"""Compression configuration (reference ``deepspeed/compression/config.py`` +
``constants.py``): the ``compression_training`` block with per-technique
groups, each carrying ``shared_parameters`` and named ``different_groups``
with ``modules`` patterns."""

from typing import Any, Dict, List, Optional

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class TechniqueGroup(DeepSpeedConfigModel):
    """One entry of ``different_groups`` (reference group schema)."""
    params: Dict[str, Any] = Field(default_factory=dict)
    modules: List[str] = Field(default_factory=lambda: ["*"])
    related_modules: Optional[List[str]] = None


class TechniqueConfig(DeepSpeedConfigModel):
    """A technique block: weight_quantization / sparse_pruning / …"""
    shared_parameters: Dict[str, Any] = Field(default_factory=dict)
    different_groups: Dict[str, TechniqueGroup] = Field(default_factory=dict)

    @property
    def enabled(self):
        return bool(self.shared_parameters.get("enabled", False))

    @property
    def schedule_offset(self):
        return int(self.shared_parameters.get("schedule_offset", 0))


class LayerReductionConfig(DeepSpeedConfigModel):
    enabled: bool = False
    keep_number_layer: Optional[int] = None
    teacher_layer: List[int] = Field(default_factory=list)
    other_module_name: List[str] = Field(default_factory=list)


class DeepSpeedCompressionConfig(DeepSpeedConfigModel):
    layer_reduction: LayerReductionConfig = Field(default_factory=LayerReductionConfig)
    weight_quantization: TechniqueConfig = Field(default_factory=TechniqueConfig)
    activation_quantization: TechniqueConfig = Field(default_factory=TechniqueConfig)
    sparse_pruning: TechniqueConfig = Field(default_factory=TechniqueConfig)
    row_pruning: TechniqueConfig = Field(default_factory=TechniqueConfig)
    head_pruning: TechniqueConfig = Field(default_factory=TechniqueConfig)
    channel_pruning: TechniqueConfig = Field(default_factory=TechniqueConfig)


def get_compression_config(param_dict: dict) -> DeepSpeedCompressionConfig:
    return DeepSpeedCompressionConfig(**param_dict.get("compression_training", {}))
