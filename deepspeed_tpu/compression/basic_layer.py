"""Compression primitives.

Reference ``deepspeed/compression/basic_layer.py`` (840 LoC) implements
LinearLayer_Compress with in-module quantizers and pruning masks. Functional
TPU redesign: each technique is a pure array transform — straight-through
quantizers for QAT inside the jitted loss, and mask builders for pruning —
applied to the param tree by ``compress.py``.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# quantizers (reference SymQuantizer / AsymQuantizer / TernaryQuantizer /
# BinaryQuantizer in compression/utils.py)
# ---------------------------------------------------------------------------
def sym_quantize(x, bits: int = 8, groups: int = 1):
    """Symmetric uniform fake-quantization (quantize-dequantize) with
    per-group absmax scaling. Straight-through: use inside the loss with
    ``ste`` for QAT."""
    q_range = 2**(bits - 1) - 1
    orig = x.shape
    g = x.reshape(groups, -1)
    scale = jnp.max(jnp.abs(g), axis=-1, keepdims=True) / q_range
    scale = jnp.maximum(scale, 1e-10)
    q = jnp.clip(jnp.round(g / scale), -q_range - 1, q_range)
    return (q * scale).reshape(orig)


def asym_quantize(x, bits: int = 8, groups: int = 1):
    """Asymmetric (min/max) fake-quantization."""
    levels = 2**bits - 1
    orig = x.shape
    g = x.reshape(groups, -1)
    mn = jnp.min(g, axis=-1, keepdims=True)
    mx = jnp.max(g, axis=-1, keepdims=True)
    scale = jnp.maximum((mx - mn) / levels, 1e-10)
    q = jnp.round((g - mn) / scale)
    return (q * scale + mn).reshape(orig)


def ternary_quantize(x, groups: int = 1):
    """TernaryQuantizer: {-a, 0, +a} with a = mean|x| over the live set."""
    orig = x.shape
    g = x.reshape(groups, -1)
    thres = 0.7 * jnp.mean(jnp.abs(g), axis=-1, keepdims=True)
    mask = (jnp.abs(g) > thres).astype(g.dtype)
    alpha = jnp.sum(jnp.abs(g) * mask, axis=-1, keepdims=True) / jnp.maximum(mask.sum(-1, keepdims=True), 1)
    return (alpha * jnp.sign(g) * mask).reshape(orig)


def binary_quantize(x, groups: int = 1):
    """BinaryQuantizer: ±mean|x|."""
    orig = x.shape
    g = x.reshape(groups, -1)
    alpha = jnp.mean(jnp.abs(g), axis=-1, keepdims=True)
    return (alpha * jnp.sign(g)).reshape(orig)


def ste(fake_quant_fn, x, *args, **kwargs):
    """Straight-through estimator: forward quantized, backward identity
    (reference autograd.Function backward pass-through)."""
    return x + jax.lax.stop_gradient(fake_quant_fn(x, *args, **kwargs) - x)


QUANTIZERS = {"symmetric": sym_quantize, "asymmetric": asym_quantize}


def quantize_weight(x, bits: int = 8, groups: int = 1, quantization_type: str = "symmetric"):
    if bits == 1:
        return binary_quantize(x, groups)
    if bits == 2:
        return ternary_quantize(x, groups)
    return QUANTIZERS[quantization_type](x, bits, groups)


# ---------------------------------------------------------------------------
# pruning masks (reference LinearLayer_Compress sparse/row/head/channel)
# ---------------------------------------------------------------------------
def sparse_pruning_mask(w, dense_ratio: float, method: str = "l1"):
    """Unstructured mask keeping the top ``dense_ratio`` fraction by |w|
    (method 'l1') or a random subset ('topk' uses |w| too; 'random' random)."""
    k = max(1, int(round(w.size * dense_ratio)))
    flat = jnp.abs(w).reshape(-1)
    if method == "random":
        scores = jax.random.uniform(jax.random.PRNGKey(0), flat.shape)
    else:
        scores = flat
    thresh = jnp.sort(scores)[-k]
    return (scores >= thresh).reshape(w.shape).astype(w.dtype)


def row_pruning_mask(w, dense_ratio: float):
    """Structured mask over output rows by row L1 norm (reference row
    pruning; rows = axis 0)."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))
    k = max(1, int(round(w.shape[0] * dense_ratio)))
    thresh = jnp.sort(norms)[-k]
    mask = (norms >= thresh).astype(w.dtype)
    return mask.reshape((-1, ) + (1, ) * (w.ndim - 1))


def channel_pruning_mask(w, dense_ratio: float):
    """Structured mask over input channels (axis -1)."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    k = max(1, int(round(w.shape[-1] * dense_ratio)))
    thresh = jnp.sort(norms)[-k]
    mask = (norms >= thresh).astype(w.dtype)
    return mask.reshape((1, ) * (w.ndim - 1) + (-1, ))


def head_pruning_mask(w, dense_ratio: float, num_heads: int):
    """Mask over attention heads: w is [hidden, num_heads*head_dim] (an
    output projection's input, reference head pruning on attn outputs)."""
    h = w.reshape(w.shape[0], num_heads, -1)
    norms = jnp.sum(jnp.abs(h), axis=(0, 2))
    k = max(1, int(round(num_heads * dense_ratio)))
    thresh = jnp.sort(norms)[-k]
    mask = (norms >= thresh).astype(w.dtype)
    return jnp.repeat(mask, w.shape[1] // num_heads).reshape(1, -1)
