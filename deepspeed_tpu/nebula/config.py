"""Nebula (async checkpoint service) config.

Reference ``deepspeed/nebula/config.py`` — the block that turns on
Microsoft's asynchronous tiered checkpoint service. The TPU-native
mechanism behind the same contract (training never blocks on persistence;
only fully persisted versions are ever advertised) is the resilience plane
(``runtime/resilience/``) over orbax's AsyncCheckpointer. Enabling nebula
flips the engine into async-save mode AND arms the service knobs:
``num_of_version_in_retention`` drives retention GC,
``persistent_time_interval`` the wall-clock auto-save cadence, and
``persistent_storage_path`` the auto/preemption save target (SIGTERM →
final checkpoint → clean exit). See README "Resilience & checkpointing".
"""

from dataclasses import dataclass
from typing import Optional

from .constants import (NEBULA, NEBULA_ENABLE_NEBULA_LOAD, NEBULA_ENABLE_NEBULA_LOAD_DEFAULT,
                        NEBULA_ENABLED, NEBULA_ENABLED_DEFAULT, NEBULA_LOAD_PATH,
                        NEBULA_LOAD_PATH_DEFAULT, NEBULA_NUM_OF_VERSION_IN_RETENTION,
                        NEBULA_NUM_OF_VERSION_IN_RETENTION_DEFAULT,
                        NEBULA_PERSISTENT_STORAGE_PATH, NEBULA_PERSISTENT_STORAGE_PATH_DEFAULT,
                        NEBULA_PERSISTENT_TIME_INTERVAL, NEBULA_PERSISTENT_TIME_INTERVAL_DEFAULT)


@dataclass
class DeepSpeedNebulaConfig:
    enabled: bool = NEBULA_ENABLED_DEFAULT
    load_path: Optional[str] = NEBULA_LOAD_PATH_DEFAULT
    enable_nebula_load: bool = NEBULA_ENABLE_NEBULA_LOAD_DEFAULT
    persistent_storage_path: Optional[str] = NEBULA_PERSISTENT_STORAGE_PATH_DEFAULT
    persistent_time_interval: int = NEBULA_PERSISTENT_TIME_INTERVAL_DEFAULT
    num_of_version_in_retention: int = NEBULA_NUM_OF_VERSION_IN_RETENTION_DEFAULT

    @classmethod
    def from_param_dict(cls, param_dict: dict) -> "DeepSpeedNebulaConfig":
        d = dict(param_dict.get(NEBULA, {}) or {})
        return cls(
            enabled=bool(d.get(NEBULA_ENABLED, NEBULA_ENABLED_DEFAULT)),
            load_path=d.get(NEBULA_LOAD_PATH, NEBULA_LOAD_PATH_DEFAULT),
            enable_nebula_load=bool(d.get(NEBULA_ENABLE_NEBULA_LOAD,
                                          NEBULA_ENABLE_NEBULA_LOAD_DEFAULT)),
            persistent_storage_path=d.get(NEBULA_PERSISTENT_STORAGE_PATH,
                                          NEBULA_PERSISTENT_STORAGE_PATH_DEFAULT),
            persistent_time_interval=int(d.get(NEBULA_PERSISTENT_TIME_INTERVAL,
                                               NEBULA_PERSISTENT_TIME_INTERVAL_DEFAULT)),
            num_of_version_in_retention=int(d.get(NEBULA_NUM_OF_VERSION_IN_RETENTION,
                                                  NEBULA_NUM_OF_VERSION_IN_RETENTION_DEFAULT)))
