"""Nebula async-checkpoint-service namespace (reference ``deepspeed/nebula``)."""

from .config import DeepSpeedNebulaConfig  # noqa: F401
