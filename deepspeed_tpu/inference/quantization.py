"""Weight-only int8 quantization for inference.

Analog of the reference inference quantization surface
(``DeepSpeedInferenceConfig.quant``, ``deepspeed/inference/config.py`` /
``module_inject.replace_module`` ``quantize=True`` path — int8 weights with
per-channel scales). TPU design: decode is HBM-bandwidth-bound on the weight
stream, so weights are STORED int8 (+fp32 per-output-channel scales) and
dequantized at the matmul operand — XLA fuses the convert+scale into the dot
read, so only int8 bytes leave HBM. Measured on v5e at decode batch sizes the
dense stack runs ~2.1x faster than bf16 storage.

``QuantizedWeight`` is a registered pytree node whose ``.astype(dt)``
returns the dequantized matrix — every weight read in the model code is
``w.astype(dt)``, so quantized params drop into the existing forward paths
(v1 engine, v2 ragged serving, scan or unrolled) without touching them.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp


class QuantizedWeight:
    """int8 weight + fp32 per-output-channel scale; dequantizes on
    ``.astype`` (the model code's universal weight accessor)."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def astype(self, dt):
        return self.q.astype(dt) * self.scale.astype(dt)

    def __getitem__(self, idx):
        return QuantizedWeight(self.q[idx], self.scale[idx])

    def __repr__(self):
        return f"QuantizedWeight(q={self.q.shape}, scale={self.scale.shape})"


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda w: ((w.q, w.scale), None),
    lambda _, children: QuantizedWeight(*children),
)


def quantize_weight_int8(w) -> QuantizedWeight:
    """Symmetric per-output-channel int8: scale over the contraction
    (second-to-last) axis so each output channel keeps its dynamic range.
    Delegates the numeric core to ``ops.pallas.quant.quantize_blockwise``
    (one block spanning the whole contraction axis), so there is a single
    absmax/127 implementation to maintain."""
    from ..ops.pallas.quant import quantize_blockwise

    wf = jnp.asarray(w)
    q, s = quantize_blockwise(wf, block_size=wf.shape[-2], axis=-2)
    return QuantizedWeight(q, s)


def quantize_params_for_inference(params: Dict[str, Any], num_bits: int = 8) -> Dict[str, Any]:
    """Quantize the bandwidth-dominant weights of a transformer param tree:
    every >=2-D block weight (``w*``) and the untied ``lm_head`` kernel.
    Embeddings, biases and norm scales stay in their original dtype (the
    embedding gather is cheap and tied unembedding wants full precision).
    """
    if num_bits != 8:
        raise NotImplementedError(f"weight-only quantization supports num_bits=8, got {num_bits}")
    out = dict(params)
    if "blocks" in params:
        blocks = dict(params["blocks"])
        for name, w in blocks.items():
            # dense (w*) AND expert (moe_w*) weights — the expert matmuls are
            # the dominant decode weight stream in a MoE model; the tiny,
            # routing-sensitive gate projection stays full precision.
            # Idempotent: already-quantized leaves pass through (the engine
            # and replace_transformer_layer may both apply the same config)
            if (name.startswith("w") or name.startswith("moe_w")) \
                    and not isinstance(w, QuantizedWeight) and getattr(w, "ndim", 0) >= 2:
                blocks[name] = quantize_weight_int8(w)
        out["blocks"] = blocks
    if "lm_head" in params and "kernel" in params["lm_head"]:
        head = dict(params["lm_head"])
        if not isinstance(head["kernel"], QuantizedWeight):
            head["kernel"] = quantize_weight_int8(head["kernel"])
        out["lm_head"] = head
    return out
