"""Weight-only int8 quantization for inference.

Analog of the reference inference quantization surface
(``DeepSpeedInferenceConfig.quant``, ``deepspeed/inference/config.py`` /
``module_inject.replace_module`` ``quantize=True`` path — int8 weights with
per-channel scales). TPU design: decode is HBM-bandwidth-bound on the weight
stream, so weights are STORED int8 (+fp32 per-output-channel scales) and
dequantized at the matmul operand — XLA fuses the convert+scale into the dot
read, so only int8 bytes leave HBM. Measured on v5e at decode batch sizes the
dense stack runs ~2.1x faster than bf16 storage.

``QuantizedWeight`` is a registered pytree node whose ``.astype(dt)``
returns the dequantized matrix — every weight read in the model code is
``w.astype(dt)``, so quantized params drop into the existing forward paths
(v1 engine, v2 ragged serving, scan or unrolled) without touching them.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp


class QuantizedWeight:
    """int8 weight + fp32 per-output-channel scale; dequantizes on
    ``.astype`` (the model code's universal weight accessor)."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def astype(self, dt):
        return self.q.astype(dt) * self.scale.astype(dt)

    def __getitem__(self, idx):
        return QuantizedWeight(self.q[idx], self.scale[idx])

    def __repr__(self):
        return f"QuantizedWeight(q={self.q.shape}, scale={self.scale.shape})"


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda w: ((w.q, w.scale), None),
    lambda _, children: QuantizedWeight(*children),
)


def quantize_weight_int8(w) -> QuantizedWeight:
    """Symmetric per-output-channel int8: scale over the contraction
    (second-to-last) axis so each output channel keeps its dynamic range.
    Delegates the numeric core to ``ops.pallas.quant.quantize_blockwise``
    (one block spanning the whole contraction axis), so there is a single
    absmax/127 implementation to maintain."""
    from ..ops.pallas.quant import quantize_blockwise

    wf = jnp.asarray(w)
    q, s = quantize_blockwise(wf, block_size=wf.shape[-2], axis=-2)
    return QuantizedWeight(q, s)


class QuantizedWeight4:
    """Packed int4 weight (two nibbles per uint8 along the contraction axis)
    + asymmetric per-output-channel scale/min — the reference's INT4 path
    (``deepspeed/inference/quantization/utils.py:66`` uint8→uint4 packing,
    asymmetric groups). HBM streams 4 bits/weight; the unpack (shift/mask)
    and dequant (q/scale + min) fuse into the matmul operand read under XLA.
    """

    __slots__ = ("q", "scale", "zero")

    def __init__(self, q, scale, zero):
        self.q = q          # uint8 [..., K/2, N] — hi nibble row 2i, lo 2i+1
        self.scale = scale  # fp32 [..., 1, N]: (max - min) / 15 — MULTIPLY to
        #                     dequantize, same semantics as QuantizedWeight
        self.zero = zero    # fp32 [..., 1, N]: min value

    @property
    def shape(self):
        s = list(self.q.shape)
        s[-2] *= 2
        return tuple(s)

    @property
    def ndim(self):
        return self.q.ndim

    def astype(self, dt):
        hi = (self.q >> 4).astype(jnp.uint8)
        lo = (self.q & 0xF).astype(jnp.uint8)
        packed = jnp.stack((hi, lo), axis=-2)          # [..., K/2, 2, N]
        k2, n = self.q.shape[-2], self.q.shape[-1]
        unpacked = packed.reshape(*self.q.shape[:-2], 2 * k2, n)
        return (unpacked.astype(jnp.float32) * self.scale + self.zero).astype(dt)

    def __getitem__(self, idx):
        # leading-dim slicing (the scan's per-layer view of stacked blocks)
        return QuantizedWeight4(self.q[idx], self.scale[idx], self.zero[idx])

    def __repr__(self):
        return f"QuantizedWeight4(q={self.q.shape}, scale={self.scale.shape})"


jax.tree_util.register_pytree_node(
    QuantizedWeight4,
    lambda w: ((w.q, w.scale, w.zero), None),
    lambda _, children: QuantizedWeight4(*children),
)


def quantize_weight_int4(w) -> QuantizedWeight4:
    """Asymmetric per-output-channel int4 over the contraction (-2) axis,
    packed two nibbles per byte (reference ``Quantizer._quantize_int8`` with
    q_range=15 + ``_compress_uint8_to_uint4``)."""
    wf = jnp.asarray(w, jnp.float32)
    assert wf.shape[-2] % 2 == 0, f"int4 packing needs an even contraction dim, got {wf.shape}"
    mn = wf.min(axis=-2, keepdims=True)
    mx = wf.max(axis=-2, keepdims=True)
    step = jnp.maximum(mx - mn, 1e-8) / 15.0
    q = jnp.clip(jnp.round((wf - mn) / step), 0, 15).astype(jnp.uint8)
    packed = ((q[..., 0::2, :] << 4) | q[..., 1::2, :]).astype(jnp.uint8)
    # store the MULTIPLICATIVE step so the hot-path dequant is q*scale+zero
    # (a fused multiply-add at the matmul operand read, not a division)
    return QuantizedWeight4(packed, step, mn)


def quantize_params_for_inference(params: Dict[str, Any], num_bits: int = 8) -> Dict[str, Any]:
    """Quantize the bandwidth-dominant weights of a transformer param tree:
    every >=2-D block weight (``w*``) and the untied ``lm_head`` kernel.
    Embeddings, biases and norm scales stay in their original dtype (the
    embedding gather is cheap and tied unembedding wants full precision).
    ``num_bits``: 8 (symmetric per-channel) or 4 (asymmetric packed,
    reference INT4 parity).
    """
    if num_bits not in (4, 8):
        raise NotImplementedError(f"weight-only quantization supports num_bits in (4, 8), got {num_bits}")
    quantize_fn = quantize_weight_int8 if num_bits == 8 else quantize_weight_int4
    _quantized = (QuantizedWeight, QuantizedWeight4)
    out = dict(params)
    if "blocks" in params:
        blocks = dict(params["blocks"])
        for name, w in blocks.items():
            # dense (w*) AND expert (moe_w*) weights — the expert matmuls are
            # the dominant decode weight stream in a MoE model; the tiny,
            # routing-sensitive gate projection stays full precision.
            # Idempotent: already-quantized leaves pass through (the engine
            # and replace_transformer_layer may both apply the same config)
            if (name.startswith("w") or name.startswith("moe_w")) \
                    and not isinstance(w, _quantized) and getattr(w, "ndim", 0) >= 2:
                blocks[name] = quantize_fn(w)
        out["blocks"] = blocks
    if "lm_head" in params and "kernel" in params["lm_head"]:
        head = dict(params["lm_head"])
        if not isinstance(head["kernel"], _quantized):
            head["kernel"] = quantize_fn(head["kernel"])
        out["lm_head"] = head
    return out
