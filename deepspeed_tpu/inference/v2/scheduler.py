"""Dynamic SplitFuse serving scheduler.

The policy layer the reference keeps in MII above ``InferenceEngineV2``
(engine mechanism: ``put``/``decode``/``can_schedule``/``flush``; policy:
the DeepSpeed-FastGen Dynamic SplitFuse composition,
``blogs/deepspeed-fastgen/README.md`` "Dynamic SplitFuse" — every forward
carries a bounded token budget filled with all runnable DECODE steps first,
then chunks of pending prefills, so long prompts never stall decode latency
and the batch shape stays in a narrow, compiled-bucket-friendly band).

Design points beyond the happy path:
- admission RESERVES capacity for a request's whole lifetime (full prompt +
  max_new_tokens worth of KV blocks), so a request that is admitted can
  always run to completion — no mid-run KV exhaustion can strand the batch;
- when the queue drains to pure decode, the loop switches to the engine's
  multi-step on-device ``decode`` (one host round-trip per horizon instead
  of per token — the steady-state fast path);
- nothing is dropped silently: un-runnable work raises with the stalled
  uids named, and partial generations stay readable via ``results``.
"""

import copy
import dataclasses
import time
from typing import Dict, List, Optional

import numpy as np

from .scheduling_utils import SchedulingResult


class _Request:
    __slots__ = ("uid", "prompt", "max_new_tokens", "eos_token_id", "fed", "generated", "done",
                 "charged_blocks", "shared_blocks", "sampling", "tenant")

    def __init__(self, uid, prompt, max_new_tokens, eos_token_id, sampling=None,
                 tenant=None):
        self.uid = uid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.sampling = sampling  # SamplingParams | None (= greedy)
        self.tenant = tenant      # owner identity (serving metering); None = untenanted
        self.fed = 0          # prompt tokens already given to the engine
        self.generated: List[int] = []
        self.done = False
        self.charged_blocks = 0  # lifetime KV reservation charged at admission
        self.shared_blocks = 0   # blocks arriving shared from the prefix cache

    @property
    def sampled(self) -> bool:
        return self.sampling is not None and not self.sampling.greedy

    @property
    def prefilling(self) -> bool:
        return self.fed < self.prompt.size

    @property
    def total_tokens(self) -> int:
        return self.prompt.size + self.max_new_tokens


class DynamicSplitFuseScheduler:
    """Continuous-batching loop over :class:`InferenceEngineV2`.

    ``token_budget`` bounds the tokens per forward (clamped to the engine's
    ``max_ragged_batch_size``; must be positive). ``submit`` enqueues
    requests; ``step`` runs one composed forward; ``run`` drives to
    completion and returns ``{uid: generated token list}``.
    """

    DECODE_HORIZON = 32  # max on-device steps per multi-step decode call

    def __init__(self, engine, token_budget: Optional[int] = None, speculative=None,
                 drafter=None):
        self.engine = engine
        sm = engine.config.state_manager
        if token_budget is None:
            token_budget = sm.max_ragged_batch_size
        if token_budget <= 0:
            raise ValueError(f"token_budget must be positive, got {token_budget}")
        self.token_budget = min(int(token_budget), sm.max_ragged_batch_size)
        self.max_seqs = sm.max_ragged_sequence_count
        self._pending: List[_Request] = []   # not yet tracked by the engine
        self._active: Dict[int, _Request] = {}
        self._results: Dict[int, List[int]] = {}
        self._reserved_blocks = 0  # KV blocks promised to active requests
        # serving-plane accounting the prefix-cache A/B reads: prompt tokens
        # actually computed vs skipped via radix hits (exact — counted at the
        # feed site, not inferred from latency)
        self.stats = {"prefill_tokens_fed": 0, "prefill_tokens_skipped": 0}
        # speculative decoding: ``speculative`` overrides the engine's
        # ``ragged.speculative`` block; ``drafter`` overrides the drafter
        # built from it (tests/benches inject oracle/junk drafters). With
        # the block absent/off, NO drafter object exists and every step
        # path below is byte-identical to the pre-speculation scheduler
        # (test-enforced zero overhead).
        self._spec = speculative if speculative is not None \
            else getattr(engine.config, "speculative", None)
        if self._spec is not None and not getattr(self._spec, "enabled", False):
            self._spec = None
        self._drafter = drafter
        if self._drafter is None and self._spec is not None:
            from .speculative import build_drafter
            self._drafter = build_drafter(self._spec)
        if self._drafter is not None and self._spec is None:
            from .config_v2 import SpeculativeConfig
            self._spec = SpeculativeConfig(mode="ngram")  # injected drafter, default k
        self.spec_stats = {"rounds": 0, "drafted": 0, "accepted": 0, "rejected": 0,
                           "backoffs": 0}
        self._spec_by_uid: Dict[int, Dict[str, int]] = {}
        # spec-burst backoff: consecutive zero-accept verify rounds per uid
        # (a hopeless drafter must stop burning k+1 verify tokens per round
        # forever); past `backoff_after` the uid stops drafting and rides
        # the plain decode burst, re-probed every `reprobe_every` rounds
        self._spec_zero: Dict[int, int] = {}
        # incremental prompt+generated context per speculating uid: generated
        # only ever APPENDS for a live request, so each round copies just the
        # delta instead of re-concatenating the whole stream (O(new tokens),
        # not O(context), in the hottest serving loop)
        self._spec_ctx: Dict[int, np.ndarray] = {}
        # optional per-step observer, `fn(uids, chunk_sizes, t0, dur, kind)`
        # after EVERY engine forward this scheduler composes — `kind` is
        # "put" (mixed decode+prefill chunks), "decode" (the multi-step
        # burst, chunk_sizes = the horizon per row) or "spec_verify" (the
        # speculative verify forward, chunk_sizes = the verify-chunk rows).
        # The serving replica attaches one to attribute forward wall time
        # to the requests whose chunks composed it (per-chunk prefill spans
        # + per-tenant compute-second apportionment). None (the default)
        # adds zero work on every path.
        self.step_observer = None

    def submit(self, uid: int, prompt, max_new_tokens: int = 32, eos_token_id=None,
               sampling=None, tenant=None):
        if uid in self._active or any(r.uid == uid for r in self._pending):
            raise ValueError(f"uid {uid} already queued")
        if sampling is not None:
            sampling.validate()  # raises ValueError on out-of-range knobs
        req = _Request(uid, prompt, max_new_tokens, eos_token_id, sampling=sampling,
                       tenant=tenant)
        if req.prompt.size == 0:
            raise ValueError(f"uid {uid}: empty prompt")
        if req.max_new_tokens <= 0:
            raise ValueError(f"uid {uid}: max_new_tokens must be positive, "
                             f"got {req.max_new_tokens}")
        if req.total_tokens > self.engine._max_context:
            raise ValueError(f"uid {uid}: prompt {req.prompt.size} + max_new_tokens "
                             f"{req.max_new_tokens} exceeds the engine max_context "
                             f"{self.engine._max_context}")
        self._pending.append(req)

    @property
    def has_work(self) -> bool:
        return bool(self._pending or self._active)

    @property
    def finished(self):
        """Uids whose generation is complete (eos or max_new_tokens) — load
        harnesses poll this after each ``step`` to stamp completion times."""
        return frozenset(self._results)

    @property
    def results(self) -> Dict[int, List[int]]:
        """Generations so far — finished requests complete, active partial."""
        out = dict(self._results)
        for uid, req in self._active.items():
            out[uid] = list(req.generated)
        return out

    def discard_result(self, uid: int) -> None:
        """Drop a FINISHED request's stored generation (and its ``finished``
        membership). The serving gateway streams tokens out incrementally
        and reads ``results`` every step — without discarding, a long-lived
        scheduler's result dict (and each per-step copy) grows with every
        request ever served. No-op for unknown/active uids."""
        self._results.pop(uid, None)
        self._spec_by_uid.pop(uid, None)

    @property
    def speculating(self) -> bool:
        """True when a drafter is wired in (``ragged.speculative`` present)."""
        return self._drafter is not None

    def spec_summary(self, uid: int) -> Optional[Dict[str, int]]:
        """Per-request speculation accounting (``{"drafted", "accepted"}``)
        for an active/finished uid, until ``discard_result``; None when the
        request never speculated. The gateway's request summary record
        carries the derived acceptance rate."""
        return self._spec_by_uid.get(uid)

    def spec_params(self) -> Optional[Dict[str, int]]:
        """The live speculative knobs (``{"k", "tree_width"}``), None when
        this scheduler is not speculating. The serving control plane reads
        this before proposing a K adaptation."""
        if self._spec is None:
            return None
        return {"k": int(self._spec.k),
                "tree_width": int(getattr(self._spec, "tree_width", 1))}

    def set_spec_params(self, k: Optional[int] = None,
                        tree_width: Optional[int] = None) -> Optional[Dict[str, int]]:
        """Retarget speculative K / tree width for FUTURE draft rounds.
        ``_spec`` may alias ``engine.config.speculative`` (shared with other
        schedulers built from the same config), so the update REPLACES the
        config object rather than mutating it in place. ``_spec_burst``
        re-reads ``self._spec`` every round, so the new knobs apply from the
        next round with no re-plumbing. No-op (returns None) when not
        speculating; returns the applied params otherwise."""
        if self._spec is None:
            return None
        kwargs = {}
        if k is not None:
            kwargs["k"] = max(1, int(k))
        if tree_width is not None:
            kwargs["tree_width"] = max(1, int(tree_width))
        if kwargs:
            try:
                self._spec = dataclasses.replace(self._spec, **kwargs)
            except TypeError:  # injected non-dataclass spec stub (tests)
                sp = copy.copy(self._spec)
                for name, v in kwargs.items():
                    setattr(sp, name, v)
                self._spec = sp
        return self.spec_params()

    def new_tokens(self, uid: int, start: int) -> List[int]:
        """Tokens generated past position ``start`` for a pending/active/
        finished uid — the gateway's per-step fan-out read. Copies only the
        TAIL, where ``results`` would copy every active generation whole
        each step (O(total tokens) per step, quadratic over a request's
        life). Unknown uids yield []."""
        req = self._active.get(uid)
        gen = req.generated if req is not None else self._results.get(uid)
        return [] if gen is None else list(gen[start:])

    def cancel(self, uid: int) -> bool:
        """Abort a request NOW: a pending one is dropped, an active one is
        finished in place (engine sequence flushed, lifetime KV reservation
        released, tokens-so-far kept in ``results``). The serving gateway
        calls this when a client times out or disconnects — without it an
        abandoned request would keep decoding to ``max_new_tokens``,
        holding its KV blocks and an admission slot against live traffic.
        MUST be called from the thread that drives ``step`` (it mutates
        scheduler/engine state). Returns False for unknown uids."""
        for i, req in enumerate(self._pending):
            if req.uid == uid:
                self._pending.pop(i)
                self._results[uid] = req.generated  # partial = empty, kept
                return True
        req = self._active.get(uid)
        if req is None:
            return False
        self._finish(req)
        return True

    def _blocks_for(self, n_tokens: int) -> int:
        bs = self.engine.config.kv_block_size
        return -(-n_tokens // bs)

    def _finish(self, req: _Request):
        req.done = True
        seq = self.engine.state_manager.get_sequence(req.uid)
        if seq is not None:
            # decode/speculate horizons reserve and materialize KV past the
            # last token an early-finished (eos) or cancelled request keeps.
            # Rewind the overshoot through the single rollback helper BEFORE
            # flush: flush publishes completed full blocks into the radix
            # tree, and without the rewind the tree would take references on
            # blocks keyed by post-eos garbage tokens — blocks that then
            # never return to the free list until LRU pressure evicts them.
            known = req.fed + max(0, len(req.generated) - 1)
            if seq.seen_tokens > known:
                # final=True: the flush below is this sequence's last act, so
                # the COW guard (which could need a block from a dry pool)
                # is skipped — a terminal rewind must never be able to fail
                self.engine.state_manager.rollback_to(seq, known, final=True)
        if self._drafter is not None:
            self._drafter.finish(req.uid)
            self._spec_ctx.pop(req.uid, None)
            self._spec_zero.pop(req.uid, None)
        self.engine.flush(req.uid)
        self._reserved_blocks -= req.charged_blocks
        self._active.pop(req.uid, None)
        self._results[req.uid] = req.generated

    def _try_admit(self, req: _Request, batch_uids: List[int], batch_lengths: List[int],
                   budget: int) -> bool:
        """Admission reserves the request's WHOLE lifetime: full-prompt KV
        blocks + generation headroom, so an admitted request can always run
        to completion regardless of later arrivals. Validation is CUMULATIVE
        — the engine sees the whole batch composed so far plus this request,
        so a combination that passes here can never be rejected by the
        final ``put(do_checks=True)`` after state was already mutated.

        Prefix-cache admission order: PROBE first (a pure lookup — a refused
        request must leave the tree, its LRU clock, and the hit stats
        untouched, and must not burn a COW copy), budget-check against only
        the UNCACHED remainder — cached prompt tokens hit neither the token
        budget (the first chunk starts after the hit) nor the block budget
        (shared blocks are already resident) — then ACQUIRE once admission
        is certain. Nothing mutates between probe and acquire (single
        thread), so the acquisition realizes exactly the probed hit."""
        if len(batch_uids) >= self.max_seqs:
            return False
        sm = self.engine.config.state_manager
        if self.engine.state_manager.n_tracked_sequences >= sm.max_tracked_sequences:
            return False  # acquisition would raise, not refuse
        n_cached, shared, tree_only, match = self.engine.probe_prefix(req.prompt)
        need = self._blocks_for(req.total_tokens) - shared
        first = min(budget, req.prompt.size - n_cached)
        if first <= 0:
            return False
        # supply side: the hit's tree-only shared blocks stop being evictable
        # the moment acquisition pins them — counting them as reclaimable
        # WHILE ALSO subtracting them from demand (`need`) would credit the
        # same blocks twice and over-admit by up to `shared`
        supply = self.engine.available_blocks - tree_only + self._owned_blocks()
        if self._reserved_blocks + need > supply:
            return False
        if self.engine.can_schedule(batch_uids + [req.uid],
                                    batch_lengths + [first]) is not SchedulingResult.Success:
            return False
        n_cached, shared = self.engine.acquire_prefix(req.uid, req.prompt, match=match,
                                                      tenant=req.tenant)
        req.fed = n_cached
        req.charged_blocks = self._blocks_for(req.total_tokens) - shared
        req.shared_blocks = shared
        self._reserved_blocks += req.charged_blocks
        self.stats["prefill_tokens_skipped"] += n_cached
        self._active[req.uid] = req
        return True

    def _owned_blocks(self) -> int:
        """Blocks active sequences allocated THEMSELVES (shared radix-tree
        blocks excluded: they were never charged against the reservation)."""
        sm = self.engine.state_manager
        return sum(max(0, s.cur_allocated_blocks - s.shared_blocks)
                   for s in (sm.get_sequence(u) for u in self._active) if s is not None)

    def _append_token(self, req: _Request, tok: int) -> None:
        req.generated.append(tok)
        hit_eos = req.eos_token_id is not None and tok == req.eos_token_id
        if len(req.generated) >= req.max_new_tokens or hit_eos:
            self._finish(req)

    def _decode_burst(self, decoding: List[_Request]) -> int:
        """Pure-decode steady state: the engine's multi-step on-device scan
        (one host round-trip per horizon instead of per token). The horizon
        quantizes DOWN to a power of two: the engine compiles one program
        per exact n_steps, so free-running horizons would pay a fresh XLA
        compile for every distinct remaining-token count."""
        horizon = min(min(r.max_new_tokens - len(r.generated) for r in decoding),
                      self.DECODE_HORIZON)
        horizon = 1 << (horizon.bit_length() - 1)  # 1,2,4,...,32: <=6 programs per bucket
        uids = [r.uid for r in decoding]
        first = [np.asarray([r.generated[-1]], np.int32) for r in decoding]
        # per-request eos rides down so the engine rewinds a mid-scan eos hit's
        # horizon overshoot before publishing (post-eos KV never enters the tree)
        eos = [r.eos_token_id for r in decoding]
        # sampling rides down only when some row actually samples — an
        # all-greedy burst keeps the original argmax scan program
        samp = [r.sampling for r in decoding] if any(r.sampled for r in decoding) else None
        if self.step_observer is None:
            toks = np.asarray(self.engine.decode(uids, first, horizon, eos_token_ids=eos,
                                                 sampling=samp))  # [S, horizon]
        else:
            t0 = time.perf_counter()
            toks = np.asarray(self.engine.decode(uids, first, horizon, eos_token_ids=eos,
                                                 sampling=samp))  # [S, horizon]
            self.step_observer(uids, [horizon] * len(uids), t0,
                               time.perf_counter() - t0, "decode")
        for req, row in zip(decoding, toks):
            for tok in row.tolist():
                self._append_token(req, int(tok))
                if req.done:
                    break  # eos/max_new inside the burst: drop the tail
        return len(decoding) * horizon

    def _spec_context(self, req: _Request) -> np.ndarray:
        """The request's committed stream (prompt + generated) as one int32
        array, sized once for the request's whole lifetime and extended by
        only the NEW generated tokens each round (generated never shrinks
        for a live request). Returns a view of the filled region."""
        n = req.prompt.size + len(req.generated)
        entry = self._spec_ctx.get(req.uid)
        if entry is None:
            buf = np.empty(req.prompt.size + req.max_new_tokens, np.int32)
            buf[:req.prompt.size] = req.prompt
            filled = req.prompt.size
        else:
            buf, filled = entry
        if filled < n:
            buf[filled:n] = req.generated[filled - req.prompt.size:]
            filled = n
        self._spec_ctx[req.uid] = (buf, filled)
        return buf[:n]

    def _spec_not_drafting(self, uid: int) -> bool:
        """Backoff decision for one uid, advancing its counter while parked:
        past ``backoff_after`` consecutive zero-accept rounds a request
        stops drafting; every ``reprobe_every`` parked rounds one probe
        round drafts again (a stream that turned repetitive gets its
        speculation back), and any accepted token resets the counter."""
        n = getattr(self._spec, "backoff_after", 0)
        z = self._spec_zero.get(uid, 0)
        if not n or z < n:
            return False
        m = max(1, getattr(self._spec, "reprobe_every", 32))
        if (z - n) % m == m - 1:
            return False  # probe round
        self._spec_zero[uid] = z + 1  # parked round consumed
        return True

    def _spec_burst(self, decoding: List[_Request]) -> int:
        """Speculative steady state: draft up to K tokens (or a
        ``tree_width``-branch token tree) per sequence, then ONE batched
        verify forward commits the deepest target-agreeing path per
        sequence (plus a bonus token) and rolls rejected KV back. Returns
        committed tokens, or 0 when this round cannot speculate — the
        caller then falls back to the plain multi-step decode burst
        (drafters came up empty / everyone is backed off, a sequence is too
        close to max_context, or the transient KV demand exceeds what the
        pool can cover). Sampled requests force linear drafts (tree
        verification is greedy-only; the rejection-sampling verify keeps
        the output distribution exact). Backed-off requests skip drafting
        and decode alongside in a separate burst."""
        k = self._spec.k
        eng = self.engine
        width = max(1, int(getattr(self._spec, "tree_width", 1)))
        if any(r.sampled for r in decoding):
            width = 1
        drafting = [r for r in decoding if not self._spec_not_drafting(r.uid)]
        if not drafting:
            return 0
        # cheap pre-draft feasibility: if not even a LINEAR round fits the
        # token budget, don't pay the O(context) drafter scans every loop
        # (the PR 9 guard; the width-dependent check below re-validates with
        # this round's actual branch shapes)
        if len(drafting) * (k + 1) > min(self.token_budget,
                                         eng.config.state_manager.max_ragged_batch_size):
            return 0
        items = [(r.uid, self._spec_context(r)) for r in drafting]
        dmap = self._drafter.draft_branches_many(items, k, width)
        branches: Dict[int, List[np.ndarray]] = {}
        for r in drafting:
            bl = [np.asarray(b, np.int32).reshape(-1)[:k] for b in dmap.get(r.uid, ())]
            branches[r.uid] = [b for b in bl if b.size][:width]
        spec_reqs = [r for r in drafting if branches[r.uid]]
        if not spec_reqs:
            return 0
        # verify-chunk shape: root + W branches of the CONFIGURED k (the
        # engine pads short drafts) — keying the compiled program on this
        # round's actual max draft length would recompile the verify
        # forward every time the drafter's match length fluctuated; wmax
        # still varies, but it is bounded by tree_width (<= width programs
        # per bucket, vs k*width)
        wmax = max(len(branches[r.uid]) for r in spec_reqs)
        n_new = 1 + wmax * k
        if len(spec_reqs) * n_new > min(self.token_budget,
                                        eng.config.state_manager.max_ragged_batch_size):
            return 0
        seqs = []
        for r in spec_reqs:
            seq = eng.state_manager.get_sequence(r.uid)
            if seq is None or seq.seen_tokens + n_new > eng.max_context:
                return 0
            seqs.append(seq)
        # the verify chunk may transiently need blocks beyond the request's
        # lifetime reservation (near its final tokens): refuse up front
        # rather than strand the composed batch mid-run
        if sum(s.blocks_needed(n_new) for s in seqs) > eng.available_blocks:
            return 0
        uids = [r.uid for r in spec_reqs]
        firsts = [np.asarray([r.generated[-1]], np.int32) for r in spec_reqs]
        samp = [r.sampling for r in spec_reqs] if any(r.sampled for r in spec_reqs) else None
        # per-request eos rides down (decode()'s contract): an eos inside
        # the accepted run truncates the commit there, so the tree never
        # receives post-eos paths even when acceptance carries past it
        spec_drafts = [branches[r.uid] if len(branches[r.uid]) > 1 else branches[r.uid][0]
                       for r in spec_reqs]
        spec_eos = [r.eos_token_id for r in spec_reqs]
        if self.step_observer is None:
            outs = eng.speculate_decode(uids, firsts, spec_drafts, k,
                                        eos_token_ids=spec_eos, sampling=samp)
        else:
            t0 = time.perf_counter()
            outs = eng.speculate_decode(uids, firsts, spec_drafts, k,
                                        eos_token_ids=spec_eos, sampling=samp)
            self.step_observer(uids, [n_new] * len(uids), t0,
                               time.perf_counter() - t0, "spec_verify")
        self.spec_stats["rounds"] += 1
        backoff_n = getattr(self._spec, "backoff_after", 0)
        committed = 0
        for req, new in zip(spec_reqs, outs):
            bl = branches[req.uid]
            drafted_n = sum(int(b.size) for b in bl)
            a = len(new) - 1  # accepted positions (pads included)
            acc = min(a, max(int(b.size) for b in bl))
            self.spec_stats["drafted"] += drafted_n
            self.spec_stats["accepted"] += acc
            self.spec_stats["rejected"] += drafted_n - acc
            rec = self._spec_by_uid.setdefault(req.uid, {"drafted": 0, "accepted": 0})
            rec["drafted"] += drafted_n
            rec["accepted"] += acc
            if acc > 0:
                self._spec_zero.pop(req.uid, None)
            else:
                z = self._spec_zero.get(req.uid, 0) + 1
                self._spec_zero[req.uid] = z
                if backoff_n and z == backoff_n:
                    # entering backoff: this drafter stops paying verify
                    # FLOPs for a stream it keeps missing
                    self.spec_stats["backoffs"] += 1
                    from ...monitor.metrics import get_metrics

                    get_metrics().counter("serving/spec_disabled_total").inc()
            committed += len(new)
            for tok in new:
                self._append_token(req, int(tok))
                if req.done:
                    break  # eos/max_new inside the burst: _finish rewound the rest
        # requests that sat this round out (backed off / empty drafts) still
        # make progress: one plain multi-step burst alongside the verify
        resting = [r for r in decoding if r.uid not in {q.uid for q in spec_reqs}
                   and not r.done]
        if resting:
            committed += self._decode_burst(resting)
        return committed

    def step(self) -> int:
        """Compose and run ONE engine call: all runnable decodes first, then
        prefill chunks up to the token budget. Returns tokens processed
        (0 = nothing runnable)."""
        decoding = [r for r in self._active.values() if not r.prefilling and not r.done]
        prefilling = [r for r in self._active.values() if r.prefilling]
        if decoding and not prefilling and not self._pending and len(decoding) <= self.max_seqs:
            if self._drafter is not None:
                n = self._spec_burst(decoding)
                if n:
                    return n
            return self._decode_burst(decoding)

        uids: List[int] = []
        chunks: List[np.ndarray] = []
        budget = self.token_budget

        for req in decoding[:min(budget, self.max_seqs)]:
            uids.append(req.uid)
            chunks.append(np.asarray([req.generated[-1]], np.int32))
            budget -= 1

        def add_prefill(req):
            nonlocal budget
            if budget <= 0 or len(uids) >= self.max_seqs:
                return False
            take = min(budget, req.prompt.size - req.fed)
            uids.append(req.uid)
            chunks.append(req.prompt[req.fed:req.fed + take])
            req.fed += take
            budget -= take
            self.stats["prefill_tokens_fed"] += take
            return True

        for req in prefilling:
            add_prefill(req)
        # FIFO-preferred admission with head-of-line skip-ahead: a pending
        # request that cannot be admitted (e.g. its lifetime KV reservation
        # exceeds what the pool can currently promise) must not starve later
        # pending requests that do fit — scan past it instead of breaking
        i = 0
        while i < len(self._pending) and budget > 0 and len(uids) < self.max_seqs:
            req = self._pending[i]
            if self._try_admit(req, uids, [c.size for c in chunks], budget):
                self._pending.pop(i)
                add_prefill(req)
            else:
                i += 1

        if not uids:
            return 0
        # sampling rides down only when a sampled row's OUTPUT matters this
        # step (its last prompt chunk or a decode row): an all-greedy batch
        # keeps the original argmax program
        samp = None
        if any(self._active[u].sampled for u in uids):
            samp = [self._active[u].sampling for u in uids]
        if self.step_observer is None:
            toks = self.engine.put(uids, chunks, sample="greedy", sampling=samp)
        else:
            t0 = time.perf_counter()
            toks = self.engine.put(uids, chunks, sample="greedy", sampling=samp)
            self.step_observer(uids, [c.size for c in chunks], t0,
                               time.perf_counter() - t0, "put")
        n = sum(c.size for c in chunks)
        for uid, tok in zip(uids, np.asarray(toks).reshape(-1)):
            req = self._active[uid]
            if req.prefilling:
                continue  # mid-prompt chunk: the "next token" is still prompt
            self._append_token(req, int(tok))
        return n

    def run(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Drive to completion. Raises (with partial generations preserved in
        ``results``) if work remains but nothing is runnable — silent drops
        would hide stalled requests."""
        steps = 0
        while self.has_work and steps < max_steps:
            if self.step() == 0:
                stalled = [r.uid for r in self._pending] + list(self._active)
                raise RuntimeError(f"scheduler stalled with unrunnable requests {stalled}: "
                                   "no pending request can be admitted (shrink them, raise "
                                   "the KV pool, or drain active work); partial generations "
                                   "remain in .results")
            steps += 1
        if self.has_work:
            raise RuntimeError(f"max_steps={max_steps} exhausted with work remaining "
                               f"({len(self._pending)} pending, {len(self._active)} active); "
                               "partial generations remain in .results")
        return dict(self._results)
