"""Engine construction helpers.

Analog of the reference ``inference/v2/engine_factory.py`` (``build_hf_engine``,
``build_engine_from_ds_checkpoint:25`` — policy lookup by model type). Here
the "policy" maps a model-family name to our native model configs; HF weight
conversion lives in ``module_inject`` (AutoTP) and plugs in through
``params``.
"""

from typing import Optional

from .config_v2 import RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2


def build_engine(model, engine_config: Optional[RaggedInferenceEngineConfig] = None, params=None):
    """Build an ``InferenceEngineV2`` from a framework model object."""
    return InferenceEngineV2(model, engine_config, params=params)


def build_model_engine(model_family: str, size: str = "tiny", engine_config=None, params=None, **cfg_over):
    """Build by family name — the policy-map entry point (reference
    ``engine_factory.py`` inventory: llama_v2 / mistral / opt)."""
    from ... import models as M

    family = model_family.lower().replace("-", "_")
    builders = {
        "llama": M.llama2,
        "llama_v2": M.llama2,
        "mistral": M.mistral,
        "gpt2": M.gpt2,
        "opt": M.opt,
        "qwen2": M.qwen2,
        "phi": M.phi,
    }
    if family not in builders:
        raise ValueError(f"unknown model family {model_family!r}; have {sorted(builders)}")
    model = builders[family](size, **cfg_over)
    return InferenceEngineV2(model, engine_config, params=params)
