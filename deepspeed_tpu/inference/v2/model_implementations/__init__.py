from .flat_model import ragged_forward
