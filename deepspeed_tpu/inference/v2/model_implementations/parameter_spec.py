"""Declarative parameter-mapping layer for v2 model families.

Reference mechanism: ``inference/v2/model_implementations/parameter_base.py``
+ ``layer_container_base.py`` (declarative parameter specs with automatic
mapping/transformation per family) — VERDICT r4 missing #4 flagged the repo's
bespoke converter-per-family pattern (11 hand-written dict builders growing
linearly) as the evidence an abstraction was overdue.

TPU-first shape of the same idea: a model family is a LIST of
:class:`ParamSpec` rows — (HF source name(s), target pytree path(s),
transform, predicate) — and ONE generic :func:`convert_with_spec` walks the
table, stacking per-layer tensors into the ``[L, ...]`` arrays the scan-based
``models.transformer`` forward consumes. Adding a family means writing a
table, not a converter; transforms are shared, named, and unit-testable.

Layout conventions encoded by the transforms:
  - torch ``nn.Linear`` stores ``[out, in]`` → our einsum layout is
    ``[in, out]`` (transform ``"t"``); GPT-2 ``Conv1D`` is already
    ``[in, out]`` (transform ``"copy"``).
  - fused query_key_value weights split per family layout: Bloom/NeoX
    per-head interleave ``(nh, 3, hd)``; Falcon GQA grouped rows
    ``[q heads..., k, v]``.
  - GPT-J's interleaved (rotate-every-two) rotary becomes our half-style
    rope via a score-preserving column permutation of q/k.
"""

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# transforms: (cfg, *source_arrays) -> tuple of target arrays
# ---------------------------------------------------------------------------
def _t(cfg, w):
    return (w.T, )


def _copy(cfg, a):
    return (a, )


def _rows_from_2(cfg, a):
    # OPT's learned positions carry a +2 offset (rows 0-1 unused)
    return (a[2:], )


def _split3_last(cfg, a):
    # GPT-2 fused c_attn: qkv concatenated on the LAST axis ([in, 3H] weight,
    # [3H] bias) — three equal slices
    return tuple(np.split(a, 3, axis=-1))


def _qkv_interleaved(cfg, w):
    """Bloom/NeoX fused qkv weight [(nh*3*hd), H] (torch [out, in]) with
    per-head interleave → ([H, nh*hd],)*3 in our [in, out] layout."""
    nh, hd = cfg.num_heads, cfg.head_dim
    H = w.shape[1]
    w3 = w.reshape(nh, 3, hd, H)
    return tuple(w3[:, j].reshape(nh * hd, H).T for j in range(3))


def _qkv_bias_interleaved(cfg, b):
    nh, hd = cfg.num_heads, cfg.head_dim
    b3 = b.reshape(nh, 3, hd)
    return tuple(b3[:, j].reshape(-1) for j in range(3))


def _qkv_gqa_rows(cfg, w):
    """Falcon MQA/GQA fused layout: per kv group [q heads..., k, v] on the
    out dim → q [H, nh*hd], k/v [H, nkv*hd]."""
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    H = w.shape[1]
    w3 = w.reshape(nkv, nh // nkv + 2, hd, H)
    q = w3[:, :-2].reshape(nh * hd, H).T
    k = w3[:, -2].reshape(nkv * hd, H).T
    v = w3[:, -1].reshape(nkv * hd, H).T
    return q, k, v


def _interleaved_to_half_perm(w_cols, nh, hd, rotary_dim):
    """Permute q/k OUTPUT columns so HF's interleaved (rotate_every_two)
    rotary becomes our half-style rope. Score-preserving: the same orthogonal
    permutation hits q and k."""
    perm_r = list(range(0, rotary_dim, 2)) + list(range(1, rotary_dim, 2))
    idx = []
    for h in range(nh):
        off = h * hd
        idx.extend(off + np.asarray(perm_r))
        idx.extend(range(off + rotary_dim, off + hd))
    return w_cols[..., np.asarray(idx)]


def _t_rotary_half(cfg, w):
    return (_interleaved_to_half_perm(w.T, cfg.num_heads, cfg.head_dim, cfg.rotary_dim), )


def _zeros_qkv(cfg):
    return (np.zeros(cfg.num_heads * cfg.head_dim, np.float32), )


def _zeros_hidden(cfg):
    return (np.zeros(cfg.hidden_size, np.float32), )


TRANSFORMS: Dict[str, Callable] = {
    "copy": _copy,
    "t": _t,
    "rows_from_2": _rows_from_2,
    "split3_last": _split3_last,
    "qkv_interleaved": _qkv_interleaved,
    "qkv_bias_interleaved": _qkv_bias_interleaved,
    "qkv_gqa_rows": _qkv_gqa_rows,
    "t_rotary_half": _t_rotary_half,
    "zeros_qkv": _zeros_qkv,
    "zeros_hidden": _zeros_hidden,
}

# predicates: (cfg, sd) -> bool, gating conditional rows
PREDICATES: Dict[str, Callable] = {
    "untied": lambda cfg, sd: not cfg.tie_embeddings,
    # qkv_bias_enabled is what the FORWARD consults (qkv_bias with a use_bias
    # fallback, transformer.py:129) — the converter must agree with it or the
    # forward KeyErrors on layer['bq']. Direct attribute access on purpose: a
    # cfg missing the property should raise, not silently skip bias rows.
    "qkv_bias": lambda cfg, sd: bool(cfg.qkv_bias_enabled),
    # falcon's 40b/180b decoder names its two parallel norms ln_attn/ln_mlp;
    # detected from the checkpoint itself, as the HF loaders do
    "falcon_new_arch": lambda cfg, sd: "transformer.h.0.ln_attn.weight" in sd,
    "falcon_old_arch": lambda cfg, sd: "transformer.h.0.ln_attn.weight" not in sd,
}


@dataclass(frozen=True)
class ParamSpec:
    """One row of a family's mapping table: ``srcs`` (HF names, ``{i}`` = layer
    index when ``per_layer``) feed ``transform``, whose outputs land at
    ``targets`` (dotted paths into the param pytree)."""

    targets: Tuple[str, ...]
    srcs: Tuple[str, ...] = ()
    transform: str = "copy"
    per_layer: bool = False
    when: Optional[str] = None

    def __post_init__(self):
        if isinstance(self.targets, str):
            object.__setattr__(self, "targets", (self.targets, ))
        if isinstance(self.srcs, str):
            object.__setattr__(self, "srcs", (self.srcs, ))
        if self.transform not in TRANSFORMS:
            raise ValueError(f"unknown transform {self.transform!r} for {self.targets}")
        if self.when is not None and self.when not in PREDICATES:
            raise ValueError(f"unknown predicate {self.when!r} for {self.targets}")


S = ParamSpec  # table-writing shorthand


def _set_path(tree: dict, dotted: str, val) -> None:
    parts = dotted.split(".")
    d = tree
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = val


def convert_with_spec(sd: Dict[str, np.ndarray], cfg, entries) -> dict:
    """Run a family's mapping table over an HF state dict → stacked fp32
    param pytree. Missing source tensors raise with the offending row named
    (a silent skip would materialize a prayer, not a model)."""
    out: dict = {}
    for e in entries:
        if e.when is not None and not PREDICATES[e.when](cfg, sd):
            continue
        tf = TRANSFORMS[e.transform]

        def fetch(name):
            if name not in sd:
                raise KeyError(
                    f"HF checkpoint is missing {name!r} (needed for {e.targets} via "
                    f"transform {e.transform!r})")
            return np.asarray(sd[name], np.float32)

        if e.per_layer:
            cols = [[] for _ in e.targets]
            for i in range(cfg.num_layers):
                outs = tf(cfg, *(fetch(s.format(i=i)) for s in e.srcs))
                for c, o in zip(cols, outs):
                    c.append(o)
            vals = [np.stack(c) for c in cols]
        else:
            vals = tf(cfg, *(fetch(s) for s in e.srcs))
        if len(vals) != len(e.targets):
            raise ValueError(f"transform {e.transform!r} produced {len(vals)} outputs "
                             f"for {len(e.targets)} targets {e.targets}")
        for t, v in zip(e.targets, vals):
            _set_path(out, t, v)
    return out


# ---------------------------------------------------------------------------
# family tables (reference model_implementations/<family>/: one container
# spec per family; here one table per family)
# ---------------------------------------------------------------------------
def _llama_family() -> tuple:
    """llama / mistral / qwen2 (qwen2 adds biased qkv via the predicate)."""
    b = "model.layers.{i}."
    return (
        S("embed.embedding", "model.embed_tokens.weight"),
        S("blocks.ln1_scale", b + "input_layernorm.weight", per_layer=True),
        S("blocks.wq", b + "self_attn.q_proj.weight", "t", per_layer=True),
        S("blocks.wk", b + "self_attn.k_proj.weight", "t", per_layer=True),
        S("blocks.wv", b + "self_attn.v_proj.weight", "t", per_layer=True),
        S("blocks.wo", b + "self_attn.o_proj.weight", "t", per_layer=True),
        S("blocks.bq", b + "self_attn.q_proj.bias", per_layer=True, when="qkv_bias"),
        S("blocks.bk", b + "self_attn.k_proj.bias", per_layer=True, when="qkv_bias"),
        S("blocks.bv", b + "self_attn.v_proj.bias", per_layer=True, when="qkv_bias"),
        S("blocks.ln2_scale", b + "post_attention_layernorm.weight", per_layer=True),
        S("blocks.w_gate", b + "mlp.gate_proj.weight", "t", per_layer=True),
        S("blocks.w_up", b + "mlp.up_proj.weight", "t", per_layer=True),
        S("blocks.w_down", b + "mlp.down_proj.weight", "t", per_layer=True),
        S("final_norm.scale", "model.norm.weight"),
        S("lm_head.kernel", "lm_head.weight", "t", when="untied"),
    )


def _phi() -> tuple:
    b = "model.layers.{i}."
    return (
        S("embed.embedding", "model.embed_tokens.weight"),
        S("blocks.ln1_scale", b + "input_layernorm.weight", per_layer=True),
        S("blocks.ln1_bias", b + "input_layernorm.bias", per_layer=True),
        S("blocks.wq", b + "self_attn.q_proj.weight", "t", per_layer=True),
        S("blocks.bq", b + "self_attn.q_proj.bias", per_layer=True),
        S("blocks.wk", b + "self_attn.k_proj.weight", "t", per_layer=True),
        S("blocks.bk", b + "self_attn.k_proj.bias", per_layer=True),
        S("blocks.wv", b + "self_attn.v_proj.weight", "t", per_layer=True),
        S("blocks.bv", b + "self_attn.v_proj.bias", per_layer=True),
        S("blocks.wo", b + "self_attn.dense.weight", "t", per_layer=True),
        S("blocks.bo", b + "self_attn.dense.bias", per_layer=True),
        S("blocks.w_up", b + "mlp.fc1.weight", "t", per_layer=True),
        S("blocks.b_up", b + "mlp.fc1.bias", per_layer=True),
        S("blocks.w_down", b + "mlp.fc2.weight", "t", per_layer=True),
        S("blocks.b_down", b + "mlp.fc2.bias", per_layer=True),
        S("final_norm.scale", "model.final_layernorm.weight"),
        S("final_norm.bias", "model.final_layernorm.bias"),
        S("lm_head.kernel", "lm_head.weight", "t"),
        S("lm_head.bias", "lm_head.bias"),
    )


def _gpt2() -> tuple:
    b = "transformer.h.{i}."
    return (
        S("embed.embedding", "transformer.wte.weight"),
        S("pos_embed.embedding", "transformer.wpe.weight"),
        S("blocks.ln1_scale", b + "ln_1.weight", per_layer=True),
        S("blocks.ln1_bias", b + "ln_1.bias", per_layer=True),
        # Conv1D stores [in, out] — no transpose; c_attn fuses qkv on out dim
        S(("blocks.wq", "blocks.wk", "blocks.wv"), b + "attn.c_attn.weight",
          "split3_last", per_layer=True),
        S(("blocks.bq", "blocks.bk", "blocks.bv"), b + "attn.c_attn.bias",
          "split3_last", per_layer=True),
        S("blocks.wo", b + "attn.c_proj.weight", per_layer=True),
        S("blocks.bo", b + "attn.c_proj.bias", per_layer=True),
        S("blocks.ln2_scale", b + "ln_2.weight", per_layer=True),
        S("blocks.ln2_bias", b + "ln_2.bias", per_layer=True),
        S("blocks.w_up", b + "mlp.c_fc.weight", per_layer=True),
        S("blocks.b_up", b + "mlp.c_fc.bias", per_layer=True),
        S("blocks.w_down", b + "mlp.c_proj.weight", per_layer=True),
        S("blocks.b_down", b + "mlp.c_proj.bias", per_layer=True),
        S("final_norm.scale", "transformer.ln_f.weight"),
        S("final_norm.bias", "transformer.ln_f.bias"),
    )


def _opt() -> tuple:
    b = "model.decoder.layers.{i}."
    return (
        S("embed.embedding", "model.decoder.embed_tokens.weight"),
        S("pos_embed.embedding", "model.decoder.embed_positions.weight", "rows_from_2"),
        S("blocks.ln1_scale", b + "self_attn_layer_norm.weight", per_layer=True),
        S("blocks.ln1_bias", b + "self_attn_layer_norm.bias", per_layer=True),
        S("blocks.wq", b + "self_attn.q_proj.weight", "t", per_layer=True),
        S("blocks.wk", b + "self_attn.k_proj.weight", "t", per_layer=True),
        S("blocks.wv", b + "self_attn.v_proj.weight", "t", per_layer=True),
        S("blocks.bq", b + "self_attn.q_proj.bias", per_layer=True),
        S("blocks.bk", b + "self_attn.k_proj.bias", per_layer=True),
        S("blocks.bv", b + "self_attn.v_proj.bias", per_layer=True),
        S("blocks.wo", b + "self_attn.out_proj.weight", "t", per_layer=True),
        S("blocks.bo", b + "self_attn.out_proj.bias", per_layer=True),
        S("blocks.ln2_scale", b + "final_layer_norm.weight", per_layer=True),
        S("blocks.ln2_bias", b + "final_layer_norm.bias", per_layer=True),
        S("blocks.w_up", b + "fc1.weight", "t", per_layer=True),
        S("blocks.b_up", b + "fc1.bias", per_layer=True),
        S("blocks.w_down", b + "fc2.weight", "t", per_layer=True),
        S("blocks.b_down", b + "fc2.bias", per_layer=True),
        S("final_norm.scale", "model.decoder.final_layer_norm.weight"),
        S("final_norm.bias", "model.decoder.final_layer_norm.bias"),
    )


def _bloom() -> tuple:
    b = "transformer.h.{i}."
    return (
        S("embed.embedding", "transformer.word_embeddings.weight"),
        S("embed_norm.scale", "transformer.word_embeddings_layernorm.weight"),
        S("embed_norm.bias", "transformer.word_embeddings_layernorm.bias"),
        S("blocks.ln1_scale", b + "input_layernorm.weight", per_layer=True),
        S("blocks.ln1_bias", b + "input_layernorm.bias", per_layer=True),
        S(("blocks.wq", "blocks.wk", "blocks.wv"),
          b + "self_attention.query_key_value.weight", "qkv_interleaved", per_layer=True),
        S(("blocks.bq", "blocks.bk", "blocks.bv"),
          b + "self_attention.query_key_value.bias", "qkv_bias_interleaved", per_layer=True),
        S("blocks.wo", b + "self_attention.dense.weight", "t", per_layer=True),
        S("blocks.bo", b + "self_attention.dense.bias", per_layer=True),
        S("blocks.ln2_scale", b + "post_attention_layernorm.weight", per_layer=True),
        S("blocks.ln2_bias", b + "post_attention_layernorm.bias", per_layer=True),
        S("blocks.w_up", b + "mlp.dense_h_to_4h.weight", "t", per_layer=True),
        S("blocks.b_up", b + "mlp.dense_h_to_4h.bias", per_layer=True),
        S("blocks.w_down", b + "mlp.dense_4h_to_h.weight", "t", per_layer=True),
        S("blocks.b_down", b + "mlp.dense_4h_to_h.bias", per_layer=True),
        S("final_norm.scale", "transformer.ln_f.weight"),
        S("final_norm.bias", "transformer.ln_f.bias"),
    )


def _gptj() -> tuple:
    b = "transformer.h.{i}."
    return (
        S("embed.embedding", "transformer.wte.weight"),
        S("blocks.ln1_scale", b + "ln_1.weight", per_layer=True),
        S("blocks.ln1_bias", b + "ln_1.bias", per_layer=True),
        # interleaved->half rotary handled by a column permutation of q/k
        S("blocks.wq", b + "attn.q_proj.weight", "t_rotary_half", per_layer=True),
        S("blocks.wk", b + "attn.k_proj.weight", "t_rotary_half", per_layer=True),
        S("blocks.wv", b + "attn.v_proj.weight", "t", per_layer=True),
        # GPT-J attention has no biases; the block layout expects them
        S("blocks.bq", transform="zeros_qkv", per_layer=True),
        S("blocks.bk", transform="zeros_qkv", per_layer=True),
        S("blocks.bv", transform="zeros_qkv", per_layer=True),
        S("blocks.wo", b + "attn.out_proj.weight", "t", per_layer=True),
        S("blocks.bo", transform="zeros_hidden", per_layer=True),
        S("blocks.w_up", b + "mlp.fc_in.weight", "t", per_layer=True),
        S("blocks.b_up", b + "mlp.fc_in.bias", per_layer=True),
        S("blocks.w_down", b + "mlp.fc_out.weight", "t", per_layer=True),
        S("blocks.b_down", b + "mlp.fc_out.bias", per_layer=True),
        S("final_norm.scale", "transformer.ln_f.weight"),
        S("final_norm.bias", "transformer.ln_f.bias"),
        S("lm_head.kernel", "lm_head.weight", "t"),
        S("lm_head.bias", "lm_head.bias"),
    )


def _gpt_neox() -> tuple:
    b = "gpt_neox.layers.{i}."
    return (
        S("embed.embedding", "gpt_neox.embed_in.weight"),
        S("blocks.ln1_scale", b + "input_layernorm.weight", per_layer=True),
        S("blocks.ln1_bias", b + "input_layernorm.bias", per_layer=True),
        S(("blocks.wq", "blocks.wk", "blocks.wv"),
          b + "attention.query_key_value.weight", "qkv_interleaved", per_layer=True),
        S(("blocks.bq", "blocks.bk", "blocks.bv"),
          b + "attention.query_key_value.bias", "qkv_bias_interleaved", per_layer=True),
        S("blocks.wo", b + "attention.dense.weight", "t", per_layer=True),
        S("blocks.bo", b + "attention.dense.bias", per_layer=True),
        S("blocks.ln2_scale", b + "post_attention_layernorm.weight", per_layer=True),
        S("blocks.ln2_bias", b + "post_attention_layernorm.bias", per_layer=True),
        S("blocks.w_up", b + "mlp.dense_h_to_4h.weight", "t", per_layer=True),
        S("blocks.b_up", b + "mlp.dense_h_to_4h.bias", per_layer=True),
        S("blocks.w_down", b + "mlp.dense_4h_to_h.weight", "t", per_layer=True),
        S("blocks.b_down", b + "mlp.dense_4h_to_h.bias", per_layer=True),
        S("final_norm.scale", "gpt_neox.final_layer_norm.weight"),
        S("final_norm.bias", "gpt_neox.final_layer_norm.bias"),
        S("lm_head.kernel", "embed_out.weight", "t", when="untied"),
    )


def _falcon() -> tuple:
    b = "transformer.h.{i}."
    return (
        S("embed.embedding", "transformer.word_embeddings.weight"),
        # 7b family: single shared input_layernorm; 40b/180b: ln_attn + ln_mlp
        S("blocks.ln1_scale", b + "input_layernorm.weight", per_layer=True,
          when="falcon_old_arch"),
        S("blocks.ln1_bias", b + "input_layernorm.bias", per_layer=True,
          when="falcon_old_arch"),
        S("blocks.ln1_scale", b + "ln_attn.weight", per_layer=True, when="falcon_new_arch"),
        S("blocks.ln1_bias", b + "ln_attn.bias", per_layer=True, when="falcon_new_arch"),
        S("blocks.ln2_scale", b + "ln_mlp.weight", per_layer=True, when="falcon_new_arch"),
        S("blocks.ln2_bias", b + "ln_mlp.bias", per_layer=True, when="falcon_new_arch"),
        S(("blocks.wq", "blocks.wk", "blocks.wv"),
          b + "self_attention.query_key_value.weight", "qkv_gqa_rows", per_layer=True),
        S("blocks.wo", b + "self_attention.dense.weight", "t", per_layer=True),
        S("blocks.w_up", b + "mlp.dense_h_to_4h.weight", "t", per_layer=True),
        S("blocks.w_down", b + "mlp.dense_4h_to_h.weight", "t", per_layer=True),
        S("final_norm.scale", "transformer.ln_f.weight"),
        S("final_norm.bias", "transformer.ln_f.bias"),
        S("lm_head.kernel", "lm_head.weight", "t", when="untied"),
    )


_LLAMA_FAMILY = _llama_family()

FAMILY_SPECS: Dict[str, tuple] = {
    "llama": _LLAMA_FAMILY,
    "mistral": _LLAMA_FAMILY,
    "qwen2": _LLAMA_FAMILY,
    "phi": _phi(),
    "gpt2": _gpt2(),
    "opt": _opt(),
    "bloom": _bloom(),
    "gptj": _gptj(),
    "gpt_neox": _gpt_neox(),
    "falcon": _falcon(),
}
