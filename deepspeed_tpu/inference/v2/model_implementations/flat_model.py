"""Ragged (flat-token) transformer forward over a paged KV pool.

This is the TPU analog of the reference FastGen data plane
(``inference/v2/model_implementations/inference_transformer_base.py`` —
``DSTransformerModelBase.forward``: per layer qkv gemm →
``linear_blocked_kv_rotary`` (rotary + append to paged KV) → ``blocked_flash``
attention over the block table → mlp → ``logits_gather`` for the last token of
each sequence). Here the whole thing is ONE jitted function over bucket-padded
arrays:

  - tokens are a flat [T] buffer mixing prefill chunks and decode steps of
    many sequences (Dynamic SplitFuse composition);
  - KV append is a scatter into the flat pool at
    ``block_table[seq, pos // bs] * bs + pos % bs`` (invalid/padding tokens
    scatter out-of-bounds with mode='drop');
  - attention gathers each sequence's context from the pool by block table
    and masks ``ctx_pos <= token_pos`` — numerics-reference path; the Pallas
    paged kernel (``ops/pallas/paged_attention.py``) replaces the gather on
    real TPU;
  - only each sequence's last token is projected to the vocabulary
    (``logits_gather`` semantics).

Works with the same stacked param pytree as ``models.transformer`` training,
so a trained checkpoint serves directly.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ....models.transformer import TransformerConfig, apply_rope, mlp_activation, rope_table


def ragged_forward(cfg: TransformerConfig, block_size: int, params: Dict[str, Any], token_ids, seq_idx, pos, valid,
                   block_tables, last_idx, k_pool, v_pool, use_pallas: bool = False,
                   unroll: bool = True, modules: Dict[str, Any] = None,
                   k_scale=None, v_scale=None, pos_ids=None, attn_mask=None,
                   ctx_pos_ids=None):
    """Returns (last-token logits [S_pad, V], k_pool, v_pool).

    token_ids/seq_idx/pos/valid: [T_pad]; block_tables: [S_pad, max_blocks];
    last_idx: [S_pad]; k_pool/v_pool: [L, NB*bs, nkv, d] (donated).

    ``pos_ids``/``attn_mask``/``ctx_pos_ids``: token-tree verification
    (``engine_v2.speculate_decode`` with branched drafts). ``pos`` stays the
    KV SLOT position (each tree node scatters into its own slot);
    ``pos_ids`` is the LOGICAL position (committed length + tree depth) that
    rotary/learned/alibi positions must see; ``attn_mask`` [T, C] is the
    ancestor-visibility mask replacing causal masking (a sibling branch at
    an earlier slot must stay invisible); ``ctx_pos_ids`` [S, C] gives every
    context slot its logical position for alibi distances. All three default
    to None = the plain causal forward, byte-identical to before.

    ``unroll``: trace the layer loop as straight-line code instead of
    ``lax.scan``. scan dynamic-slices each layer's weights out of the
    stacked pytree into a fresh buffer every iteration — measured ~3x the
    weight-streaming roofline at decode batch sizes; unrolled indexing is
    ~1.5x. Serving compiles each shape bucket once (and caches), so the
    extra trace/compile time only pays at warmup. Models deeper than 48
    layers fall back to scan to bound compile time.

    ``modules``: the pluggable module set (``modules/heuristics.build_modules``
    — attention / linear / embedding / unembed / norm slots, reference
    FastGen's DSModule layer). None builds the auto set from ``cfg`` and
    ``use_pallas``, preserving the pre-registry call surface.

    ``k_scale``/``v_scale``: int8-KV mode — [nkv, L*pool_len] fp32 absmax
    scales (lane-major over slots, the layout both the scatter and the
    Pallas kernel consume without a transpose). When given, the pools hold
    int8, each layer quantizes its fresh K/V per (token, head) before the
    scatter, and the return gains the updated scale pools:
    (logits, k_pool, v_pool, k_scale, v_scale).
    """
    if modules is None:
        from ..config_v2 import RaggedInferenceEngineConfig
        from ..modules.heuristics import build_modules

        ec = RaggedInferenceEngineConfig()
        ec.kv_block_size = block_size
        modules = build_modules(cfg, ec, use_pallas=use_pallas)
    attention, linear = modules["attention"], modules["linear"]
    embedding, unembed, pre_norm = modules["embedding"], modules["unembed"], modules["norm"]
    if getattr(cfg, "sparse_attention", None) is not None:
        # same policy as forward_with_cache: dense paged decode would
        # silently mismatch a sparse-trained model's attention distribution
        raise NotImplementedError("sparse_attention serving is not implemented on the ragged "
                                  "plane; unset sparse_attention for inference")
    T = token_ids.shape[0]
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    pool_len = k_pool.shape[1]

    pid = pos if pos_ids is None else pos_ids
    x = embedding(params, token_ids, pid)  # [T, H]
    sin, cos = rope_table(cfg, pid) if cfg.positions == "rotary" else (None, None)

    # flat KV slot of each token; padding tokens dropped via OOB scatter.
    # The pools ride the layer scan as CARRY over a layers-flattened view
    # [(L*NB*bs), nkv, d]: scatter/gather address layer l via an l*pool_len
    # (resp. l*NB block-table) offset. Pools as scan xs/ys would instead
    # round-trip the whole cache through fresh stacked outputs every forward
    # — at serving scale that copy (~2x pool bytes of HBM traffic per decode
    # step) dominated the step budget.
    NB = pool_len // block_size
    L = k_pool.shape[0]
    flat_len = L * pool_len
    slot = block_tables[seq_idx, pos // block_size] * block_size + pos % block_size

    quant = k_scale is not None

    def layer(x, blk, l, k_flat, v_flat, ks_flat, vs_flat):
        h1 = pre_norm(x, blk["ln1_scale"], blk.get("ln1_bias"))
        bias = (lambda n: blk[n]) if cfg.use_bias else (lambda n: None)
        qkvb = (lambda n: blk[n]) if cfg.qkv_bias_enabled else (lambda n: None)
        q = linear(h1, blk["wq"], qkvb("bq")).reshape(T, nq, d)
        k = linear(h1, blk["wk"], qkvb("bk")).reshape(T, nkv, d)
        v = linear(h1, blk["wv"], qkvb("bv")).reshape(T, nkv, d)
        if cfg.positions == "rotary":
            q = apply_rope(q[None], sin, cos)[0]
            k = apply_rope(k[None], sin, cos)[0]

        # append this batch's KV to the paged pool (linear_blocked_kv_rotary);
        # in-place scatter on the scan carry at layer l's offset
        slot_l = jnp.where(valid, l * pool_len + slot, flat_len)
        if quant:
            # symmetric int8 per (token, kv-head): absmax/127 over head_dim
            ks = jnp.maximum(jnp.max(jnp.abs(k.astype(jnp.float32)), axis=-1) / 127.0, 1e-8)
            vs = jnp.maximum(jnp.max(jnp.abs(v.astype(jnp.float32)), axis=-1) / 127.0, 1e-8)
            k = jnp.round(k.astype(jnp.float32) / ks[..., None])
            v = jnp.round(v.astype(jnp.float32) / vs[..., None])
            heads = jnp.arange(nkv, dtype=jnp.int32)[None, :]
            ks_flat = ks_flat.at[heads, slot_l[:, None]].set(ks, mode="drop")
            vs_flat = vs_flat.at[heads, slot_l[:, None]].set(vs, mode="drop")
        k_flat = k_flat.at[slot_l].set(k.astype(k_flat.dtype), mode="drop")
        v_flat = v_flat.at[slot_l].set(v.astype(v_flat.dtype), mode="drop")

        tables_l = block_tables + l * NB  # layer l's blocks in the flat pool
        # scales/tree kwargs only passed when active, so full-precision
        # causal third-party attention implementations keep the original
        # 6-arg call signature
        scales = {"k_scale": ks_flat, "v_scale": vs_flat} if quant else {}
        if attn_mask is not None:
            scales = dict(scales, pos_ids=pid, mask=attn_mask, ctx_pos_ids=ctx_pos_ids)
        ctx = attention(q, k_flat, v_flat, tables_l, seq_idx, pos, **scales)

        attn_out = linear(ctx.reshape(T, nq * d), blk["wo"], bias("bo"))

        def mlp(h):
            up = linear(h, blk["w_up"], bias("b_up"))
            if cfg.mlp == "swiglu":
                act = mlp_activation(cfg, up, linear(h, blk["w_gate"], None))
            else:
                act = mlp_activation(cfg, up)
            return linear(act, blk["w_down"], bias("b_down"))

        if cfg.parallel_residual:  # GPT-J / NeoX / Falcon
            h2 = h1 if cfg.shared_ln else pre_norm(x, blk["ln2_scale"], blk.get("ln2_bias"))
            return x + attn_out + mlp(h2), k_flat, v_flat, ks_flat, vs_flat
        x = x + attn_out
        h2 = pre_norm(x, blk["ln2_scale"], blk.get("ln2_bias"))
        return x + mlp(h2), k_flat, v_flat, ks_flat, vs_flat

    k_flat = k_pool.reshape(flat_len, nkv, d)
    v_flat = v_pool.reshape(flat_len, nkv, d)
    ks_flat, vs_flat = k_scale, v_scale  # already [nkv, flat_len] or None
    if unroll and L <= 48:
        for l in range(L):
            blk_l = jax.tree_util.tree_map(lambda a: a[l], params["blocks"])
            x, k_flat, v_flat, ks_flat, vs_flat = layer(x, blk_l, l, k_flat, v_flat,
                                                        ks_flat, vs_flat)
    else:
        def scan_body(carry, inp):
            x, kf, vf, ksf, vsf = carry
            blk, l = inp
            return layer(x, blk, l, kf, vf, ksf, vsf), None

        (x, k_flat, v_flat, ks_flat, vs_flat), _ = jax.lax.scan(
            scan_body, (x, k_flat, v_flat, ks_flat, vs_flat),
            (params["blocks"], jnp.arange(L, dtype=jnp.int32)))
    k_pool = k_flat.reshape(L, pool_len, nkv, d)
    v_pool = v_flat.reshape(L, pool_len, nkv, d)

    # logits_gather semantics: final norm + unembed only each sequence's
    # last token, through the pluggable unembed module
    logits = unembed(params, x, last_idx)
    if quant:
        return logits, k_pool, v_pool, ks_flat, vs_flat
    return logits, k_pool, v_pool
