"""Ragged (flat-token) transformer forward over a paged KV pool.

This is the TPU analog of the reference FastGen data plane
(``inference/v2/model_implementations/inference_transformer_base.py`` —
``DSTransformerModelBase.forward``: per layer qkv gemm →
``linear_blocked_kv_rotary`` (rotary + append to paged KV) → ``blocked_flash``
attention over the block table → mlp → ``logits_gather`` for the last token of
each sequence). Here the whole thing is ONE jitted function over bucket-padded
arrays:

  - tokens are a flat [T] buffer mixing prefill chunks and decode steps of
    many sequences (Dynamic SplitFuse composition);
  - KV append is a scatter into the flat pool at
    ``block_table[seq, pos // bs] * bs + pos % bs`` (invalid/padding tokens
    scatter out-of-bounds with mode='drop');
  - attention gathers each sequence's context from the pool by block table
    and masks ``ctx_pos <= token_pos`` — numerics-reference path; the Pallas
    paged kernel (``ops/pallas/paged_attention.py``) replaces the gather on
    real TPU;
  - only each sequence's last token is projected to the vocabulary
    (``logits_gather`` semantics).

Works with the same stacked param pytree as ``models.transformer`` training,
so a trained checkpoint serves directly.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ....models.transformer import (TransformerConfig, _norm, alibi_slopes, apply_rope,
                                    mlp_activation, rope_table)


def ragged_forward(cfg: TransformerConfig, block_size: int, params: Dict[str, Any], token_ids, seq_idx, pos, valid,
                   block_tables, last_idx, k_pool, v_pool, use_pallas: bool = False):
    """Returns (last-token logits [S_pad, V], k_pool, v_pool).

    token_ids/seq_idx/pos/valid: [T_pad]; block_tables: [S_pad, max_blocks];
    last_idx: [S_pad]; k_pool/v_pool: [L, NB*bs, nkv, d] (donated).
    """
    dt = cfg.dtype
    T = token_ids.shape[0]
    S, max_blocks = block_tables.shape
    C = max_blocks * block_size
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    g = nq // nkv
    pool_len = k_pool.shape[1]

    x = params["embed"]["embedding"].astype(dt)[token_ids]  # [T, H]
    if cfg.positions == "learned":
        x = x + params["pos_embed"]["embedding"].astype(dt)[pos]
    if cfg.embed_layernorm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg.norm, cfg.norm_eps)
    sin, cos = rope_table(cfg, pos) if cfg.positions == "rotary" else (None, None)

    # flat KV slot of each token; padding tokens dropped via OOB scatter
    slot = block_tables[seq_idx, pos // block_size] * block_size + pos % block_size
    slot = jnp.where(valid, slot, pool_len)

    def layer(x, blk_kv):
        blk, k_pool_l, v_pool_l = blk_kv
        h1 = _norm(x, blk["ln1_scale"], blk.get("ln1_bias"), cfg.norm, cfg.norm_eps)
        q = jnp.einsum("th,hd->td", h1, blk["wq"].astype(dt)).reshape(T, nq, d)
        k = jnp.einsum("th,hd->td", h1, blk["wk"].astype(dt)).reshape(T, nkv, d)
        v = jnp.einsum("th,hd->td", h1, blk["wv"].astype(dt)).reshape(T, nkv, d)
        if cfg.use_bias:
            q = q + blk["bq"].astype(dt).reshape(nq, d)
            k = k + blk["bk"].astype(dt).reshape(nkv, d)
            v = v + blk["bv"].astype(dt).reshape(nkv, d)
        if cfg.positions == "rotary":
            q = apply_rope(q[None], sin, cos)[0]
            k = apply_rope(k[None], sin, cos)[0]

        # append this batch's KV to the paged pool (linear_blocked_kv_rotary)
        k_pool_l = k_pool_l.at[slot].set(k.astype(k_pool_l.dtype), mode="drop")
        v_pool_l = v_pool_l.at[slot].set(v.astype(v_pool_l.dtype), mode="drop")

        from ....ops.pallas.paged_attention import paged_attention, paged_attention_reference

        alibi = alibi_slopes(nq) if cfg.positions == "alibi" else None
        if use_pallas:
            ctx = paged_attention(q, k_pool_l, v_pool_l, block_tables, seq_idx, pos, block_size,
                                  window=cfg.sliding_window, alibi=alibi)
        else:
            ctx = paged_attention_reference(q, k_pool_l, v_pool_l, block_tables, seq_idx, pos,
                                            block_size, window=cfg.sliding_window, alibi=alibi)

        attn_out = jnp.einsum("td,dh->th", ctx.reshape(T, nq * d), blk["wo"].astype(dt))
        if cfg.use_bias:
            attn_out = attn_out + blk["bo"].astype(dt)

        def mlp(h):
            up = jnp.einsum("th,hf->tf", h, blk["w_up"].astype(dt))
            if cfg.use_bias:
                up = up + blk["b_up"].astype(dt)
            if cfg.mlp == "swiglu":
                act = mlp_activation(cfg, up, jnp.einsum("th,hf->tf", h, blk["w_gate"].astype(dt)))
            else:
                act = mlp_activation(cfg, up)
            down = jnp.einsum("tf,fh->th", act, blk["w_down"].astype(dt))
            if cfg.use_bias:
                down = down + blk["b_down"].astype(dt)
            return down

        if cfg.parallel_residual:  # GPT-J / NeoX / Falcon
            h2 = h1 if cfg.shared_ln else _norm(x, blk["ln2_scale"], blk.get("ln2_bias"),
                                                cfg.norm, cfg.norm_eps)
            return x + attn_out + mlp(h2), (k_pool_l, v_pool_l)
        x = x + attn_out
        h2 = _norm(x, blk["ln2_scale"], blk.get("ln2_bias"), cfg.norm, cfg.norm_eps)
        return x + mlp(h2), (k_pool_l, v_pool_l)

    def scan_body(x, blk_kv):
        x, pools = layer(x, blk_kv)
        return x, pools

    x, (k_pool, v_pool) = jax.lax.scan(scan_body, x, (params["blocks"], k_pool, v_pool))

    h = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    h_last = h[last_idx]  # [S, H] — logits_gather: unembed only last tokens
    if cfg.tie_embeddings:
        logits = jnp.einsum("sh,vh->sv", h_last, params["embed"]["embedding"].astype(dt))
    else:
        logits = jnp.einsum("sh,hv->sv", h_last, params["lm_head"]["kernel"].astype(dt))
        if "bias" in params["lm_head"]:
            logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
    return logits.astype(jnp.float32), k_pool, v_pool
