"""Ragged (flat-token) transformer forward over a paged KV pool.

This is the TPU analog of the reference FastGen data plane
(``inference/v2/model_implementations/inference_transformer_base.py`` —
``DSTransformerModelBase.forward``: per layer qkv gemm →
``linear_blocked_kv_rotary`` (rotary + append to paged KV) → ``blocked_flash``
attention over the block table → mlp → ``logits_gather`` for the last token of
each sequence). Here the whole thing is ONE jitted function over bucket-padded
arrays:

  - tokens are a flat [T] buffer mixing prefill chunks and decode steps of
    many sequences (Dynamic SplitFuse composition);
  - KV append is a scatter into the flat pool at
    ``block_table[seq, pos // bs] * bs + pos % bs`` (invalid/padding tokens
    scatter out-of-bounds with mode='drop');
  - attention gathers each sequence's context from the pool by block table
    and masks ``ctx_pos <= token_pos`` — numerics-reference path; the Pallas
    paged kernel (``ops/pallas/paged_attention.py``) replaces the gather on
    real TPU;
  - only each sequence's last token is projected to the vocabulary
    (``logits_gather`` semantics).

Works with the same stacked param pytree as ``models.transformer`` training,
so a trained checkpoint serves directly.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ....models.transformer import (TransformerConfig, _norm, alibi_slopes, apply_rope,
                                    mlp_activation, rope_table)


def ragged_forward(cfg: TransformerConfig, block_size: int, params: Dict[str, Any], token_ids, seq_idx, pos, valid,
                   block_tables, last_idx, k_pool, v_pool, use_pallas: bool = False,
                   unroll: bool = True):
    """Returns (last-token logits [S_pad, V], k_pool, v_pool).

    token_ids/seq_idx/pos/valid: [T_pad]; block_tables: [S_pad, max_blocks];
    last_idx: [S_pad]; k_pool/v_pool: [L, NB*bs, nkv, d] (donated).

    ``unroll``: trace the layer loop as straight-line code instead of
    ``lax.scan``. scan dynamic-slices each layer's weights out of the
    stacked pytree into a fresh buffer every iteration — measured ~3x the
    weight-streaming roofline at decode batch sizes; unrolled indexing is
    ~1.5x. Serving compiles each shape bucket once (and caches), so the
    extra trace/compile time only pays at warmup. Models deeper than 48
    layers fall back to scan to bound compile time.
    """
    if getattr(cfg, "sparse_attention", None) is not None:
        # same policy as forward_with_cache: dense paged decode would
        # silently mismatch a sparse-trained model's attention distribution
        raise NotImplementedError("sparse_attention serving is not implemented on the ragged "
                                  "plane; unset sparse_attention for inference")
    dt = cfg.dtype
    T = token_ids.shape[0]
    S, max_blocks = block_tables.shape
    C = max_blocks * block_size
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    g = nq // nkv
    pool_len = k_pool.shape[1]

    x = params["embed"]["embedding"].astype(dt)[token_ids]  # [T, H]
    if cfg.positions == "learned":
        x = x + params["pos_embed"]["embedding"].astype(dt)[pos]
    if cfg.embed_layernorm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg.norm, cfg.norm_eps)
    sin, cos = rope_table(cfg, pos) if cfg.positions == "rotary" else (None, None)

    # flat KV slot of each token; padding tokens dropped via OOB scatter.
    # The pools ride the layer scan as CARRY over a layers-flattened view
    # [(L*NB*bs), nkv, d]: scatter/gather address layer l via an l*pool_len
    # (resp. l*NB block-table) offset. Pools as scan xs/ys would instead
    # round-trip the whole cache through fresh stacked outputs every forward
    # — at serving scale that copy (~2x pool bytes of HBM traffic per decode
    # step) dominated the step budget.
    NB = pool_len // block_size
    L = k_pool.shape[0]
    flat_len = L * pool_len
    slot = block_tables[seq_idx, pos // block_size] * block_size + pos % block_size

    def layer(x, blk, l, k_flat, v_flat):
        h1 = _norm(x, blk["ln1_scale"], blk.get("ln1_bias"), cfg.norm, cfg.norm_eps)
        q = jnp.einsum("th,hd->td", h1, blk["wq"].astype(dt)).reshape(T, nq, d)
        k = jnp.einsum("th,hd->td", h1, blk["wk"].astype(dt)).reshape(T, nkv, d)
        v = jnp.einsum("th,hd->td", h1, blk["wv"].astype(dt)).reshape(T, nkv, d)
        if cfg.use_bias:
            q = q + blk["bq"].astype(dt).reshape(nq, d)
            k = k + blk["bk"].astype(dt).reshape(nkv, d)
            v = v + blk["bv"].astype(dt).reshape(nkv, d)
        if cfg.positions == "rotary":
            q = apply_rope(q[None], sin, cos)[0]
            k = apply_rope(k[None], sin, cos)[0]

        # append this batch's KV to the paged pool (linear_blocked_kv_rotary);
        # in-place scatter on the scan carry at layer l's offset
        slot_l = jnp.where(valid, l * pool_len + slot, flat_len)
        k_flat = k_flat.at[slot_l].set(k.astype(k_flat.dtype), mode="drop")
        v_flat = v_flat.at[slot_l].set(v.astype(v_flat.dtype), mode="drop")

        from ....ops.pallas.paged_attention import paged_attention, paged_attention_reference

        tables_l = block_tables + l * NB  # layer l's blocks in the flat pool
        alibi = alibi_slopes(nq) if cfg.positions == "alibi" else None
        if use_pallas:
            ctx = paged_attention(q, k_flat, v_flat, tables_l, seq_idx, pos, block_size,
                                  window=cfg.sliding_window, alibi=alibi)
        else:
            ctx = paged_attention_reference(q, k_flat, v_flat, tables_l, seq_idx, pos,
                                            block_size, window=cfg.sliding_window, alibi=alibi)

        attn_out = jnp.einsum("td,dh->th", ctx.reshape(T, nq * d), blk["wo"].astype(dt))
        if cfg.use_bias:
            attn_out = attn_out + blk["bo"].astype(dt)

        def mlp(h):
            up = jnp.einsum("th,hf->tf", h, blk["w_up"].astype(dt))
            if cfg.use_bias:
                up = up + blk["b_up"].astype(dt)
            if cfg.mlp == "swiglu":
                act = mlp_activation(cfg, up, jnp.einsum("th,hf->tf", h, blk["w_gate"].astype(dt)))
            else:
                act = mlp_activation(cfg, up)
            down = jnp.einsum("tf,fh->th", act, blk["w_down"].astype(dt))
            if cfg.use_bias:
                down = down + blk["b_down"].astype(dt)
            return down

        if cfg.parallel_residual:  # GPT-J / NeoX / Falcon
            h2 = h1 if cfg.shared_ln else _norm(x, blk["ln2_scale"], blk.get("ln2_bias"),
                                                cfg.norm, cfg.norm_eps)
            return x + attn_out + mlp(h2), k_flat, v_flat
        x = x + attn_out
        h2 = _norm(x, blk["ln2_scale"], blk.get("ln2_bias"), cfg.norm, cfg.norm_eps)
        return x + mlp(h2), k_flat, v_flat

    k_flat = k_pool.reshape(flat_len, nkv, d)
    v_flat = v_pool.reshape(flat_len, nkv, d)
    if unroll and L <= 48:
        for l in range(L):
            blk_l = jax.tree_util.tree_map(lambda a: a[l], params["blocks"])
            x, k_flat, v_flat = layer(x, blk_l, l, k_flat, v_flat)
    else:
        def scan_body(carry, inp):
            x, kf, vf = carry
            blk, l = inp
            return layer(x, blk, l, kf, vf), None

        (x, k_flat, v_flat), _ = jax.lax.scan(
            scan_body, (x, k_flat, v_flat),
            (params["blocks"], jnp.arange(L, dtype=jnp.int32)))
    k_pool = k_flat.reshape(L, pool_len, nkv, d)
    v_pool = v_flat.reshape(L, pool_len, nkv, d)

    h = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    h_last = h[last_idx]  # [S, H] — logits_gather: unembed only last tokens
    if cfg.tie_embeddings:
        logits = jnp.einsum("sh,vh->sv", h_last, params["embed"]["embedding"].astype(dt))
    else:
        logits = jnp.einsum("sh,hv->sv", h_last, params["lm_head"]["kernel"].astype(dt))
        if "bias" in params["lm_head"]:
            logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
    return logits.astype(jnp.float32), k_pool, v_pool
