"""Admission-control results.

Analog of the reference ``inference/v2/scheduling_utils.py`` (SchedulingResult
/ SchedulingError consumed by MII's scheduler through ``engine.can_schedule``).
"""

import enum


class SchedulingResult(enum.Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    TokenLimitExceeded = 3
    KVCacheLimitExceeded = 4


class SchedulingError(RuntimeError):

    def __init__(self, result: SchedulingResult):
        self.status = result
        super().__init__(f"Scheduling failed: {result.name}")
