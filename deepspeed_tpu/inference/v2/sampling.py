"""Sampling for the ragged serving plane: temperature / top-p decoding and
the speculative rejection-sampling verify step.

Until this module, the whole serving plane was greedy-only — ``put`` /
``decode`` / ``speculate_decode`` all argmax on device, so the gateway could
not expose ``temperature`` at all. Two pieces:

* :class:`SamplingParams` — the per-request knob set (``temperature`` /
  ``top_p`` / ``seed``), validated once at the gateway door. Temperature 0
  is EXACT greedy (the argmax code path, not a small-temperature limit), so
  greedy parity guarantees are untouched by this module's existence.

* The device-side draw helpers. Determinism contract: every random draw is
  keyed by ``fold_in(PRNGKey(seed), token_position)`` (plus a small
  substream index), so a fixed ``(seed, prompt)`` pair replays the same
  stream across runs, batch compositions, and decode-path choices (put
  loop vs multi-step scan) — the key depends on the REQUEST's seed and the
  token's absolute position, never on batch layout.

* :func:`spec_verify_draws` — standard speculative sampling (Leviathan et
  al. / Chen et al.): the drafter proposes token ``d_i``; since every
  drafter here is deterministic given context, its proposal distribution is
  a point mass, so the accept test degenerates to ``u_i < p_i(d_i)`` under
  the target's (temperature/top-p filtered) distribution ``p_i``, and a
  rejection resamples from the normalized residual — ``p_i`` with ``d_i``
  masked out. The committed stream is then distributed EXACTLY as direct
  sampling from the target (asserted statistically in
  ``tests/test_speculative.py``); speculation changes throughput, never the
  distribution.
"""

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs. ``temperature == 0`` is exact greedy;
    ``top_p`` keeps the smallest nucleus whose mass reaches it (top-1 is
    always kept); ``seed`` keys the request's whole random stream (None =
    derived from the request uid, so replays within one process are
    deterministic but two clients don't share draws by default)."""

    temperature: float = 0.0
    top_p: float = 1.0
    seed: Optional[int] = None

    def validate(self) -> "SamplingParams":
        t = float(self.temperature)
        if not np.isfinite(t) or t < 0.0 or t > 100.0:
            raise ValueError(f"temperature must be in [0, 100], got {self.temperature!r}")
        p = float(self.top_p)
        if not np.isfinite(p) or not 0.0 < p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p!r}")
        if self.seed is not None:
            s = int(self.seed)
            if not -2**31 <= s < 2**31:
                raise ValueError(f"seed must fit int32, got {self.seed!r}")
        return self

    @property
    def greedy(self) -> bool:
        return float(self.temperature) <= 0.0


def pack_sampling(params: Sequence[Optional[SamplingParams]], uids: Sequence[int],
                  s_bucket: int):
    """Pack per-sequence sampling params into the two device operands the
    compiled sampled paths take: float32 ``[S_bucket, 2]`` (temperature,
    top_p) and int32 ``[S_bucket]`` seeds. ``None`` entries are greedy rows
    (temperature 0 → the argmax branch on device); an unset seed derives
    from the uid."""
    f = np.zeros((s_bucket, 2), np.float32)
    f[:, 1] = 1.0
    seeds = np.zeros(s_bucket, np.int32)
    for i, (sp, uid) in enumerate(zip(params, uids)):
        if sp is None:
            continue
        f[i, 0] = float(sp.temperature)
        f[i, 1] = float(sp.top_p)
        seeds[i] = np.int32((int(uid) * 2654435761) & 0x7FFFFFFF) if sp.seed is None \
            else np.int32(int(sp.seed))
    return f, seeds


def all_greedy(params) -> bool:
    """True when no row needs the sampled code path (params absent or every
    entry None/temperature-0) — the caller then keeps the byte-identical
    greedy program."""
    return params is None or all(sp is None or sp.greedy for sp in params)


# ---------------------------------------------------------------------------
# device-side draws (pure jnp — called inside the engine's compiled paths,
# and directly by the distribution-equivalence test)
# ---------------------------------------------------------------------------

def _keys(seeds, ctrs):
    """One PRNG key per row: ``fold_in(PRNGKey(seed), ctr)`` — ctr is the
    token's absolute position, making draws batch-layout-independent."""
    import jax

    def one(s, c):
        return jax.random.fold_in(jax.random.PRNGKey(s), c)

    return jax.vmap(one)(seeds, ctrs)


def filter_top_p(logits, top_p):
    """Mask ``logits`` (last axis = vocab) outside the smallest nucleus
    whose probability mass reaches ``top_p`` (broadcastable; 1.0 = no-op
    mask in VALUE — the masked set is empty). Top-1 is always kept."""
    import jax
    import jax.numpy as jnp

    sorted_l = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a token is kept while the mass BEFORE it is < top_p (keeps top-1 even
    # when its own mass exceeds top_p)
    keep = (cum - probs) < jnp.asarray(top_p)[..., None]
    kept_min = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits >= kept_min, logits, -jnp.inf)


def _filtered(logits, temps, top_ps):
    """Temperature-scaled, top-p-filtered logits (f32). ``temps``/``top_ps``
    broadcast over the leading axes ([S] against [S, ..., V])."""
    import jax.numpy as jnp

    extra = logits.ndim - 1 - temps.ndim + 1
    t = temps.reshape(temps.shape + (1, ) * extra)
    p = top_ps.reshape(top_ps.shape + (1, ) * (extra - 1))
    scaled = logits.astype(jnp.float32) / jnp.maximum(t, 1e-6)
    return filter_top_p(scaled, p)


def sample_tokens(logits, temps, top_ps, seeds, ctrs):
    """One token per row from ``logits [S, V]``: argmax where
    ``temps <= 0``, else categorical over the temperature/top-p filtered
    distribution, keyed by ``(seed, ctr)`` (ctr = the sampled token's own
    absolute position)."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filt = _filtered(logits, temps, top_ps)
    sampled = jax.vmap(jax.random.categorical)(_keys(seeds, ctrs), filt).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


def spec_verify_draws(logits, chunk, temps, top_ps, seeds, starts):
    """The speculative-sampling verify step over one ragged verify chunk.

    ``logits [S, k+1, V]``: the target's logits at every chunk position
    (position i's distribution conditions on chunk tokens ``..i``);
    ``chunk [S, k+1]`` the fed tokens (pending first token + k drafts, pads
    included); ``starts [S]`` each sequence's pre-chunk ``seen_tokens``.

    Returns ``(accept [S, k] bool, nxt [S, k+1] int32)``:

    * ``accept[s, i]`` — draft ``chunk[s, i+1]`` survives at position i
      (greedy rows: equals the argmax; sampled rows: ``u < p_i(d_i)``, the
      point-mass-draft acceptance test);
    * ``nxt[s, i]`` for ``i < k`` — the token to commit INSTEAD when i is
      the first rejection: greedy rows the argmax, sampled rows a draw from
      the normalized residual (``p_i`` with ``d_i`` masked out — the
      ``(p - q)^+`` of speculative sampling with a point-mass q);
    * ``nxt[s, k]`` — the bonus token when every draft survives (a fresh
      draw from position k's distribution / the argmax).

    The caller walks accept to the first False exactly as the greedy path
    walks its argmax mismatch — the host-side commit logic is shared.
    """
    import jax
    import jax.numpy as jnp

    S, k1, V = logits.shape
    k = k1 - 1
    lg = logits.astype(jnp.float32)
    greedy_row = jnp.argmax(lg, axis=-1).astype(jnp.int32)         # [S, k+1]
    filt = _filtered(lg, temps, top_ps)                            # [S, k+1, V]
    probs = jax.nn.softmax(filt, axis=-1)
    drafts = chunk[:, 1:]                                          # [S, k]
    p_draft = jnp.take_along_axis(probs[:, :k], drafts[..., None], axis=-1)[..., 0]

    # keys: one per (row, chunk position), keyed by the TARGET position the
    # draw decides (start + i + 1), substreams 0=accept, 1=residual, 2=bonus
    def row_keys(seed, start):
        base = jax.random.PRNGKey(seed)
        ks = jax.vmap(lambda i: jax.random.fold_in(base, start + 1 + i))(
            jnp.arange(k1, dtype=jnp.int32))
        return ks

    keys = jax.vmap(row_keys)(seeds, starts)                       # [S, k+1, 2]
    sub = jax.vmap(jax.vmap(jax.random.fold_in))
    u = jax.vmap(jax.vmap(jax.random.uniform))(sub(keys[:, :k], jnp.zeros((S, k), jnp.int32)))
    residual = jnp.where(
        jax.nn.one_hot(drafts, V, dtype=bool), -jnp.inf, filt[:, :k])
    # degenerate nucleus == {draft}: the residual is empty, but then
    # p(draft) == 1 and the accept test never consults the resample — keep
    # the draw well-defined rather than categorical over all -inf
    res_dead = jnp.all(jnp.isneginf(residual), axis=-1, keepdims=True)
    residual = jnp.where(res_dead, filt[:, :k], residual)
    res_tok = jax.vmap(jax.vmap(jax.random.categorical))(
        sub(keys[:, :k], jnp.ones((S, k), jnp.int32)), residual).astype(jnp.int32)
    # the bonus draw only ever applies at the LAST position (full
    # acceptance) — draw just there, with the same (position-k, substream-2)
    # key a full-width draw would have used, so streams are unchanged
    bonus_tok = jax.vmap(jax.vmap(jax.random.categorical))(
        sub(keys[:, k:], jnp.full((S, 1), 2, jnp.int32)), filt[:, k:]).astype(jnp.int32)

    sampled_rows = (temps > 0.0)[:, None]
    accept = jnp.where(sampled_rows, u < p_draft, drafts == greedy_row[:, :k])
    nxt = jnp.where(sampled_rows, jnp.concatenate(
        [res_tok, bonus_tok], axis=1), greedy_row)
    return accept, nxt
