"""HuggingFace checkpoint loading for inference.

Reference ``inference/v2/checkpoint/huggingface_engine.py`` (the FastGen
checkpoint engine iterating HF weights into the layer containers) +
``engine_factory.build_hf_engine``. Here the containers are the stacked
param pytree of ``models.transformer``: per-family name maps stack the
per-layer HF tensors into [L, ...] arrays, transposing torch Linear weights
([out, in]) into our [in, out] einsum layout. Supported families mirror the
reference inventory (llama_v2, mistral, opt) plus gpt2.
"""

import json
import os
import re
from typing import Dict, Iterator, Tuple

import numpy as np

from ....utils.logging import logger


class HuggingFaceCheckpointEngine:
    """Iterate (name, np.ndarray) weights from an HF model dir or hub name
    (reference class of the same name: ``parameters()`` iterator)."""

    def __init__(self, model_name_or_path: str, auth_token: str = None):
        self.model_name_or_path = model_name_or_path
        self._sd = None

    def _load(self):
        if self._sd is not None:
            return self._sd
        path = self.model_name_or_path
        sd = {}
        if os.path.isdir(path):
            safes = [f for f in os.listdir(path) if f.endswith(".safetensors")]
            bins = [f for f in os.listdir(path) if f.endswith(".bin")]
            if safes:
                from safetensors import safe_open

                for f in sorted(safes):
                    with safe_open(os.path.join(path, f), framework="np") as fh:
                        for k in fh.keys():
                            sd[k] = fh.get_tensor(k)
            elif bins:
                import torch

                for f in sorted(bins):
                    part = torch.load(os.path.join(path, f), map_location="cpu", weights_only=True)
                    for k, v in part.items():
                        sd[k] = v.float().numpy()
            else:
                raise FileNotFoundError(f"no .safetensors/.bin weights in {path}")
        else:  # hub name → go through transformers
            from transformers import AutoModelForCausalLM

            model = AutoModelForCausalLM.from_pretrained(path)
            sd = {k: v.detach().float().numpy() for k, v in model.state_dict().items()}
        self._sd = sd
        return sd

    def parameters(self) -> Iterator[Tuple[str, np.ndarray]]:
        yield from self._load().items()

    def state_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._load())

    def model_config(self):
        path = self.model_name_or_path
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) else None
        if cfg_file and os.path.isfile(cfg_file):
            with open(cfg_file) as f:
                return json.load(f)
        from transformers import AutoConfig

        return AutoConfig.from_pretrained(path).to_dict()


# ---------------------------------------------------------------------------
# config mapping
# ---------------------------------------------------------------------------
def transformer_config_from_hf(hf_cfg: dict):
    """HF config.json → TransformerConfig (the per-family policy lookup,
    reference ``engine_factory.py`` model_type dispatch)."""
    from ....models.transformer import TransformerConfig

    mt = hf_cfg.get("model_type", "llama")
    if mt in ("llama", "mistral", "qwen2"):
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            num_kv_heads=hf_cfg.get("num_key_value_heads", hf_cfg["num_attention_heads"]),
            intermediate_size=hf_cfg["intermediate_size"],
            max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="rmsnorm", positions="rotary", mlp="swiglu", use_bias=False,
            qkv_bias=(mt == "qwen2"),  # qwen2: biased qkv only
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", False)),
            rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
            norm_eps=float(hf_cfg.get("rms_norm_eps", 1e-5))), mt
    if mt == "phi":
        d = hf_cfg["hidden_size"] // hf_cfg["num_attention_heads"]
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            intermediate_size=hf_cfg["intermediate_size"],
            max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
            parallel_residual=True, shared_ln=True,
            rotary_dim=int(round(hf_cfg.get("partial_rotary_factor", 0.5) * d)),
            tie_embeddings=False,
            rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
            norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-5))), mt
    if mt == "gpt2":
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["n_embd"],
            num_layers=hf_cfg["n_layer"], num_heads=hf_cfg["n_head"],
            intermediate_size=4 * hf_cfg["n_embd"], max_seq_len=hf_cfg.get("n_positions", 1024),
            norm="layernorm", positions="learned", mlp="gelu", use_bias=True,
            tie_embeddings=True, norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    if mt == "opt":
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            intermediate_size=hf_cfg["ffn_dim"], max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="layernorm", positions="learned", mlp="relu", use_bias=True,
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", True)), norm_eps=1e-5), mt
    if mt == "bloom":
        H = hf_cfg.get("hidden_size", hf_cfg.get("n_embed"))
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=H,
            num_layers=hf_cfg.get("num_hidden_layers", hf_cfg.get("n_layer")),
            num_heads=hf_cfg.get("num_attention_heads", hf_cfg.get("n_head")),
            intermediate_size=4 * H, max_seq_len=2048,
            norm="layernorm", positions="alibi", mlp="gelu", use_bias=True,
            tie_embeddings=True, embed_layernorm=True,
            norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    if mt == "gptj":
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["n_embd"],
            num_layers=hf_cfg["n_layer"], num_heads=hf_cfg["n_head"],
            intermediate_size=hf_cfg.get("n_inner") or 4 * hf_cfg["n_embd"],
            max_seq_len=hf_cfg.get("n_positions", 2048),
            norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
            tie_embeddings=False, parallel_residual=True, shared_ln=True,
            rotary_dim=hf_cfg.get("rotary_dim") or hf_cfg["n_embd"] // hf_cfg["n_head"],
            norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    if mt == "gpt_neox":
        hd = hf_cfg["hidden_size"] // hf_cfg["num_attention_heads"]
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            intermediate_size=hf_cfg["intermediate_size"],
            max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", False)),
            parallel_residual=bool(hf_cfg.get("use_parallel_residual", True)), shared_ln=False,
            rotary_dim=max(2, int(hd * float(hf_cfg.get("rotary_pct", 0.25))) // 2 * 2),
            rope_theta=float(hf_cfg.get("rotary_emb_base", 10000.0)),
            norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-5))), mt
    if mt == "falcon":
        nh = hf_cfg.get("num_attention_heads", hf_cfg.get("n_head"))
        new_arch = bool(hf_cfg.get("new_decoder_architecture", False))
        # HF semantics: num_kv_heads applies whenever new_decoder_architecture
        # or not multi_query; only legacy multi_query models force MQA (1)
        if new_arch or not hf_cfg.get("multi_query", True):
            nkv = hf_cfg.get("num_kv_heads") or hf_cfg.get("n_head_kv") or nh
        else:
            nkv = 1
        if hf_cfg.get("alibi", False):
            raise ValueError("falcon checkpoints with alibi=true (falcon-rw family) are not "
                             "supported yet: the converter maps falcon to rotary positions")
        if hf_cfg.get("bias", False):
            raise ValueError("falcon checkpoints with bias=true are not supported yet: the "
                             "converter does not extract attention/MLP biases for falcon")
        if not hf_cfg.get("parallel_attn", True) and not new_arch:
            raise ValueError("sequential falcon (parallel_attn=false) is not supported yet: the "
                             "converter emits no post-attention norm for that layout")
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg.get("num_hidden_layers", hf_cfg.get("n_layer")),
            num_heads=nh, num_kv_heads=nkv,
            intermediate_size=4 * hf_cfg["hidden_size"], max_seq_len=2048,
            norm="layernorm", positions="rotary", mlp="gelu",
            use_bias=bool(hf_cfg.get("bias", False)),
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", True)),
            parallel_residual=bool(hf_cfg.get("parallel_attn", True)) or new_arch,
            shared_ln=bool(hf_cfg.get("parallel_attn", True)) and not new_arch,
            norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    raise ValueError(f"unsupported model_type {mt!r}; supported: llama, mistral, qwen2, phi, gpt2, opt, "
                     "bloom, gptj, gpt_neox, falcon")


# ---------------------------------------------------------------------------
# weight conversion
# ---------------------------------------------------------------------------
def _stack(sd, fmt, L, transpose=False):
    ws = [np.asarray(sd[fmt.format(i=i)], np.float32) for i in range(L)]
    if transpose:
        ws = [w.T for w in ws]
    return np.stack(ws)


def _split_fused_qkv(w, nh, hd, nkv=None):
    """Split a fused per-head query_key_value weight [(…)*hd, H] (torch
    [out, in] layout) into our [L-free] (H, nh*hd) q and (H, nkv*hd) k/v.

    ``nkv=None``: Bloom/NeoX per-head interleave (nh, 3, hd); else the
    Falcon MQA/GQA layout [q heads..., k heads, v heads] on the out dim.
    """
    H = w.shape[1]
    if nkv is None:
        w3 = w.reshape(nh, 3, hd, H)
        q, k, v = (w3[:, j].reshape(nh * hd, H).T for j in range(3))
        return q, k, v
    w3 = w.reshape(nkv, nh // nkv + 2, hd, H)
    q = w3[:, :-2].reshape(nh * hd, H).T
    k = w3[:, -2].reshape(nkv * hd, H).T
    v = w3[:, -1].reshape(nkv * hd, H).T
    return q, k, v


def _split_fused_qkv_bias(b, nh, hd):
    b3 = b.reshape(nh, 3, hd)
    return b3[:, 0].reshape(-1), b3[:, 1].reshape(-1), b3[:, 2].reshape(-1)


def _interleaved_to_half_perm(w_cols, nh, hd, rotary_dim):
    """Permute q/k projection OUTPUT columns so HF's interleaved (GPT-J
    rotate_every_two) rotary becomes our half-style rope: within each head's
    first ``rotary_dim`` dims, reorder [0,1,2,...] -> [0,2,4,...,1,3,...].
    Score-preserving because the same orthogonal permutation hits q and k."""
    perm_r = list(range(0, rotary_dim, 2)) + list(range(1, rotary_dim, 2))
    idx = []
    for h in range(nh):
        off = h * hd
        idx.extend(off + np.asarray(perm_r))
        idx.extend(range(off + rotary_dim, off + hd))
    return w_cols[..., np.asarray(idx)]


def convert_hf_state_dict(sd: Dict[str, np.ndarray], cfg, model_type: str):
    """HF state dict → stacked param pytree (numpy, fp32)."""
    L = cfg.num_layers
    if model_type in ("llama", "mistral", "qwen2"):
        p = {
            "embed": {"embedding": np.asarray(sd["model.embed_tokens.weight"], np.float32)},
            "blocks": {
                "ln1_scale": _stack(sd, "model.layers.{i}.input_layernorm.weight", L),
                "wq": _stack(sd, "model.layers.{i}.self_attn.q_proj.weight", L, transpose=True),
                "wk": _stack(sd, "model.layers.{i}.self_attn.k_proj.weight", L, transpose=True),
                "wv": _stack(sd, "model.layers.{i}.self_attn.v_proj.weight", L, transpose=True),
                "wo": _stack(sd, "model.layers.{i}.self_attn.o_proj.weight", L, transpose=True),
                "ln2_scale": _stack(sd, "model.layers.{i}.post_attention_layernorm.weight", L),
                "w_gate": _stack(sd, "model.layers.{i}.mlp.gate_proj.weight", L, transpose=True),
                "w_up": _stack(sd, "model.layers.{i}.mlp.up_proj.weight", L, transpose=True),
                "w_down": _stack(sd, "model.layers.{i}.mlp.down_proj.weight", L, transpose=True),
            },
            "final_norm": {"scale": np.asarray(sd["model.norm.weight"], np.float32)},
        }
        if model_type == "qwen2":  # biased qkv only
            p["blocks"]["bq"] = _stack(sd, "model.layers.{i}.self_attn.q_proj.bias", L)
            p["blocks"]["bk"] = _stack(sd, "model.layers.{i}.self_attn.k_proj.bias", L)
            p["blocks"]["bv"] = _stack(sd, "model.layers.{i}.self_attn.v_proj.bias", L)
        if not cfg.tie_embeddings:
            p["lm_head"] = {"kernel": np.asarray(sd["lm_head.weight"], np.float32).T}
        return p
    if model_type == "phi":
        # parallel residual, single shared input_layernorm, partial rotary;
        # phi's rotary uses the half-split convention (same as our apply_rope)
        p = {
            "embed": {"embedding": np.asarray(sd["model.embed_tokens.weight"], np.float32)},
            "blocks": {
                "ln1_scale": _stack(sd, "model.layers.{i}.input_layernorm.weight", L),
                "ln1_bias": _stack(sd, "model.layers.{i}.input_layernorm.bias", L),
                "wq": _stack(sd, "model.layers.{i}.self_attn.q_proj.weight", L, transpose=True),
                "bq": _stack(sd, "model.layers.{i}.self_attn.q_proj.bias", L),
                "wk": _stack(sd, "model.layers.{i}.self_attn.k_proj.weight", L, transpose=True),
                "bk": _stack(sd, "model.layers.{i}.self_attn.k_proj.bias", L),
                "wv": _stack(sd, "model.layers.{i}.self_attn.v_proj.weight", L, transpose=True),
                "bv": _stack(sd, "model.layers.{i}.self_attn.v_proj.bias", L),
                "wo": _stack(sd, "model.layers.{i}.self_attn.dense.weight", L, transpose=True),
                "bo": _stack(sd, "model.layers.{i}.self_attn.dense.bias", L),
                "w_up": _stack(sd, "model.layers.{i}.mlp.fc1.weight", L, transpose=True),
                "b_up": _stack(sd, "model.layers.{i}.mlp.fc1.bias", L),
                "w_down": _stack(sd, "model.layers.{i}.mlp.fc2.weight", L, transpose=True),
                "b_down": _stack(sd, "model.layers.{i}.mlp.fc2.bias", L),
            },
            "final_norm": {"scale": np.asarray(sd["model.final_layernorm.weight"], np.float32),
                           "bias": np.asarray(sd["model.final_layernorm.bias"], np.float32)},
            "lm_head": {"kernel": np.asarray(sd["lm_head.weight"], np.float32).T,
                        "bias": np.asarray(sd["lm_head.bias"], np.float32)},
        }
        return p
    if model_type == "gpt2":
        H = cfg.hidden_size
        # Conv1D stores [in, out] — NO transpose; c_attn fuses qkv on out dim
        c_attn = _stack(sd, "transformer.h.{i}.attn.c_attn.weight", L)
        b_attn = _stack(sd, "transformer.h.{i}.attn.c_attn.bias", L)
        p = {
            "embed": {"embedding": np.asarray(sd["transformer.wte.weight"], np.float32)},
            "pos_embed": {"embedding": np.asarray(sd["transformer.wpe.weight"], np.float32)},
            "blocks": {
                "ln1_scale": _stack(sd, "transformer.h.{i}.ln_1.weight", L),
                "ln1_bias": _stack(sd, "transformer.h.{i}.ln_1.bias", L),
                "wq": c_attn[:, :, :H], "wk": c_attn[:, :, H:2 * H], "wv": c_attn[:, :, 2 * H:],
                "bq": b_attn[:, :H], "bk": b_attn[:, H:2 * H], "bv": b_attn[:, 2 * H:],
                "wo": _stack(sd, "transformer.h.{i}.attn.c_proj.weight", L),
                "bo": _stack(sd, "transformer.h.{i}.attn.c_proj.bias", L),
                "ln2_scale": _stack(sd, "transformer.h.{i}.ln_2.weight", L),
                "ln2_bias": _stack(sd, "transformer.h.{i}.ln_2.bias", L),
                "w_up": _stack(sd, "transformer.h.{i}.mlp.c_fc.weight", L),
                "b_up": _stack(sd, "transformer.h.{i}.mlp.c_fc.bias", L),
                "w_down": _stack(sd, "transformer.h.{i}.mlp.c_proj.weight", L),
                "b_down": _stack(sd, "transformer.h.{i}.mlp.c_proj.bias", L),
            },
            "final_norm": {"scale": np.asarray(sd["transformer.ln_f.weight"], np.float32),
                           "bias": np.asarray(sd["transformer.ln_f.bias"], np.float32)},
        }
        return p
    if model_type == "opt":
        base = "model.decoder.layers.{i}."
        p = {
            "embed": {"embedding": np.asarray(sd["model.decoder.embed_tokens.weight"], np.float32)},
            # OPT's learned positions carry a +2 offset (rows 0-1 unused for
            # dense position_ids starting at 0)
            "pos_embed": {"embedding": np.asarray(sd["model.decoder.embed_positions.weight"], np.float32)[2:]},
            "blocks": {
                "ln1_scale": _stack(sd, base + "self_attn_layer_norm.weight", L),
                "ln1_bias": _stack(sd, base + "self_attn_layer_norm.bias", L),
                "wq": _stack(sd, base + "self_attn.q_proj.weight", L, transpose=True),
                "wk": _stack(sd, base + "self_attn.k_proj.weight", L, transpose=True),
                "wv": _stack(sd, base + "self_attn.v_proj.weight", L, transpose=True),
                "bq": _stack(sd, base + "self_attn.q_proj.bias", L),
                "bk": _stack(sd, base + "self_attn.k_proj.bias", L),
                "bv": _stack(sd, base + "self_attn.v_proj.bias", L),
                "wo": _stack(sd, base + "self_attn.out_proj.weight", L, transpose=True),
                "bo": _stack(sd, base + "self_attn.out_proj.bias", L),
                "ln2_scale": _stack(sd, base + "final_layer_norm.weight", L),
                "ln2_bias": _stack(sd, base + "final_layer_norm.bias", L),
                "w_up": _stack(sd, base + "fc1.weight", L, transpose=True),
                "b_up": _stack(sd, base + "fc1.bias", L),
                "w_down": _stack(sd, base + "fc2.weight", L, transpose=True),
                "b_down": _stack(sd, base + "fc2.bias", L),
            },
            "final_norm": {"scale": np.asarray(sd["model.decoder.final_layer_norm.weight"], np.float32),
                           "bias": np.asarray(sd["model.decoder.final_layer_norm.bias"], np.float32)},
        }
        return p
    if model_type == "bloom":
        L_, nh, hd = L, cfg.num_heads, cfg.head_dim
        base = "transformer.h.{i}."
        qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
        for i in range(L_):
            w = np.asarray(sd[base.format(i=i) + "self_attention.query_key_value.weight"], np.float32)
            b = np.asarray(sd[base.format(i=i) + "self_attention.query_key_value.bias"], np.float32)
            q, k, v = _split_fused_qkv(w, nh, hd)
            bq, bk, bv = _split_fused_qkv_bias(b, nh, hd)
            qs.append(q), ks.append(k), vs.append(v)
            bqs.append(bq), bks.append(bk), bvs.append(bv)
        p = {
            "embed": {"embedding": np.asarray(sd["transformer.word_embeddings.weight"], np.float32)},
            "embed_norm": {"scale": np.asarray(sd["transformer.word_embeddings_layernorm.weight"], np.float32),
                           "bias": np.asarray(sd["transformer.word_embeddings_layernorm.bias"], np.float32)},
            "blocks": {
                "ln1_scale": _stack(sd, base + "input_layernorm.weight", L_),
                "ln1_bias": _stack(sd, base + "input_layernorm.bias", L_),
                "wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
                "bq": np.stack(bqs), "bk": np.stack(bks), "bv": np.stack(bvs),
                "wo": _stack(sd, base + "self_attention.dense.weight", L_, transpose=True),
                "bo": _stack(sd, base + "self_attention.dense.bias", L_),
                "ln2_scale": _stack(sd, base + "post_attention_layernorm.weight", L_),
                "ln2_bias": _stack(sd, base + "post_attention_layernorm.bias", L_),
                "w_up": _stack(sd, base + "mlp.dense_h_to_4h.weight", L_, transpose=True),
                "b_up": _stack(sd, base + "mlp.dense_h_to_4h.bias", L_),
                "w_down": _stack(sd, base + "mlp.dense_4h_to_h.weight", L_, transpose=True),
                "b_down": _stack(sd, base + "mlp.dense_4h_to_h.bias", L_),
            },
            "final_norm": {"scale": np.asarray(sd["transformer.ln_f.weight"], np.float32),
                           "bias": np.asarray(sd["transformer.ln_f.bias"], np.float32)},
        }
        return p
    if model_type == "gptj":
        nh, hd, r = cfg.num_heads, cfg.head_dim, cfg.rotary_dim
        base = "transformer.h.{i}."
        Z = np.zeros((L, nh * hd), np.float32)
        p = {
            "embed": {"embedding": np.asarray(sd["transformer.wte.weight"], np.float32)},
            "blocks": {
                "ln1_scale": _stack(sd, base + "ln_1.weight", L),
                "ln1_bias": _stack(sd, base + "ln_1.bias", L),
                # interleaved->half rotary handled by column permutation
                "wq": _interleaved_to_half_perm(
                    _stack(sd, base + "attn.q_proj.weight", L, transpose=True), nh, hd, r),
                "wk": _interleaved_to_half_perm(
                    _stack(sd, base + "attn.k_proj.weight", L, transpose=True), nh, hd, r),
                "wv": _stack(sd, base + "attn.v_proj.weight", L, transpose=True),
                "bq": Z, "bk": Z, "bv": Z,  # GPT-J attention has no biases
                "wo": _stack(sd, base + "attn.out_proj.weight", L, transpose=True),
                "bo": np.zeros((L, cfg.hidden_size), np.float32),
                "w_up": _stack(sd, base + "mlp.fc_in.weight", L, transpose=True),
                "b_up": _stack(sd, base + "mlp.fc_in.bias", L),
                "w_down": _stack(sd, base + "mlp.fc_out.weight", L, transpose=True),
                "b_down": _stack(sd, base + "mlp.fc_out.bias", L),
            },
            "final_norm": {"scale": np.asarray(sd["transformer.ln_f.weight"], np.float32),
                           "bias": np.asarray(sd["transformer.ln_f.bias"], np.float32)},
            "lm_head": {"kernel": np.asarray(sd["lm_head.weight"], np.float32).T,
                        "bias": np.asarray(sd["lm_head.bias"], np.float32)},
        }
        return p
    if model_type == "gpt_neox":
        nh, hd = cfg.num_heads, cfg.head_dim
        base = "gpt_neox.layers.{i}."
        qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
        for i in range(L):
            w = np.asarray(sd[base.format(i=i) + "attention.query_key_value.weight"], np.float32)
            b = np.asarray(sd[base.format(i=i) + "attention.query_key_value.bias"], np.float32)
            q, k, v = _split_fused_qkv(w, nh, hd)
            bq, bk, bv = _split_fused_qkv_bias(b, nh, hd)
            qs.append(q), ks.append(k), vs.append(v)
            bqs.append(bq), bks.append(bk), bvs.append(bv)
        p = {
            "embed": {"embedding": np.asarray(sd["gpt_neox.embed_in.weight"], np.float32)},
            "blocks": {
                "ln1_scale": _stack(sd, base + "input_layernorm.weight", L),
                "ln1_bias": _stack(sd, base + "input_layernorm.bias", L),
                "wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
                "bq": np.stack(bqs), "bk": np.stack(bks), "bv": np.stack(bvs),
                "wo": _stack(sd, base + "attention.dense.weight", L, transpose=True),
                "bo": _stack(sd, base + "attention.dense.bias", L),
                "ln2_scale": _stack(sd, base + "post_attention_layernorm.weight", L),
                "ln2_bias": _stack(sd, base + "post_attention_layernorm.bias", L),
                "w_up": _stack(sd, base + "mlp.dense_h_to_4h.weight", L, transpose=True),
                "b_up": _stack(sd, base + "mlp.dense_h_to_4h.bias", L),
                "w_down": _stack(sd, base + "mlp.dense_4h_to_h.weight", L, transpose=True),
                "b_down": _stack(sd, base + "mlp.dense_4h_to_h.bias", L),
            },
            "final_norm": {"scale": np.asarray(sd["gpt_neox.final_layer_norm.weight"], np.float32),
                           "bias": np.asarray(sd["gpt_neox.final_layer_norm.bias"], np.float32)},
        }
        if not cfg.tie_embeddings:
            p["lm_head"] = {"kernel": np.asarray(sd["embed_out.weight"], np.float32).T}
        return p
    if model_type == "falcon":
        nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        base = "transformer.h.{i}."
        # new_decoder_architecture (40b/180b) names its two parallel norms
        # ln_attn/ln_mlp; the 7b family has a single input_layernorm
        new_arch = base.format(i=0) + "ln_attn.weight" in sd
        ln1 = "ln_attn" if new_arch else "input_layernorm"
        qs, ks, vs = [], [], []
        for i in range(L):
            w = np.asarray(sd[base.format(i=i) + "self_attention.query_key_value.weight"], np.float32)
            q, k, v = _split_fused_qkv(w, nh, hd, nkv=nkv)
            qs.append(q), ks.append(k), vs.append(v)
        blocks = {
            "ln1_scale": _stack(sd, base + ln1 + ".weight", L),
            "ln1_bias": _stack(sd, base + ln1 + ".bias", L),
            "wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
            "wo": _stack(sd, base + "self_attention.dense.weight", L, transpose=True),
            "w_up": _stack(sd, base + "mlp.dense_h_to_4h.weight", L, transpose=True),
            "w_down": _stack(sd, base + "mlp.dense_4h_to_h.weight", L, transpose=True),
        }
        if new_arch:  # separate MLP-branch norm (shared_ln=False)
            blocks["ln2_scale"] = _stack(sd, base + "ln_mlp.weight", L)
            blocks["ln2_bias"] = _stack(sd, base + "ln_mlp.bias", L)
        p = {
            "embed": {"embedding": np.asarray(sd["transformer.word_embeddings.weight"], np.float32)},
            "blocks": blocks,
            "final_norm": {"scale": np.asarray(sd["transformer.ln_f.weight"], np.float32),
                           "bias": np.asarray(sd["transformer.ln_f.bias"], np.float32)},
        }
        if not cfg.tie_embeddings:
            p["lm_head"] = {"kernel": np.asarray(sd["lm_head.weight"], np.float32).T}
        return p
    raise ValueError(f"unsupported model_type {model_type!r}")


def build_hf_engine(model_name_or_path: str, engine_config=None, dtype=None):
    """HF checkpoint → ready InferenceEngineV2 (reference
    ``engine_factory.build_hf_engine``)."""
    from ....models.transformer import TransformerLM
    from ..engine_v2 import InferenceEngineV2

    ckpt = HuggingFaceCheckpointEngine(model_name_or_path)
    cfg, model_type = transformer_config_from_hf(ckpt.model_config())
    if dtype is not None:
        cfg.dtype = dtype
    params = convert_hf_state_dict(ckpt.state_dict(), cfg, model_type)
    logger.info(f"built {model_type} inference model from {model_name_or_path} "
                f"({cfg.num_layers}L/{cfg.hidden_size}H)")
    return InferenceEngineV2(TransformerLM(cfg), engine_config, params=params)
