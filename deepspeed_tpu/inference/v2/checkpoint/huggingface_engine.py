"""HuggingFace checkpoint loading for inference.

Reference ``inference/v2/checkpoint/huggingface_engine.py`` (the FastGen
checkpoint engine iterating HF weights into the layer containers) +
``engine_factory.build_hf_engine``. Here the containers are the stacked
param pytree of ``models.transformer``: each family's mapping is a
declarative ParamSpec table (``model_implementations/parameter_spec.py`` —
the reference's parameter_base/layer_container_base mechanism) consumed by
one generic converter that stacks per-layer HF tensors into [L, ...] arrays
and transposes torch Linear weights ([out, in]) into our [in, out] einsum
layout. Supported families: llama, mistral, qwen2, phi, gpt2, opt, bloom,
gptj, gpt_neox, falcon.
"""

import json
import os
from typing import Dict, Iterator, Tuple

import numpy as np

from ....utils.logging import logger


class HuggingFaceCheckpointEngine:
    """Iterate (name, np.ndarray) weights from an HF model dir or hub name
    (reference class of the same name: ``parameters()`` iterator)."""

    def __init__(self, model_name_or_path: str, auth_token: str = None):
        self.model_name_or_path = model_name_or_path
        self._sd = None

    def _load(self):
        if self._sd is not None:
            return self._sd
        path = self.model_name_or_path
        sd = {}
        if os.path.isdir(path):
            safes = [f for f in os.listdir(path) if f.endswith(".safetensors")]
            bins = [f for f in os.listdir(path) if f.endswith(".bin")]
            if safes:
                from safetensors import safe_open

                for f in sorted(safes):
                    with safe_open(os.path.join(path, f), framework="np") as fh:
                        for k in fh.keys():
                            sd[k] = fh.get_tensor(k)
            elif bins:
                import torch

                for f in sorted(bins):
                    part = torch.load(os.path.join(path, f), map_location="cpu", weights_only=True)
                    for k, v in part.items():
                        sd[k] = v.float().numpy()
            else:
                raise FileNotFoundError(f"no .safetensors/.bin weights in {path}")
        else:  # hub name → go through transformers
            from transformers import AutoModelForCausalLM

            model = AutoModelForCausalLM.from_pretrained(path)
            sd = {k: v.detach().float().numpy() for k, v in model.state_dict().items()}
        self._sd = sd
        return sd

    def parameters(self) -> Iterator[Tuple[str, np.ndarray]]:
        yield from self._load().items()

    def state_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._load())

    def model_config(self):
        path = self.model_name_or_path
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) else None
        if cfg_file and os.path.isfile(cfg_file):
            with open(cfg_file) as f:
                return json.load(f)
        from transformers import AutoConfig

        return AutoConfig.from_pretrained(path).to_dict()


# ---------------------------------------------------------------------------
# config mapping
# ---------------------------------------------------------------------------
def transformer_config_from_hf(hf_cfg: dict):
    """HF config.json → TransformerConfig (the per-family policy lookup,
    reference ``engine_factory.py`` model_type dispatch)."""
    from ....models.transformer import TransformerConfig

    mt = hf_cfg.get("model_type", "llama")
    if mt in ("llama", "mistral", "qwen2"):
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            num_kv_heads=hf_cfg.get("num_key_value_heads", hf_cfg["num_attention_heads"]),
            intermediate_size=hf_cfg["intermediate_size"],
            max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="rmsnorm", positions="rotary", mlp="swiglu", use_bias=False,
            qkv_bias=(mt == "qwen2"),  # qwen2: biased qkv only
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", False)),
            rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
            norm_eps=float(hf_cfg.get("rms_norm_eps", 1e-5))), mt
    if mt == "phi":
        d = hf_cfg["hidden_size"] // hf_cfg["num_attention_heads"]
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            intermediate_size=hf_cfg["intermediate_size"],
            max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
            parallel_residual=True, shared_ln=True,
            rotary_dim=int(round(hf_cfg.get("partial_rotary_factor", 0.5) * d)),
            tie_embeddings=False,
            rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
            norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-5))), mt
    if mt == "gpt2":
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["n_embd"],
            num_layers=hf_cfg["n_layer"], num_heads=hf_cfg["n_head"],
            intermediate_size=4 * hf_cfg["n_embd"], max_seq_len=hf_cfg.get("n_positions", 1024),
            norm="layernorm", positions="learned", mlp="gelu", use_bias=True,
            tie_embeddings=True, norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    if mt == "opt":
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            intermediate_size=hf_cfg["ffn_dim"], max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="layernorm", positions="learned", mlp="relu", use_bias=True,
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", True)), norm_eps=1e-5), mt
    if mt == "bloom":
        H = hf_cfg.get("hidden_size", hf_cfg.get("n_embed"))
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=H,
            num_layers=hf_cfg.get("num_hidden_layers", hf_cfg.get("n_layer")),
            num_heads=hf_cfg.get("num_attention_heads", hf_cfg.get("n_head")),
            intermediate_size=4 * H, max_seq_len=2048,
            norm="layernorm", positions="alibi", mlp="gelu", use_bias=True,
            tie_embeddings=True, embed_layernorm=True,
            norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    if mt == "gptj":
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["n_embd"],
            num_layers=hf_cfg["n_layer"], num_heads=hf_cfg["n_head"],
            intermediate_size=hf_cfg.get("n_inner") or 4 * hf_cfg["n_embd"],
            max_seq_len=hf_cfg.get("n_positions", 2048),
            norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
            tie_embeddings=False, parallel_residual=True, shared_ln=True,
            rotary_dim=hf_cfg.get("rotary_dim") or hf_cfg["n_embd"] // hf_cfg["n_head"],
            norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    if mt == "gpt_neox":
        hd = hf_cfg["hidden_size"] // hf_cfg["num_attention_heads"]
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg["num_hidden_layers"], num_heads=hf_cfg["num_attention_heads"],
            intermediate_size=hf_cfg["intermediate_size"],
            max_seq_len=hf_cfg.get("max_position_embeddings", 2048),
            norm="layernorm", positions="rotary", mlp="gelu", use_bias=True,
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", False)),
            parallel_residual=bool(hf_cfg.get("use_parallel_residual", True)), shared_ln=False,
            rotary_dim=max(2, int(hd * float(hf_cfg.get("rotary_pct", 0.25))) // 2 * 2),
            rope_theta=float(hf_cfg.get("rotary_emb_base", 10000.0)),
            norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-5))), mt
    if mt == "falcon":
        nh = hf_cfg.get("num_attention_heads", hf_cfg.get("n_head"))
        new_arch = bool(hf_cfg.get("new_decoder_architecture", False))
        # HF semantics: num_kv_heads applies whenever new_decoder_architecture
        # or not multi_query; only legacy multi_query models force MQA (1)
        if new_arch or not hf_cfg.get("multi_query", True):
            nkv = hf_cfg.get("num_kv_heads") or hf_cfg.get("n_head_kv") or nh
        else:
            nkv = 1
        if hf_cfg.get("alibi", False):
            raise ValueError("falcon checkpoints with alibi=true (falcon-rw family) are not "
                             "supported yet: the converter maps falcon to rotary positions")
        if hf_cfg.get("bias", False):
            raise ValueError("falcon checkpoints with bias=true are not supported yet: the "
                             "converter does not extract attention/MLP biases for falcon")
        if not hf_cfg.get("parallel_attn", True) and not new_arch:
            raise ValueError("sequential falcon (parallel_attn=false) is not supported yet: the "
                             "converter emits no post-attention norm for that layout")
        return TransformerConfig(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            num_layers=hf_cfg.get("num_hidden_layers", hf_cfg.get("n_layer")),
            num_heads=nh, num_kv_heads=nkv,
            intermediate_size=4 * hf_cfg["hidden_size"], max_seq_len=2048,
            norm="layernorm", positions="rotary", mlp="gelu",
            use_bias=bool(hf_cfg.get("bias", False)),
            tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", True)),
            parallel_residual=bool(hf_cfg.get("parallel_attn", True)) or new_arch,
            shared_ln=bool(hf_cfg.get("parallel_attn", True)) and not new_arch,
            norm_eps=float(hf_cfg.get("layer_norm_epsilon", 1e-5))), mt
    raise ValueError(f"unsupported model_type {mt!r}; supported: llama, mistral, qwen2, phi, gpt2, opt, "
                     "bloom, gptj, gpt_neox, falcon")


# ---------------------------------------------------------------------------
# weight conversion — declarative since r5: each family is a ParamSpec table
# in model_implementations/parameter_spec.py (the reference's
# parameter_base.py / layer_container_base.py mechanism); one generic
# convert_with_spec replaces the former 11 hand-written converters
# ---------------------------------------------------------------------------
def convert_hf_state_dict(sd: Dict[str, np.ndarray], cfg, model_type: str):
    """HF state dict → stacked param pytree (numpy, fp32)."""
    from ..model_implementations.parameter_spec import FAMILY_SPECS, convert_with_spec

    spec = FAMILY_SPECS.get(model_type)
    if spec is None:
        raise ValueError(f"unsupported model_type {model_type!r}; supported: "
                         f"{sorted(FAMILY_SPECS)}")
    return convert_with_spec(sd, cfg, spec)


def build_hf_engine(model_name_or_path: str, engine_config=None, dtype=None):
    """HF checkpoint → ready InferenceEngineV2 (reference
    ``engine_factory.build_hf_engine``)."""
    from ....models.transformer import TransformerLM
    from ..engine_v2 import InferenceEngineV2

    ckpt = HuggingFaceCheckpointEngine(model_name_or_path)
    cfg, model_type = transformer_config_from_hf(ckpt.model_config())
    if dtype is not None:
        cfg.dtype = dtype
    params = convert_hf_state_dict(ckpt.state_dict(), cfg, model_type)
    logger.info(f"built {model_type} inference model from {model_name_or_path} "
                f"({cfg.num_layers}L/{cfg.hidden_size}H)")
    return InferenceEngineV2(TransformerLM(cfg), engine_config, params=params)
