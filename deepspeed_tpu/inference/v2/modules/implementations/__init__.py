"""Concrete module implementations (reference
``inference/v2/modules/implementations/``). Importing this package registers
every implementation with its interface's registry."""

from .attention import DenseBlockedAttention, PallasPagedAttention
from .embedding import RaggedEmbedding
from .linear import BlasFPLinear, Int8BlockwiseLinear
from .moe import GroupedGemmMoE, TopKGatedMoE
from .norm import FusedPreNorm
from .unembed import LastTokenUnembed

__all__ = [
    "DenseBlockedAttention", "PallasPagedAttention", "RaggedEmbedding",
    "BlasFPLinear", "Int8BlockwiseLinear", "TopKGatedMoE", "GroupedGemmMoE", "FusedPreNorm",
    "LastTokenUnembed",
]
