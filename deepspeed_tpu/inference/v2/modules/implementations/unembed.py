"""Unembed implementation (reference
``implementations/unembed/ragged_unembed.py``): final norm → last-token
gather (``logits_gather``: only each sequence's last token is projected to
the vocabulary) → tied/untied head → fp32 logits."""

import jax.numpy as jnp

from .....models.transformer import _norm
from ..configs import DSUnembedConfig
from ..interfaces import DSUnembedBase, DSUnembedRegistry


@DSUnembedRegistry.register_module
class LastTokenUnembed(DSUnembedBase):

    @staticmethod
    def name() -> str:
        return "last_token_unembed"

    @staticmethod
    def supports_config(config: DSUnembedConfig) -> bool:
        return True

    def __call__(self, params, hidden, last_idx):
        cfg = self.config
        h = _norm(hidden, params["final_norm"]["scale"], params["final_norm"].get("bias"),
                  cfg.norm, cfg.norm_eps)
        h_last = h[last_idx]  # [S, H]
        if cfg.tie_embeddings:
            logits = jnp.einsum("sh,vh->sv", h_last, params["embed"]["embedding"].astype(cfg.dtype))
        else:
            logits = jnp.einsum("sh,hv->sv", h_last, params["lm_head"]["kernel"].astype(cfg.dtype))
            if "bias" in params["lm_head"]:
                logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
        return logits.astype(jnp.float32)
