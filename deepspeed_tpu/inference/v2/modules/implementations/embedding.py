"""Embedding implementation (reference
``implementations/embedding/ragged_embedding.py``): token gather + optional
learned-position add + optional embed layernorm over the flat ragged batch."""

from .....models.transformer import _norm
from ..configs import DSEmbeddingsConfig
from ..interfaces import DSEmbeddingBase, DSEmbeddingRegistry


@DSEmbeddingRegistry.register_module
class RaggedEmbedding(DSEmbeddingBase):

    @staticmethod
    def name() -> str:
        return "ragged_embedding"

    @staticmethod
    def supports_config(config: DSEmbeddingsConfig) -> bool:
        return True

    def __call__(self, params, token_ids, pos):
        cfg = self.config
        x = params["embed"]["embedding"].astype(cfg.dtype)[token_ids]
        if cfg.positions == "learned":
            x = x + params["pos_embed"]["embedding"].astype(cfg.dtype)[pos]
        if cfg.embed_layernorm:
            en = params["embed_norm"]
            x = _norm(x, en["scale"], en.get("bias"), cfg.norm, cfg.norm_eps)
        return x
