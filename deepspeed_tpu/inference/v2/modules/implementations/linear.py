"""Linear (gemm) implementations (reference
``implementations/linear/blas_fp_linear.py`` + the quantized variants under
``csrc/quantization/`` exposed through module_inject's ``quantize=True``).

- ``blas_fp_linear``: plain dot in the module's compute dtype; XLA maps it
  onto the MXU.
- ``int8_blockwise_linear``: ``transform_params`` re-stores every block
  weight as int8 + per-output-channel fp32 scales (``QuantizedWeight``);
  the dequant is fused into the dot's operand read so only int8 bytes leave
  HBM — the decode weight stream halves, which is the bandwidth-bound term
  at serving batch sizes.

Both accept either raw arrays or ``QuantizedWeight`` (its ``.astype`` is the
dequant), so a checkpoint quantized elsewhere still serves through
``blas_fp_linear``.
"""

import jax.numpy as jnp

from ..configs import DSLinearConfig
from ..interfaces import DSLinearBase, DSLinearRegistry


def _matmul(x, w, b, dt):
    # w is [in, out] (possibly pre-reshaped by the caller); QuantizedWeight
    # dequantizes inside astype and XLA fuses it into the dot read
    out = jnp.einsum("ti,io->to", x, w.astype(dt))
    if b is not None:
        out = out + b.astype(dt)
    return out


@DSLinearRegistry.register_module
class BlasFPLinear(DSLinearBase):

    @staticmethod
    def name() -> str:
        return "blas_fp_linear"

    @staticmethod
    def supports_config(config: DSLinearConfig) -> bool:
        return True

    def __call__(self, x, w, b=None):
        return _matmul(x, w, b, self.config.dtype)


@DSLinearRegistry.register_module
class Int8BlockwiseLinear(DSLinearBase):

    @staticmethod
    def name() -> str:
        return "int8_blockwise_linear"

    @staticmethod
    def supports_config(config: DSLinearConfig) -> bool:
        return True

    def transform_params(self, params):
        from ....quantization import quantize_params_for_inference

        return quantize_params_for_inference(params)

    def __call__(self, x, w, b=None):
        return _matmul(x, w, b, self.config.dtype)


@DSLinearRegistry.register_module
class Int4BlockwiseLinear(DSLinearBase):
    """INT4 weight-only (reference ``quantize_intX``/mixed_gemm int4 path):
    asymmetric per-output-channel groups packed two nibbles per byte — the
    decode weight stream QUARTERS vs bf16; unpack+dequant fuse into the
    dot's operand read (``QuantizedWeight4.astype``)."""

    @staticmethod
    def name() -> str:
        return "int4_blockwise_linear"

    @staticmethod
    def supports_config(config: DSLinearConfig) -> bool:
        return True

    def transform_params(self, params):
        from ....quantization import quantize_params_for_inference

        return quantize_params_for_inference(params, num_bits=4)

    def __call__(self, x, w, b=None):
        return _matmul(x, w, b, self.config.dtype)
