"""MoE implementation (reference
``implementations/moe/cutlass_multi_gemm_moe.py``).

The reference's CUTLASS multi-gemm gathers each expert's tokens and runs E
variable-size gemms. On TPU, dynamic per-expert token counts are shape-hostile
(XLA wants static shapes), so the serving MoE uses *dense dispatch*: every
token is pushed through every expert as one batched [E]-stacked einsum and
combined with the (renormalized) top-k gate weights. For serving expert
counts (8-64) the batched gemm keeps the MXU saturated and avoids the
gather/scatter latency chain; training-scale EP sharding lives in
``moe/sharded_moe.py``'s capacity-based all-to-all instead.
"""

import jax
import jax.numpy as jnp

from ..configs import DSMoEConfig
from ..interfaces import DSMoEBase, DSMoERegistry


@DSMoERegistry.register_module
class TopKGatedMoE(DSMoEBase):

    @staticmethod
    def name() -> str:
        return "top_k_gated_moe"

    @staticmethod
    def supports_config(config: DSMoEConfig) -> bool:
        return 1 <= config.top_k <= config.n_experts

    def __call__(self, x, gate_w, expert_up, expert_gate, expert_down):
        """x: [T, H]; gate_w: [H, E]; expert_up/expert_gate: [E, H, F]
        (expert_gate may be None for non-glu); expert_down: [E, F, H]."""
        cfg = self.config
        dt = cfg.dtype
        logits = jnp.einsum("th,he->te", x, gate_w.astype(dt)).astype(jnp.float32)
        top_vals, top_idx = jax.lax.top_k(logits, cfg.top_k)  # [T, k]
        weights = jax.nn.softmax(top_vals, axis=-1).astype(dt)
        # dense dispatch: combine weight is nonzero only for the top-k experts
        combine = jnp.zeros(logits.shape, dt).at[
            jnp.arange(logits.shape[0])[:, None], top_idx].set(weights)  # [T, E]

        up = jnp.einsum("th,ehf->etf", x, expert_up.astype(dt))
        if expert_gate is not None:  # swiglu
            g = jnp.einsum("th,ehf->etf", x, expert_gate.astype(dt))
            act = jax.nn.silu(g) * up
        else:
            act = jax.nn.gelu(up)
        out = jnp.einsum("etf,efh->eth", act, expert_down.astype(dt))
        return jnp.einsum("te,eth->th", combine, out)


@DSMoERegistry.register_module
class GroupedGemmMoE(DSMoEBase):
    """Grouped ragged-matmul MoE (reference cutlass_ops moe_gemm analog):
    expert-sorted tokens through the Pallas grouped GEMM
    (``ops/pallas/grouped_matmul.py``) — FFN work scales with the T*k routed
    tokens instead of the dense-dispatch T*E. The large-E serving choice;
    select via ``modules={"moe": "grouped_gemm_moe"}`` or ConfigBundle name."""

    @staticmethod
    def name() -> str:
        return "grouped_gemm_moe"

    @staticmethod
    def supports_config(config) -> bool:
        return 1 <= config.top_k <= config.n_experts

    def __call__(self, x, gate_w, expert_up, expert_gate, expert_down):
        """Same contract as :class:`TopKGatedMoE`."""
        from deepspeed_tpu.moe.grouped import grouped_moe_ffn

        cfg = self.config
        dt = cfg.dtype
        logits = jnp.einsum("th,he->te", x, gate_w.astype(dt)).astype(jnp.float32)
        top_vals, top_idx = jax.lax.top_k(logits, cfg.top_k)
        weights = jax.nn.softmax(top_vals, axis=-1).astype(dt)

        def act(up, gate):
            return jax.nn.silu(gate) * up if gate is not None else jax.nn.gelu(up)

        # routing goes in precomputed (idx, weights) form — no dense [T, E]
        # scatter + re-top-k round trip (the O(T*E) work this path avoids)
        return grouped_moe_ffn(x.astype(dt), None, expert_up, expert_down,
                               top_k=cfg.top_k, wg=expert_gate, activation=act,
                               top_idx=top_idx, top_w=weights)
