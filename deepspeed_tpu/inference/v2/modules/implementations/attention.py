"""Ragged paged-attention implementations (reference
``implementations/attention/dense_blocked_attention.py``).

Two real implementations behind one interface:

- ``dense_blocked_attention``: the gather-based jnp oracle — runs anywhere,
  the numerics reference.
- ``paged_pallas_attention``: the Pallas LUT-prefetch paged kernel — the TPU
  serving path; ``implementation_config={'interpret': True}`` runs the same
  kernel through the Pallas interpreter so CPU CI can cover the kernel's
  program (not its Mosaic lowering).
"""

import numpy as np

from .....models.transformer import alibi_slopes
from .....ops.pallas.paged_attention import _pallas_paged, paged_attention, paged_attention_reference
from ..configs import DSSelfAttentionConfig
from ..interfaces import DSSelfAttentionBase, DSSelfAttentionRegistry


def _alibi(cfg: DSSelfAttentionConfig):
    return alibi_slopes(cfg.num_heads) if cfg.positions == "alibi" else None


@DSSelfAttentionRegistry.register_module
class DenseBlockedAttention(DSSelfAttentionBase):

    @staticmethod
    def name() -> str:
        return "dense_blocked_attention"

    @staticmethod
    def supports_config(config: DSSelfAttentionConfig) -> bool:
        return config.num_heads % max(config.num_kv_heads, 1) == 0

    def __call__(self, q, k_flat, v_flat, tables_l, seq_idx, pos, k_scale=None, v_scale=None,
                 pos_ids=None, mask=None, ctx_pos_ids=None):
        cfg = self.config
        return paged_attention_reference(q, k_flat, v_flat, tables_l, seq_idx, pos,
                                         cfg.block_size, window=cfg.sliding_window,
                                         alibi=_alibi(cfg), k_scale=k_scale, v_scale=v_scale,
                                         pos_ids=pos_ids, mask=mask, ctx_pos_ids=ctx_pos_ids)


@DSSelfAttentionRegistry.register_module
class PallasPagedAttention(DSSelfAttentionBase):

    @staticmethod
    def name() -> str:
        return "paged_pallas_attention"

    @staticmethod
    def supports_config(config: DSSelfAttentionConfig) -> bool:
        # the kernel tiles heads on the 8-lane sublane dim and d on 128 lanes
        return (config.num_heads % max(config.num_kv_heads, 1) == 0
                and config.head_dim % 2 == 0)

    def __call__(self, q, k_flat, v_flat, tables_l, seq_idx, pos, k_scale=None, v_scale=None,
                 pos_ids=None, mask=None, ctx_pos_ids=None):
        cfg = self.config
        if mask is not None:
            # token-tree verification: the Pallas grids know only the causal
            # (+window) mask — the tree's ancestor mask routes the verify
            # forward through the gather oracle. A verify chunk is k+1
            # tokens per sequence, so the dense gather costs one prefill-
            # chunk-sized pass per round, not a per-token hot path.
            return paged_attention_reference(q, k_flat, v_flat, tables_l, seq_idx, pos,
                                             cfg.block_size, window=cfg.sliding_window,
                                             alibi=_alibi(cfg), k_scale=k_scale,
                                             v_scale=v_scale, pos_ids=pos_ids, mask=mask,
                                             ctx_pos_ids=ctx_pos_ids)
        if self.implementation_config.get("interpret", False):
            import jax.numpy as jnp

            al = _alibi(cfg)
            return _pallas_paged(q, k_flat, v_flat, tables_l, seq_idx.astype(jnp.int32),
                                 pos.astype(jnp.int32), block_size=cfg.block_size,
                                 interpret=True, window=cfg.sliding_window,
                                 alibi=tuple(np.asarray(al).tolist()) if al is not None else None,
                                 k_scale=k_scale, v_scale=v_scale)
        # paged_attention itself falls back (loudly) off-TPU / tiny heads
        return paged_attention(q, k_flat, v_flat, tables_l, seq_idx, pos,
                               cfg.block_size, window=cfg.sliding_window, alibi=_alibi(cfg),
                               k_scale=k_scale, v_scale=v_scale)
