"""Pre-norm implementation (reference ``implementations/pre_norm/``):
rmsnorm/layernorm dispatch — XLA fuses it into the adjacent gemm read, so
one implementation covers what the reference ships as CUDA variants."""

from .....models.transformer import _norm
from ..configs import DSNormConfig
from ..interfaces import DSPreNormBase, DSPreNormRegistry


@DSPreNormRegistry.register_module
class FusedPreNorm(DSPreNormBase):

    @staticmethod
    def name() -> str:
        return "fused_pre_norm"

    @staticmethod
    def supports_config(config: DSNormConfig) -> bool:
        return config.norm in ("rmsnorm", "layernorm")

    def __call__(self, x, scale, bias=None):
        return _norm(x, scale, bias, self.config.norm, self.config.norm_eps)
