"""Per-interface module configs (reference ``inference/v2/modules/configs/*``).

Plain dataclasses derived from the model's ``TransformerConfig`` at engine
build (``heuristics.build_modules``); they carry exactly what each module
needs to trace — implementations never reach back into the model config.
"""

from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp

from .ds_module import DSModuleConfig


@dataclass
class DSSelfAttentionConfig(DSModuleConfig):
    """Paged ragged attention over the flat KV pool
    (reference ``configs/attention_configs.py``)."""
    num_heads: int = 0
    num_kv_heads: int = 0
    head_dim: int = 0
    block_size: int = 64
    sliding_window: Optional[int] = None
    positions: str = "rotary"  # 'alibi' adds slope-biased scores
    dtype: Any = jnp.bfloat16


@dataclass
class DSLinearConfig(DSModuleConfig):
    """A single gemm of the layer stack (reference ``configs/linear_config.py``)."""
    dtype: Any = jnp.bfloat16


@dataclass
class DSEmbeddingsConfig(DSModuleConfig):
    """Token (+ learned position) embedding with optional embed-layernorm
    (reference ``configs/embedding_config.py``)."""
    positions: str = "rotary"
    embed_layernorm: bool = False
    norm: str = "layernorm"
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16


@dataclass
class DSUnembedConfig(DSModuleConfig):
    """Final norm + last-token gather + vocabulary projection
    (reference ``configs/unembed_config.py`` — its DSUnembed also folds the
    final norm and gather)."""
    tie_embeddings: bool = False
    norm: str = "rmsnorm"
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16


@dataclass
class DSNormConfig(DSModuleConfig):
    """Pre-attention / pre-MLP normalization (reference ``configs/norm_config.py``)."""
    norm: str = "rmsnorm"  # 'rmsnorm' | 'layernorm'
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16


@dataclass
class DSMoEConfig(DSModuleConfig):
    """Token-level top-k routed expert MLP (reference ``configs/moe_config.py``)."""
    n_experts: int = 1
    top_k: int = 1
    activation: str = "swiglu"
    dtype: Any = jnp.bfloat16
