"""Implementation registries (reference ``inference/v2/modules/module_registry.py:22``).

Each functionality interface owns a registry mapping implementation names to
classes; ``@<Interface>Registry.register_module`` on an implementation class
makes it reachable from a config string without the engine importing it
explicitly. ``instantiate_config`` validates ``supports_config`` before
construction so a bad config fails at engine build, not at trace time.
"""

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Dict, Type

from .ds_module import DSModuleBase, DSModuleConfig


@dataclass
class ConfigBundle:
    """A named implementation choice plus its configs (reference
    ``module_registry.py:13``)."""
    name: str
    config: DSModuleConfig
    implementation_config: Dict[str, Any] = field(default_factory=dict)


class DSModuleRegistryBase(ABC):
    """Tracks the implementations of one functionality interface.

    Subclasses declare ``registry: dict = {}`` (their own class attribute,
    one namespace per interface) and implement ``associated_class``.
    """

    registry: Dict[str, Type[DSModuleBase]]

    @classmethod
    def instantiate_config(cls, config_bundle: ConfigBundle) -> DSModuleBase:
        if config_bundle.name not in cls.registry:
            raise KeyError(f"Unknown DSModule: {config_bundle.name!r}; "
                           f"known: {sorted(cls.registry)}")
        target = cls.registry[config_bundle.name]
        if not target.supports_config(config_bundle.config):
            raise ValueError(f"Config {config_bundle.config} is not supported by {target.__name__}")
        return target(config_bundle.config, config_bundle.implementation_config)

    @staticmethod
    @abstractmethod
    def associated_class() -> Type[DSModuleBase]:
        """The interface class whose implementations this registry tracks."""

    @classmethod
    def register_module(cls, child_class):
        if not issubclass(child_class, cls.associated_class()):
            raise TypeError(f"Can only register subclasses of "
                            f"{cls.associated_class().__name__}; got {child_class.__name__}")
        cls.registry[child_class.name()] = child_class
        return child_class
