"""DSModule base — the unit of FastGen extensibility.

Analog of the reference ``inference/v2/modules/ds_module.py:19``
(``DSModuleBase``: ``name()`` / ``config_class()`` / ``supports_config()``),
re-designed for JAX: a module is a lightweight *host-side* object built once
at engine construction (outside ``jit``) whose ``__call__`` is pure traced
code. Implementations therefore carry no parameters of their own — params
stay in the engine's pytree and flow through the call — and swapping an
implementation never changes the compiled program's signature, only its body.
"""

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Any, Dict, Optional, Type


@dataclass
class DSModuleConfig:
    """Base class for per-interface module configs (reference
    ``ds_module.py:14``). Subclasses are plain dataclasses: everything a
    module needs to trace its forward must be here or in the
    ``implementation_config`` dict — never read from globals at trace time."""


class DSModuleBase(ABC):
    """Base class for all inference modules. Only abstract functionality
    interfaces (attention / linear / embedding / ...) inherit directly;
    concrete implementations inherit from those interfaces and are looked up
    by ``name()`` through their interface's registry."""

    @staticmethod
    @abstractmethod
    def name() -> str:
        """Memorable, human-readable key used in inference configurations."""

    @staticmethod
    @abstractmethod
    def config_class() -> Type[DSModuleConfig]:
        """The config dataclass this interface consumes."""

    @staticmethod
    @abstractmethod
    def supports_config(config: DSModuleConfig) -> bool:
        """Whether this implementation can be instantiated for ``config``
        (static feasibility only — device availability is the heuristics
        layer's concern)."""

    def __init__(self, config: DSModuleConfig,
                 implementation_config: Optional[Dict[str, Any]] = None) -> None:
        self._config = config
        self._implementation_config = dict(implementation_config or {})

    @property
    def config(self):
        return self._config

    @property
    def implementation_config(self) -> Dict[str, Any]:
        return self._implementation_config

    def transform_params(self, params):
        """Optional one-time parameter-layout transform applied at engine
        build (reference's ``transform_param`` hooks on the module
        interfaces). Default: identity."""
        return params
