"""Functionality interfaces + their registries.

Analog of the reference ``inference/v2/modules/interfaces/`` package
(``attention_base.py``, ``linear_base.py``, ``embedding_base.py``,
``unembed_base.py``, ``pre_norm_base.py``, ``moe_base.py``) collapsed into
one module: each interface fixes the traced call signature its
implementations must honor, so the ragged forward can swap implementations
without re-plumbing.
"""

from abc import abstractmethod
from typing import Type

from .configs import (DSEmbeddingsConfig, DSLinearConfig, DSMoEConfig, DSNormConfig,
                      DSSelfAttentionConfig, DSUnembedConfig)
from .ds_module import DSModuleBase, DSModuleConfig
from .module_registry import DSModuleRegistryBase


class DSSelfAttentionBase(DSModuleBase):
    """Ragged paged attention (reference ``interfaces/attention_base.py``).

    ``__call__(q, k_flat, v_flat, tables_l, seq_idx, pos, k_scale=None,
    v_scale=None, pos_ids=None, mask=None)`` with q: [T, nq, d];
    k_flat/v_flat: flat layer-offset KV pool views [(L*NB*bs), nkv, d];
    tables_l: [S, max_blocks] block tables already offset to layer l;
    seq_idx/pos: [T]; k_scale/v_scale: int8-KV dequant factors
    [nkv, (L*NB*bs)] (None = full-precision pools). ``pos_ids``: logical
    positions for rotary/alibi when they differ from the KV slot positions
    (token-tree verification assigns tree nodes distinct KV slots but
    depth-based logical positions); ``mask``: explicit [T, C] visibility
    (C = table capacity in tokens) REPLACING the causal mask — the tree
    attention mask. Returns context [T, nq, d].
    """

    @staticmethod
    def config_class() -> Type[DSModuleConfig]:
        return DSSelfAttentionConfig

    @abstractmethod
    def __call__(self, q, k_flat, v_flat, tables_l, seq_idx, pos, k_scale=None, v_scale=None,
                 pos_ids=None, mask=None, ctx_pos_ids=None):
        ...


class DSSelfAttentionRegistry(DSModuleRegistryBase):
    registry = {}

    @staticmethod
    def associated_class():
        return DSSelfAttentionBase


class DSLinearBase(DSModuleBase):
    """One gemm: ``__call__(x, w, b=None)`` → ``x @ w (+ b)`` with the
    module's compute dtype (reference ``interfaces/linear_base.py``).
    ``transform_params`` may re-lay-out weights (e.g. int8 quantization)."""

    @staticmethod
    def config_class() -> Type[DSModuleConfig]:
        return DSLinearConfig

    @abstractmethod
    def __call__(self, x, w, b=None):
        ...


class DSLinearRegistry(DSModuleRegistryBase):
    registry = {}

    @staticmethod
    def associated_class():
        return DSLinearBase


class DSEmbeddingBase(DSModuleBase):
    """``__call__(params, token_ids, pos)`` → hidden [T, H]
    (reference ``interfaces/embedding_base.py``)."""

    @staticmethod
    def config_class() -> Type[DSModuleConfig]:
        return DSEmbeddingsConfig

    @abstractmethod
    def __call__(self, params, token_ids, pos):
        ...


class DSEmbeddingRegistry(DSModuleRegistryBase):
    registry = {}

    @staticmethod
    def associated_class():
        return DSEmbeddingBase


class DSUnembedBase(DSModuleBase):
    """``__call__(params, hidden, last_idx)`` → fp32 logits [S, V]: final
    norm, last-token gather, vocab projection
    (reference ``interfaces/unembed_base.py``)."""

    @staticmethod
    def config_class() -> Type[DSModuleConfig]:
        return DSUnembedConfig

    @abstractmethod
    def __call__(self, params, hidden, last_idx):
        ...


class DSUnembedRegistry(DSModuleRegistryBase):
    registry = {}

    @staticmethod
    def associated_class():
        return DSUnembedBase


class DSPreNormBase(DSModuleBase):
    """``__call__(x, scale, bias=None)`` → normalized x
    (reference ``interfaces/pre_norm_base.py``)."""

    @staticmethod
    def config_class() -> Type[DSModuleConfig]:
        return DSNormConfig

    @abstractmethod
    def __call__(self, x, scale, bias=None):
        ...


class DSPreNormRegistry(DSModuleRegistryBase):
    registry = {}

    @staticmethod
    def associated_class():
        return DSPreNormBase


class DSMoEBase(DSModuleBase):
    """``__call__(x, gate_w, expert_up, expert_gate, expert_down)`` → [T, H]
    token-level top-k routed expert MLP (reference ``interfaces/moe_base.py``)."""

    @staticmethod
    def config_class() -> Type[DSModuleConfig]:
        return DSMoEConfig

    @abstractmethod
    def __call__(self, x, gate_w, expert_up, expert_gate, expert_down):
        ...


class DSMoERegistry(DSModuleRegistryBase):
    registry = {}

    @staticmethod
    def associated_class():
        return DSMoEBase
