"""Config → implementation selection (reference
``inference/v2/modules/heuristics.py``).

``build_modules`` is the single point where an engine decides which concrete
implementation serves each functionality slot. Every slot accepts either
``"auto"`` (policy below), an implementation name, or a
``{"name": ..., "implementation_config": {...}}`` dict; the chosen bundle
goes through the interface registry so third-party implementations
registered with ``@<Interface>Registry.register_module`` are selectable by
config string alone.

Auto policy:
- attention: the Pallas paged kernel when the engine resolved
  ``use_pallas_kernels`` to true (TPU), else the dense gather oracle;
- linear: int8 blockwise when the engine asks for weight quantization
  (decode is weight-stream-bound), else the plain-dtype gemm;
- embedding / unembed / norm: the single TPU implementation each (XLA fuses
  what the reference ships as kernel variants).
"""

from typing import Union

from .configs import (DSEmbeddingsConfig, DSLinearConfig, DSNormConfig,
                      DSSelfAttentionConfig, DSUnembedConfig)
from .interfaces import (DSEmbeddingRegistry, DSLinearRegistry, DSPreNormRegistry,
                         DSSelfAttentionRegistry, DSUnembedRegistry)
from .module_registry import ConfigBundle
from . import implementations  # noqa: F401 — populates the registries


def _bundle(choice: Union[str, dict], default_name: str, config) -> ConfigBundle:
    if isinstance(choice, dict):
        return ConfigBundle(name=choice.get("name", default_name), config=config,
                            implementation_config=choice.get("implementation_config", {}))
    name = default_name if choice in (None, "auto") else choice
    return ConfigBundle(name=name, config=config)


def instantiate_attention(attention_config: DSSelfAttentionConfig, engine_config,
                          use_pallas: bool = False):
    choice = getattr(engine_config.modules, "attention", "auto")
    default = "paged_pallas_attention" if use_pallas else "dense_blocked_attention"
    return DSSelfAttentionRegistry.instantiate_config(_bundle(choice, default, attention_config))


def instantiate_linear(linear_config: DSLinearConfig, engine_config):
    choice = getattr(engine_config.modules, "linear", "auto")
    qw = getattr(engine_config, "quantize_weights", False)
    # quantize_weights: False | True (-> int8) | 4 | 8
    default = ("int4_blockwise_linear" if qw == 4
               else "int8_blockwise_linear" if qw
               else "blas_fp_linear")
    return DSLinearRegistry.instantiate_config(_bundle(choice, default, linear_config))


def instantiate_embed(embed_config: DSEmbeddingsConfig, engine_config):
    choice = getattr(engine_config.modules, "embedding", "auto")
    return DSEmbeddingRegistry.instantiate_config(_bundle(choice, "ragged_embedding", embed_config))


def instantiate_unembed(unembed_config: DSUnembedConfig, engine_config):
    choice = getattr(engine_config.modules, "unembed", "auto")
    return DSUnembedRegistry.instantiate_config(_bundle(choice, "last_token_unembed", unembed_config))


def instantiate_pre_norm(norm_config: DSNormConfig, engine_config):
    choice = getattr(engine_config.modules, "norm", "auto")
    return DSPreNormRegistry.instantiate_config(_bundle(choice, "fused_pre_norm", norm_config))


def build_modules(model_config, engine_config, use_pallas: bool = False) -> dict:
    """Derive every slot's config from the model config and instantiate the
    full module set the ragged forward consumes."""
    mc = model_config
    dt = mc.dtype
    attn = DSSelfAttentionConfig(
        num_heads=mc.num_heads, num_kv_heads=mc.num_kv_heads, head_dim=mc.head_dim,
        block_size=engine_config.kv_block_size, sliding_window=mc.sliding_window,
        positions=mc.positions, dtype=dt)
    return {
        "attention": instantiate_attention(attn, engine_config, use_pallas=use_pallas),
        "linear": instantiate_linear(DSLinearConfig(dtype=dt), engine_config),
        "embedding": instantiate_embed(DSEmbeddingsConfig(
            positions=mc.positions, embed_layernorm=mc.embed_layernorm, norm=mc.norm,
            norm_eps=mc.norm_eps, dtype=dt), engine_config),
        "unembed": instantiate_unembed(DSUnembedConfig(
            tie_embeddings=mc.tie_embeddings, norm=mc.norm, norm_eps=mc.norm_eps,
            dtype=dt), engine_config),
        "norm": instantiate_pre_norm(DSNormConfig(norm=mc.norm, norm_eps=mc.norm_eps,
                                                  dtype=dt), engine_config),
    }
