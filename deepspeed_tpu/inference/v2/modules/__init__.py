"""Pluggable inference module layer (reference
``deepspeed/inference/v2/modules/`` — ``ds_module.py`` /
``module_registry.py`` / ``heuristics.py`` / ``interfaces/`` /
``implementations/``): the config→implementation selection point where an
alternative attention/linear/embedding/unembed/MoE kernel can be swapped
per-op without touching the engine."""

from .configs import (DSEmbeddingsConfig, DSLinearConfig, DSMoEConfig, DSNormConfig,
                      DSSelfAttentionConfig, DSUnembedConfig)
from .ds_module import DSModuleBase, DSModuleConfig
from .heuristics import (build_modules, instantiate_attention, instantiate_embed,
                         instantiate_linear, instantiate_pre_norm, instantiate_unembed)
from .interfaces import (DSEmbeddingBase, DSEmbeddingRegistry, DSLinearBase, DSLinearRegistry,
                         DSMoEBase, DSMoERegistry, DSPreNormBase, DSPreNormRegistry,
                         DSSelfAttentionBase, DSSelfAttentionRegistry, DSUnembedBase,
                         DSUnembedRegistry)
from .module_registry import ConfigBundle, DSModuleRegistryBase

__all__ = [
    "DSModuleBase", "DSModuleConfig", "ConfigBundle", "DSModuleRegistryBase",
    "DSSelfAttentionConfig", "DSLinearConfig", "DSEmbeddingsConfig", "DSUnembedConfig",
    "DSNormConfig", "DSMoEConfig",
    "DSSelfAttentionBase", "DSSelfAttentionRegistry", "DSLinearBase", "DSLinearRegistry",
    "DSEmbeddingBase", "DSEmbeddingRegistry", "DSUnembedBase", "DSUnembedRegistry",
    "DSPreNormBase", "DSPreNormRegistry", "DSMoEBase", "DSMoERegistry",
    "build_modules", "instantiate_attention", "instantiate_linear", "instantiate_embed",
    "instantiate_unembed", "instantiate_pre_norm",
]
