"""Speculative decoding for the ragged serving plane.

Decode is strictly sequential (one argmax token fed back per step) and
dominates per-user serving cost. This package drafts K candidate tokens
cheaply, verifies them in ONE ragged forward (a multi-token chunk on an
in-decode sequence — the packed-batch path already supports ragged chunk
sizes), and commits the longest draft prefix the target model itself would
have produced, rolling the rejected tail back through
``DSStateManager.rollback_to`` (the paged KV layout makes rollback a
refcount-aware tail release, never a copy).

Two interchangeable drafters behind one :class:`Drafter` protocol:

* :class:`NgramDrafter` — prompt-lookup / self-speculation: match the
  suffix n-gram of the generated stream against the sequence's OWN history
  and propose the continuation. No second model; a pure win on the
  shared-prefix / repetitive workloads the prefix cache already targets.
* :class:`DraftModelDrafter` — a small same-tokenizer member of the model
  family running on its own :class:`InferenceEngineV2` (its own small KV
  pool), kept in sync with the target stream via the SAME rollback helper.

Greedy parity is unconditional by construction: a draft token is accepted
only when it EQUALS the target model's own argmax at that position, so the
committed stream is bit-identical to non-speculative greedy decoding
regardless of what the drafter proposes (asserted for both drafters in
``tests/test_speculative.py``).
"""

from .drafter import Drafter, build_drafter
from .ngram import NgramDrafter
from .draft_model import DraftModelDrafter

__all__ = ["Drafter", "build_drafter", "NgramDrafter", "DraftModelDrafter"]
