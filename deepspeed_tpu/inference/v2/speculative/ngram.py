"""Prompt-lookup / n-gram self-speculation (no second model).

The drafting signal is the sequence's OWN token stream: if the suffix
n-gram of (prompt + generated) occurred earlier, propose the tokens that
followed it last time. Structured serving traffic is full of such repeats —
shared system prompts quoted back, JSON/code templates, multi-turn
histories, and the repetition loops greedy decoding itself falls into — so
acceptance is high exactly on the workloads the prefix cache already
targets, and drafting costs one numpy scan per sequence per round.
"""

import numpy as np

from .drafter import Drafter


class NgramDrafter(Drafter):
    """``max_ngram`` down to ``min_match``: longer suffix matches are tried
    first (they are more specific, so their continuations are accepted more
    often); the MOST RECENT earlier occurrence wins (locality: the stream's
    current loop beats a stale one). ``max_history`` bounds the scan window
    (0 = the whole stream)."""

    name = "ngram"

    def __init__(self, min_match: int = 2, max_ngram: int = 4, max_history: int = 0):
        if min_match < 1:
            raise ValueError(f"min_match must be >= 1, got {min_match}")
        if max_ngram < min_match:
            raise ValueError(f"max_ngram {max_ngram} < min_match {min_match}")
        self.min_match = int(min_match)
        self.max_ngram = int(max_ngram)
        self.max_history = int(max_history)

    def draft(self, uid: int, context: np.ndarray, k: int) -> np.ndarray:
        branches = self.draft_branches(uid, context, k, 1)
        return branches[0] if branches else np.empty(0, np.int32)

    def draft_branches(self, uid: int, context: np.ndarray, k: int, width: int):
        """Top-``width`` DISTINCT continuations as tree branches: longer
        suffix matches first (more specific), most-recent occurrence first
        within a match length (locality), duplicates collapsed — branch 0
        is exactly what :meth:`draft` proposed before trees existed, so
        width=1 keeps the PR 9 drafting stream bit-identical. On the
        low-accept workloads a single guess covers one hypothesis; the
        verify forward prices extra branches at k tokens each, and any ONE
        of them matching lifts the round's acceptance."""
        ctx = np.asarray(context, np.int32).reshape(-1)
        if self.max_history and ctx.size > self.max_history:
            ctx = ctx[-self.max_history:]
        m = ctx.size
        # haystack excludes the final token so the suffix can never match
        # itself (an identity match would propose the suffix again with no
        # new information)
        hay = ctx[:m - 1]
        out, seen = [], set()
        for n in range(min(self.max_ngram, m - 1), self.min_match - 1, -1):
            if hay.size < n:
                continue
            pat = ctx[m - n:]
            windows = np.lib.stride_tricks.sliding_window_view(hay, n)
            hits = np.nonzero((windows == pat).all(axis=1))[0]
            # a hit at i proposes ctx[i+n : i+n+k]; it must have at least
            # one continuation token inside the stream
            hits = hits[hits + n < m]
            for i in hits[::-1]:
                cand = ctx[int(i) + n:int(i) + n + k].copy()
                key = cand.tobytes()
                if key in seen:
                    continue
                seen.add(key)
                out.append(cand)
                if len(out) >= width:
                    return out
        return out
