"""Draft-model speculation: a small same-family model proposes K tokens.

The drafter owns a second, much smaller :class:`InferenceEngineV2` (its own
small KV pool) whose sequences MIRROR the target's committed streams. Each
round it (1) re-syncs a mirror to the target's context — longest common
prefix, then ``DSStateManager.rollback_to`` rewinds any rejected draft tail
out of the mirror's KV (the same helper the target's verifier uses), then a
catch-up prefill chunk for newly committed tokens — and (2) runs the draft
model's own multi-step greedy decode scan (``engine.decode``) to propose K
continuation tokens in ONE compiled call.

Any failure (draft pool exhausted, context overflow) degrades to an empty
draft for that request — the drafter is policy only, so the target stream
is never at risk.
"""

from typing import Dict, Iterable, List, Tuple

import numpy as np

from .drafter import Drafter


class DraftModelDrafter(Drafter):

    name = "draft_model"

    def __init__(self, draft_engine):
        self.engine = draft_engine
        # uid -> token ids materialized in the mirror sequence's KV (the
        # draft-side analog of DSSequenceDescriptor.token_history)
        self._hist: Dict[int, List[int]] = {}

    def draft_many(self, items: Iterable[Tuple[int, np.ndarray]], k: int) -> Dict[int, np.ndarray]:
        eng = self.engine
        sm = eng.state_manager
        out: Dict[int, np.ndarray] = {}
        ready = []          # (uid, context) mirrors synced and ready to decode
        catchup_u, catchup_c = [], []
        for uid, ctx in items:
            ctx = np.asarray(ctx, np.int32).reshape(-1)
            m = ctx.size
            if m < 2 or m - 1 + k > eng.max_context:
                out[uid] = np.empty(0, np.int32)
                continue
            try:
                hist = self._hist.setdefault(uid, [])
                seq = sm.get_sequence(uid)
                if seq is None and hist:
                    hist.clear()  # mirror lost (e.g. prior failure reset)
                # longest common prefix of the mirror with the target's
                # committed stream; everything past it is rejected-draft
                # tail that rollback_to rewinds out of the mirror's KV
                lim = min(len(hist), m - 1)
                neq = np.nonzero(np.asarray(hist[:lim], np.int32) != ctx[:lim])[0]
                p = int(neq[0]) if neq.size else lim
                if seq is not None and seq.seen_tokens > p:
                    sm.rollback_to(seq, p)
                del hist[p:]
                if p < m - 1:  # catch-up prefill: newly committed tokens
                    catchup_u.append(uid)
                    catchup_c.append(ctx[p:m - 1])
                    hist.extend(int(t) for t in ctx[p:m - 1])
                ready.append((uid, ctx))
            except Exception:
                self._reset(uid)
                out[uid] = np.empty(0, np.int32)
        if catchup_u:
            try:
                self._feed_catchup(catchup_u, catchup_c)
            except Exception:
                failed = set(catchup_u)
                for uid in failed:
                    self._reset(uid)
                    out[uid] = np.empty(0, np.int32)
                ready = [(u, c) for u, c in ready if u not in failed]
        if ready:
            uids = [u for u, _ in ready]
            firsts = [np.asarray([c[-1]], np.int32) for _, c in ready]
            try:
                rows = np.asarray(eng.decode(uids, firsts, k))
            except Exception:
                for uid in uids:
                    self._reset(uid)
                    out[uid] = np.empty(0, np.int32)
                return out
            for (uid, ctx), row in zip(ready, rows):
                out[uid] = row.astype(np.int32, copy=True)
                # the decode scan materialized the fed token + k-1 feedbacks
                hist = self._hist[uid]
                hist.append(int(ctx[-1]))
                hist.extend(int(t) for t in row[:k - 1])
        return out

    def _feed_catchup(self, uids, chunks) -> None:
        """Feed the mirrors' catch-up prefill within the draft engine's own
        ragged-batch budget: several mirrors re-syncing at once (or one long
        context) can exceed ``max_ragged_batch_size``, so the feed chunks
        SplitFuse-style across as many ``put`` calls as it takes.
        ``block=False`` throughout — the chunk tokens are already known, so
        the draft logits of a catch-up forward are never fetched."""
        eng = self.engine
        sm = eng.config.state_manager
        budget, max_seqs = sm.max_ragged_batch_size, sm.max_ragged_sequence_count
        pend = [(u, np.asarray(c, np.int32).reshape(-1)) for u, c in zip(uids, chunks)]
        while pend:
            batch_u, batch_c, rest, tokens = [], [], [], 0
            for u, c in pend:
                take = min(c.size, budget - tokens) if len(batch_u) < max_seqs else 0
                if take > 0:
                    batch_u.append(u)
                    batch_c.append(c[:take])
                    tokens += take
                    if take < c.size:
                        rest.append((u, c[take:]))
                else:
                    rest.append((u, c))
            if not batch_u:  # budget 0? cannot happen, but never spin
                raise RuntimeError("draft catch-up cannot make progress")
            eng.put(batch_u, batch_c, sample="greedy", block=False)
            pend = rest

    def finish(self, uid: int) -> None:
        self._reset(uid)

    def _reset(self, uid: int) -> None:
        self._hist.pop(uid, None)
        try:
            self.engine.flush(uid)
        except Exception:
            pass
