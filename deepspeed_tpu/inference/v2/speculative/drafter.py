"""The ``Drafter`` protocol: propose up to K continuation tokens per round.

A drafter is pure POLICY — it never touches the target engine's KV state.
The scheduler asks it for candidates, the engine verifies them in one
ragged forward, and acceptance is decided by the target model's own argmax
(``InferenceEngineV2.speculate_decode``), so a bad drafter can only cost
throughput, never correctness.
"""

from typing import Dict, Iterable, List, Tuple

import numpy as np


class Drafter:
    """Base drafter. Subclasses implement :meth:`draft`; stateful drafters
    (the draft-model path) may also override :meth:`draft_many` to batch
    their own forwards, and :meth:`finish` to drop per-request state.
    Branch-capable drafters (token-tree verification) additionally override
    :meth:`draft_branches` to propose several candidate continuations per
    round — the default wraps the linear draft as a one-branch tree, so
    every existing drafter keeps working unchanged under a tree scheduler."""

    name = "base"

    def draft(self, uid: int, context: np.ndarray, k: int) -> np.ndarray:
        """Up to ``k`` proposed continuation token ids (1-D int32; may be
        empty = nothing to propose this round). ``context`` is the request's
        full committed stream so far (prompt + generated tokens)."""
        raise NotImplementedError

    def draft_many(self, items: Iterable[Tuple[int, np.ndarray]], k: int) -> Dict[int, np.ndarray]:
        """Batched entry the scheduler actually calls: ``{uid: drafts}`` for
        every ``(uid, context)``. Default maps :meth:`draft`."""
        return {uid: self.draft(uid, ctx, k) for uid, ctx in items}

    def draft_branches(self, uid: int, context: np.ndarray, k: int,
                       width: int) -> List[np.ndarray]:
        """Up to ``width`` candidate branches, each up to ``k`` tokens (the
        token tree ``speculate_decode`` verifies in ONE forward — accept =
        deepest branch matching the target's own argmax path). Default:
        the linear draft as a single branch."""
        d = np.asarray(self.draft(uid, context, k), np.int32).reshape(-1)
        return [d] if d.size else []

    def draft_branches_many(self, items: Iterable[Tuple[int, np.ndarray]], k: int,
                            width: int) -> Dict[int, List[np.ndarray]]:
        """Batched branch drafting. A drafter WITHOUT a branch-capable
        :meth:`draft_branches` override routes through its own (possibly
        batched) :meth:`draft_many`, so the draft-model path keeps its one
        multi-sequence decode scan per round instead of degrading to
        per-request forwards."""
        if width <= 1 or type(self).draft_branches is Drafter.draft_branches:
            out = {}
            for uid, d in self.draft_many(items, k).items():
                d = np.asarray(d, np.int32).reshape(-1)
                out[uid] = [d] if d.size else []
            return out
        return {uid: self.draft_branches(uid, ctx, k, width) for uid, ctx in items}

    def finish(self, uid: int) -> None:
        """The request is done (finished or cancelled) — release any
        per-request state. Must tolerate unknown uids."""


def build_drafter(cfg) -> Drafter:
    """Resolve a ``ragged.speculative`` config block into a drafter."""
    from .draft_model import DraftModelDrafter
    from .ngram import NgramDrafter

    if cfg.mode == "ngram":
        return NgramDrafter(min_match=cfg.min_match, max_ngram=cfg.max_ngram,
                            max_history=cfg.max_history)
    if cfg.mode == "draft_model":
        if cfg.draft_engine is None:
            raise ValueError("speculative.mode='draft_model' requires speculative.draft_engine "
                             "(a small InferenceEngineV2 sharing the target's tokenizer)")
        return DraftModelDrafter(cfg.draft_engine)
    raise ValueError(f"unknown speculative mode {cfg.mode!r}: 'off' | 'ngram' | 'draft_model'")
