"""The ``Drafter`` protocol: propose up to K continuation tokens per round.

A drafter is pure POLICY — it never touches the target engine's KV state.
The scheduler asks it for candidates, the engine verifies them in one
ragged forward, and acceptance is decided by the target model's own argmax
(``InferenceEngineV2.speculate_decode``), so a bad drafter can only cost
throughput, never correctness.
"""

from typing import Dict, Iterable, Tuple

import numpy as np


class Drafter:
    """Base drafter. Subclasses implement :meth:`draft`; stateful drafters
    (the draft-model path) may also override :meth:`draft_many` to batch
    their own forwards, and :meth:`finish` to drop per-request state."""

    name = "base"

    def draft(self, uid: int, context: np.ndarray, k: int) -> np.ndarray:
        """Up to ``k`` proposed continuation token ids (1-D int32; may be
        empty = nothing to propose this round). ``context`` is the request's
        full committed stream so far (prompt + generated tokens)."""
        raise NotImplementedError

    def draft_many(self, items: Iterable[Tuple[int, np.ndarray]], k: int) -> Dict[int, np.ndarray]:
        """Batched entry the scheduler actually calls: ``{uid: drafts}`` for
        every ``(uid, context)``. Default maps :meth:`draft`."""
        return {uid: self.draft(uid, ctx, k) for uid, ctx in items}

    def finish(self, uid: int) -> None:
        """The request is done (finished or cancelled) — release any
        per-request state. Must tolerate unknown uids."""


def build_drafter(cfg) -> Drafter:
    """Resolve a ``ragged.speculative`` config block into a drafter."""
    from .draft_model import DraftModelDrafter
    from .ngram import NgramDrafter

    if cfg.mode == "ngram":
        return NgramDrafter(min_match=cfg.min_match, max_ngram=cfg.max_ngram,
                            max_history=cfg.max_history)
    if cfg.mode == "draft_model":
        if cfg.draft_engine is None:
            raise ValueError("speculative.mode='draft_model' requires speculative.draft_engine "
                             "(a small InferenceEngineV2 sharing the target's tokenizer)")
        return DraftModelDrafter(cfg.draft_engine)
    raise ValueError(f"unknown speculative mode {cfg.mode!r}: 'off' | 'ngram' | 'draft_model'")
