"""Inference v2 configuration.

Analog of the reference ``inference/v2/config_v2.py`` (RaggedInferenceEngineConfig
with ``state_manager: DSStateManagerConfig`` and tensor-parallel settings).
"""

from dataclasses import dataclass, field
from typing import Union

import jax.numpy as jnp


@dataclass
class DSStateManagerConfig:
    max_tracked_sequences: int = 128
    max_ragged_batch_size: int = 768
    max_ragged_sequence_count: int = 64
    max_context: int = 2048  # per-sequence context ceiling (blocks * block_size)
    memory_config: str = "auto"  # 'auto' sizes the KV pool from free HBM
    offload: bool = False  # reference kv_cache.py:169 offload hooks — not yet


@dataclass
class CacheTelemetryConfig:
    """``ragged.prefix_cache.telemetry`` block: the memory & KV-cache
    observability plane (``ragged/cache_telemetry.py``) — per-block
    lifecycle accounting (allocate/publish/hit/evict/free, refcount
    classes, block-age / reuse-interval / eviction-victim-age histograms,
    occupancy + fragmentation gauges) and the online SHARDS miss-ratio-curve
    estimator predicting the hit rate at {0.5x..8x} the current pool size.
    Off by default with the PR 5 zero-overhead contract: absent/disabled ⇒
    no telemetry objects anywhere, no threads, no per-block allocations —
    every hook site is one ``is not None`` check (test-enforced in
    ``tests/test_cache_telemetry.py``)."""
    enabled: bool = False
    # SHARDS key-sampling rate in (0, 1]: 1.0 tracks every chunk (exact
    # stack distances), lower rates bound memory/CPU on hot admission paths
    mrc_sample_rate: float = 0.25
    # hard cap on tracked sampled keys; past it the coldest is dropped (its
    # next access reads as a cold miss — an under-estimate, never a promise)
    mrc_max_tracked: int = 4096
    # capacity multipliers the MRC is evaluated at (x current pool blocks)
    mrc_capacity_mults: tuple = (0.5, 1.0, 2.0, 4.0, 8.0)


@dataclass
class HostTierConfig:
    """``ragged.prefix_cache.host_tier`` block: the capacity tier under the
    radix tree (``ragged/tiered_store.py``) — evicted tree-only blocks are
    DEMOTED to a pinned host block pool (async D2H through a bounded
    migration queue) instead of dropped, and a later hit on a demoted chain
    PROMOTES the blocks back to HBM ahead of prefill. Presence-enabled:
    when this block is absent (``PrefixCacheConfig.host_tier is None``) no
    host pool, no worker thread and no per-block residency state exist
    anywhere (the PR 5 zero-overhead contract, test-enforced in
    ``tests/test_tiered_store.py``). Size the pool from the MRC curve
    (``serving/mrc_hit_rate``): flat by 2x the HBM pool ⇒ leave the tier
    off; still climbing at 8x ⇒ give the host pool the capacity the curve
    says the workload wants."""
    enabled: bool = True
    # host pool capacity in blocks; 0 derives it from host_pool_bytes
    host_blocks: int = 0
    # alternative sizing: host bytes -> blocks via the HBM pool's block_bytes
    host_pool_bytes: int = 0
    # proactive-demotion watermarks on the HBM FREE fraction: when free
    # drops below `low_watermark`, cold tree-only leaves are demoted in the
    # background until free reaches `high_watermark` — demand eviction then
    # rarely has to demote inline on the admission path
    low_watermark: float = 0.10
    high_watermark: float = 0.25
    # bounded migration queue depth (the ResilientSaver discipline: a slow
    # tier back-pressures into plain drops, never into unbounded memory)
    queue_depth: int = 8
    # optional disk tier: directory for spilled host blocks (None = off).
    # Block files are checksummed and tracked in a manifest; corrupt or
    # missing files read as misses, never as wrong KV.
    disk_path: object = None
    # disk tier capacity in blocks (ignored when disk_path is None)
    disk_blocks: int = 256


@dataclass
class PrefixCacheConfig:
    """``ragged.prefix_cache`` block: block-granular KV reuse across requests
    (PagedAttention sharing + RadixAttention LRU tree). Off by default —
    when enabled, identical outputs are guaranteed (greedy parity asserted
    in ``tests/test_prefix_cache.py``) and shared-prefix workloads skip the
    cached portion of prefill."""
    enabled: bool = False
    # leaf-eviction policy when the block pool runs dry ('lru' only for now)
    eviction: str = "lru"
    # minimum hit size (in blocks, COW tail included) worth taking: tiny
    # hits fragment the pool for negligible prefill savings
    min_hit_blocks: int = 1
    # memory & cache observability plane (block lifecycle + MRC estimator);
    # rides the prefix cache because the radix tree is what gives block
    # reuse a lifecycle worth accounting
    telemetry: CacheTelemetryConfig = field(default_factory=CacheTelemetryConfig)
    # host-memory (+ optional disk) capacity tier under the radix tree:
    # presence-enabled — None means no tier objects exist anywhere
    host_tier: object = None  # Optional[HostTierConfig]


@dataclass
class SpeculativeConfig:
    """``ragged.speculative`` block: speculative decoding over the ragged
    plane (draft K tokens cheaply, verify them in ONE batched ragged
    forward, commit the longest prefix the target model's own argmax
    agrees with, roll the rejected tail back through
    ``DSStateManager.rollback_to``). Off by default — greedy parity is
    unconditional when enabled (asserted in ``tests/test_speculative.py``),
    so the only tradeoff is throughput: larger ``k`` amortizes more host
    round-trips per accepted run but wastes more verify compute when the
    acceptance rate is low."""

    mode: str = "off"  # 'off' | 'ngram' (self-speculative prompt lookup) | 'draft_model'
    k: int = 4         # draft tokens verified per speculative step (per branch)
    # token-tree verification: candidate branches verified per round (1 =
    # linear, the PR 9 behavior). Each extra branch costs k verify tokens
    # and any ONE matching lifts the round's acceptance — the lever for
    # workloads where a single n-gram guess is weak. Greedy only: sampled
    # requests fall back to one linear branch (rejection-sampling verify).
    tree_width: int = 1
    # spec-burst backoff: after this many CONSECUTIVE zero-accept verify
    # rounds a request stops drafting (its verify FLOPs were pure waste)
    # and rides the plain multi-step decode burst; 0 disables backoff
    backoff_after: int = 8
    # while backed off, re-probe (draft again) every this many rounds so a
    # stream that BECOMES repetitive gets speculation back
    reprobe_every: int = 32
    # ngram drafter: shortest suffix n-gram worth matching (higher = fewer,
    # better-grounded drafts) and the longest tried first
    min_match: int = 2
    max_ngram: int = 4
    # ngram drafter: search window over the sequence's own stream (0 = the
    # whole stream). Bounded by default: the scan runs per sequence per
    # verify round in the hottest serving loop, and an unbounded window
    # would make steady-state decode O(context) on long-context requests;
    # the recent window is also where the live repetition signal is.
    max_history: int = 256
    # draft_model mode: a small same-tokenizer InferenceEngineV2 (object
    # handle, not serialized config — built by the caller)
    draft_engine: object = None

    @property
    def enabled(self) -> bool:
        return self.mode != "off"


@dataclass
class ModulesConfig:
    """Per-op implementation selection (reference ``modules/heuristics.py``
    config surface). Each slot is ``"auto"`` (heuristic pick), a registered
    implementation name, or ``{"name": ..., "implementation_config": {...}}``
    — resolved through the interface registries in
    ``modules/heuristics.build_modules`` at engine construction."""
    attention: object = "auto"
    linear: object = "auto"
    embedding: object = "auto"
    unembed: object = "auto"
    norm: object = "auto"


@dataclass
class RaggedInferenceEngineConfig:
    tensor_parallel_degree: int = 1
    kv_block_size: int = 64
    # pool size in blocks; 0/'auto' sizes the pool from the device's free
    # HBM after params (memory_config fraction below), reference
    # DSStateManagerConfig.memory_config semantics
    num_kv_blocks: object = "auto"
    kv_dtype: object = jnp.bfloat16
    # fraction of post-params free HBM given to the KV pool in auto mode
    kv_memory_fraction: float = 0.8
    state_manager: DSStateManagerConfig = field(default_factory=DSStateManagerConfig)
    # prefix-cache subsystem (refcounted COW block sharing + radix reuse)
    prefix_cache: PrefixCacheConfig = field(default_factory=PrefixCacheConfig)
    # speculative decoding (n-gram self-drafting or a draft model, batched
    # K-token verification with refcount-aware rollback)
    speculative: SpeculativeConfig = field(default_factory=SpeculativeConfig)
    use_pallas_kernels: str = "auto"  # 'auto' | 'never' | 'always'
    # weight-only int8 (per-output-channel scales): halves the decode weight
    # stream, which is the bandwidth-bound term at serving batch sizes
    # weight-only quantization for the serving weight stream:
    # False | True (int8) | 8 | 4 (packed nibbles — quarter the bf16 bytes)
    quantize_weights: Union[bool, int] = False
    # pluggable module layer: which implementation serves each op slot
    modules: ModulesConfig = field(default_factory=ModulesConfig)
