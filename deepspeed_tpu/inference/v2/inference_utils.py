"""v2 inference enums + small helpers (reference
``inference/v2/inference_utils.py``: NormTypeEnum, DtypeEnum,
ActivationType, is_gated, elem_size, ceil_div) — jnp dtypes instead of
torch."""

from enum import Enum, IntEnum

import jax.numpy as jnp
import numpy as np


class NormTypeEnum(Enum):
    LayerNorm = "layer_norm"
    GroupNorm = "group_norm"
    RMSNorm = "rms_norm"


class DtypeEnum(Enum):
    fp16 = (jnp.float16, "torch.float16", "fp16", "float16", "half")
    bf16 = (jnp.bfloat16, "torch.bfloat16", "bf16", "bfloat16", "brain floating point")
    fp32 = (jnp.float32, "torch.float32", "fp32", "float32", "float")
    int8 = (jnp.int8, "torch.int8", "int8")

    @classmethod
    def from_str(cls, value: str) -> "DtypeEnum":
        for member in cls:
            if value in member.value:
                return member
        raise ValueError(f"unknown dtype {value!r}")

    @property
    def dtype(self):
        return self.value[0]


class ActivationType(IntEnum):
    GELU = 0
    RELU = 1
    SILU = 2
    GEGLU = 3
    ReGLU = 4
    SiGLU = 5
    IDENTITY = 6
    InvalidType = -1


def is_gated(act_fn: ActivationType) -> bool:
    return act_fn in (ActivationType.GEGLU, ActivationType.ReGLU, ActivationType.SiGLU)


def elem_size(dtype) -> int:
    return np.dtype(dtype).itemsize


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)
