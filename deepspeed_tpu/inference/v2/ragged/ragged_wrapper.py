"""Ragged batch packing.

Analog of the reference ``inference/v2/ragged/ragged_wrapper.py``
(``RaggedBatchWrapper``: packs token ids + per-sequence metadata into pinned
host buffers, ``finalize()`` uploads once per forward). TPU version: the
arrays are padded to *bucketed* static shapes so the jitted ragged forward
compiles once per (token-bucket, seq-bucket, block-bucket) triple, then the
whole descriptor set ships to the device as one transfer.
"""

from dataclasses import dataclass
from typing import Dict, List

import numpy as np


def next_bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds the largest bucket {buckets[-1]}")


@dataclass
class RaggedBatch:
    """Finalized, padded batch — everything the device forward needs."""

    token_ids: np.ndarray  # [T_pad] int32
    token_seq_idx: np.ndarray  # [T_pad] int32 — batch row of each token
    token_pos: np.ndarray  # [T_pad] int32 — absolute position in its sequence
    token_valid: np.ndarray  # [T_pad] bool
    block_tables: np.ndarray  # [S_pad, max_blocks] int32
    seq_start_len: np.ndarray  # [S_pad] int32 — tokens already in cache
    seq_total_len: np.ndarray  # [S_pad] int32 — start + new tokens this batch
    last_token_idx: np.ndarray  # [S_pad] int32 — flat index of each seq's last token
    n_tokens: int
    n_seqs: int

    @property
    def max_context_bucket(self) -> int:
        return self.block_tables.shape[1]

    def packed(self) -> np.ndarray:
        """All descriptor arrays as ONE int32 vector — a single host→device
        transfer per forward (the analog of the reference's single pinned-
        buffer upload, ``ragged_wrapper.py finalize()``; on a tunneled
        runtime each array upload is an RPC, so one packed transfer matters).
        Layout: [T ids][T seq_idx][T pos][T valid][S*max_blocks tables][S last_idx].
        """
        return np.concatenate([
            self.token_ids, self.token_seq_idx, self.token_pos,
            self.token_valid.astype(np.int32), self.block_tables.reshape(-1),
            self.last_token_idx,
        ]).astype(np.int32)


def unpack_descriptors(packed, t_bucket: int, s_bucket: int, max_blocks: int):
    """In-jit inverse of ``RaggedBatch.packed()`` (shapes are static per
    bucket). Returns (token_ids, seq_idx, pos, valid, block_tables, last_idx)."""
    T, S = t_bucket, s_bucket
    token_ids = packed[0:T]
    seq_idx = packed[T:2 * T]
    pos = packed[2 * T:3 * T]
    valid = packed[3 * T:4 * T].astype(bool)
    tables = packed[4 * T:4 * T + S * max_blocks].reshape(S, max_blocks)
    last_idx = packed[4 * T + S * max_blocks:4 * T + S * max_blocks + S]
    return token_ids, seq_idx, pos, valid, tables, last_idx


class RaggedBatchWrapper:

    def __init__(self, max_ragged_batch_size: int = 768, max_ragged_sequence_count: int = 128,
                 max_blocks_per_seq: int = 32, block_size: int = 64,
                 token_buckets=None, seq_buckets=None):
        self.max_tokens = max_ragged_batch_size
        self.max_seqs = max_ragged_sequence_count
        self.max_blocks_per_seq = max_blocks_per_seq
        self.block_size = block_size
        self.token_buckets = token_buckets or _pow2_buckets(max_ragged_batch_size)
        self.seq_buckets = seq_buckets or _pow2_buckets(max_ragged_sequence_count)
        self.clear()

    def clear(self):
        self._tokens: List[np.ndarray] = []
        self._descs = []

    def insert_sequence(self, desc, tokens: np.ndarray) -> None:
        """Queue ``tokens`` (1-D int array) of sequence ``desc`` for this
        forward (reference ``ragged_wrapper.py`` insert_sequence)."""
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        if len(self._descs) >= self.max_seqs:
            raise ValueError(f"batch already holds {self.max_seqs} sequences")
        if self.current_tokens + tokens.size > self.max_tokens:
            raise ValueError(f"token budget exceeded: {self.current_tokens}+{tokens.size} > {self.max_tokens}")
        self._tokens.append(tokens)
        self._descs.append(desc)

    @property
    def current_tokens(self) -> int:
        return int(sum(t.size for t in self._tokens))

    @property
    def current_sequences(self) -> int:
        return len(self._descs)

    def finalize(self) -> RaggedBatch:
        """Pack into bucket-padded arrays (reference ``finalize()`` — its
        single pinned-host upload is here the bucketed transfer of this
        struct's arrays when they are passed into the jitted forward)."""
        n_seqs = len(self._descs)
        n_tokens = self.current_tokens
        assert n_seqs > 0, "empty ragged batch"
        T = next_bucket(n_tokens, self.token_buckets)
        S = next_bucket(n_seqs, self.seq_buckets)

        token_ids = np.zeros(T, np.int32)
        seq_idx = np.zeros(T, np.int32)
        pos = np.zeros(T, np.int32)
        valid = np.zeros(T, bool)
        tables = np.zeros((S, self.max_blocks_per_seq), np.int32)
        start_len = np.zeros(S, np.int32)
        total_len = np.zeros(S, np.int32)
        last_idx = np.zeros(S, np.int32)

        cur = 0
        for i, (desc, toks) in enumerate(zip(self._descs, self._tokens)):
            n = toks.size
            token_ids[cur:cur + n] = toks
            seq_idx[cur:cur + n] = i
            pos[cur:cur + n] = desc.seen_tokens + np.arange(n)
            valid[cur:cur + n] = True
            tables[i] = desc.block_table(self.max_blocks_per_seq)
            start_len[i] = desc.seen_tokens
            total_len[i] = desc.seen_tokens + n
            last_idx[i] = cur + n - 1
            cur += n

        return RaggedBatch(token_ids=token_ids, token_seq_idx=seq_idx, token_pos=pos, token_valid=valid,
                           block_tables=tables, seq_start_len=start_len, seq_total_len=total_len,
                           last_token_idx=last_idx, n_tokens=n_tokens, n_seqs=n_seqs)


def _pow2_buckets(max_n: int):
    out, b = [], 8
    while b < max_n:
        out.append(b)
        b *= 2
    out.append(max_n)
    return out
