"""Blocked (paged) KV cache on device.

Analog of the reference ``inference/v2/ragged/kv_cache.py:40``
(``BlockedKVCache``: device block pool fronted by a ``BlockedAllocator``).
TPU-native layout: one stacked pool per cache group,

    k_pool / v_pool : [num_layers, num_blocks * block_size, num_kv_heads, head_dim]

i.e. the block dimension is flattened so a token's slot is the flat index
``block_id * block_size + offset`` — scatter (append) and gather (attention)
are then single-index operations that XLA lowers to efficient dynamic-slice /
dynamic-update-slice, and the Pallas paged-attention kernel indexes the same
flat pool. The pool shards over the ``model`` axis on the kv-head dim (TP).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .blocked_allocator import BlockedAllocator


class BlockedKVCache:
    """``dtype=jnp.int8`` (or the string ``"int8"``) selects the quantized
    cache (the TPU analog of the reference FastGen quantized KV variants,
    ``csrc/quantization/``): values stored int8 with one fp32 absmax/127
    scale per (token, kv-head) in side pools ``k_scale``/``v_scale``
    [nkv, L*NB*bs] (kv-heads on sublanes, flat slots on lanes — the layout
    the forward's scatter and the Pallas kernel read without a transpose).
    Decode is bound by the KV byte stream, so int8 halves that term (scales
    add 1/(2·head_dim) back)."""

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int, num_blocks: int, block_size: int = 64,
                 dtype=jnp.bfloat16, sharding=None):
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        if dtype in ("int8", jnp.int8, np.int8):
            dtype = jnp.int8
        self.dtype = dtype
        self.quantized = dtype == jnp.int8
        self._allocator = BlockedAllocator(num_blocks)
        shape = (num_layers, self.num_blocks * self.block_size, num_kv_heads, head_dim)
        self.k_pool = jnp.zeros(shape, dtype)
        self.v_pool = jnp.zeros(shape, dtype)
        self.k_scale = self.v_scale = None
        if self.quantized:
            # [nkv, L * NB * bs] — kv-heads on sublanes, slots on lanes: the
            # layout the forward's scatter and the Pallas kernel's scale
            # BlockSpec both consume without a per-call transpose
            flat = num_layers * self.num_blocks * self.block_size
            self.k_scale = jnp.zeros((num_kv_heads, flat), jnp.float32)
            self.v_scale = jnp.zeros((num_kv_heads, flat), jnp.float32)
        if sharding is not None:
            self.k_pool = jax.device_put(self.k_pool, sharding)
            self.v_pool = jax.device_put(self.v_pool, sharding)
            if self.quantized:
                # scales shard with the kv-head dim (pool dim 2 → scale dim 0)
                from jax.sharding import NamedSharding, PartitionSpec as P

                if isinstance(sharding, NamedSharding) and len(sharding.spec) >= 3:
                    sc = NamedSharding(sharding.mesh, P(sharding.spec[2], None))
                    self.k_scale = jax.device_put(self.k_scale, sc)
                    self.v_scale = jax.device_put(self.v_scale, sc)

    @property
    def free_blocks(self) -> int:
        return self._allocator.free_blocks

    @property
    def total_blocks(self) -> int:
        return self._allocator.total_blocks

    def reserve(self, n_blocks: int) -> np.ndarray:
        """Allocate ``n_blocks`` at refcount 1 (reference ``kv_cache.py:147``)."""
        return self._allocator.allocate(n_blocks)

    def free(self, blocks) -> None:
        self._allocator.free(blocks)

    # -- refcount-aware sharing surface (prefix cache) ---------------------
    def incref(self, blocks) -> None:
        """One more holder per block: the block contents become IMMUTABLE
        until the count drops back to one (copy-on-write for mutation)."""
        self._allocator.incref(blocks)

    def release(self, blocks) -> None:
        """Drop one reference per block; physical free happens at zero."""
        self._allocator.release(blocks)

    def refcount(self, block) -> int:
        return self._allocator.refcount(block)

    def refcount_snapshot(self):
        """Copy of the whole refcount table (cache telemetry's pool
        decomposition)."""
        return self._allocator.refcount_snapshot()

    def set_telemetry(self, telemetry) -> None:
        """Arm (or with None, disarm) the allocator's lifecycle hooks —
        the facade's only sanctioned route to them."""
        self._allocator.telemetry = telemetry

    def set_meter(self, view) -> None:
        """Arm (or with None, disarm) the tenant-metering view on the same
        allocator lifecycle surface cache telemetry rides."""
        self._allocator.meter = view

    def copy_block(self, src: int, dst: int) -> None:
        """Device-side copy of one block's KV slots ``src`` → ``dst`` (the
        copy-on-write primitive: a sequence that must write into a SHARED
        block first duplicates it into a privately-held block). Eager jnp
        ops — COW is rare (one copy per partial-tail prefix hit), so the
        dispatch cost is noise next to the prefill it saves."""
        bs = self.block_size
        s, d = int(src) * bs, int(dst) * bs
        self.k_pool = self.k_pool.at[:, d:d + bs].set(self.k_pool[:, s:s + bs])
        self.v_pool = self.v_pool.at[:, d:d + bs].set(self.v_pool[:, s:s + bs])
        if self.quantized:
            # scale layout [nkv, L * NB * bs]: per-layer strided slots — copy
            # through a [nkv, L, NB*bs] view so each layer's span moves
            nkv = self.num_kv_heads
            span = self.num_blocks * bs
            for name in ("k_scale", "v_scale"):
                sc = getattr(self, name).reshape(nkv, self.num_layers, span)
                sc = sc.at[:, :, d:d + bs].set(sc[:, :, s:s + bs])
                setattr(self, name, sc.reshape(nkv, -1))

    # -- tier migration surface (ragged/tiered_store.py) -------------------
    def read_block(self, block: int):
        """Value-snapshot of one block's KV for D2H demotion:
        ``(k, v, k_scale, v_scale)`` device arrays (scales None on the
        non-quantized layout), each a NEW functional slice of the pools.
        The snapshot is safe to materialize from another thread AFTER the
        physical block is freed and even after the pool buffers themselves
        are donated to a later forward — jax slicing captures the pool
        VALUE at call time, so the migration worker's ``np.asarray`` reads
        the snapshot, never the live (possibly reused) slots."""
        bs = self.block_size
        s = int(block) * bs
        k = self.k_pool[:, s:s + bs]
        v = self.v_pool[:, s:s + bs]
        ks = vs = None
        if self.quantized:
            nkv, span = self.num_kv_heads, self.num_blocks * bs
            ks = self.k_scale.reshape(nkv, self.num_layers, span)[:, :, s:s + bs]
            vs = self.v_scale.reshape(nkv, self.num_layers, span)[:, :, s:s + bs]
        return k, v, ks, vs

    def write_block(self, block: int, k, v, k_scale=None, v_scale=None) -> None:
        """H2D promotion: install host-resident KV into one block's slots
        (the inverse of :meth:`read_block`, same shapes). MUST run on the
        driver thread between forwards — it replaces the pool arrays, and
        racing a forward's donation would read an invalidated buffer."""
        bs = self.block_size
        d = int(block) * bs
        self.k_pool = self.k_pool.at[:, d:d + bs].set(jnp.asarray(k, self.k_pool.dtype))
        self.v_pool = self.v_pool.at[:, d:d + bs].set(jnp.asarray(v, self.v_pool.dtype))
        if self.quantized and k_scale is not None:
            nkv, span = self.num_kv_heads, self.num_blocks * bs
            for name, blk in (("k_scale", k_scale), ("v_scale", v_scale)):
                sc = getattr(self, name).reshape(nkv, self.num_layers, span)
                sc = sc.at[:, :, d:d + bs].set(jnp.asarray(blk, jnp.float32))
                setattr(self, name, sc.reshape(nkv, -1))

    def compact_slots(self, src_slots, dst_slots) -> None:
        """Device-side KV move of individual token slots ``src → dst``
        across every layer — the token-tree verification commit: an
        accepted branch's nodes were verified at their FLAT tree slots and
        must land at the sequence's canonical contiguous positions before
        decoding continues. All reads happen before any write (one gather,
        one scatter), and the tree layout guarantees dst < src with the two
        ranges disjoint, so the move is alias-safe. Eager jnp ops like
        :meth:`copy_block` — a handful of slots per verify round."""
        src = jnp.asarray(src_slots, jnp.int32).reshape(-1)
        dst = jnp.asarray(dst_slots, jnp.int32).reshape(-1)
        if src.size == 0:
            return
        self.k_pool = self.k_pool.at[:, dst].set(self.k_pool[:, src])
        self.v_pool = self.v_pool.at[:, dst].set(self.v_pool[:, src])
        if self.quantized:
            nkv = self.num_kv_heads
            span = self.num_blocks * self.block_size
            for name in ("k_scale", "v_scale"):
                sc = getattr(self, name).reshape(nkv, self.num_layers, span)
                sc = sc.at[:, :, dst].set(sc[:, :, src])
                setattr(self, name, sc.reshape(nkv, -1))

    def pools(self):
        """The donated pool tuple the compiled forwards thread through:
        (k, v) full-precision, (k, v, k_scale, v_scale) quantized."""
        if self.quantized:
            return (self.k_pool, self.v_pool, self.k_scale, self.v_scale)
        return (self.k_pool, self.v_pool)

    def update(self, k_pool, v_pool, k_scale=None, v_scale=None) -> None:
        """Install the pools returned by the jitted forward (donated in/out)."""
        self.k_pool, self.v_pool = k_pool, v_pool
        if k_scale is not None:
            self.k_scale, self.v_scale = k_scale, v_scale

    def memory_bytes(self) -> int:
        n = 2 * self.k_pool.size * self.k_pool.dtype.itemsize
        if self.quantized:
            n += 2 * self.k_scale.size * 4
        return n

    def block_bytes(self) -> int:
        """Device bytes one block occupies across all layers (K + V, scales
        included on the int8 layout) — the unit of the prefix cache's
        ``cow_bytes`` accounting and the MRC's capacity math."""
        return self.memory_bytes() // self.num_blocks
