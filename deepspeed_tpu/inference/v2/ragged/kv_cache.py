"""Blocked (paged) KV cache on device.

Analog of the reference ``inference/v2/ragged/kv_cache.py:40``
(``BlockedKVCache``: device block pool fronted by a ``BlockedAllocator``).
TPU-native layout: one stacked pool per cache group,

    k_pool / v_pool : [num_layers, num_blocks * block_size, num_kv_heads, head_dim]

i.e. the block dimension is flattened so a token's slot is the flat index
``block_id * block_size + offset`` — scatter (append) and gather (attention)
are then single-index operations that XLA lowers to efficient dynamic-slice /
dynamic-update-slice, and the Pallas paged-attention kernel indexes the same
flat pool. The pool shards over the ``model`` axis on the kv-head dim (TP).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .blocked_allocator import BlockedAllocator


class BlockedKVCache:

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int, num_blocks: int, block_size: int = 64,
                 dtype=jnp.bfloat16, sharding=None):
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.dtype = dtype
        self._allocator = BlockedAllocator(num_blocks)
        shape = (num_layers, self.num_blocks * self.block_size, num_kv_heads, head_dim)
        self.k_pool = jnp.zeros(shape, dtype)
        self.v_pool = jnp.zeros(shape, dtype)
        if sharding is not None:
            self.k_pool = jax.device_put(self.k_pool, sharding)
            self.v_pool = jax.device_put(self.v_pool, sharding)

    @property
    def free_blocks(self) -> int:
        return self._allocator.free_blocks

    def reserve(self, n_blocks: int) -> np.ndarray:
        """Allocate ``n_blocks`` (reference ``kv_cache.py:147`` reserve)."""
        return self._allocator.allocate(n_blocks)

    def free(self, blocks) -> None:
        self._allocator.free(blocks)

    def update(self, k_pool, v_pool) -> None:
        """Install the pools returned by the jitted forward (donated in/out)."""
        self.k_pool, self.v_pool = k_pool, v_pool

    def memory_bytes(self) -> int:
        return 2 * self.k_pool.size * self.k_pool.dtype.itemsize
