"""Tiered KV-block store: host-memory (+ optional disk) capacity tier
under the prefix cache's radix tree.

The DeepSpeed ZeRO-Infinity / ``runtime/swap_tensor`` lineage re-idiomized
for the ragged serving plane: the reusable-prefix corpus (system prompts,
tenant few-shot templates, long multi-turn histories) no longer dies at the
HBM pool boundary. ``PrefixKVCache.evict`` DEMOTES cold tree-only blocks
into a host block pool that mirrors the :class:`BlockedKVCache` layouts
(bf16 and the int8+scale variant), and a later radix hit on a demoted chain
PROMOTES the blocks back ahead of prefill. Host-pool pressure optionally
spills further to manifest-checksummed block files on disk.

Threading contract (the whole design hangs on it):

  * ALL device-array operations happen on the replica driver thread — the
    compiled forwards DONATE the KV pools, so a background thread touching
    ``k_pool``/``v_pool`` races buffer invalidation. Demotion therefore
    captures a functional VALUE snapshot of the victim block on the driver
    thread (``BlockedKVCache.read_block`` — jax slices capture the pool
    value at call time) and frees the HBM block immediately; the migration
    worker only ever materializes the snapshot to numpy (``np.asarray`` is
    the D2H copy) and writes host/disk memory. Promotion's H2D
    (``write_block``) likewise runs on the driver thread, inside admission
    (``acquire``), NEVER inside a decode step.
  * the migration queue is depth-bounded (the ResilientSaver discipline
    from ``runtime/resilience/saver.py`` / ``swap_tensor/async_swapper.py``):
    a slow tier back-pressures into plain drops — eviction never waits on
    the worker, decode steps never block on migration.
  * node residency transitions (``hbm -> in_flight -> host -> disk``) are
    finalized under the prefix cache's tree lock; the worker crashing
    mid-demotion (chaos point ``cache/demote``) loses exactly the demoting
    block — the failure callback drops that node (and any host descendants,
    unusable without their parent's KV) and the worker survives.

"Pinned" is aspirational on this runtime: numpy host arrays are not
registered with the TPU driver, but the pool mirrors the device layout so
each block's D2H/H2D is one contiguous memcpy — the slot a real pinned
allocator drops into.
"""

import os
import threading
import time
import zlib
from collections import OrderedDict, deque

import numpy as np

from ....runtime.resilience import chaos

# residency states a radix node moves through (``_Node.res``); kept here so
# every module spells them identically
RES_HBM = "hbm"
RES_IN_FLIGHT = "in_flight"  # demotion queued/running: unusable, unmatched
RES_HOST = "host"
RES_DISK = "disk"


class HostBlockPool:
    """Host mirror of one :class:`BlockedKVCache`'s block layout.

    Same axes as the device pools — ``k/v: [L, HB*bs, nkv, hd]`` in the
    device dtype (int8 included) and, on the quantized layout, fp32 scale
    side pools ``[nkv, L*HB*bs]`` — so a block moves between tiers as one
    contiguous span per pool, no transpose, no re-quantization. All
    mutation goes through the ``host_*`` methods below; like the device
    pool's ``.free``, raw calls outside the sanctioned modules are a
    ``tools/check_kv_blocks.py`` violation.
    """

    def __init__(self, kv_cache, num_blocks: int):
        self.block_size = kv_cache.block_size
        self.num_blocks = int(num_blocks)
        self.num_layers = kv_cache.num_layers
        self.num_kv_heads = kv_cache.num_kv_heads
        self.quantized = kv_cache.quantized
        if self.num_blocks < 1:
            raise ValueError(f"host pool needs >= 1 block, got {num_blocks}")
        shape = (self.num_layers, self.num_blocks * self.block_size,
                 kv_cache.num_kv_heads, kv_cache.head_dim)
        dtype = np.dtype(kv_cache.k_pool.dtype)  # ml_dtypes covers bf16
        self.k_pool = np.zeros(shape, dtype)
        self.v_pool = np.zeros(shape, dtype)
        self.k_scale = self.v_scale = None
        if self.quantized:
            flat = self.num_layers * self.num_blocks * self.block_size
            self.k_scale = np.zeros((self.num_kv_heads, flat), np.float32)
            self.v_scale = np.zeros((self.num_kv_heads, flat), np.float32)
        # free-list under its own lock: the migration worker reserves/writes
        # while the driver thread frees promoted blocks
        self._mu = threading.Lock()
        self._free = list(range(self.num_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        with self._mu:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    def host_reserve(self) -> int:
        """One block at single ownership, ``-1`` when the pool is full (the
        caller spills or drops — never blocks)."""
        with self._mu:
            return self._free.pop() if self._free else -1

    def host_free(self, block: int) -> None:
        with self._mu:
            self._free.append(int(block))

    def _scales(self):
        span = self.num_blocks * self.block_size
        return (self.k_scale.reshape(self.num_kv_heads, self.num_layers, span),
                self.v_scale.reshape(self.num_kv_heads, self.num_layers, span))

    def host_write(self, block: int, k, v, k_scale=None, v_scale=None) -> None:
        """Install one block's KV (shapes of ``BlockedKVCache.read_block``).
        Only the reserving owner may write — a block is never writable in
        two tiers at once (fuzz-enforced in ``tests/test_tiered_store.py``)."""
        bs = self.block_size
        d = int(block) * bs
        self.k_pool[:, d:d + bs] = k
        self.v_pool[:, d:d + bs] = v
        if self.quantized and k_scale is not None:
            ks, vs = self._scales()
            ks[:, :, d:d + bs] = k_scale
            vs[:, :, d:d + bs] = v_scale

    def host_read(self, block: int):
        """Views of one resident block: ``(k, v, k_scale, v_scale)`` —
        promotion copies them device-side before the block is freed."""
        bs = self.block_size
        s = int(block) * bs
        k = self.k_pool[:, s:s + bs]
        v = self.v_pool[:, s:s + bs]
        if not self.quantized:
            return k, v, None, None
        ks, vs = self._scales()
        return k, v, ks[:, :, s:s + bs], vs[:, :, s:s + bs]

    def memory_bytes(self) -> int:
        n = 2 * self.k_pool.size * self.k_pool.dtype.itemsize
        if self.quantized:
            n += 2 * self.k_scale.size * 4
        return n


class TieredBlockStore:
    """Migration engine between the HBM pool, a :class:`HostBlockPool`, and
    an optional disk tier. Owned by :class:`PrefixKVCache` (``attach``);
    presence-enabled — when ``ragged.prefix_cache.host_tier`` is absent no
    instance, no worker thread and no per-node residency state exist."""

    def __init__(self, kv_cache, config, telemetry=None, clock=time.monotonic):
        self.kv_cache = kv_cache
        self.config = config
        n = int(getattr(config, "host_blocks", 0) or 0)
        if n <= 0 and getattr(config, "host_pool_bytes", 0):
            n = int(config.host_pool_bytes) // max(1, kv_cache.block_bytes())
        if n <= 0:
            raise ValueError("host_tier needs host_blocks or host_pool_bytes "
                             "sizing at least one block")
        self.pool = HostBlockPool(kv_cache, n)
        self.queue_depth = max(1, int(getattr(config, "queue_depth", 8)))
        self._telemetry = telemetry
        self._meter = None  # EngineMeterView (charge_host_kv), set via set_meter
        self._clock = clock
        self._cache = None  # attach() wires the owning PrefixKVCache
        # host-LRU bookkeeping: node -> host block, insertion order = demote
        # order (touched on promotion-miss only via re-demotion, so plain
        # insertion order is the eviction order we want). Guarded by the
        # TREE lock: every mutator already holds it.
        self._host_nodes = OrderedDict()
        # per-host-block tenant stamp for PR 15 metering: owner + residency
        # start, charged to ``host_kv_s`` when the block leaves the tier
        self._host_stamp = {}
        # disk tier (optional): manifest maps disk_id -> {file, crc, nbytes};
        # `_disk_pending` covers the window where a spill's payload is only
        # in worker memory (a racing promotion reads it from here). `_mu`
        # guards manifest/pending/counters against worker vs driver access.
        self._mu = threading.Lock()
        self._disk_dir = getattr(config, "disk_path", None)
        self._disk_cap = int(getattr(config, "disk_blocks", 0) or 0)
        self._disk_manifest = {}
        self._disk_pending = {}
        self._next_disk_id = 0
        if self._disk_dir is not None:
            self._disk_dir = str(self._disk_dir)
            os.makedirs(self._disk_dir, exist_ok=True)
        self.counters = {"demotions": 0, "demote_failures": 0,
                         "demote_cancelled": 0, "promotions_host": 0,
                         "promotions_disk": 0, "host_evictions": 0,
                         "disk_spills": 0, "disk_corrupt": 0,
                         "disk_drops": 0, "prefetch_enqueued": 0,
                         "prefetch_hits": 0, "prefetch_unused": 0,
                         "host_installs": 0}
        # chain-lookahead staging: node -> materialized payload the worker
        # parked ahead of the driver's promotion walk (``prefetch``), plus
        # the in-flight markers that dedupe enqueues. Guarded by ``_mu``;
        # parking re-checks residency under the TREE lock so a dropped
        # node's payload can never wedge a slot.
        self._prefetched = {}
        self._prefetch_inflight = set()
        self._q = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="kv-tier-migrator")
        self._worker.start()

    # -- wiring ------------------------------------------------------------
    def attach(self, prefix_cache) -> None:
        """Bind to the owning tree: residency finalization happens under its
        ``_tree_lock`` through the cache's ``_demote_finalized`` /
        ``_demote_failed`` callbacks."""
        self._cache = prefix_cache

    def set_meter(self, view) -> None:
        self._meter = view

    # -- demotion (driver side: enqueue-only, never blocks) -----------------
    def try_demote(self, node, snapshot) -> bool:
        """Queue one D2H migration. Called under the tree lock from
        ``PrefixKVCache.evict`` with ``snapshot`` = the block's functional
        device slices (``read_block``). Returns False — caller drops the
        block the old way — when the queue is at depth or the store is shut
        down; never waits (the decode-never-blocks rule)."""
        with self._cv:
            if self._stop or len(self._q) >= self.queue_depth:
                return False
            self._q.append(("demote", node, snapshot, self._clock()))
            self._cv.notify()
        return True

    def prefetch(self, node) -> bool:
        """Queue a background materialization of a demoted node's payload —
        the chain-lookahead: while the driver H2Ds chain[i], the worker
        stages chain[i+1]'s host/disk bytes so the next ``promote_payload``
        is a dict pop instead of a copy (+ disk read + crc). Enqueue-only,
        called under the tree lock; depth-bounded like demotion, so a busy
        worker just leaves that promotion synchronous — never blocks, never
        wrong."""
        if node.res not in (RES_HOST, RES_DISK):
            return False
        with self._cv:
            if self._stop or len(self._q) >= self.queue_depth:
                return False
            with self._mu:
                if node in self._prefetched or node in self._prefetch_inflight:
                    return True
                self._prefetch_inflight.add(node)
                self.counters["prefetch_enqueued"] += 1
            self._q.append(("prefetch", node))
            self._cv.notify()
        return True

    @property
    def queued(self) -> int:
        with self._cv:
            return len(self._q)

    # -- promotion (driver side) -------------------------------------------
    def promote_payload(self, node):
        """Host/disk payload of a demoted node for H2D restore:
        ``(k, v, k_scale, v_scale)`` or None when the backing copy is gone
        or fails its checksum — the caller drops the node (a miss, never
        wrong KV). Called under the tree lock on the driver thread. A
        payload the lookahead worker already parked is consumed directly."""
        with self._mu:
            parked = self._prefetched.pop(node, None)
            if parked is not None:
                self.counters["prefetch_hits"] += 1
        if parked is not None:
            return parked
        if node.res == RES_HOST:
            # copy, don't alias: on CPU backends jnp.asarray may wrap the
            # host buffer zero-copy, and host_free can recycle the slot
            # before the async .at[].set consumes it
            return tuple(None if a is None else np.array(a)
                         for a in self.pool.host_read(node.host_block))
        if node.res == RES_DISK:
            with self._mu:
                pending = self._disk_pending.get(node.disk_id)
            if pending is not None:
                return pending
            return self._disk_read(node.disk_id)
        return None

    def note_promoted(self, from_disk: bool) -> None:
        with self._mu:
            self.counters["promotions_disk" if from_disk
                          else "promotions_host"] += 1

    def release_resident(self, node) -> None:
        """Drop a node's host/disk copy (after promotion installed it in
        HBM, or when the node is being discarded). Tree lock held."""
        with self._mu:
            if self._prefetched.pop(node, None) is not None:
                self.counters["prefetch_unused"] += 1
            self._prefetch_inflight.discard(node)
        if node.host_block >= 0:
            self._release_host_block(node.host_block)
            self._host_nodes.pop(node, None)
            node.host_block = -1
        if node.disk_id >= 0:
            self._disk_drop(node.disk_id)
            node.disk_id = -1

    # -- handoff adoption (disaggregated serving) ----------------------------
    def host_install(self, payload) -> int:
        """Reserve a host block and fill it with an externally-produced KV
        payload (``read_block`` shapes) — the landing zone of a
        cross-replica handoff (``serving/handoff.py``). Makes room by
        evicting cold host residents exactly like the demotion worker;
        returns -1 only when the pool holds no evictable leaf. Host-memory
        and file ops only, so it is safe OFF this replica's driver thread
        (the handoff broker runs on the SOURCE replica's driver)."""
        hb = self.pool.host_reserve()
        while hb < 0:
            try:
                self._evict_host_one()
            except RuntimeError:
                return -1
            hb = self.pool.host_reserve()
        k, v, ks, vs = payload
        self.pool.host_write(hb, k, v, ks, vs)
        with self._mu:
            self.counters["host_installs"] += 1
        return hb

    def register_host_node(self, node, host_block: int) -> None:
        """Finalize adoption: bind an installed host block to its new tree
        node as a first-class host resident (LRU-tracked, owner-stamped so
        PR 15's ``host_kv_s`` conservation holds across the handoff). Tree
        lock held by the caller (``PrefixKVCache.install_host_chain``)."""
        node.res = RES_HOST
        node.host_block = int(host_block)
        self._host_nodes[node] = int(host_block)
        self._host_stamp[int(host_block)] = (node.owner, self._clock())
        if self._telemetry is not None:
            self._telemetry.note_host_used(self.pool.used_blocks)

    # -- watermark surface ---------------------------------------------------
    def demotion_target(self) -> int:
        """Blocks proactive demotion should move now: when the HBM free
        fraction is under ``low_watermark``, the shortfall up to
        ``high_watermark`` (0 otherwise — and 0 whenever the queue is full,
        so the check stays O(1) and dropless)."""
        total = self.kv_cache.total_blocks
        free = self.kv_cache.free_blocks
        if total <= 0 or free >= self.config.low_watermark * total:
            return 0
        return max(0, int(self.config.high_watermark * total) - free)

    # -- stats ---------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            c = dict(self.counters)
            disk_used = len(self._disk_manifest)
            c["prefetched_parked"] = len(self._prefetched)
        c.update(host_blocks=self.pool.num_blocks,
                 host_used=self.pool.used_blocks,
                 host_bytes=self.pool.memory_bytes(),
                 queue_depth=self.queue_depth, queued=self.queued,
                 disk_blocks=self._disk_cap if self._disk_dir else 0,
                 disk_used=disk_used)
        return c

    def shutdown(self, timeout: float = 5.0) -> None:
        """Stop the worker (drains nothing: queued jobs are cancelled by the
        stop flag and their nodes dropped via the failure path)."""
        with self._cv:
            self._stop = True
            pending = list(self._q)
            self._q.clear()
            self._cv.notify_all()
        self._worker.join(timeout)
        for item in pending:
            if item[0] == "demote":
                self._fail_node(item[1], cancelled=True)
            else:
                with self._mu:
                    self._prefetch_inflight.discard(item[1])

    # -- migration worker -----------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                item = self._q.popleft()
            if item[0] == "prefetch":
                self._run_prefetch(item[1])
                continue
            _, node, snapshot, t0 = item
            try:
                # chaos point: a hook here simulates the worker dying
                # mid-copy — the except arm below is the blast-radius
                # contract (this block only) the tests pin down
                chaos.fire("cache/demote", {"queued": self.queued})
                hb = self._reserve_host_block(exclude=node)
                k, v, ks, vs = snapshot
                # np.asarray IS the D2H copy — of the functional snapshot,
                # not the live (long since reused) pool slots
                self.pool.host_write(hb, np.asarray(k), np.asarray(v),
                                     None if ks is None else np.asarray(ks),
                                     None if vs is None else np.asarray(vs))
                self._finalize_demote(node, hb, t0)
            except Exception:
                with self._mu:
                    self.counters["demote_failures"] += 1
                self._fail_node(node)

    def _run_prefetch(self, node) -> None:
        """Worker half of :meth:`prefetch`: materialize one demoted node's
        payload (host memcpy or disk read, never a device op) and park it.
        Residency is checked under the tree lock both before the read and
        at park time — a node promoted or dropped since enqueue just clears
        its in-flight marker, and a stale payload can never occupy a slot
        (every drop path pops ``_prefetched`` under the same lock)."""
        cache = self._cache
        payload = disk_id = None
        try:
            with cache._tree_lock:
                if node.res == RES_HOST and node.host_block >= 0:
                    payload = tuple(None if a is None else np.array(a)
                                    for a in self.pool.host_read(node.host_block))
                elif node.res == RES_DISK and node.disk_id >= 0:
                    disk_id = node.disk_id
            if disk_id is not None:
                with self._mu:
                    payload = self._disk_pending.get(disk_id)
                if payload is None:
                    payload = self._disk_read(disk_id)
            with cache._tree_lock:
                with self._mu:
                    self._prefetch_inflight.discard(node)
                    if (payload is not None
                            and node.res in (RES_HOST, RES_DISK)
                            and len(self._prefetched) < self.queue_depth):
                        self._prefetched[node] = payload
        except Exception:
            with self._mu:
                self._prefetch_inflight.discard(node)

    def _finalize_demote(self, node, host_block: int, t0: float) -> None:
        cache = self._cache
        with cache._tree_lock:
            if node.res != RES_IN_FLIGHT or node.parent is None:
                # the node was dropped (clear()/shutdown race) while we
                # copied: give the host block back, charge nothing
                with self._mu:
                    self.counters["demote_cancelled"] += 1
                self.pool.host_free(host_block)
                return
            node.res = RES_HOST
            node.host_block = int(host_block)
            self._host_nodes[node] = int(host_block)
            self._host_stamp[int(host_block)] = (node.owner, self._clock())
            with self._mu:
                self.counters["demotions"] += 1
            if self._telemetry is not None:
                self._telemetry.on_demote(self.pool.used_blocks,
                                          wait_s=self._clock() - t0)

    def _fail_node(self, node, cancelled: bool = False) -> None:
        cache = self._cache
        try:
            with cache._tree_lock:
                if node.res == RES_IN_FLIGHT and node.parent is not None:
                    cache._drop_node_subtree(node)
                if cancelled:
                    with self._mu:
                        self.counters["demote_cancelled"] += 1
        except Exception:
            pass  # forensic path: the worker must survive anything here

    def _reserve_host_block(self, exclude=None) -> int:
        """Worker-side host reservation; a full pool spills (or drops) the
        coldest host-resident chain leaf first. Never returns -1."""
        hb = self.pool.host_reserve()
        while hb < 0:
            self._evict_host_one(exclude=exclude)
            hb = self.pool.host_reserve()
        return hb

    def _evict_host_one(self, exclude=None) -> None:
        cache = self._cache
        with cache._tree_lock:
            victim = None
            for node in self._host_nodes:
                if node is exclude:
                    continue
                # only chain leaves leave the host tier: dropping/spilling a
                # mid-chain node under host children would break the
                # root-ward residency ordering the match walk relies on
                if not any(c.res in (RES_HOST, RES_IN_FLIGHT)
                           for c in node.children.values()):
                    victim = node
                    break
            if victim is None:
                raise RuntimeError("host pool full with no evictable chain leaf")
            with self._mu:
                self.counters["host_evictions"] += 1
                disk_ok = (self._disk_dir is not None
                           and len(self._disk_manifest) + len(self._disk_pending)
                           < self._disk_cap)
            if disk_ok:
                payload = tuple(None if a is None else np.array(a)
                                for a in self.pool.host_read(victim.host_block))
                with self._mu:
                    disk_id = self._next_disk_id
                    self._next_disk_id += 1
                    self._disk_pending[disk_id] = payload
                self._release_host_block(victim.host_block)
                self._host_nodes.pop(victim, None)
                victim.host_block = -1
                victim.res = RES_DISK
                victim.disk_id = disk_id
            else:
                if self._disk_dir is not None:
                    with self._mu:
                        self.counters["disk_drops"] += 1
                cache._drop_node_subtree(victim)
                payload = disk_id = None
        if payload is not None:
            self._disk_write(disk_id, payload)

    # -- host-block metering ---------------------------------------------------
    def _release_host_block(self, hb: int) -> None:
        owner, t0 = self._host_stamp.pop(int(hb), (None, None))
        if self._meter is not None and t0 is not None:
            self._meter.charge_host_kv(owner, max(0.0, self._clock() - t0))
        self.pool.host_free(hb)
        if self._telemetry is not None:
            self._telemetry.note_host_used(self.pool.used_blocks)

    # -- disk tier --------------------------------------------------------------
    def _disk_file(self, disk_id: int) -> str:
        return os.path.join(self._disk_dir, f"kvblock_{disk_id:08d}.npz")

    def _disk_write(self, disk_id: int, payload) -> None:
        """Bounded-writer spill (the ``swap_tensor/async_swapper`` lineage:
        one worker, depth-limited in-flight payloads): serialize outside
        every lock, fsync-free tmp+rename commit, crc32 in the manifest so
        a torn/corrupt file reads as a MISS, never as wrong KV."""
        k, v, ks, vs = payload
        path = self._disk_file(disk_id)
        try:
            import io

            buf = io.BytesIO()
            # KV goes to disk as raw bytes (uint8 view) — np.savez has no
            # portable story for ml_dtypes bf16, and the pool dtype is known
            # at read time anyway
            arrs = {"k": np.ascontiguousarray(k).view(np.uint8),
                    "v": np.ascontiguousarray(v).view(np.uint8)}
            if ks is not None:
                arrs["ks"], arrs["vs"] = ks, vs
            np.savez(buf, **arrs)
            raw = buf.getvalue()
            crc = zlib.crc32(raw) & 0xFFFFFFFF
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
            with self._mu:
                # the id may have been dropped (node discarded) while we
                # wrote: record only a still-wanted file
                if disk_id in self._disk_pending:
                    self._disk_manifest[disk_id] = {
                        "file": os.path.basename(path), "crc": crc,
                        "nbytes": len(raw), "dtype": str(self.pool.k_pool.dtype)}
                    del self._disk_pending[disk_id]
                    self.counters["disk_spills"] += 1
                    self._write_manifest_locked()
                    return
            os.remove(path)
        except Exception:
            # failed spill: the pending payload is the only copy — dropping
            # it turns the node into a permanent miss at next promotion
            with self._mu:
                self._disk_pending.pop(disk_id, None)
                self.counters["disk_corrupt"] += 1

    def _disk_read(self, disk_id: int):
        with self._mu:
            ent = self._disk_manifest.get(disk_id)
        if ent is None:
            return None
        path = os.path.join(self._disk_dir, ent["file"])
        try:
            with open(path, "rb") as f:
                raw = f.read()
            if (zlib.crc32(raw) & 0xFFFFFFFF) != ent["crc"]:
                raise ValueError("crc mismatch")
            import io

            with np.load(io.BytesIO(raw)) as z:
                dtype = np.dtype(self.pool.k_pool.dtype)
                k = np.ascontiguousarray(z["k"]).view(dtype)
                v = np.ascontiguousarray(z["v"]).view(dtype)
                ks = z["ks"].copy() if "ks" in z.files else None
                vs = z["vs"].copy() if "vs" in z.files else None
                return k, v, ks, vs
        except Exception:
            with self._mu:
                self.counters["disk_corrupt"] += 1
            return None

    def _disk_drop(self, disk_id: int) -> None:
        with self._mu:
            self._disk_pending.pop(disk_id, None)
            ent = self._disk_manifest.pop(disk_id, None)
            if ent is not None:
                self._write_manifest_locked()
        if ent is not None:
            try:
                os.remove(os.path.join(self._disk_dir, ent["file"]))
            except OSError:
                pass

    def _write_manifest_locked(self) -> None:
        import json

        path = os.path.join(self._disk_dir, "MANIFEST.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({str(i): e for i, e in self._disk_manifest.items()},
                          f, indent=0)
            os.replace(tmp, path)
        except OSError:
            pass
