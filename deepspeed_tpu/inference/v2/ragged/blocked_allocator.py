"""Free-list KV block allocator.

Analog of the reference ``inference/v2/ragged/blocked_allocator.py:11``
(``BlockedAllocator``: fixed pool of KV-cache blocks handed out to sequences
and returned on release). Host-side bookkeeping only — the device never sees
this object, just the block-table arrays it produces.
"""

from typing import Iterable, Union

import numpy as np


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"allocator requires at least 1 block, got {num_blocks}")
        self._num_blocks = int(num_blocks)
        # singly-linked free list in a flat array (same layout the reference
        # keeps on-device; plain numpy here — it is pure host metadata)
        self._next = np.arange(1, num_blocks + 1, dtype=np.int64)
        self._head = 0
        self._free = num_blocks

    @property
    def free_blocks(self) -> int:
        return self._free

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> np.ndarray:
        """Pop ``num_blocks`` block ids; raises ValueError when exhausted
        (reference ``blocked_allocator.py:50``)."""
        if num_blocks < 1:
            raise ValueError(f"must allocate at least 1 block, got {num_blocks}")
        if num_blocks > self._free:
            raise ValueError(f"requested {num_blocks} blocks, only {self._free} free")
        out = np.empty(num_blocks, dtype=np.int64)
        for i in range(num_blocks):
            out[i] = self._head
            self._head = self._next[self._head]
        self._free -= num_blocks
        return out

    def free(self, blocks: Union[int, Iterable[int]]) -> None:
        if isinstance(blocks, (int, np.integer)):
            blocks = [int(blocks)]
        for b in blocks:
            b = int(b)
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"invalid block id {b}")
            self._next[b] = self._head
            self._head = b
            self._free += 1
