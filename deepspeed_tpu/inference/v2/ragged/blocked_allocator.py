"""Free-list KV block allocator with per-block refcounts.

Analog of the reference ``inference/v2/ragged/blocked_allocator.py:11``
(``BlockedAllocator``: fixed pool of KV-cache blocks handed out to sequences
and returned on release). Host-side bookkeeping only — the device never sees
this object, just the block-table arrays it produces.

Refcount semantics (the prefix-cache sharing substrate): ``allocate`` hands
out blocks at refcount 1; every additional holder (another sequence sharing
the block, or the prefix-cache radix tree itself) takes a reference with
``incref``; ``release`` drops one reference and only relinks the block onto
the free list when the count reaches zero. A block's contents are IMMUTABLE
while its refcount exceeds one — writers must copy-on-write first
(``BlockedKVCache.copy_block``). Releasing a free block, or a block id that
was never allocated, raises ``ValueError`` loudly instead of silently
corrupting the free list (the pre-refcount ``free`` relinked the id at the
head and over-counted ``_free``).
"""

from typing import Iterable, Union

import numpy as np


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"allocator requires at least 1 block, got {num_blocks}")
        self._num_blocks = int(num_blocks)
        # singly-linked free list in a flat array (same layout the reference
        # keeps on-device; plain numpy here — it is pure host metadata)
        self._next = np.arange(1, num_blocks + 1, dtype=np.int64)
        self._head = 0
        self._free = num_blocks
        # holders per block: 0 = on the free list
        self._refcount = np.zeros(num_blocks, dtype=np.int64)
        # cache-telemetry hook (``ragged/cache_telemetry.py``): None (the
        # default) keeps every lifecycle event at a single attribute check —
        # the zero-overhead-off contract
        self.telemetry = None
        # tenant-metering hook (``serving/metering.py`` EngineMeterView):
        # the SAME lifecycle surface, second consumer, same None contract
        self.meter = None

    @property
    def free_blocks(self) -> int:
        return self._free

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def refcount(self, block: int) -> int:
        """Current holder count of ``block`` (0 = free)."""
        b = int(block)
        if not 0 <= b < self._num_blocks:
            raise ValueError(f"invalid block id {b}")
        return int(self._refcount[b])

    def refcount_snapshot(self) -> np.ndarray:
        """Copy of the whole refcount table (telemetry's pool decomposition
        reads it; a copy so callers can never corrupt the free-list math)."""
        return self._refcount.copy()

    def allocate(self, num_blocks: int) -> np.ndarray:
        """Pop ``num_blocks`` block ids at refcount 1; raises ValueError when
        exhausted (reference ``blocked_allocator.py:50``)."""
        if num_blocks < 1:
            raise ValueError(f"must allocate at least 1 block, got {num_blocks}")
        if num_blocks > self._free:
            raise ValueError(f"requested {num_blocks} blocks, only {self._free} free")
        out = np.empty(num_blocks, dtype=np.int64)
        for i in range(num_blocks):
            out[i] = self._head
            self._head = self._next[self._head]
        self._free -= num_blocks
        self._refcount[out] = 1
        if self.telemetry is not None:
            self.telemetry.on_allocate(out)
        if self.meter is not None:
            self.meter.on_allocate(out)
        return out

    def incref(self, blocks: Union[int, Iterable[int]]) -> None:
        """Register one more holder per block (sharing). Blocks must be live."""
        for b in self._as_ids(blocks):
            if self._refcount[b] == 0:
                raise ValueError(f"incref on free block {b}: only allocated blocks can be shared")
            self._refcount[b] += 1

    def release(self, blocks: Union[int, Iterable[int]]) -> None:
        """Drop one reference per block; a block returns to the free list only
        at refcount zero. Releasing an already-free block (double free) or a
        never-allocated id raises instead of corrupting the free list."""
        freed = [] if (self.telemetry is not None or self.meter is not None) else None
        for b in self._as_ids(blocks):
            if self._refcount[b] == 0:
                raise ValueError(f"double free of block {b}: block is already on the free list")
            self._refcount[b] -= 1
            if self._refcount[b] == 0:
                self._next[b] = self._head
                self._head = b
                self._free += 1
                if freed is not None:
                    freed.append(b)
        if freed:
            if self.telemetry is not None:
                self.telemetry.on_free(freed)
            if self.meter is not None:
                self.meter.on_free(freed)

    # the historical name: one holder dropping its reference. Kept as an
    # exact alias so pre-refcount callers get the loud double-free guard
    # for free (ISSUE 3 satellite: silent free-list corruption fix).
    free = release

    def _as_ids(self, blocks):
        if isinstance(blocks, (int, np.integer)):
            blocks = [int(blocks)]
        out = []
        for b in blocks:
            b = int(b)
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"invalid block id {b}")
            out.append(b)
        return out
