from .blocked_allocator import BlockedAllocator
from .cache_telemetry import CacheTelemetry, MRCEstimator, chunk_key
from .kv_cache import BlockedKVCache
from .prefix_cache import PrefixKVCache, PrefixMatch
from .ragged_manager import DSStateManager
from .ragged_wrapper import RaggedBatch, RaggedBatchWrapper
from .sequence_descriptor import DSSequenceDescriptor
