"""Memory & KV-cache observability: block lifecycle accounting + an online
miss-ratio-curve estimator.

ROADMAP items 1 (tiered host/disk KV spill) and 2 (disaggregated prefill /
decode with cross-replica prefix sharing) are capacity-planning problems
before they are engineering problems: nobody can size a host block pool from
an aggregate hit counter. This module is the telemetry that makes those
items sizeable, in two halves:

  * :class:`CacheTelemetry` — per-block lifecycle tracking fed by narrow
    hooks in ``BlockedAllocator`` (allocate / physical free),
    ``PrefixKVCache`` (publish / hit / COW / evict) and ``DSStateManager``
    (occupancy provider). Pre-allocated numpy stamp arrays sized to the pool
    (bounded, no per-block dict entries), local histograms for block age,
    reuse interval and eviction-victim age ("how cold was what we threw
    away"), refcount-class accounting (active / tree-only / free), and
    allocator occupancy/fragmentation gauges. Events mirror onto the
    existing PR 1/5 buses: the metrics registry (when enabled) receives the
    same histogram observations under ``cache/*`` names, evictions leave a
    flight-recorder breadcrumb, and the health exporter renders
    :meth:`CacheTelemetry.gauge_rows` as labelled ``/metrics`` gauges.

  * :class:`MRCEstimator` — SHARDS-style sampled reuse-distance tracking
    (Waldspurger et al., FAST'15) over the radix ``acquire`` lookup stream
    at block-chunk granularity (one reference per full-block token chunk,
    so token-granularity up to the fixed block size), in bounded memory.
    Produces the predicted hit rate at {0.5x, 1x, 2x, 4x, 8x} the current
    block-pool capacity — the miss-ratio curve that answers "how much would
    the hit rate improve if the pool were 4x bigger" from a dashboard
    instead of a guess. Validated against an exact LRU stack-distance
    simulation in ``tests/test_cache_telemetry.py`` and against the live
    measured hit rate by ``tools/serving_load.py cache_pressure``.

Zero overhead when the ``ragged.prefix_cache.telemetry`` block is absent:
no CacheTelemetry object exists anywhere, every hook site is a single
``is not None`` check, and no per-block allocations happen (test-enforced,
the PR 5 contract).
"""

import bisect
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ....monitor.flight import get_flight_recorder
from ....monitor.metrics import Histogram, get_metrics
from ....monitor.trace import get_tracer

# seconds-scale buckets for block-lifecycle histograms (ages span from
# sub-millisecond churn in tests to hours of cold residency in production)
AGE_BUCKETS_S = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0,
                 1800.0, 7200.0, 43200.0)


def chunk_key(prev: int, tokens) -> int:
    """Rolling 32-bit key of one block-aligned token chunk, chained on the
    previous chunk's key — the radix-tree PATH identity (two chunks with the
    same tokens under different prefixes get different keys), deterministic
    across processes (crc32, not PYTHONHASHSEED-dependent ``hash``)."""
    return zlib.crc32(np.ascontiguousarray(tokens, dtype=np.int64).tobytes(),
                      prev) & 0xFFFFFFFF


class MRCEstimator:
    """Online miss-ratio-curve estimation from sampled reuse distances.

    The reference stream is block-chunk keys (see :func:`chunk_key`): each
    ``record`` call is one radix lookup's full-block chunks, in order. A
    reference at LRU stack distance ``d`` (distinct keys touched since the
    key's previous access) hits in an LRU cache of ``C`` blocks iff
    ``d < C``; SHARDS samples keys at a fixed rate ``R`` by key hash and
    scales each sampled rank by ``1/R``, so memory is bounded by the sampled
    working set (further capped at ``max_tracked`` — beyond it the coldest
    tracked key is dropped and its next access counts as a cold miss).

    Validity regime (measured in tests/test_cache_telemetry.py): key
    sampling assumes the sampled population is large relative to the hot
    head of the popularity distribution. The chunk-granular stream helps —
    a hot PREFIX is a chain of many chunk keys, each sampled independently
    — but on smoke-scale pools (tens of blocks, hundreds of refs) the
    sampled-key mix dominates the error: use ``sample_rate=1.0`` there
    (still bounded by ``max_tracked``) and reserve sub-1 rates for
    production-scale pools, where 0.25 tracks the exact simulation to
    within a few thousandths.

    Two feed kinds, mirroring what actually consumes pool capacity:

      * ``record(keys, observed_hits)`` — DEMAND references (admission-side
        ``acquire`` lookups): they enter the predicted-hit-rate accounting
        AND update recency. ``observed_hits`` is how many of them the real
        cache served (full-block radix hits), accumulated for the live
        accuracy check ``observed_hit_rate`` vs ``predict()[1.0]``.
      * ``note_insert(keys)`` — capacity-consuming insertions that are not
        demand (publish-side: a request's uncached suffix and generated
        blocks entering the tree). They update recency and push everything
        else deeper in the stack, but are not counted as references — a
        published block nobody ever looks up again must COST capacity in
        the model without inflating the predicted hit rate.
    """

    def __init__(self, capacity_blocks: int, sample_rate: float = 0.25,
                 max_tracked: int = 4096,
                 capacity_mults: Tuple[float, ...] = (0.5, 1.0, 2.0, 4.0, 8.0)):
        if not 0.0 < sample_rate <= 1.0:
            raise ValueError(f"sample_rate must be in (0, 1], got {sample_rate}")
        if capacity_blocks < 1:
            raise ValueError(f"capacity_blocks must be >= 1, got {capacity_blocks}")
        self.capacity_blocks = int(capacity_blocks)
        self.sample_rate = float(sample_rate)
        self.max_tracked = max(16, int(max_tracked))
        self.capacity_mults = tuple(float(m) for m in capacity_mults)
        self._threshold = int(self.sample_rate * (1 << 32))
        self._stamp = 0
        self._last: Dict[int, int] = {}     # sampled key -> last-access stamp
        self._stamps: List[int] = []        # the same stamps, ascending
        self._keys: List[int] = []          # parallel to _stamps
        self._hits = [0] * len(self.capacity_mults)
        self._refs_sampled = 0              # sampled demand refs (cold misses incl.)
        self.refs_total = 0                 # all demand refs, sampled or not
        self.observed_hits = 0              # real-cache full-block hits, same stream

    # -- feeds -------------------------------------------------------------
    def record(self, keys, observed_hits: int = 0) -> None:
        """One lookup's ordered full-block chunk keys + how many of them the
        REAL cache served (its shared full-block hits)."""
        for k in keys:
            self._access(int(k), counted=True)
        self.refs_total += len(keys)
        self.observed_hits += int(observed_hits)

    def note_insert(self, keys) -> None:
        """Capacity-consuming, non-demand accesses (publish-side)."""
        for k in keys:
            self._access(int(k), counted=False)

    def _access(self, key: int, counted: bool) -> None:
        if key >= self._threshold:  # unsampled: invisible to the model
            return
        self._stamp += 1
        prev = self._last.get(key)
        if prev is not None:
            idx = bisect.bisect_left(self._stamps, prev)
            rank = len(self._stamps) - idx - 1  # distinct sampled keys since
            self._stamps.pop(idx)
            self._keys.pop(idx)
            if counted:
                self._refs_sampled += 1
                dist = rank / self.sample_rate
                for i, m in enumerate(self.capacity_mults):
                    if dist < m * self.capacity_blocks:
                        self._hits[i] += 1
        else:
            if counted:
                self._refs_sampled += 1  # cold miss: denominator only
            if len(self._last) >= self.max_tracked:
                # bounded memory: drop the coldest tracked key — its next
                # access reads as a cold miss (a small hit-rate UNDER-
                # estimate at the largest capacities, never an over-promise)
                self._last.pop(self._keys.pop(0), None)
                self._stamps.pop(0)
        # the new stamp is the global max: append keeps _stamps sorted
        self._last[key] = self._stamp
        self._stamps.append(self._stamp)
        self._keys.append(key)

    # -- read side ---------------------------------------------------------
    def predict(self) -> Dict[float, Optional[float]]:
        """Predicted hit rate per capacity multiplier (None before any
        sampled reference lands — no data is not 0% hit rate)."""
        if self._refs_sampled == 0:
            return {m: None for m in self.capacity_mults}
        return {m: self._hits[i] / self._refs_sampled
                for i, m in enumerate(self.capacity_mults)}

    @property
    def observed_hit_rate(self) -> Optional[float]:
        """The REAL cache's full-block hit rate over the same reference
        stream — what ``predict()[1.0]`` claims to estimate."""
        if not self.refs_total:
            return None
        return self.observed_hits / self.refs_total

    @property
    def tracked_keys(self) -> int:
        return len(self._last)

    def reset(self) -> None:
        self._stamp = 0
        self._last.clear()
        self._stamps.clear()
        self._keys.clear()
        self._hits = [0] * len(self.capacity_mults)
        self._refs_sampled = 0
        self.refs_total = 0
        self.observed_hits = 0


class CacheTelemetry:
    """Per-block lifecycle accounting + the MRC estimator, owned by
    :class:`~.ragged_manager.DSStateManager` when the
    ``ragged.prefix_cache.telemetry`` block is enabled.

    All hook entry points are O(blocks touched) with pre-allocated state;
    gauges (occupancy, fragmentation, refcount classes) are computed on
    demand (``gauge_rows`` / ``snapshot``), never per step.
    """

    def __init__(self, kv_cache, config=None, clock=time.perf_counter):
        self.kv = kv_cache
        self._clock = clock
        nb = kv_cache.num_blocks
        self.block_size = kv_cache.block_size
        # per-block stamps: last allocate, last tree touch (publish or hit)
        self._alloc_t = np.zeros(nb, np.float64)
        self._access_t = np.zeros(nb, np.float64)
        self._tree_held = np.zeros(nb, bool)
        # lifetime event counters (ints, monotonic). The demote/promote
        # trio stays zero (and costs nothing) without a host tier.
        self.counters = {"allocated": 0, "freed": 0, "published": 0,
                         "hit_blocks": 0, "evicted": 0,
                         "demote_queued": 0, "demoted": 0, "promoted": 0}
        # local histograms: self-contained and deterministic whether or not
        # the global metrics registry is armed (the registry gets mirrored
        # observations when it is — cumulative Prometheus buckets for free)
        self.block_age_s = Histogram("cache/block_age_s", buckets=AGE_BUCKETS_S)
        self.reuse_interval_s = Histogram("cache/reuse_interval_s", buckets=AGE_BUCKETS_S)
        self.evicted_block_age_s = Histogram("cache/evicted_block_age_s",
                                             buckets=AGE_BUCKETS_S)
        # tier migration latency distributions: promote is the admission-
        # side wait a request actually eats (headline p50/p99 in the
        # serving_load host_tier A/B); demote is worker-side queue+copy time
        self.promote_latency_s = Histogram("cache/promote_latency_s",
                                           buckets=AGE_BUCKETS_S)
        self.demote_latency_s = Histogram("cache/demote_latency_s",
                                          buckets=AGE_BUCKETS_S)
        # host-tier occupancy-time integral ∫ host_used_blocks dt — the
        # host-pool ground truth the tenant meter's host_kv_s charges must
        # sum to (same conservation contract as the HBM integral below).
        # Advanced with ABSOLUTE used-counts the tier reports on every
        # transition (all under the tree lock, so no extra locking here).
        self._host_occ_blocks = 0
        self._host_occ_last_t = self._clock()
        self._host_occ_integral_s = 0.0
        # occupancy-time integral ∫ occupied_blocks dt (block-seconds),
        # advanced at every allocate/free event: the pool-side ground truth
        # the tenant meter's per-owner KV-block-second charges must sum to
        # (the PR 15 conservation acceptance check)
        self._occ_blocks = 0
        self._occ_last_t = self._clock()
        self._occ_integral_s = 0.0
        sample_rate = getattr(config, "mrc_sample_rate", 0.25) if config else 0.25
        max_tracked = getattr(config, "mrc_max_tracked", 4096) if config else 4096
        mults = getattr(config, "mrc_capacity_mults", None) if config else None
        self.mrc = MRCEstimator(nb, sample_rate=sample_rate, max_tracked=max_tracked,
                                capacity_mults=mults or (0.5, 1.0, 2.0, 4.0, 8.0))
        # (used_token_slots, seq_allocated_blocks) across live sequences —
        # set by the owning DSStateManager; None keeps fragmentation at 0
        self.occupancy_provider = None

    def _advance_occupancy(self, now, delta_blocks) -> None:
        self._occ_integral_s += self._occ_blocks * max(0.0, now - self._occ_last_t)
        self._occ_last_t = now
        self._occ_blocks = max(0, self._occ_blocks + delta_blocks)

    def occupancy_integral_s(self) -> float:
        """Block-seconds of pool occupancy since construction (the partial
        interval of currently-resident blocks included)."""
        now = self._clock()
        return self._occ_integral_s + self._occ_blocks * max(0.0, now - self._occ_last_t)

    # -- allocator hooks ---------------------------------------------------
    def on_allocate(self, blocks) -> None:
        now = self._clock()
        self._advance_occupancy(now, len(blocks))
        self._alloc_t[np.asarray(blocks, np.int64)] = now
        self.counters["allocated"] += len(blocks)

    def on_free(self, blocks) -> None:
        """Physical frees (refcount reached zero): block age = allocate ->
        free, the residency distribution of the whole pool."""
        now = self._clock()
        self._advance_occupancy(now, -len(blocks))
        reg = get_metrics()
        mirror = reg.histogram("cache/block_age_s", buckets=AGE_BUCKETS_S) \
            if reg.enabled else None
        for b in blocks:
            age = now - self._alloc_t[b]
            self.block_age_s.observe(age)
            if mirror is not None:
                mirror.observe(age)
            self._tree_held[b] = False
        self.counters["freed"] += len(blocks)

    # -- prefix-cache hooks (called under the tree lock) -------------------
    def on_publish(self, block: int) -> None:
        b = int(block)
        self._access_t[b] = self._clock()
        self._tree_held[b] = True
        self.counters["published"] += 1

    def on_hit(self, blocks) -> None:
        """A lookup took references on shared tree blocks: the interval
        since each block's previous tree touch is its reuse interval."""
        now = self._clock()
        reg = get_metrics()
        mirror = reg.histogram("cache/reuse_interval_s", buckets=AGE_BUCKETS_S) \
            if reg.enabled else None
        for b in blocks:
            prev = self._access_t[b]
            if prev > 0.0:
                self.reuse_interval_s.observe(now - prev)
                if mirror is not None:
                    mirror.observe(now - prev)
            self._access_t[b] = now
        self.counters["hit_blocks"] += len(blocks)

    def on_evict(self, block: int) -> None:
        """Eviction victim: age since last touch = how cold the LRU leaf we
        threw away actually was (a steadily WARM victim age means the pool
        is too small — the direct item-1 sizing signal)."""
        b = int(block)
        now = self._clock()
        age = now - (self._access_t[b] if self._access_t[b] > 0.0 else self._alloc_t[b])
        self.evicted_block_age_s.observe(age)
        self._tree_held[b] = False
        self.counters["evicted"] += 1
        reg = get_metrics()
        if reg.enabled:
            reg.histogram("cache/evicted_block_age_s", buckets=AGE_BUCKETS_S).observe(age)
        get_flight_recorder().record("cache", "evict", block=b, age_s=round(age, 4))
        tr = get_tracer()
        if tr.enabled:
            tr.instant("cache/evict", tid="serving", block=b, age_s=round(age, 4))

    def on_tree_clear(self, blocks) -> None:
        """Eviction flush (``PrefixKVCache.clear``): the tree reference is
        gone but this was not LRU pressure — no victim-age samples."""
        self._tree_held[np.asarray(list(blocks), np.int64)] = False

    # -- tier hooks (tiered_store.py; all under the tree lock) -------------
    def on_demote_queued(self, block: int) -> None:
        """Eviction handed a block to the migration queue instead of
        dropping it (the HBM block is released NOW; the D2H completes on
        the worker)."""
        self.counters["demote_queued"] += 1
        self._tree_held[int(block)] = False

    def on_demote(self, host_used_blocks: int, wait_s: float = 0.0) -> None:
        """The migration worker finalized one demotion into the host pool:
        ``wait_s`` is enqueue→resident (queue wait + D2H + host write)."""
        self.counters["demoted"] += 1
        self.demote_latency_s.observe(max(0.0, wait_s))
        self.note_host_used(host_used_blocks)
        reg = get_metrics()
        if reg.enabled:
            reg.histogram("cache/demote_latency_s",
                          buckets=AGE_BUCKETS_S).observe(max(0.0, wait_s))

    def on_promote(self, block: int, wait_s: float = 0.0,
                   from_disk: bool = False) -> None:
        """A demoted chain hit was restored to HBM on the admission path:
        ``wait_s`` is the synchronous H2D (+ disk read) the request ate."""
        self.counters["promoted"] += 1
        self._tree_held[int(block)] = True
        self._access_t[int(block)] = self._clock()
        self.promote_latency_s.observe(max(0.0, wait_s))
        reg = get_metrics()
        if reg.enabled:
            reg.histogram("cache/promote_latency_s",
                          buckets=AGE_BUCKETS_S).observe(max(0.0, wait_s))

    def note_host_used(self, used_blocks: int) -> None:
        """Advance the host occupancy-time integral to an ABSOLUTE used
        count (the tier reports after every host-pool transition)."""
        now = self._clock()
        self._host_occ_integral_s += self._host_occ_blocks * max(0.0, now - self._host_occ_last_t)
        self._host_occ_last_t = now
        self._host_occ_blocks = max(0, int(used_blocks))

    def host_occupancy_integral_s(self) -> float:
        """Host-block-seconds of tier occupancy since construction (current
        residents' partial interval included) — what the per-tenant
        ``host_kv_s`` charges must reconcile against."""
        now = self._clock()
        return self._host_occ_integral_s + self._host_occ_blocks * max(0.0, now - self._host_occ_last_t)

    # -- MRC feed (called under the tree lock) -----------------------------
    def record_lookup(self, keys, observed_hits: int) -> None:
        self.mrc.record(keys, observed_hits)

    def record_inserts(self, keys) -> None:
        self.mrc.note_insert(keys)

    # -- read side ---------------------------------------------------------
    def refcount_classes(self) -> Dict[str, int]:
        """Exact pool decomposition by holder class: ``free`` (refcount 0),
        ``tree_only`` (the radix tree is the sole holder — evictable cold
        capacity), ``active`` (some sequence holds it, shared or not)."""
        rc = self.kv.refcount_snapshot()
        free = int((rc == 0).sum())
        tree_only = int(((rc == 1) & self._tree_held).sum())
        return {"free": free, "tree_only": tree_only,
                "active": int(rc.size) - free - tree_only}

    def occupancy(self) -> float:
        total = self.kv.total_blocks
        return (total - self.kv.free_blocks) / total

    def fragmentation(self) -> float:
        """Internal fragmentation of live-sequence allocations: the fraction
        of their allocated token slots not (yet) holding KV — partial tails
        and decode-horizon headroom. Tree-held blocks are full by
        construction, so this is exactly the slack a block-size change or a
        tail-packing scheme could recover."""
        if self.occupancy_provider is None:
            return 0.0
        used, allocated = self.occupancy_provider()
        if allocated == 0:
            return 0.0
        return max(0.0, 1.0 - used / (allocated * self.block_size))

    def gauge_rows(self, labels: Optional[dict] = None):
        """Labelled gauge rows for the health exporter's ``/metrics``
        (``HealthPlane.set_gauge_provider`` shape). ``labels`` are merged
        into every row — a multi-replica gateway passes a per-engine label
        so replicas' series stay distinct instead of colliding."""
        base = dict(labels or {})

        def row(name, extra, v):
            return (name, {**base, **extra}, v)

        rows = []
        for m, v in self.mrc.predict().items():
            if v is not None:
                rows.append(row("serving/mrc_hit_rate", {"capacity_mult": f"{m:g}"}, v))
        ohr = self.mrc.observed_hit_rate
        if ohr is not None:
            rows.append(row("serving/mrc_observed_hit_rate", {}, ohr))
        for cls, n in self.refcount_classes().items():
            rows.append(row("cache/blocks", {"class": cls}, n))
        rows.append(row("cache/occupancy", {}, self.occupancy()))
        rows.append(row("cache/fragmentation", {}, self.fragmentation()))
        rows.append(row("cache/block_age_p50_s", {}, self.block_age_s.percentile(50)))
        rows.append(row("cache/reuse_interval_p50_s", {},
                        self.reuse_interval_s.percentile(50)))
        rows.append(row("cache/evicted_block_age_p50_s", {},
                        self.evicted_block_age_s.percentile(50)))
        if self.counters["demote_queued"] or self._host_occ_blocks:
            rows.append(row("cache/host_blocks_used", {}, self._host_occ_blocks))
            rows.append(row("cache/promote_latency_p50_s", {},
                            self.promote_latency_s.percentile(50)))
        return rows

    def snapshot(self) -> dict:
        """One JSON-able dict: the bench/tool surface (``bench.py``'s
        ``cache{...}`` block and ``serving_load.py cache_pressure``)."""
        return {
            "counters": dict(self.counters),
            "classes": self.refcount_classes(),
            "occupancy": round(self.occupancy(), 4),
            "occupancy_integral_s": round(self.occupancy_integral_s(), 6),
            "fragmentation": round(self.fragmentation(), 4),
            "block_age_s": self.block_age_s.summary(),
            "reuse_interval_s": self.reuse_interval_s.summary(),
            "evicted_block_age_s": self.evicted_block_age_s.summary(),
            "mrc": {f"{m:g}x": (round(v, 4) if v is not None else None)
                    for m, v in self.mrc.predict().items()},
            "mrc_observed_hit_rate": (round(self.mrc.observed_hit_rate, 4)
                                      if self.mrc.observed_hit_rate is not None else None),
            "mrc_refs": self.mrc.refs_total,
            "mrc_tracked_keys": self.mrc.tracked_keys,
            "tiers": {
                "demote_queued": self.counters["demote_queued"],
                "demoted": self.counters["demoted"],
                "promoted": self.counters["promoted"],
                "host_blocks_used": self._host_occ_blocks,
                "host_occupancy_integral_s": round(self.host_occupancy_integral_s(), 6),
                "promote_latency_s": self.promote_latency_s.summary(),
                "demote_latency_s": self.demote_latency_s.summary(),
            },
        }

    def reset(self) -> None:
        """Zero every accumulator (A/B harnesses reset between arms). Stamp
        arrays and tree-held flags are LIVE state, not accumulators — they
        track blocks still resident and survive the reset."""
        self.mrc.reset()
        for k in self.counters:
            self.counters[k] = 0
        self.block_age_s = Histogram("cache/block_age_s", buckets=AGE_BUCKETS_S)
        self.reuse_interval_s = Histogram("cache/reuse_interval_s", buckets=AGE_BUCKETS_S)
        self.evicted_block_age_s = Histogram("cache/evicted_block_age_s",
                                             buckets=AGE_BUCKETS_S)
        self.promote_latency_s = Histogram("cache/promote_latency_s",
                                           buckets=AGE_BUCKETS_S)
        self.demote_latency_s = Histogram("cache/demote_latency_s",
                                          buckets=AGE_BUCKETS_S)
        # the host occupancy INTEGRAL is an accumulator; the current used
        # count is live state and survives (same rule as the stamp arrays)
        self._host_occ_integral_s = 0.0
        self._host_occ_last_t = self._clock()
