"""Per-sequence tracking state.

Analog of the reference ``inference/v2/ragged/sequence_descriptor.py``
(``DSSequenceDescriptor``: seen tokens, KV block ids, in-flight count). The
reference mirrors this metadata into pinned host tensors; on TPU the metadata
lives as plain numpy and is shipped to the device once per forward inside the
``RaggedBatchWrapper`` arrays.
"""

from dataclasses import dataclass, field
from typing import List

import numpy as np


@dataclass
class DSSequenceDescriptor:
    uid: int
    block_size: int
    seen_tokens: int = 0  # tokens whose KV is already materialized
    in_flight_tokens: int = 0  # tokens scheduled in the current forward
    kv_blocks: List[int] = field(default_factory=list)
    # prefix-cache bookkeeping: the token ids behind the materialized KV (so
    # completed full blocks can be published into the radix tree), how many
    # leading blocks arrived SHARED from the tree (immutable for this
    # sequence), and how many prompt tokens the cache let prefill skip.
    # ``history_valid`` drops to False when generated tokens were never
    # fetched to host (decode(block=False)) — publishing then stops at the
    # last known-token boundary forever, never guesses.
    token_history: List[int] = field(default_factory=list)
    history_valid: bool = True
    shared_blocks: int = 0
    prefix_cached_tokens: int = 0
    published_blocks: int = 0  # publish() walk cursor: full blocks already walked
    # owner identity (serving/metering.py): stamped at creation when the
    # request plane knows a tenant; rides into published radix-tree nodes
    # so hits and eviction pressure are attributable. None = untenanted.
    tenant: str = None

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self.kv_blocks)

    @property
    def max_context(self) -> int:
        return len(self.kv_blocks) * self.block_size

    def blocks_needed(self, new_tokens: int) -> int:
        """Additional blocks required to hold ``new_tokens`` more KV entries."""
        total = self.seen_tokens + new_tokens
        need = -(-total // self.block_size)  # ceil
        return max(0, need - len(self.kv_blocks))

    def extend_blocks(self, blocks) -> None:
        self.kv_blocks.extend(int(b) for b in np.atleast_1d(blocks))

    def pre_forward(self, num_tokens: int) -> None:
        self.in_flight_tokens = num_tokens

    def post_forward(self) -> None:
        self.seen_tokens += self.in_flight_tokens
        self.in_flight_tokens = 0

    def block_table(self, max_blocks: int) -> np.ndarray:
        out = np.zeros(max_blocks, dtype=np.int32)
        n = min(len(self.kv_blocks), max_blocks)
        out[:n] = self.kv_blocks[:n]
        return out
