"""Sequence state manager.

Analog of the reference ``inference/v2/ragged/ragged_manager.py:19``
(``DSStateManager``: tracked sequences → KV block tables, owns the
``BlockedKVCache``). With ``prefix_cache`` enabled it also owns the
:class:`PrefixKVCache` radix tree: sequence creation pre-populates the block
table and ``seen_tokens`` from the longest cached prefix, completed full
blocks are published back on the way out, and every block release routes
through the refcount-aware path (``tools/check_kv_blocks.py`` gates raw
``.free`` calls out of this plane).
"""

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...config import DeepSpeedInferenceConfig  # noqa: F401  (parity import)
from .blocked_allocator import BlockedAllocator  # noqa: F401
from .cache_telemetry import CacheTelemetry
from .kv_cache import BlockedKVCache
from .prefix_cache import PrefixKVCache
from .sequence_descriptor import DSSequenceDescriptor


class DSStateManager:

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int, *, max_tracked_sequences: int = 128,
                 num_blocks: int = 256, block_size: int = 64, dtype=jnp.bfloat16, kv_sharding=None,
                 prefix_cache_config=None):
        self.max_tracked_sequences = max_tracked_sequences
        self.block_size = block_size
        self.kv_cache = BlockedKVCache(num_layers, num_kv_heads, head_dim, num_blocks, block_size, dtype=dtype,
                                       sharding=kv_sharding)
        self.prefix_cache: Optional[PrefixKVCache] = None
        # host/disk capacity tier under the radix tree (tiered_store.py);
        # None whenever ragged.prefix_cache.host_tier is absent/disabled —
        # the zero-overhead-absent contract
        self.tiered_store = None
        # memory & cache observability plane (``ragged.prefix_cache.telemetry``
        # block): when absent/off, NO telemetry object exists anywhere and
        # every hook in the allocator/tree stays one `is not None` check —
        # the zero-overhead contract tests/test_cache_telemetry.py enforces
        self.cache_telemetry: Optional[CacheTelemetry] = None
        tel_cfg = getattr(prefix_cache_config, "telemetry", None) \
            if prefix_cache_config is not None else None
        if prefix_cache_config is not None and getattr(prefix_cache_config, "enabled", False):
            if tel_cfg is not None and getattr(tel_cfg, "enabled", False):
                self.cache_telemetry = CacheTelemetry(self.kv_cache, config=tel_cfg)
                self.cache_telemetry.occupancy_provider = self._occupancy
                self.kv_cache.set_telemetry(self.cache_telemetry)
            self.prefix_cache = PrefixKVCache(self.kv_cache,
                                              min_hit_blocks=prefix_cache_config.min_hit_blocks,
                                              eviction=prefix_cache_config.eviction,
                                              telemetry=self.cache_telemetry)
            ht_cfg = getattr(prefix_cache_config, "host_tier", None)
            if ht_cfg is not None and getattr(ht_cfg, "enabled", False):
                # host/disk capacity tier (ragged.prefix_cache.host_tier):
                # presence-enabled — this branch is the ONLY place tier
                # objects (and the migration worker thread) come to exist
                from .tiered_store import TieredBlockStore

                self.tiered_store = TieredBlockStore(self.kv_cache, ht_cfg,
                                                     telemetry=self.cache_telemetry)
                self.prefix_cache.attach_tier(self.tiered_store)
        elif tel_cfg is not None and getattr(tel_cfg, "enabled", False):
            # the telemetry plane rides the prefix cache (blocks only have a
            # reuse lifecycle once the radix tree shares them) — an enabled
            # telemetry block under a disabled cache would otherwise vanish
            # silently and cost someone a dashboard-debugging session
            from ....utils.logging import logger

            logger.warning("ragged.prefix_cache.telemetry.enabled=True ignored: "
                           "the prefix cache itself is disabled — enable "
                           "ragged.prefix_cache to arm cache telemetry")
        self._seqs: Dict[int, DSSequenceDescriptor] = {}
        # tenant metering view (serving/metering.py EngineMeterView): set by
        # the engine's set_tenant_meter; None keeps every stamp site below
        # at one attribute check (the zero-overhead-off contract)
        self.tenant_meter = None

    def set_tenant_meter(self, view) -> None:
        """Wire (or with None, unwire) a per-engine tenant-meter view into
        the block lifecycle: the allocator's allocate/free hooks (alongside
        cache telemetry), owner stamping here, and the prefix cache's
        tenant-level publish/hit/evict forwards."""
        self.tenant_meter = view
        self.kv_cache.set_meter(view)
        if self.prefix_cache is not None:
            self.prefix_cache.set_meter(view)

    # -- queries -----------------------------------------------------------
    @property
    def n_tracked_sequences(self) -> int:
        return len(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.kv_cache.free_blocks

    @property
    def available_blocks(self) -> int:
        """Blocks a new allocation could actually obtain: the free list plus
        what LRU eviction could reclaim from tree-only holders. Admission
        must budget against THIS, not ``free_blocks`` — a warm cache keeps
        the free list near empty by design."""
        free = self.kv_cache.free_blocks
        if self.prefix_cache is not None:
            free += self.prefix_cache.evictable_blocks
        return free

    def _occupancy(self):
        """(used_token_slots, allocated_blocks) over live sequences — the
        cache telemetry's fragmentation numerator/denominator. Tree-held
        blocks are full by construction and excluded; the slack measured
        here is exactly partial tails + decode-horizon headroom."""
        used = allocated = 0
        bs = self.block_size
        # list(): the health exporter thread calls this mid-scrape while the
        # replica driver mutates _seqs — iterating the live dict would raise
        for seq in list(self._seqs.values()):
            allocated += len(seq.kv_blocks)
            used += min(seq.seen_tokens + seq.in_flight_tokens, len(seq.kv_blocks) * bs)
        return used, allocated

    def query(self, uid: Optional[int] = None):
        """Reference ``engine_v2.query``-backing lookup: per-sequence state
        or the (tracked, free-block) summary."""
        if uid is None:
            out = {"tracked": self.n_tracked_sequences, "free_blocks": self.free_blocks}
            if self.prefix_cache is not None:
                out["prefix_cache"] = dict(self.prefix_cache.stats,
                                           cached_blocks=self.prefix_cache.n_cached_blocks,
                                           hit_rate=self.prefix_cache.hit_rate)
            if self.tiered_store is not None:
                out["host_tier"] = self.tiered_store.snapshot()
            return out
        return self._seqs.get(uid)

    # -- lifecycle ---------------------------------------------------------
    def get_sequence(self, uid: int) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create_sequence(self, uid: int) -> DSSequenceDescriptor:
        """Reference ``ragged_manager.py:135``."""
        seq = self._seqs.get(uid)
        if seq is not None:
            return seq
        return self.create_sequence_with_prefix(uid, None)[0]

    def create_sequence_with_prefix(self, uid: int, prompt_tokens, match=None,
                                    tenant=None) -> Tuple[DSSequenceDescriptor, int]:
        """Create a FRESH sequence, pre-populated from the prefix cache when
        ``prompt_tokens`` (the tokens about to be fed) hit the radix tree:
        the block table starts with the shared run (plus a COW tail copy)
        and ``seen_tokens`` at the hit length, so prefill starts AFTER the
        hit. ``match`` (from a prior pure probe) skips the re-match.
        Returns ``(seq, n_cached_tokens)`` — the caller must skip the
        first ``n_cached_tokens`` of ``prompt_tokens`` when feeding."""
        if uid in self._seqs:
            raise ValueError(f"uid {uid} already tracked: prefix acquisition is create-only")
        if len(self._seqs) >= self.max_tracked_sequences:
            raise RuntimeError(f"already tracking {self.max_tracked_sequences} sequences")
        seq = DSSequenceDescriptor(uid=uid, block_size=self.block_size)
        seq.tenant = tenant
        n_cached = 0
        if self.prefix_cache is not None and prompt_tokens is not None:
            prompt_tokens = np.asarray(prompt_tokens).reshape(-1)
            blocks, n_cached, shared = self.prefix_cache.acquire(prompt_tokens, match=match,
                                                                 tenant=tenant)
            if n_cached:
                seq.kv_blocks = [int(b) for b in blocks]
                seq.seen_tokens = n_cached
                seq.shared_blocks = shared
                seq.prefix_cached_tokens = n_cached
                seq.token_history = [int(t) for t in prompt_tokens[:n_cached]]
        self._seqs[uid] = seq
        return seq, n_cached

    def allocate_blocks(self, seq: DSSequenceDescriptor, new_tokens: int) -> None:
        """Reference ``model.maybe_allocate_kv`` → ``BlockedKVCache.reserve``,
        with the prefix cache as the pressure valve: a dry free list evicts
        LRU tree-only blocks before the reserve."""
        need = seq.blocks_needed(new_tokens)
        if need > 0:
            if self.prefix_cache is not None and need > self.kv_cache.free_blocks:
                self.prefix_cache.evict(need - self.kv_cache.free_blocks)
            fresh = self.kv_cache.reserve(need)
            if self.tenant_meter is not None:
                # block-second attribution: the sequence's owner holds the
                # residency of every block it materializes KV into
                self.tenant_meter.stamp(fresh, seq.tenant)
            seq.extend_blocks(fresh)
        if self.tiered_store is not None:
            # proactive watermark demotion: below low_watermark free HBM,
            # push cold tree-only leaves toward the host tier so demand
            # eviction rarely demotes inline on the admission path. O(1)
            # when above the watermark.
            target = self.tiered_store.demotion_target()
            if target > 0:
                self.prefix_cache.demote_cold(target)

    def note_tokens(self, seq: DSSequenceDescriptor, tokens) -> None:
        """Record the token ids being materialized this forward (put chunk,
        or the fetched results of a decode burst) so completed full blocks
        can be published. Non-contiguous appends (a gap the host never saw)
        permanently stop publishing for this sequence instead of guessing."""
        if self.prefix_cache is None or not seq.history_valid:
            return
        if len(seq.token_history) != seq.seen_tokens:
            seq.history_valid = False
            return
        seq.token_history.extend(int(t) for t in np.asarray(tokens).reshape(-1))

    def publish_sequence(self, seq: DSSequenceDescriptor) -> None:
        """Insert ``seq``'s completed full blocks into the radix tree."""
        if self.prefix_cache is not None and seq.history_valid:
            self.prefix_cache.publish(seq)

    def rollback_to(self, seq: DSSequenceDescriptor, n_tokens: int,
                    final: bool = False) -> int:
        """THE single sequence-rewind primitive for the serving plane
        (speculative-draft rejection, decode-horizon overshoot at early
        finish/cancel — ``tools/check_spec_rollback.py`` gates all other
        rewind sites out): truncate ``token_history``, rewind
        ``seen_tokens`` to ``n_tokens``, and release now-unreferenced tail
        blocks back through the refcount-aware path — a block shared with
        the radix tree (or another sequence) merely loses THIS sequence's
        reference and survives for the other holders. Returns the number of
        tail references released.

        If the rewind lands mid-block in a block that is still SHARED, the
        block is copy-on-write duplicated first: the sequence's next tokens
        will scatter into the tail slots, and writing into a shared block
        would corrupt every other holder's view. The duplicate is reserved
        BEFORE any state mutates, so a dry pool fails the call atomically
        (the sequence is untouched). ``final=True`` skips the COW guard —
        the caller promises the sequence will never be written again (it is
        about to be flushed: finish/cancel paths), so a shared partial tail
        is harmless and a dry pool cannot fail a terminal rewind."""
        n_tokens = int(n_tokens)
        if not 0 <= n_tokens <= seq.seen_tokens:
            raise ValueError(f"rollback_to({n_tokens}): sequence {seq.uid} has "
                             f"{seq.seen_tokens} materialized tokens")
        if seq.in_flight_tokens:
            raise RuntimeError(f"rollback_to on sequence {seq.uid} with "
                               f"{seq.in_flight_tokens} tokens in flight: rewinds happen "
                               "BETWEEN forwards only")
        bs = self.block_size
        keep = -(-n_tokens // bs)  # blocks still (partially) holding kept KV
        cow_src = cow_dst = None
        if (not final and n_tokens % bs and keep
                and self.kv_cache.refcount(seq.kv_blocks[keep - 1]) > 1):
            # COW guard: the new tail block is partial AND shared — future
            # appends would scatter into slots other holders read. Reserve
            # + copy first: if the pool is truly dry this raises with the
            # sequence still in its pre-rollback state.
            cow_src = seq.kv_blocks[keep - 1]
            if self.prefix_cache is not None and self.kv_cache.free_blocks < 1:
                self.prefix_cache.evict(1)
            cow_dst = int(self.kv_cache.reserve(1)[0])
            if self.tenant_meter is not None:
                self.tenant_meter.stamp([cow_dst], seq.tenant)
            self.kv_cache.copy_block(cow_src, cow_dst)
        tail = seq.kv_blocks[keep:]
        del seq.kv_blocks[keep:]
        if tail:
            self.kv_cache.release(tail)
        seq.seen_tokens = n_tokens
        if len(seq.token_history) > n_tokens:
            del seq.token_history[n_tokens:]
        seq.published_blocks = min(seq.published_blocks, n_tokens // bs)
        seq.shared_blocks = min(seq.shared_blocks, keep)
        if cow_dst is not None:
            seq.kv_blocks[keep - 1] = cow_dst
            self.kv_cache.release(cow_src)
            seq.shared_blocks = min(seq.shared_blocks, keep - 1)
        return len(tail)

    def commit_speculative(self, seq: DSSequenceDescriptor, n_tokens: int,
                           committed_tokens=None, src_positions=None) -> int:
        """Tree-verification commit: the branched cousin of a plain
        :meth:`rollback_to`. The verify forward materialized the WHOLE
        flattened token tree (every branch at its own flat slot) and noted
        the flat chunk into ``token_history``; the accepted path is in
        general NOT the flat prefix, so three things must happen together
        (same plane, same call — exactly why rollback_to is single-homed):

        1. when ``src_positions`` is given, the winning branch's KV moves
           from its flat tree slots to the canonical contiguous positions
           (``BlockedKVCache.compact_slots`` — dst strictly below src, both
           inside blocks this sequence exclusively owns: publish only ever
           shares FULL blocks, and the tree region starts past the last
           published boundary);
        2. ``rollback_to(n_tokens)`` releases the rejected remainder;
        3. ``committed_tokens`` overwrites the history tail so the radix
           tree can only ever see the VERIFIED stream — a rejected sibling
           branch's tokens must never be publishable.

        Returns rollback_to's released-reference count."""
        if src_positions:
            bs = self.block_size
            src = [seq.kv_blocks[p // bs] * bs + p % bs for p, _ in src_positions]
            dst = [seq.kv_blocks[p // bs] * bs + p % bs for _, p in src_positions]
            self.kv_cache.compact_slots(src, dst)
        released = self.rollback_to(seq, n_tokens)
        if committed_tokens is not None and seq.history_valid:
            m = len(committed_tokens)
            if m and len(seq.token_history) >= n_tokens >= m:
                seq.token_history[n_tokens - m:n_tokens] = [int(t) for t in committed_tokens]
        return released

    def shutdown(self) -> None:
        """Stop the tier's migration worker (engine destroy / test teardown);
        a no-op without a tier."""
        if self.tiered_store is not None:
            self.tiered_store.shutdown()

    def flush_sequence(self, uid: int) -> None:
        """Release a finished sequence's block references (reference
        ``flush:228``): publish completed full blocks first (the tree takes
        its own reference), then drop the sequence's — a block only goes
        physically free when no sequence AND no tree node holds it."""
        seq = self._seqs.pop(uid, None)
        if seq is None:
            return
        self.publish_sequence(seq)
        if seq.kv_blocks:
            self.kv_cache.release(seq.kv_blocks)
