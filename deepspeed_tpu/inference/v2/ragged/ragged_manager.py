"""Sequence state manager.

Analog of the reference ``inference/v2/ragged/ragged_manager.py:19``
(``DSStateManager``: tracked sequences → KV block tables, owns the
``BlockedKVCache``).
"""

from typing import Dict, Optional

import jax.numpy as jnp

from ...config import DeepSpeedInferenceConfig  # noqa: F401  (parity import)
from .blocked_allocator import BlockedAllocator  # noqa: F401
from .kv_cache import BlockedKVCache
from .sequence_descriptor import DSSequenceDescriptor


class DSStateManager:

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int, *, max_tracked_sequences: int = 128,
                 num_blocks: int = 256, block_size: int = 64, dtype=jnp.bfloat16, kv_sharding=None):
        self.max_tracked_sequences = max_tracked_sequences
        self.block_size = block_size
        self.kv_cache = BlockedKVCache(num_layers, num_kv_heads, head_dim, num_blocks, block_size, dtype=dtype,
                                       sharding=kv_sharding)
        self._seqs: Dict[int, DSSequenceDescriptor] = {}

    # -- queries -----------------------------------------------------------
    @property
    def n_tracked_sequences(self) -> int:
        return len(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.kv_cache.free_blocks

    def query(self, uid: Optional[int] = None):
        """Reference ``engine_v2.query``-backing lookup: per-sequence state
        or the (tracked, free-block) summary."""
        if uid is None:
            return {"tracked": self.n_tracked_sequences, "free_blocks": self.free_blocks}
        return self._seqs.get(uid)

    # -- lifecycle ---------------------------------------------------------
    def get_sequence(self, uid: int) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create_sequence(self, uid: int) -> DSSequenceDescriptor:
        """Reference ``ragged_manager.py:135``."""
        seq = self._seqs.get(uid)
        if seq is not None:
            return seq
        if len(self._seqs) >= self.max_tracked_sequences:
            raise RuntimeError(f"already tracking {self.max_tracked_sequences} sequences")
        seq = DSSequenceDescriptor(uid=uid, block_size=self.block_size)
        self._seqs[uid] = seq
        return seq

    def allocate_blocks(self, seq: DSSequenceDescriptor, new_tokens: int) -> None:
        """Reference ``model.maybe_allocate_kv`` → ``BlockedKVCache.reserve``."""
        need = seq.blocks_needed(new_tokens)
        if need > 0:
            seq.extend_blocks(self.kv_cache.reserve(need))

    def flush_sequence(self, uid: int) -> None:
        """Release a finished sequence's blocks (reference ``flush:228``)."""
        seq = self._seqs.pop(uid, None)
        if seq is None:
            return
        if seq.kv_blocks:
            self.kv_cache.free(seq.kv_blocks)
