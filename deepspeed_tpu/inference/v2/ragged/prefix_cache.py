"""Prefix-cache subsystem: radix-tree reuse of refcounted KV blocks.

Production request streams are dominated by shared prefixes (system prompts,
few-shot templates, multi-turn histories). This module turns that overlap
into skipped prefill: a radix tree keyed on BLOCK-ALIGNED token-id chunks
maps a new prompt to its longest run of already-materialized KV blocks
(PagedAttention block sharing, Kwon et al. SOSP'23; RadixAttention LRU tree,
Zheng et al. 2023). The serving plane then starts prefill AFTER the hit —
``DSSequenceDescriptor.seen_tokens`` pre-seeded, block table pre-populated.

Invariants this subsystem threads through allocator / tree / state manager /
scheduler / engine (asserted by ``tests/test_prefix_cache.py`` and the
``test_engine_churn_invariants_prefix_cache`` fuzz):

  * a block's contents are IMMUTABLE while shared (refcount > 1, or held by
    the tree): sequences never write into full blocks, and a partial-tail
    hit duplicates the block first (copy-on-write, ``kv_cache.copy_block``);
  * every holder is counted: each sequence sharing a block and the tree
    itself own exactly one reference; physical free happens only at zero;
  * only FULL blocks enter the tree (a partial block's tail is still being
    written by its owner), and eviction removes LRU LEAVES whose sole holder
    is the tree — so eviction never yanks a block out from under a sequence.
"""

import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ....monitor.flight import get_flight_recorder
from ....monitor.metrics import get_metrics
from .cache_telemetry import chunk_key
from .tiered_store import RES_DISK, RES_HBM, RES_HOST, RES_IN_FLIGHT


class _Node:
    """One radix-tree edge = one full KV block: ``chunk`` (block_size token
    ids) → ``block`` (physical block id). Children keyed by their chunk.
    ``owner`` is the publishing sequence's tenant (serving metering): one
    string reference, stamped at insert — it makes hits and eviction
    pressure attributable per tenant, and is the exact prerequisite for
    ROADMAP item 4's tenant-prefixed radix keys.

    ``res``/``host_block``/``disk_id`` are the tiered-store residency
    fields (``tiered_store.py``): which tier holds this chunk's KV and its
    slot there. Without a host tier they stay at the class-constant-like
    defaults forever (shared small ints / interned str — no per-block
    allocations, preserving the zero-overhead-absent contract). The
    invariant the tier maintains: along any root→leaf path residency is
    monotone ``hbm* (in_flight|host)* disk*`` — a demoted node never sits
    above an HBM one, so the match walk's HBM run is always a tree prefix."""

    __slots__ = ("chunk", "block", "parent", "children", "last_access", "owner",
                 "res", "host_block", "disk_id")

    def __init__(self, chunk, block, parent, owner=None):
        self.chunk = chunk
        self.block = int(block)
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_access = 0
        self.owner = owner
        self.res = RES_HBM
        self.host_block = -1
        self.disk_id = -1


@dataclass
class PrefixMatch:
    """Result of a (pure) longest-prefix walk."""

    n_cached_tokens: int = 0      # tokens of prompt covered (full + COW tail)
    shared_blocks: List[int] = field(default_factory=list)  # HBM full-block hits
    cow_src: Optional[int] = None  # block to duplicate for a partial tail
    cow_tokens: int = 0            # tokens of the COW block that are reusable
    # demoted chain matched past the HBM run (host/disk residency): COUNT
    # only — the blocks have no HBM id yet; ``acquire`` promotes them.
    # Admission treats these as uncached supply-wise (promotion charges the
    # budget like uncached tokens), so they are deliberately NOT part of
    # ``shared_blocks``.
    host_blocks: int = 0

    @property
    def hit_blocks(self) -> int:
        return (len(self.shared_blocks) + self.host_blocks
                + (1 if self.cow_src is not None else 0))


class PrefixKVCache:
    """Radix tree over a :class:`BlockedKVCache`'s refcounted blocks.

    ``acquire`` is the admission-side entry (match + take references + COW),
    ``publish`` the exit side (insert a sequence's completed full blocks),
    ``evict`` the allocator's pressure valve (LRU leaves, tree-only holders).
    LRU ordering uses a monotonic access counter, not wall time, so eviction
    is deterministic under test/bench replay.
    """

    def __init__(self, kv_cache, min_hit_blocks: int = 1, eviction: str = "lru",
                 telemetry=None):
        if eviction != "lru":
            raise ValueError(f"unknown eviction policy {eviction!r}: 'lru'")
        if min_hit_blocks < 1:
            raise ValueError(f"min_hit_blocks must be >= 1, got {min_hit_blocks}")
        self.kv_cache = kv_cache
        self.block_size = kv_cache.block_size
        self.min_hit_blocks = int(min_hit_blocks)
        self.eviction = eviction
        # block-lifecycle + MRC observability (``cache_telemetry.py``); None
        # keeps every hook below at a single attribute check
        self._telemetry = telemetry
        # tenant metering view (serving/metering.py EngineMeterView), wired
        # by DSStateManager.set_tenant_meter: hit attribution via node
        # owners, publish credit, eviction pressure. Same None contract.
        self._meter = None
        # host/disk capacity tier (tiered_store.TieredBlockStore), wired by
        # attach_tier when ragged.prefix_cache.host_tier is present. Same
        # None contract: absent ⇒ every tier branch is one attribute check.
        self._tier = None
        self._root = _Node(chunk=(), block=-1, parent=None)
        self._n_nodes = 0
        self._clock = 0  # monotonic LRU clock
        # the serving gateway's router/admission probe the tree with `match`
        # from HTTP handler threads while the replica driver publishes/evicts
        # — concurrent dict iteration against a mutating node.children is a
        # CPython RuntimeError, so every tree walk serializes on this lock.
        # RLock: acquire() reaches evict() through _reserve_with_eviction.
        # Uncontended cost is ~100ns per op, noise against a forward.
        self._tree_lock = threading.RLock()
        # evicted_tokens/cow_bytes: eviction used to count blocks only, so
        # token-level cache-pressure math (serving_load, the MRC accuracy
        # check) had to approximate — both also ride the Prometheus
        # registry as cache/evicted_tokens + cache/cow_bytes counters
        self.stats = {"lookups": 0, "hits": 0, "cached_tokens": 0, "cow_copies": 0,
                      "insertions": 0, "evictions": 0, "evicted_tokens": 0,
                      "cow_bytes": 0,
                      # tier lifecycle (all zero and inert without a tier)
                      "demotions_queued": 0, "promotions": 0,
                      "promoted_tokens": 0, "promote_wait_s": 0.0,
                      "evict_starved": 0, "readoptions": 0,
                      "host_installed": 0}

    # -- queries -----------------------------------------------------------
    @property
    def n_cached_blocks(self) -> int:
        return self._n_nodes

    @property
    def hit_rate(self) -> float:
        return self.stats["hits"] / self.stats["lookups"] if self.stats["lookups"] else 0.0

    def cached_block_ids(self) -> List[int]:
        """HBM block ids currently held by the tree (one tree reference
        each). Demoted nodes have no HBM block and are excluded."""
        with self._tree_lock:
            return [n.block for n in self._iter_nodes() if n.res == RES_HBM]

    @property
    def evictable_blocks(self) -> int:
        """HBM blocks eviction could return to the free list RIGHT NOW:
        tree-held blocks whose only reference is the tree's (demoted nodes
        hold no HBM block — ``available_blocks`` stays HBM-only by
        construction). Exact, not an upper bound: a sequence holding a node
        always holds its whole ancestor path (``acquire`` pins the matched
        run, ``publish`` descends only through blocks the publisher holds),
        so a sole-owner node's entire subtree is sole-owner too and repeated
        leaf eviction reaches all of it. O(tree) per call — fine at the
        current pool scale; an incrementally maintained counter needs
        refcount-transition hooks in the allocator and is the first thing
        to add if admission ever shows up hot."""
        with self._tree_lock:
            return sum(1 for n in self._iter_nodes()
                       if n.res == RES_HBM and self.kv_cache.refcount(n.block) == 1)

    @property
    def host_resident_blocks(self) -> int:
        """Nodes whose KV currently lives in the host (or disk) tier."""
        with self._tree_lock:
            return sum(1 for n in self._iter_nodes()
                       if n.res in (RES_HOST, RES_DISK))

    def set_meter(self, view) -> None:
        """Arm (or with None, disarm) the tenant-metering forwards."""
        with self._tree_lock:
            self._meter = view
            if self._tier is not None:
                self._tier.set_meter(view)

    def attach_tier(self, tier) -> None:
        """Wire the host/disk capacity tier (``tiered_store.py``) under the
        tree: eviction demotes instead of dropping, the match walk extends
        into demoted chains, ``acquire`` promotes them back."""
        with self._tree_lock:
            self._tier = tier
            tier.attach(self)
            if self._meter is not None:
                tier.set_meter(self._meter)

    # -- admission side ----------------------------------------------------
    def match(self, tokens) -> PrefixMatch:
        """PURE longest-prefix walk (no refs taken, no LRU touch): how much
        of ``tokens`` the tree could serve. The usable prefix is capped at
        ``len(tokens) - 1`` — the engine must always compute at least the
        last prompt token to produce the first generated token.
        Thread-safe: the serving gateway's router/admission probe from HTTP
        handler threads while the owning replica driver mutates the tree."""
        tokens = np.asarray(tokens, dtype=np.int64).reshape(-1)
        with self._tree_lock:
            return self._match_locked(tokens)

    def _match_locked(self, tokens) -> PrefixMatch:
        m = PrefixMatch()
        bs = self.block_size
        usable = tokens.size - 1
        if usable < 1:
            return m
        node = self._root
        j = 0
        while (j + 1) * bs <= usable:
            child = node.children.get(tuple(int(t) for t in tokens[j * bs:(j + 1) * bs]))
            if child is None:
                break
            if child.res == RES_HBM:
                if m.host_blocks:
                    break  # unreachable by the residency-ordering invariant
                m.shared_blocks.append(child.block)
            elif child.res in (RES_HOST, RES_DISK):
                # demoted chain: usable after promotion — counted, not id'd
                m.host_blocks += 1
            else:
                # in_flight: the migration worker owns it; neither tier's
                # copy is authoritative yet, so the walk stops here
                break
            node = child
            j += 1
        # partial tail: the longest common prefix between the remaining
        # tokens and any child chunk is reusable via copy-on-write — this is
        # the "shared prefix ends mid-block" case (and the exact-full-prompt
        # hit, where the cap forbids sharing the final block outright)
        rest = tokens[j * bs:]
        # the tail can reuse at most the remaining usable tokens; a full-bs
        # reuse is unreachable here (an exact-chunk child would have matched
        # above unless the cap already stopped the walk)
        cap = min(usable - j * bs, bs)
        # COW needs a device-side source block, so only HBM children apply —
        # and only when the run didn't end inside a demoted chain
        if cap >= 1 and node.children and m.host_blocks == 0:
            best, best_t = None, 0
            for child in node.children.values():
                if child.res != RES_HBM:
                    continue
                key = np.asarray(child.chunk[:cap], dtype=np.int64)
                neq = np.nonzero(rest[:key.size] != key)[0]
                t = int(neq[0]) if neq.size else int(key.size)
                if t > best_t:
                    best, best_t = child, t
            # a COW copy costs a block + a device copy: with no shared run in
            # front (an accidental few-token overlap between unrelated
            # prompts) demand it save at least half a block before paying
            floor = 1 if m.shared_blocks else max(1, bs // 2)
            if best is not None and best_t >= floor:
                m.cow_src, m.cow_tokens = best.block, best_t
        m.n_cached_tokens = j * bs + m.cow_tokens
        if m.hit_blocks < self.min_hit_blocks:
            return PrefixMatch()
        return m

    def acquire(self, tokens, match: Optional[PrefixMatch] = None,
                tenant: Optional[str] = None) -> Tuple[List[int], int, int]:
        """Match ``tokens`` and take ownership of the hit on behalf of a new
        sequence: incref every shared full block, then (for a partial tail)
        allocate + device-copy the COW block. ``match`` reuses the result of
        a prior :meth:`match` on the same tokens (the admission path probes
        first; single-threaded, so nothing moved in between). Returns
        ``(block_ids, n_cached_tokens, n_shared_full_blocks)`` —
        ``block_ids`` become the sequence's leading ``kv_blocks`` and
        ``seen_tokens`` starts at ``n_cached_tokens``. A miss returns
        ``([], 0, 0)``.

        Order matters: shared blocks are pinned (incref) BEFORE the COW
        allocation can trigger eviction, so eviction can never reclaim the
        blocks this very hit depends on."""
        tokens = np.asarray(tokens, dtype=np.int64).reshape(-1)
        bs = self.block_size
        with self._tree_lock:
            self.stats["lookups"] += 1
            if self._tier is not None:
                # residency can change between the admission probe and here
                # (the migration worker finalizes demotions on its own
                # thread), so with a tier armed the match is always redone
                # under the lock — O(prompt), noise against the promotion
                # D2H/H2D it guards
                m = self._match_locked(tokens)
            else:
                m = match if match is not None else self._match_locked(tokens)
            if self._telemetry is not None:
                # MRC demand feed: EVERY usable full-block chunk of the
                # prompt is one reference (path-chained keys), hit or miss —
                # cold misses belong in the miss-ratio denominator. Fed
                # before the early return so refused hits still count.
                # Demoted-chain hits count as demand too: the MRC models the
                # HIERARCHY (a host hit at 4x capacity is the evidence the
                # curve exists to surface).
                key, keys = 0, []
                for i in range((tokens.size - 1) // bs):
                    key = chunk_key(key, tokens[i * bs:(i + 1) * bs])
                    keys.append(key)
                self._telemetry.record_lookup(keys, len(m.shared_blocks) + m.host_blocks)
            if m.n_cached_tokens == 0:
                return [], 0, 0
            # touch the matched path (LRU), pin the HBM run, collect the
            # demoted chain for promotion
            node = self._root
            hit_owners = [] if self._meter is not None else None
            n_shared = len(m.shared_blocks)
            chain = []
            for i in range(n_shared + m.host_blocks):
                node = node.children[tuple(int(t) for t in np.asarray(tokens[i * bs:(i + 1) * bs]))]
                self._touch(node)
                if i < n_shared:
                    if hit_owners is not None:
                        hit_owners.append((node.owner, bs))
                else:
                    chain.append(node)
            if m.shared_blocks:
                self.kv_cache.incref(m.shared_blocks)
                if self._telemetry is not None:
                    self._telemetry.on_hit(m.shared_blocks)
            blocks = list(m.shared_blocks)
            n_cached = n_shared * bs
            if chain:
                n_cached += self._promote_chain(chain, blocks, hit_owners, tenant)
            if m.cow_src is not None:
                try:
                    dst = int(self._reserve_with_eviction(1)[0])
                except ValueError:
                    dst = None  # pool truly dry: fall back to the full-block hit
                if dst is not None:
                    self.kv_cache.copy_block(m.cow_src, dst)
                    if self._meter is not None:
                        # the duplicate belongs to the REQUESTER (it will
                        # write its own tail into it); the saved tokens are
                        # still credited to the COW source's publisher
                        self._meter.stamp([dst], tenant)
                        cow_owner = next((c.owner for c in node.children.values()
                                          if c.block == m.cow_src), None)
                        hit_owners.append((cow_owner, m.cow_tokens))
                    blocks.append(dst)
                    n_cached += m.cow_tokens
                    self.stats["cow_copies"] += 1
                    self.stats["cow_bytes"] += self.kv_cache.block_bytes()
                    get_metrics().counter("cache/cow_bytes").inc(
                        self.kv_cache.block_bytes())
            if self._meter is not None and tenant is not None and hit_owners:
                # per-tenant hit ATTRIBUTION: consumer's saved tokens split
                # self vs cross-tenant, publishers credited served_tokens
                self._meter.on_prefix_hit(tenant,
                                          [o for o, _ in hit_owners],
                                          [t for _, t in hit_owners])
            if n_cached == 0:
                return [], 0, 0
            self.stats["hits"] += 1
            self.stats["cached_tokens"] += n_cached
            return blocks, n_cached, len(m.shared_blocks)

    def _promote_chain(self, chain, blocks, hit_owners, tenant) -> int:
        """H2D-restore a matched demoted run IN ORDER (root-ward first) on
        the driver thread, ahead of prefill — the admission-side half of the
        tier, and the only synchronous migration anywhere (decode steps
        never reach here). Each promoted node regains an HBM block holding
        the tree's reference plus the requesting sequence's — the incref
        immediately after install pins it against the NEXT iteration's
        ``_reserve_with_eviction``. Returns the tokens restored; a dry pool
        or a lost backing copy SHORTENS the hit instead of failing it.

        Lookahead: before materializing chain[i], chain[i+1] is handed to
        the migration worker (``tier.prefetch``) so its host memcpy / disk
        read + crc overlaps this block's H2D instead of serializing behind
        it — the PR 17 residual. A busy worker just leaves that step
        synchronous."""
        bs = self.block_size
        tier = self._tier
        promoted = 0
        for i, hn in enumerate(chain):
            t0 = time.monotonic()
            if i + 1 < len(chain):
                tier.prefetch(chain[i + 1])
            payload = tier.promote_payload(hn)
            if payload is None:
                # backing copy gone (disk corruption / torn spill): the
                # node and its demoted descendants are unusable without it
                # — a shorter hit, never wrong KV
                self._drop_node_subtree(hn)
                break
            try:
                dst = int(self._reserve_with_eviction(1)[0])
            except ValueError:
                break  # HBM dry even after eviction: shorten the hit
            from_disk = hn.res == RES_DISK
            self.kv_cache.write_block(dst, *payload)
            tier.release_resident(hn)
            hn.res = RES_HBM
            hn.block = dst
            # tree reference came with the reserve; this is the sequence's
            self.kv_cache.incref([dst])
            tier.note_promoted(from_disk)
            if self._meter is not None:
                # residency restarts under the original publisher, exactly
                # like a publish stamp — the owner survives the round trip
                self._meter.stamp([dst], hn.owner)
            blocks.append(dst)
            promoted += 1
            dt = time.monotonic() - t0
            self.stats["promote_wait_s"] += dt
            if hit_owners is not None:
                hit_owners.append((hn.owner, bs))
            if self._telemetry is not None:
                self._telemetry.on_promote(dst, wait_s=dt, from_disk=from_disk)
        if promoted:
            self.stats["promotions"] += promoted
            self.stats["promoted_tokens"] += promoted * bs
            get_metrics().counter("cache/promotions").inc(promoted)
        return promoted * bs

    # -- exit side ---------------------------------------------------------
    def publish(self, seq) -> int:
        """Insert ``seq``'s completed FULL blocks on the way out (after a
        prefill chunk, a decode burst, or at flush). Idempotent root walk:
        an existing node at a chunk keeps its block (first writer wins —
        both copies hold identical KV, keeping one maximizes sharing); a
        missing node takes one tree reference on the sequence's block.

        The walk descends ONLY through nodes whose block this sequence
        itself holds. If another sequence won the race for a chunk (same
        tokens, different physical block), publishing stops there: inserting
        deeper children under a path the publisher does not hold would
        create interior tree-only nodes that leaf eviction can never reach —
        breaking the exactness of :attr:`evictable_blocks` and letting
        admission promise blocks eviction cannot free.

        ``seq.published_blocks`` is the walked-up-to cursor: the common
        steady-state call (a decode burst that completed no new full block)
        returns after one integer compare instead of re-walking the whole
        chain every forward. The cursor also forfeits re-publishing a chain
        the tree evicted while the sequence lives — a coverage loss, not a
        correctness one.

        Returns the number of newly inserted blocks."""
        bs = self.block_size
        known = min(len(seq.token_history), seq.seen_tokens)
        full = min(known // bs, len(seq.kv_blocks))
        if full <= getattr(seq, "published_blocks", 0):
            return 0
        with self._tree_lock:
            tel = self._telemetry
            node = self._root
            inserted = 0
            key, new_keys = 0, []
            for b in range(full):
                chunk = tuple(int(t) for t in seq.token_history[b * bs:(b + 1) * bs])
                if tel is not None:
                    key = chunk_key(key, chunk)
                child = node.children.get(chunk)
                if child is None:
                    child = _Node(chunk=chunk, block=seq.kv_blocks[b], parent=node,
                                  owner=getattr(seq, "tenant", None))
                    self.kv_cache.incref(child.block)
                    node.children[chunk] = child
                    self._n_nodes += 1
                    self.stats["insertions"] += 1
                    self._touch(child)
                    inserted += 1
                    if tel is not None:
                        tel.on_publish(child.block)
                        new_keys.append(key)
                elif child.res != RES_HBM:
                    # re-adopt: the publisher holds a live HBM copy of a
                    # chunk the tree only has demoted (or mid-demotion) —
                    # take the publisher's block as the node's HBM copy for
                    # free (no H2D) and drop the tier copy; an in-flight
                    # demotion finalizes as cancelled when the worker sees
                    # the residency flipped back
                    if self._tier is not None:
                        self._tier.release_resident(child)
                    child.res = RES_HBM
                    child.block = int(seq.kv_blocks[b])
                    self.kv_cache.incref(child.block)
                    self.stats["readoptions"] += 1
                    self._touch(child)
                    if tel is not None:
                        tel.on_publish(child.block)
                elif child.block != seq.kv_blocks[b]:
                    break  # a different writer owns this path from here down
                node = child
            if tel is not None and new_keys:
                # capacity-consuming, non-demand MRC accesses: a request's
                # uncached suffix / generated blocks entering the tree push
                # reusable chains deeper in the modeled LRU stack without
                # inflating the predicted hit rate
                tel.record_inserts(new_keys)
            if self._meter is not None and inserted:
                self._meter.on_publish(getattr(seq, "tenant", None), inserted)
            seq.published_blocks = full
            return inserted

    def install_host_chain(self, token_chunks, payloads,
                           tenant: Optional[str] = None) -> int:
        """Adopt an externally-exported chain of full KV blocks as HOST
        residents — the receiving half of a cross-replica handoff
        (``serving/handoff.py``). Walks/extends the radix tree from the
        root: a chunk the tree already holds (any residency) is skipped —
        first writer wins, exactly like :meth:`publish` — and each new
        chunk lands in the host tier (``TieredBlockStore.host_install``) as
        a first-class demoted node, so the resuming request's ``acquire``
        promotes it H2D through the standard ``_promote_chain`` lookahead
        path, and every OTHER replica's future requests can hit it too
        (fleet-shared prefix state). Host-memory ops only: callable off
        this replica's driver thread (the broker runs on the source's).
        Installation stops at a disk-resident ancestor (a host child below
        a disk parent would break the residency ordering) or when the host
        pool cannot make room. Returns the number of blocks installed."""
        if self._tier is None:
            return 0
        installed = 0
        with self._tree_lock:
            node = self._root
            for chunk, payload in zip(token_chunks, payloads):
                key = tuple(int(t) for t in chunk)
                child = node.children.get(key)
                if child is not None:
                    if child.res == RES_DISK:
                        break
                    self._touch(child)
                    node = child
                    continue
                hb = self._tier.host_install(payload)
                if hb < 0:
                    break
                child = _Node(chunk=key, block=-1, parent=node, owner=tenant)
                self._tier.register_host_node(child, hb)
                node.children[key] = child
                self._n_nodes += 1
                self._touch(child)
                installed += 1
                node = child
            if installed:
                self.stats["host_installed"] += installed
                get_metrics().counter("cache/host_installed").inc(installed)
        return installed

    # -- pressure valve ----------------------------------------------------
    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` HBM blocks from tree-only holders, LRU
        HBM-leaves first (nodes with no HBM children — demoted descendants
        don't anchor their parent). One pass builds a min-heap of evictable
        leaves; a removed leaf that exposes its parent pushes the parent —
        no per-block rescan of the whole tree.

        With a tier attached each victim is DEMOTED (functional device
        snapshot captured here on the driver thread, HBM block released
        immediately, the D2H copy finishes on the migration worker); a full
        migration queue falls back to the plain drop — eviction never waits
        on the worker. Returns how many HBM blocks actually went back to
        the free list; a shortfall is counted and breadcrumbed so operators
        can tell eviction-starved (all holders active) from pool-dry
        (nothing tree-held at all)."""
        with self._tree_lock:
            requested = int(n_blocks)
            heap = [(n.last_access, id(n), n) for n in self._iter_hbm_leaves()
                    if self.kv_cache.refcount(n.block) == 1]
            heapq.heapify(heap)
            freed = 0
            while heap and freed < n_blocks:
                _, _, node = heapq.heappop(heap)
                parent = node.parent
                if not self._demote_node(node):
                    if node.children:
                        # demoted/in-flight children can't outlive their
                        # parent's KV: the drop takes the whole subtree
                        self._drop_node_subtree(node)
                    else:
                        self._remove(node)
                        self.stats["evictions"] += 1
                freed += 1
                if (parent is not self._root and parent.res == RES_HBM
                        and self.kv_cache.refcount(parent.block) == 1
                        and not any(c.res == RES_HBM
                                    for c in parent.children.values())):
                    heapq.heappush(heap, (parent.last_access, id(parent), parent))
            if freed < requested:
                self.stats["evict_starved"] += 1
                get_metrics().counter("cache/evict_starved_total").inc()
                reason = "pool_dry"
                for n in self._iter_nodes():
                    if n.res == RES_HBM:
                        reason = "eviction_starved"
                        break
                get_flight_recorder().record("cache", "evict_starved",
                                             requested=requested, freed=freed,
                                             reason=reason)
            return freed

    def _demote_node(self, node) -> bool:
        """Hand one HBM victim to the tier's migration queue: capture the
        functional device snapshot (driver thread — the donation-safety
        rule), mark the node ``in_flight``, release the HBM block NOW so
        the caller's reserve succeeds without waiting for the D2H. False
        (tier absent / queue at depth) means the caller drops instead."""
        if self._tier is None:
            return False
        snapshot = self.kv_cache.read_block(node.block)
        if not self._tier.try_demote(node, snapshot):
            return False
        block = node.block
        node.res = RES_IN_FLIGHT
        node.block = -1
        self.stats["demotions_queued"] += 1
        if self._telemetry is not None:
            self._telemetry.on_demote_queued(block)
        self.kv_cache.release(block)
        return True

    def demote_cold(self, n_blocks: int) -> int:
        """Proactive watermark demotion (``host_tier.low_watermark``): move
        up to ``n_blocks`` cold tree-only HBM-leaves to the tier WITHOUT
        dropping anything — a full queue stops the pass (unlike demand
        ``evict``, nothing here has to free memory). Keeps demand eviction
        off the inline-demote path in the steady state."""
        if self._tier is None or n_blocks <= 0:
            return 0
        with self._tree_lock:
            heap = [(n.last_access, id(n), n) for n in self._iter_hbm_leaves()
                    if self.kv_cache.refcount(n.block) == 1]
            heapq.heapify(heap)
            moved = 0
            while heap and moved < n_blocks:
                _, _, node = heapq.heappop(heap)
                parent = node.parent
                if not self._demote_node(node):
                    break
                moved += 1
                if (parent is not self._root and parent.res == RES_HBM
                        and self.kv_cache.refcount(parent.block) == 1
                        and not any(c.res == RES_HBM
                                    for c in parent.children.values())):
                    heapq.heappush(heap, (parent.last_access, id(parent), parent))
            return moved

    def clear(self) -> int:
        """Release EVERY tree reference (eviction flush): HBM blocks whose
        only holder was the tree return to the free list; blocks still held
        by live sequences merely lose the tree's reference; host/disk
        copies are dropped and in-flight demotions finalize as cancelled
        (the worker sees the node detached)."""
        with self._tree_lock:
            nodes = list(self._iter_nodes())
            hbm = [n.block for n in nodes if n.res == RES_HBM]
            if self._telemetry is not None and hbm:
                # a flush is not LRU pressure: drop the tree-held flags
                # without recording eviction-victim ages
                self._telemetry.on_tree_clear(hbm)
            for node in nodes:
                if node.res == RES_HBM:
                    self.kv_cache.release(node.block)
                elif self._tier is not None:
                    self._tier.release_resident(node)
                node.parent = None  # detaches any in-flight migration
                node.children = {}
            self._root.children = {}
            self._n_nodes = 0
            return len(nodes)

    def _reserve_with_eviction(self, n: int) -> np.ndarray:
        short = n - self.kv_cache.free_blocks
        if short > 0:
            self.evict(short)
        return self.kv_cache.reserve(n)

    # -- internals ---------------------------------------------------------
    def _touch(self, node) -> None:
        self._clock += 1
        node.last_access = self._clock

    def _iter_nodes(self):
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n

    def _iter_leaves(self):
        return (n for n in self._iter_nodes() if not n.children)

    def _iter_hbm_leaves(self):
        """Eviction/demotion victims: HBM-resident nodes with no HBM
        children. Demoted (host/disk/in-flight) descendants don't anchor
        their parent — demoting the parent keeps the root-ward residency
        ordering (it joins them in the lower tier). Without a tier every
        node is HBM and this degenerates to plain leaves."""
        return (n for n in self._iter_nodes()
                if n.res == RES_HBM
                and not any(c.res == RES_HBM for c in n.children.values()))

    def _remove(self, node) -> None:
        assert not node.children, "only leaves are evictable"
        del node.parent.children[node.chunk]
        # token-granular eviction accounting (tree nodes are FULL blocks by
        # construction, so each eviction discards exactly block_size tokens)
        self.stats["evicted_tokens"] += self.block_size
        get_metrics().counter("cache/evicted_tokens").inc(self.block_size)
        if self._telemetry is not None:
            self._telemetry.on_evict(node.block)  # victim age BEFORE the free
        if self._meter is not None:
            # eviction pressure attributed to the evicted block's publisher
            self._meter.on_evict(node.owner)
        self.kv_cache.release(node.block)
        self._n_nodes -= 1

    def _drop_node_subtree(self, node) -> int:
        """Remove ``node`` and every descendant (demotion failure, disk
        corruption, host-tier overflow drop, queue-full eviction of a node
        with demoted children): by the residency ordering the descendants
        are host/disk/in-flight — unusable without this node's KV, so the
        whole subtree goes. Tier copies are freed, in-flight jobs are left
        to cancel themselves (the worker sees the node detached). Called
        under the tree lock, from the driver thread OR the migration
        worker's failure path. Returns the node count dropped."""
        if node.parent is None:
            return 0  # already detached (racing drop)
        del node.parent.children[node.chunk]
        dropped = 0
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            n.children = {}
            n.parent = None
            if n.res == RES_HBM and n.block >= 0:
                if self._telemetry is not None:
                    self._telemetry.on_evict(n.block)
                if self._meter is not None:
                    self._meter.on_evict(n.owner)
                self.kv_cache.release(n.block)
            elif self._tier is not None:
                self._tier.release_resident(n)
            n.block = -1
            self._n_nodes -= 1
            dropped += 1
            self.stats["evictions"] += 1
            self.stats["evicted_tokens"] += self.block_size
        return dropped
