"""InferenceEngineV2 — continuous-batching ragged inference engine.

Analog of the reference ``inference/v2/engine_v2.py:30`` (``put:107``,
``query:153``, ``can_schedule:179``, ``flush:228``, ``serialize:237``). The
serving loop is host-driven exactly like the reference's (MII calls put() with
whatever mix of prefill chunks and decode steps the scheduler admitted); the
device side is one jitted ragged forward per shape-bucket with the KV pools
donated through, so steady-state decode reuses a single compiled program and
the only host→device traffic is the packed batch descriptor arrays.
"""

import functools
import time
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...monitor.flight import get_flight_recorder
from ...monitor.goodput import get_goodput
from ...monitor.health import get_health
from ...monitor.memory import get_memory, tree_device_bytes
from ...monitor.metrics import get_metrics
from ...monitor.roofline import get_roofline
from ...monitor.trace import (get_tracer, observe_latency, pop_compile_source,
                              push_compile_source)
from ...utils.logging import log_dist
from .config_v2 import RaggedInferenceEngineConfig
from .model_implementations.flat_model import ragged_forward
from .ragged.ragged_manager import DSStateManager
from .ragged.ragged_wrapper import RaggedBatchWrapper, next_bucket
from .scheduling_utils import SchedulingError, SchedulingResult


def _serving_compile_scope(method):
    """Label this thread's XLA compiles as ``serving`` for the duration of
    a forward — the compile listener (monitor/trace.py) attributes each
    compile event to the thread-local source, so a serving engine compiling
    from a replica thread counts under ``serving/compile_events``, not
    ``train/`` (the pre-goodput drift). Pushed only when something is
    listening: one enabled check otherwise."""

    @functools.wraps(method)
    def wrapped(self, *args, **kwargs):
        if not (self.goodput_ledger is not None or get_metrics().enabled
                or get_tracer().enabled):
            return method(self, *args, **kwargs)
        prev = push_compile_source("serving")
        try:
            return method(self, *args, **kwargs)
        finally:
            pop_compile_source(prev)

    return wrapped


class InferenceEngineV2:

    def __init__(self, model, config: Optional[RaggedInferenceEngineConfig] = None, params=None):
        """``model``: framework model object (e.g. ``models.llama2()``);
        ``params``: trained param pytree (initialized randomly if omitted)."""
        self.config = config or RaggedInferenceEngineConfig()
        self.module = model
        self.model_config = model.config
        mc, ic = self.model_config, self.config

        if ic.use_pallas_kernels == "auto":
            self._use_pallas = jax.default_backend() == "tpu"
        else:
            self._use_pallas = ic.use_pallas_kernels == "always"

        # pluggable module layer (reference FastGen's DSModule registry +
        # heuristics): config→implementation selection happens HERE, once;
        # every compiled bucket traces through the same module set
        from .modules.heuristics import build_modules

        self._modules = build_modules(mc, ic, use_pallas=self._use_pallas)

        if params is None:
            params = jax.jit(lambda r: model.init(r, None))(jax.random.PRNGKey(0))
        for m in self._modules.values():
            # one-time parameter-layout transforms (e.g. the int8 linear
            # implementation quantizes the weight stream)
            params = m.transform_params(params)
        self.params = params

        bs = ic.kv_block_size
        max_context = ic.state_manager.max_context
        model_max = getattr(mc, "max_seq_len", None)
        if model_max is not None and max_context > model_max:
            # past max_seq_len a learned-position model would silently clamp
            # its position gather — refuse to track context beyond the model
            log_dist(f"clamping max_context {max_context} -> model max_seq_len {model_max}", ranks=[0])
            max_context = model_max
        self._max_context = max_context
        self._max_blocks_per_seq = -(-max_context // bs)
        # resolve 'auto' into a LOCAL count (the caller's config object is
        # not mutated: a reused config re-measures for the next engine)
        if ic.num_kv_blocks in ("auto", 0, None):
            self.num_kv_blocks = self._auto_kv_blocks(mc, ic, max_context)
        else:
            self.num_kv_blocks = int(ic.num_kv_blocks)
        self.state_manager = DSStateManager(
            mc.num_layers, mc.num_kv_heads, mc.head_dim,
            max_tracked_sequences=ic.state_manager.max_tracked_sequences,
            num_blocks=self.num_kv_blocks, block_size=bs, dtype=ic.kv_dtype,
            prefix_cache_config=ic.prefix_cache)
        self.batch = RaggedBatchWrapper(
            max_ragged_batch_size=ic.state_manager.max_ragged_batch_size,
            max_ragged_sequence_count=ic.state_manager.max_ragged_sequence_count,
            max_blocks_per_seq=self._max_blocks_per_seq, block_size=bs)

        self._compiled: Dict[Tuple[int, int, Optional[str]], object] = {}
        # speculative-decoding lifetime totals (two int adds per verify
        # step; the gauge feeding off them only updates when metrics are on)
        self._spec_totals = {"drafted": 0, "accepted": 0}
        # HBM attribution (monitor/memory.py): this engine's params + KV
        # block pool enter the process-wide ledger. Weakly owned — a
        # discarded engine self-prunes from the registry. A draft engine
        # referenced by our speculative config re-files its bytes under
        # `spec_draft_engine` so the decomposition names the sidecar cost.
        self._memory_role = None
        get_memory().register(f"engine_v2-{id(self)}",
                              lambda eng: eng._memory_sections(), self)
        draft = getattr(ic.speculative, "draft_engine", None)
        if draft is not None and hasattr(draft, "set_memory_role"):
            draft.set_memory_role("spec_draft_engine")
        # goodput ledger + recompile sentinel (monitor/goodput.py): the
        # owning replica (or a direct caller) attaches a serving ledger
        # post-warmup via `goodput_ledger`; `_gp_warmed` is this engine's
        # own warmup boundary — compiled-cache misses after it are flagged
        # by the sentinel. All None/False by default: one attribute check
        # per forward when the plane is off.
        self.goodput_ledger = None
        self._gp_warmed = False
        self._gp_last_uids = None
        self.gp_rid_resolver = None
        # tenant metering (serving/metering.py): the owning replica attaches
        # the gateway's TenantMeter via `set_tenant_meter`, which wires one
        # per-engine EngineMeterView into the block-lifecycle hooks. None by
        # default — no stamp arrays exist and every hook site below the
        # state manager stays one attribute check.
        self._tenant_meter = None
        # live-health plane: serving heartbeats (`serving` watchdog source,
        # armed per forward) + a /healthz section. One boolean per call when
        # the plane is off.
        self._health = get_health()
        if self._health.enabled:
            import weakref

            # the plane is a process-global singleton and this engine has no
            # destroy(): a strong closure would pin the whole KV cache (and
            # keep /healthz reporting a dead engine) after the engine is
            # discarded — hold a weakref and self-unregister once collected
            ref = weakref.ref(self)

            def _serving_state():
                eng = ref()
                if eng is None:
                    get_health().set_state_provider("serving", None)
                    return {"engine": "collected"}
                return {"tracked_sequences": eng.state_manager.n_tracked_sequences,
                        "free_blocks": eng.free_blocks,
                        "available_blocks": eng.available_blocks}

            self._health.set_state_provider("serving", _serving_state)
            if self.state_manager.cache_telemetry is not None:
                # cache observability rides the same weakref discipline:
                # MRC + refcount-class + occupancy gauges on /metrics, a
                # full telemetry snapshot in every forensic dump. Names and
                # labels are per-engine — a multi-replica gateway must show
                # every replica's curve, not whichever registered last —
                # and a collected engine self-unregisters its providers.
                tag = f"cache_telemetry-{id(self):x}"
                labels = {"engine": f"{id(self):x}"}

                def _cache_rows():
                    eng = ref()
                    tel = eng.state_manager.cache_telemetry if eng is not None else None
                    if tel is None:
                        get_health().set_gauge_provider(tag, None)
                        return []
                    return tel.gauge_rows(labels=labels)

                def _cache_dump():
                    eng = ref()
                    tel = eng.state_manager.cache_telemetry if eng is not None else None
                    if tel is None:
                        get_health().set_dump_provider(tag, None)
                        return {"engine": "collected"}
                    return tel.snapshot()

                self._health.set_gauge_provider(tag, _cache_rows)
                self._health.set_dump_provider(tag, _cache_dump)
        log_dist(
            f"InferenceEngineV2 ready: blocks={self.num_kv_blocks}x{bs} "
            f"kv={self.state_manager.kv_cache.memory_bytes()/2**20:.0f}MiB "
            f"max_batch_tokens={ic.state_manager.max_ragged_batch_size} pallas={self._use_pallas}", ranks=[0])

    # ------------------------------------------------------------------
    def _auto_kv_blocks(self, mc, ic, max_context: int) -> int:
        """Size the KV pool from the device's free HBM after params
        (resolves the round-2 'auto sizing TODO against HBM stats'):
        blocks = kv_memory_fraction x free / bytes_per_block, clamped to at
        least one max-context sequence and to the tracked-sequence budget.
        Without memory stats (CPU) the demand is capped at a conservative
        host budget instead of allocating the full tracked-sequence demand."""
        import numpy as _np

        bs = ic.kv_block_size
        dt_bytes = _np.dtype(ic.kv_dtype).itemsize  # accepts "int8" and jnp dtypes alike
        per_block = 2 * mc.num_layers * mc.num_kv_heads * mc.head_dim * bs * dt_bytes
        if dt_bytes == 1:  # int8 KV: absmax scales ride along, fp32 per (token, head)
            per_block += 2 * mc.num_layers * mc.num_kv_heads * bs * 4
        min_blocks = -(-max_context // bs) + 1
        want_blocks = ic.state_manager.max_tracked_sequences * -(-max_context // bs)
        free = None
        try:
            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                param_bytes = sum(int(_np.prod(x.shape)) * x.dtype.itemsize
                                  for x in jax.tree_util.tree_leaves(self.params))
                used = max(stats.get("bytes_in_use", 0), param_bytes)
                free = max(0, int(stats["bytes_limit"]) - used)
        except Exception:
            free = None
        if free is None:
            # stats unavailable (CPU backend): cap the pool at ~2GiB so an
            # unconfigured engine cannot demand hundreds of GB of host RAM
            cap = max(min_blocks, (2 * 2**30) // per_block)
            return max(min_blocks, min(want_blocks, cap))
        blocks = int(free * ic.kv_memory_fraction) // per_block
        blocks = max(min_blocks, min(blocks, want_blocks))
        log_dist(f"auto KV pool: {blocks} x {bs}-token blocks "
                 f"({blocks * per_block / 2**20:.0f}MiB of {free / 2**20:.0f}MiB free)", ranks=[0])
        return blocks

    def can_schedule(self, uids: Iterable[int], lengths: Iterable[int]) -> SchedulingResult:
        """Admission control (reference ``engine_v2.py:179``): sequence,
        token and KV-block budgets for the proposed batch."""
        uids, lengths = list(uids), list(lengths)
        cur_len = len(uids)
        tokens = sum(lengths)
        sm = self.config.state_manager

        if len(set(uids)) != len(uids):
            # a uid twice in one batch would pack both chunks at the same
            # positions and corrupt the KV cache — reject at admission
            return SchedulingResult.BatchSequenceLimitExceeded
        if cur_len > sm.max_ragged_sequence_count:
            return SchedulingResult.BatchSequenceLimitExceeded
        n_new = sum(1 for u in uids if self.state_manager.get_sequence(u) is None)
        if self.state_manager.n_tracked_sequences + n_new > sm.max_tracked_sequences:
            return SchedulingResult.EngineSequenceLimitExceeded
        if tokens > sm.max_ragged_batch_size:
            return SchedulingResult.TokenLimitExceeded

        bs = self.config.kv_block_size
        blocks_needed = 0
        for u, n in zip(uids, lengths):
            seq = self.state_manager.get_sequence(u)
            total = n + (seq.seen_tokens if seq is not None else 0)
            if total > self._max_context:
                return SchedulingResult.KVCacheLimitExceeded
            # clamp per-sequence demand at zero: a sequence holding excess
            # blocks must not mask OTHER sequences' demand against the pool
            blocks_needed += max(0, -(-total // bs)
                                 - (seq.cur_allocated_blocks if seq is not None else 0))
        # budget against free + evictable: a warm prefix cache keeps the free
        # list near empty by design, and allocation evicts LRU tree-only
        # blocks on demand
        if blocks_needed > self.state_manager.available_blocks:
            return SchedulingResult.KVCacheLimitExceeded
        return SchedulingResult.Success

    # ------------------------------------------------------------------
    def put(self, batch_uids: List[int], batch_tokens: List[np.ndarray], do_checks: bool = True,
            sample: Optional[str] = None, block: bool = True, sampling=None) -> np.ndarray:
        """Run one ragged forward (reference ``put:107``). ``batch_tokens[i]``
        are the new tokens of sequence ``batch_uids[i]`` (whole prompt for
        prefill, one token for decode). Returns last-token logits
        [len(batch_uids), vocab] — or, with ``sample='greedy'``, the argmax
        token ids [len(batch_uids)] sampled ON DEVICE, so only a few bytes
        travel back to the host per step (the serving loop's steady-state
        transfer instead of the full vocab row per sequence).

        ``block=False`` returns the device array without a host fetch, so a
        scheduler that doesn't need the values (e.g. speculative admission,
        or a benchmark on a high-latency relay) can pipeline several steps
        into the device queue.

        ``sampling``: per-sequence :class:`SamplingParams` list (None
        entries = greedy rows). With any temperature > 0 the returned
        tokens are drawn from the tempered/top-p distribution ON DEVICE
        (``sampling.sample_tokens``), keyed by (seed, token position) so a
        fixed seed replays the same stream; all-greedy lists keep the
        byte-identical argmax program."""
        hb = self._health
        # normalize ONCE, before any breadcrumb math: both arguments may be
        # single-pass iterables, and _put's re-asarray of the converted rows
        # is then a free no-op
        batch_uids = list(batch_uids)
        batch_tokens = [np.asarray(t, np.int32).reshape(-1) for t in batch_tokens]
        gl = self.goodput_ledger
        if gl is None and not hb.enabled:
            return self._put(batch_uids, batch_tokens, do_checks, sample, block, sampling)
        if gl is not None:
            self._gp_last_uids = batch_uids
            gp_cat = ("prefill_active" if any(t.size > 1 for t in batch_tokens)
                      else "decode_active")
            t_gp = time.perf_counter()
        if hb.enabled:
            # operation-style heartbeat: `serving` is watched exactly while a
            # forward is in flight, so a wedged device call trips the watchdog
            hb.begin("serving")
            get_flight_recorder().record("serving", "put", seqs=len(batch_uids),
                                         tokens=int(sum(t.size for t in batch_tokens)))
        try:
            return self._put(batch_uids, batch_tokens, do_checks, sample, block, sampling)
        finally:
            if hb.enabled:
                hb.end("serving")
            if gl is not None:
                gl.book(gp_cat, time.perf_counter() - t_gp)

    @_serving_compile_scope
    def _put(self, batch_uids, batch_tokens, do_checks, sample, block, sampling=None):
        observing = get_tracer().enabled or get_metrics().enabled
        t0 = time.perf_counter() if observing else 0.0
        rf = get_roofline()
        t_rf = time.perf_counter() if rf.enabled else 0.0
        batch_tokens = [np.asarray(t, np.int32).reshape(-1) for t in batch_tokens]
        if any(t.size == 0 for t in batch_tokens):
            # an empty chunk would alias the PREVIOUS row's last_idx in the
            # packed batch and silently return the wrong sequence's logits
            raise ValueError("put(): zero-length token chunk "
                             f"(uids {[u for u, t in zip(batch_uids, batch_tokens) if t.size == 0]})")
        # classify prefill vs decode from the PRE-trim sizes: a cache hit can
        # trim a repeat prompt down to one token, but its latency is still a
        # TTFT sample (and the hit is exactly what makes it worth recording)
        had_prefill = any(t.size > 1 for t in batch_tokens)
        if do_checks:
            result = self.can_schedule(batch_uids, [t.size for t in batch_tokens])
            if result is not SchedulingResult.Success:
                raise SchedulingError(result)

        self.batch.clear()
        descs = []
        for i, (uid, toks) in enumerate(zip(batch_uids, batch_tokens)):
            seq = self.state_manager.get_sequence(uid)
            if seq is None:
                # cache-hit prefill path: a new sequence's first chunk is
                # matched against the radix tree; the hit's blocks arrive
                # shared (seen_tokens pre-seeded) and only the uncached
                # suffix is actually fed/computed
                seq, skip = self._create_with_prefix(uid, toks)
                if skip:
                    toks = batch_tokens[i] = toks[skip:]
            self.state_manager.note_tokens(seq, toks)
            self.state_manager.allocate_blocks(seq, toks.size)
            seq.pre_forward(toks.size)
            self.batch.insert_sequence(seq, toks)
            descs.append(seq)
        rb = self.batch.finalize()

        from .sampling import all_greedy, pack_sampling

        kv = self.state_manager.kv_cache
        if sampling is not None and not all_greedy(sampling):
            if sample is None:
                # sample=None means "give me logits" — silently returning
                # sampled token ids instead would hand a logits consumer an
                # int32 vector
                raise ValueError("put(sample=None) returns logits; pass sample='greedy' "
                                 "with a sampling list to draw tokens on device")
            # sampled rows draw on device (greedy rows argmax via temp 0);
            # sample='greedy' callers without sampling keep the original
            # compiled program byte-for-byte
            mode = "sample"
            fn = self._get_compiled(rb.token_ids.shape[0], rb.block_tables.shape[0],
                                    "sample")
            samp_f, seeds = pack_sampling(sampling, batch_uids, rb.block_tables.shape[0])
            out, pools = fn(self.params, jnp.asarray(rb.packed()), jnp.asarray(samp_f),
                            jnp.asarray(seeds), kv.pools())
        else:
            mode = sample
            fn = self._get_compiled(rb.token_ids.shape[0], rb.block_tables.shape[0], sample)
            # ONE descriptor upload per forward (reference single pinned-buffer
            # upload; each separate array would be its own RPC on a tunnel)
            out, pools = fn(self.params, jnp.asarray(rb.packed()), kv.pools())
        kv.update(*pools)
        for seq in descs:
            seq.post_forward()
            self.state_manager.publish_sequence(seq)  # completed full blocks → tree
        out = out[:rb.n_seqs]  # slice ON DEVICE: the host fetch moves
        out = out if not block else np.asarray(out)  # n_seqs rows, not the padded bucket
        if rf.enabled and block:
            # wall join through the blocking host fetch — the same window the
            # outer put() books as prefill/decode-active in the goodput ledger,
            # so the roofline and goodput accountings reconcile
            rf.note_wall(f"put/t{rb.token_ids.shape[0]}/s{rb.block_tables.shape[0]}"
                         f"/{mode or 'logits'}", time.perf_counter() - t_rf)
        if observing:
            # prefill (multi-token chunks) latency IS TTFT when block=True
            # (admission -> first token on host, the FastGen definition);
            # block=False measures only async dispatch, so no latency sample
            hist = ("serving/ttft_ms" if had_prefill else "serving/decode_step_ms") if block else None
            # uids ride the span so a request-scoped trace can attribute
            # every engine forward to the requests composing it (capped:
            # span args are JSONL payload, not a table); span name as a
            # two-literal conditional so check_goodput_taxonomy can map both
            observe_latency(t0, "serving/prefill" if had_prefill else "serving/decode_step",
                            hist_name=hist,
                            span_args={"seqs": len(batch_uids),
                                       "tokens": int(sum(t.size for t in batch_tokens)),
                                       "uids": [int(u) for u in batch_uids[:16]],
                                       "blocked": bool(block)})
        return out

    # ------------------------------------------------------------------
    def decode(self, batch_uids: List[int], first_tokens, n_steps: int, block: bool = True,
               eos_token_ids=None, sampling=None) -> np.ndarray:
        """Run ``n_steps`` greedy decode steps ON DEVICE in one compiled
        program (a ``lax.scan`` feeding each step's argmax back as the next
        token), for sequences already tracked by the engine.

        This is the steady-state continuous-batching fast path: ``put`` pays
        one host round-trip per token, which on a relay/tunneled runtime
        dominates the step time; ``decode`` pays it once per ``n_steps``.
        KV blocks for the whole horizon are reserved up front (admission
        refuses if the pool can't cover it). Returns token ids
        [len(batch_uids), n_steps].

        ``eos_token_ids`` (blocking mode only): one eos id — a scalar, or a
        per-sequence list with ``None`` entries — lets the engine rewind the
        horizon OVERSHOOT of a sequence that hits eos mid-scan: the KV (and
        token history) materialized past the eos is rolled back through
        ``DSStateManager.rollback_to`` before publish, so the radix tree
        never receives post-eos garbage paths and the tail blocks return to
        the pool immediately instead of idling until flush.

        ``sampling``: per-sequence :class:`SamplingParams` (None = greedy
        rows). The sampled scan draws each fed-back token from the
        tempered/top-p distribution on device, keyed by (seed, position);
        all-greedy lists keep the original argmax scan program.
        """
        batch_uids = list(batch_uids)
        hb = self._health
        gl = self.goodput_ledger
        if gl is None and not hb.enabled:
            return self._decode(batch_uids, first_tokens, n_steps, block, eos_token_ids,
                                sampling)
        if gl is not None:
            self._gp_last_uids = batch_uids
            t_gp = time.perf_counter()
        if hb.enabled:
            hb.begin("serving")
            get_flight_recorder().record("serving", "decode", seqs=len(batch_uids),
                                         steps=int(n_steps))
        try:
            return self._decode(batch_uids, first_tokens, n_steps, block, eos_token_ids,
                                sampling)
        finally:
            if hb.enabled:
                hb.end("serving")
            if gl is not None:
                gl.book("decode_active", time.perf_counter() - t_gp)

    @_serving_compile_scope
    def _decode(self, batch_uids, first_tokens, n_steps, block, eos_token_ids=None,
                sampling=None):
        observing = get_tracer().enabled or get_metrics().enabled
        t0 = time.perf_counter() if observing else 0.0
        rf = get_roofline()
        t_rf = time.perf_counter() if rf.enabled else 0.0
        uids = list(batch_uids)
        S = len(uids)
        if len(set(uids)) != len(uids):
            # same corruption mode put()'s admission rejects: two rows of one
            # uid would write divergent KV at the same positions
            raise SchedulingError(SchedulingResult.BatchSequenceLimitExceeded)
        if S > self.batch.max_seqs:
            # must reject BEFORE allocate/pre_forward: a mid-loop wrapper
            # ValueError would strand in-flight state on every sequence
            raise SchedulingError(SchedulingResult.BatchSequenceLimitExceeded)
        first = [np.asarray(t, np.int32).reshape(-1) for t in first_tokens]
        assert all(t.size == 1 for t in first), "decode() takes exactly one next token per sequence"
        seqs = []
        for uid in uids:
            seq = self.state_manager.get_sequence(uid)
            if seq is None:
                raise SchedulingError(SchedulingResult.EngineSequenceLimitExceeded)
            if seq.seen_tokens + n_steps > self._max_context:
                raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
            seqs.append(seq)
        blocks_needed = sum(s.blocks_needed(n_steps) for s in seqs)
        if blocks_needed > self.state_manager.available_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        if not hasattr(self, "_decode_batch"):
            # the scan packs exactly one token per sequence, so its wrapper
            # uses the SAME bucket table for tokens and sequences
            self._decode_batch = RaggedBatchWrapper(
                max_ragged_batch_size=self.batch.max_seqs,
                max_ragged_sequence_count=self.batch.max_seqs,
                max_blocks_per_seq=self._max_blocks_per_seq, block_size=self.config.kv_block_size,
                token_buckets=self.batch.seq_buckets, seq_buckets=self.batch.seq_buckets)
        for seq, toks in zip(seqs, first):
            self.state_manager.allocate_blocks(seq, n_steps)
            seq.pre_forward(n_steps)

        self._decode_batch.clear()
        for seq, toks in zip(seqs, first):
            # tables now cover the full horizon; positions advance in-scan
            self._decode_batch.insert_sequence(seq, toks)
        rb = self._decode_batch.finalize()

        from .sampling import all_greedy, pack_sampling

        kv = self.state_manager.kv_cache
        s_bucket = rb.token_ids.shape[0]
        rf_sampled = sampling is not None and not all_greedy(sampling)
        rf_bucket = f"decode/s{s_bucket}/n{n_steps}{'/sampled' if rf_sampled else ''}"
        if rf_sampled:
            fn = self._get_compiled_decode(s_bucket, n_steps, sampled=True)
            samp_f, seeds = pack_sampling(sampling, uids, s_bucket)
            toks, pools = fn(self.params, jnp.asarray(rb.packed()), jnp.asarray(samp_f),
                             jnp.asarray(seeds), kv.pools())
        else:
            fn = self._get_compiled_decode(s_bucket, n_steps)
            # start positions already ride inside packed() (each decode row
            # is one token at its position) — no separate seq_start_len upload
            toks, pools = fn(self.params, jnp.asarray(rb.packed()), kv.pools())
        kv.update(*pools)
        toks = toks[:S]  # on-device slice before any host fetch
        pc = self.state_manager.prefix_cache
        if block:
            toks = np.asarray(toks)
            if eos_token_ids is None or isinstance(eos_token_ids, (int, np.integer)):
                eos_list = [eos_token_ids] * S
            else:
                eos_list = list(eos_token_ids)
                assert len(eos_list) == S, "eos_token_ids must match batch_uids"
            for seq, f, row, eos in zip(seqs, first, toks, eos_list):
                start = seq.seen_tokens
                if pc is not None:
                    # tokens materialized this burst: the fed first token
                    # plus every in-scan feedback token except the last
                    # output (whose KV is not written until it is fed back)
                    self.state_manager.note_tokens(seq, np.concatenate([f, row[:-1]]))
                seq.post_forward()
                if eos is not None:
                    hit = np.nonzero(row == eos)[0]
                    if hit.size and int(hit[0]) + 1 < n_steps:
                        # horizon overshoot: the caller keeps row[:hit+1];
                        # KV/history past the eos is garbage — rewind it
                        # BEFORE publish so the tree never sees it
                        self.state_manager.rollback_to(seq, start + 1 + int(hit[0]))
                self.state_manager.publish_sequence(seq)
        else:
            if pc is not None:
                for seq in seqs:
                    seq.history_valid = False  # generated ids never reached host
            for seq in seqs:
                seq.post_forward()
                self.state_manager.publish_sequence(seq)
        if rf.enabled and block:
            rf.note_wall(rf_bucket, time.perf_counter() - t_rf)
        if observing:
            # as with put(): without the host fetch the wall time is dispatch
            # only — emit the span (blocked flag disclosed), skip the samples
            observe_latency(t0, "serving/decode",
                            hist_name="serving/decode_ms" if block else None,
                            gauges=({"serving/decode_tokens_per_sec":
                                     lambda dt: S * n_steps / max(dt, 1e-9)} if block else None),
                            span_args={"seqs": S, "steps": int(n_steps),
                                       "uids": [int(u) for u in uids[:16]],
                                       "blocked": bool(block)})
        return toks

    def _ragged_step(self, params, packed, pools, t_bucket, s_bucket, gather_k: int = 0,
                     tree_meta=None):
        """One ragged forward over the pool tuple (2 = bf16 pools, 4 = int8
        pools + scales). The SINGLE builder both compiled paths share —
        quant/non-quant variation lives in the tuple arity, not in four
        hand-copied closures.

        ``gather_k``: the speculative-verify variant — project logits for
        each sequence's ENTIRE ``gather_k + 1``-token chunk (the chunk is
        contiguous in the packed layout, so the positions are
        ``last_idx - gather_k .. last_idx``) instead of only the last
        token. Returns logits ``[S * (gather_k + 1), V]`` row-major per
        sequence.

        ``tree_meta``: token-tree verification — one int32 ``[3 * T]``
        operand carrying per-token [logical pos_ids | branch id | depth]
        rows for the flattened draft tree. Each tree node occupies its own
        KV SLOT (``pos`` = start + flat node index, so sibling branches
        never collide in the cache) but its LOGICAL position is
        start + depth; visibility is ancestors-only — committed context,
        the shared root (depth 0), and earlier nodes of the token's OWN
        branch. The mask/ctx-position arrays built here feed
        ``ragged_forward``'s tree kwargs; with ``tree_meta`` None this is
        byte-identical to the plain causal step."""
        from .ragged.ragged_wrapper import unpack_descriptors

        token_ids, seq_idx, pos, valid, tables, last_idx = unpack_descriptors(
            packed, t_bucket, s_bucket, self._max_blocks_per_seq)
        extra = {}
        if tree_meta is not None:
            assert gather_k, "tree_meta requires the gather_k verify layout"
            T = t_bucket
            k1 = gather_k + 1
            pos_ids = tree_meta[0:T]
            branch = tree_meta[T:2 * T]
            depth = tree_meta[2 * T:3 * T]
            C = self._max_blocks_per_seq * self.config.kv_block_size
            # chunk-local flat node index from the packed layout alone:
            # every verify chunk is exactly k1 tokens ending at last_idx
            node_idx = jnp.arange(T, dtype=jnp.int32) - (last_idx[seq_idx] - gather_k)
            start = pos - node_idx                    # committed length, per token
            ctx_p = jnp.arange(C, dtype=jnp.int32)[None, :]
            j = ctx_p - start[:, None]                # ctx slot's flat node index
            jj = jnp.clip(j, 0, gather_k)
            # per-sequence node tables scattered from this batch's own rows
            b_tbl = jnp.zeros((s_bucket, k1), jnp.int32).at[seq_idx, node_idx].set(
                branch, mode="drop")
            d_tbl = jnp.zeros((s_bucket, k1), jnp.int32).at[seq_idx, node_idx].set(
                depth, mode="drop")
            cb = jnp.take_along_axis(b_tbl[seq_idx], jj, axis=1)   # [T, C]
            cd = jnp.take_along_axis(d_tbl[seq_idx], jj, axis=1)
            in_tree = (j >= 0) & (j <= gather_k)
            # ancestor visibility: committed prefix | root (depth 0) | an
            # EARLIER node of my own branch — a sibling branch's KV sits at
            # an earlier slot but must stay invisible
            vis_tree = in_tree & (cd <= depth[:, None]) & ((cd == 0) | (cb == branch[:, None]))
            mask = (ctx_p < start[:, None]) | vis_tree
            window = getattr(self.model_config, "sliding_window", None)
            if window:
                ctx_pid_t = jnp.where(in_tree, start[:, None] + cd, ctx_p)
                mask = mask & (pos_ids[:, None] - ctx_pid_t < int(window))
            # ctx logical positions per sequence (alibi distances)
            start_s = pos[jnp.maximum(last_idx, 0)] - gather_k     # [S]
            js = ctx_p - start_s[:, None]
            jjs = jnp.clip(js, 0, gather_k)
            ds = jnp.take_along_axis(d_tbl, jjs, axis=1)
            ctx_pid = jnp.where((js >= 0) & (js <= gather_k), start_s[:, None] + ds,
                                jnp.broadcast_to(ctx_p, (s_bucket, C)))
            extra = {"pos_ids": pos_ids, "attn_mask": mask, "ctx_pos_ids": ctx_pid}
        if gather_k:
            idx = last_idx[:, None] - gather_k + jnp.arange(gather_k + 1, dtype=jnp.int32)
            # padding rows carry last_idx 0 — clamp their (negative) indices;
            # the caller slices the garbage rows off with [:n_seqs]
            last_idx = jnp.maximum(idx, 0).reshape(-1)
        scales = {"k_scale": pools[2], "v_scale": pools[3]} if len(pools) == 4 else {}
        out = ragged_forward(self.model_config, self.config.kv_block_size, params,
                             token_ids, seq_idx, pos, valid, tables, last_idx,
                             pools[0], pools[1], use_pallas=self._use_pallas,
                             modules=self._modules, **scales, **extra)
        return out[0], tuple(out[1:])  # logits, new pool tuple

    # ------------------------------------------------------------------
    def speculate_decode(self, batch_uids: List[int], first_tokens, draft_tokens,
                         k: Optional[int] = None, eos_token_ids=None,
                         sampling=None) -> List[np.ndarray]:
        """One speculative verify step over tracked, in-decode sequences:
        feed ``[next_token, d_1..d_K]`` as ONE ragged chunk per sequence
        (the packed-batch path already supports multi-token chunks), accept
        the longest prefix of drafts matching the model's OWN greedy argmax
        at each position, commit the accepted KV and roll the rejected tail
        back through ``DSStateManager.rollback_to``.

        ``first_tokens[i]`` — the sequence's pending next token (exactly as
        :meth:`decode` takes it); ``draft_tokens[i]`` — up to ``k`` proposed
        continuations (shorter drafts are padded; a pad is only ever
        accepted when it happens to EQUAL the greedy choice, so parity is
        unconditional). Returns one 1-D int32 array per sequence: the newly
        committed tokens — the accepted drafts plus one bonus token from
        the verify logits. Always at least 1, at most ``k + 1``; the LAST
        entry is the new pending token (its KV is not yet materialized),
        exactly like the final column of :meth:`decode`'s output.

        ``eos_token_ids`` (scalar or per-sequence list with ``None``
        entries): an eos landing INSIDE the accepted run truncates the
        commit there — the returned tokens end at the eos, and KV/history
        past it is rolled back before publish, so the radix tree never
        receives post-eos paths (the same contract as :meth:`decode`'s
        eos rewind).

        ``draft_tokens[i]`` may also be a LIST of candidate branches
        (token-tree verification): the branches flatten into one ragged
        chunk — root (the pending token) + every branch at its own KV
        slots, ancestors-only attention via the tree mask in
        ``_ragged_step`` — and the DEEPEST branch matching the target's own
        argmax at each step wins; the winner's KV compacts to the canonical
        contiguous positions and every rejected branch rolls back, so a
        rejected sibling can never reach the radix tree. Tree verification
        is greedy-only.

        ``sampling``: per-sequence :class:`SamplingParams` (None entries =
        greedy rows). With any temperature > 0 the verify step switches to
        speculative REJECTION sampling (``sampling.spec_verify_draws``):
        draft ``d_i`` survives with probability ``p_i(d_i)`` under the
        target's tempered/top-p distribution and a rejection resamples the
        normalized residual — the committed stream is distributed exactly
        as direct sampling, so speculation stays a pure throughput lever
        at any temperature. Linear drafts only.

        Compiled once per (token-bucket, seq-bucket, K, tree, sampled);
        rollback is free — accepted tokens just advance ``seen_tokens``,
        rejected drafts release block-table tail refs via the PR 3
        refcount machinery."""
        batch_uids = list(batch_uids)
        hb = self._health
        gl = self.goodput_ledger
        if gl is None and not hb.enabled:
            return self._speculate(batch_uids, first_tokens, draft_tokens, k, eos_token_ids,
                                   sampling)
        if gl is not None:
            self._gp_last_uids = batch_uids
            t_gp = time.perf_counter()
        if hb.enabled:
            hb.begin("serving")
            get_flight_recorder().record("serving", "speculate", seqs=len(batch_uids),
                                         k=int(k) if k is not None else -1)
        try:
            return self._speculate(batch_uids, first_tokens, draft_tokens, k, eos_token_ids,
                                   sampling)
        finally:
            if hb.enabled:
                hb.end("serving")
            if gl is not None:
                gl.book("spec_verify", time.perf_counter() - t_gp)

    @_serving_compile_scope
    def _speculate(self, batch_uids, first_tokens, draft_tokens, k, eos_token_ids=None,
                   sampling=None):
        from .sampling import all_greedy, pack_sampling

        observing = get_tracer().enabled or get_metrics().enabled
        t0 = time.perf_counter() if observing else 0.0
        rf = get_roofline()
        t_rf = time.perf_counter() if rf.enabled else 0.0
        uids = list(batch_uids)
        S = len(uids)
        firsts = [np.asarray(t, np.int32).reshape(-1) for t in first_tokens]
        # normalize drafts to per-sequence branch LISTS (a bare array is one
        # linear branch — the PR 9 call surface unchanged)
        branches: List[List[np.ndarray]] = []
        for d in draft_tokens:
            bl = [np.asarray(b, np.int32).reshape(-1) for b in d] \
                if isinstance(d, (list, tuple)) else [np.asarray(d, np.int32).reshape(-1)]
            branches.append([b for b in bl if b.size])
        tree = any(len(bl) > 1 for bl in branches)
        sampled = not all_greedy(sampling)
        if tree and sampled:
            raise ValueError("token-tree verification is greedy-only; a sampled request "
                             "verifies one linear draft via rejection sampling")
        if k is None:
            k = max((b.size for bl in branches for b in bl), default=0)
        k = int(k)
        if k < 1:
            raise ValueError("speculate_decode needs k >= 1 (use decode() for plain steps)")
        assert all(t.size == 1 for t in firsts), \
            "speculate_decode takes exactly one pending next token per sequence"
        if any(b.size > k for bl in branches for b in bl):
            raise ValueError(f"draft longer than k={k}")
        W = max((len(bl) for bl in branches), default=1) if tree else 1
        n_new = 1 + W * k  # fed chunk length: root + every (padded) branch
        if len(set(uids)) != len(uids) or S > self.batch.max_seqs:
            raise SchedulingError(SchedulingResult.BatchSequenceLimitExceeded)
        if S * n_new > self.batch.max_tokens:
            raise SchedulingError(SchedulingResult.TokenLimitExceeded)
        seqs = []
        for uid in uids:
            seq = self.state_manager.get_sequence(uid)
            if seq is None:
                raise SchedulingError(SchedulingResult.EngineSequenceLimitExceeded)
            if seq.seen_tokens + n_new > self._max_context:
                raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
            seqs.append(seq)
        if sum(s.blocks_needed(n_new) for s in seqs) > self.state_manager.available_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)

        # uniform chunks; short drafts/branch lists pad by repeating their
        # last token (branch 0 clones for missing branches): pads ride the
        # forward like any draft and only ever COMMIT when they equal the
        # target's own choice, so parity is unconditional
        chunks, padded = [], []
        for f, bl in zip(firsts, branches):
            if tree:
                bl = list(bl) or [np.full(k, int(f[0]), np.int32)]
                while len(bl) < W:
                    bl.append(bl[0])
                pb = [np.concatenate([b, np.full(k - b.size,
                                                 int(b[-1]) if b.size else int(f[0]),
                                                 np.int32)]) for b in bl]
                padded.append(pb)
                chunks.append(np.concatenate([f] + pb))
            else:
                d = bl[0] if bl else np.empty(0, np.int32)
                pad = np.full(k - d.size, int(d[-1]) if d.size else int(f[0]), np.int32)
                padded.append([np.concatenate([d, pad])])
                chunks.append(np.concatenate([f, d, pad]))
        starts = [s.seen_tokens for s in seqs]
        self.batch.clear()
        for seq, c in zip(seqs, chunks):
            # note BEFORE the forward, like _put: history mirrors the fed
            # chunk; commit_speculative/rollback_to reconcile it afterwards
            self.state_manager.note_tokens(seq, c)
            self.state_manager.allocate_blocks(seq, n_new)
            seq.pre_forward(n_new)
            self.batch.insert_sequence(seq, c)
        rb = self.batch.finalize()
        t_bucket, s_bucket = rb.token_ids.shape[0], rb.block_tables.shape[0]

        kv = self.state_manager.kv_cache
        fn = self._get_compiled_verify(t_bucket, s_bucket, n_new - 1, tree=tree,
                                       sampled=sampled)
        if tree:
            # per-token tree metadata rows [pos_ids | branch | depth]: node
            # 0 is the shared root at depth 0; branch b's nodes carry depth
            # 1..k and LOGICAL position start + depth (their KV slots stay
            # flat — the mask in _ragged_step keeps siblings invisible)
            meta = np.zeros((3, t_bucket), np.int32)
            depth_row = np.concatenate([[0]] + [np.arange(1, k + 1)] * W).astype(np.int32)
            branch_row = np.concatenate([[0]] + [np.full(k, b) for b in range(W)]).astype(np.int32)
            cur = 0
            for start in starts:
                meta[0, cur:cur + n_new] = start + depth_row
                meta[1, cur:cur + n_new] = branch_row
                meta[2, cur:cur + n_new] = depth_row
                cur += n_new
            out, pools = fn(self.params, jnp.asarray(rb.packed()),
                            jnp.asarray(meta.reshape(-1)), kv.pools())
        elif sampled:
            samp_f, seeds = pack_sampling(sampling, uids, s_bucket)
            out, pools = fn(self.params, jnp.asarray(rb.packed()),
                            jnp.asarray(samp_f), jnp.asarray(seeds), kv.pools())
        else:
            out, pools = fn(self.params, jnp.asarray(rb.packed()), kv.pools())
        kv.update(*pools)

        if eos_token_ids is None or isinstance(eos_token_ids, (int, np.integer)):
            eos_list = [eos_token_ids] * S
        else:
            eos_list = list(eos_token_ids)
            assert len(eos_list) == S, "eos_token_ids must match batch_uids"
        results = []
        drafted = accepted = 0
        accepts = []
        if sampled:
            acc_m = np.asarray(out[0][:S]).astype(bool)  # [S, k] accept bits
            nxt_m = np.asarray(out[1][:S])               # [S, k+1] resample/bonus
        else:
            rows = np.asarray(out[:S])  # [S, n_new] greedy argmax per position
        for i, (seq, c, start, bl, eos) in enumerate(zip(seqs, chunks, starts, branches,
                                                         eos_list)):
            src_dst = None
            if sampled:
                d = padded[i][0]
                rej = np.nonzero(~acc_m[i])[0]
                a = int(rej[0]) if rej.size else k
                committed = list(c[1:1 + a]) + [int(nxt_m[i, a])]
                path = c[1:1 + a]
                real = int(bl[0].size) if bl else 0
            elif tree:
                row = rows[i]
                # deepest-argmax-path walk: branch b's node at depth t+1 is
                # accepted iff its token equals the argmax at its PARENT
                # node (root for t=0); ties keep the first branch, so a
                # padded branch-0 clone can never displace the original
                a, bwin = -1, 0
                for b in range(W):
                    pb = padded[i][b]
                    parents = np.concatenate(
                        [[0], 1 + b * k + np.arange(k - 1)]).astype(np.int64)
                    neq = np.nonzero(pb != row[parents])[0]
                    a_b = int(neq[0]) if neq.size else k
                    if a_b > a:
                        a, bwin = a_b, b
                path = padded[i][bwin][:a]
                bonus = int(row[0] if a == 0 else row[1 + bwin * k + a - 1])
                committed = list(path) + [bonus]
                if bwin != 0 and a > 0:
                    # winner's KV sits at its flat tree slots — move it to
                    # the canonical contiguous positions before rollback
                    src_dst = [(start + 1 + bwin * k + t, start + 1 + t)
                               for t in range(a)]
                real = int(bl[bwin].size) if bwin < len(bl) else 0
                drafted += sum(int(b.size) for b in bl)
            else:
                row = rows[i]
                neq = np.nonzero(c[1:] != row[:k])[0]
                a = int(neq[0]) if neq.size else k
                committed = list(row[:a + 1])
                path = row[:a]
                real = int(bl[0].size) if bl else 0
            if eos is not None:
                # an eos among the ACCEPTED tokens ends the stream there:
                # commit through the eos only, so the post-eos accepted
                # tail (KV + history) is rolled back with the rejects and
                # never published (the bonus-position eos needs nothing —
                # its KV was never materialized)
                hit = np.nonzero(np.asarray(path)[:a] == eos)[0]
                if hit.size:
                    a = int(hit[0])
                    committed = committed[:a + 1]
                    if src_dst is not None:
                        src_dst = src_dst[:a]
            seq.post_forward()                       # seen = start + n_new
            if tree:
                self.state_manager.commit_speculative(
                    seq, start + 1 + a,
                    [int(c[0])] + [int(t) for t in committed[:a]], src_dst)
            else:
                self.state_manager.rollback_to(seq, start + 1 + a)
            self.state_manager.publish_sequence(seq)  # accepted full blocks → tree
            results.append(np.asarray(committed, np.int32))
            if not tree:
                drafted += real
            accepted += min(a, real)  # pads excluded from the honest rate
            accepts.append(a)
        self._spec_totals["drafted"] += drafted
        self._spec_totals["accepted"] += accepted
        if rf.enabled:
            # speculate always fetches to host (the committed rows), so the
            # verify wall join needs no block gate
            rf.note_wall(f"verify/t{t_bucket}/s{s_bucket}/k{n_new - 1}"
                         f"{'/tree' if tree else ''}{'/sampled' if sampled else ''}",
                         time.perf_counter() - t_rf)
        if observing:
            m = get_metrics()
            if m.enabled:
                m.counter("serving/spec_drafted_tokens").inc(drafted)
                m.counter("serving/spec_accepted_tokens").inc(accepted)
                m.counter("serving/spec_rejected_tokens").inc(drafted - accepted)
                m.gauge("serving/spec_accept_rate").set(
                    self._spec_totals["accepted"] / max(1, self._spec_totals["drafted"]))
            committed_n = int(sum(len(r) for r in results))
            observe_latency(t0, "serving/spec_verify",
                            hist_name="serving/spec_verify_ms",
                            gauges={"serving/spec_tokens_per_sec":
                                    lambda dt: committed_n / max(dt, 1e-9)},
                            span_args={"seqs": S, "k": k, "drafted": drafted,
                                       "tree_width": W, "sampled": bool(sampled),
                                       "accepted": accepts[:16],
                                       "uids": [int(u) for u in uids[:16]]})
        return results

    def _note_compile(self, bucket):
        """Recompile-sentinel feed: a compiled-cache miss IS the moment XLA
        compiles a new (bucket) program — report it with this engine's own
        warmup-boundary verdict and the in-flight uids (joined to request
        ids when the replica registered a resolver)."""
        gp = get_goodput()
        if not gp.enabled:
            return
        uids = list(self._gp_last_uids or [])[:8]
        rids = None
        res = self.gp_rid_resolver
        if res is not None:
            try:
                rids = [res(u) for u in uids]
            except Exception:  # noqa: BLE001 — telemetry never raises
                rids = None
        gp.sentinel.note_compile("serving", bucket=bucket, warmed=self._gp_warmed,
                                 uids=uids, rids=rids)

    def declare_gp_warmed(self):
        """Declare this engine's recompile-sentinel warmup boundary without
        running :meth:`warmup` — for callers (bench, tests) that warmed the
        compiled-program cache with real traffic instead of zero
        descriptors. Every later compiled-cache miss is flagged."""
        self._gp_warmed = True
        gp = get_goodput()
        if gp.enabled:
            gp.sentinel.declare_warmed("serving")
        return self

    def _get_compiled_verify(self, t_bucket: int, s_bucket: int, k: int,
                             tree: bool = False, sampled: bool = False):
        key = ("verify", t_bucket, s_bucket, k, bool(tree), bool(sampled))
        if key not in self._compiled:
            bucket = (f"verify/t{t_bucket}/s{s_bucket}/k{k}"
                      f"{'/tree' if tree else ''}{'/sampled' if sampled else ''}")
            self._note_compile(bucket)
            step_fn = self._ragged_step
            mb = self._max_blocks_per_seq

            if sampled:
                from .sampling import spec_verify_draws

                def fwd(params, packed, samp_f, seeds, pools):
                    logits, pools = step_fn(params, packed, pools, t_bucket, s_bucket,
                                            gather_k=k)
                    lg = logits.reshape(s_bucket, k + 1, -1)
                    last = packed[4 * t_bucket + s_bucket * mb:
                                  4 * t_bucket + s_bucket * mb + s_bucket]
                    idx = jnp.maximum(
                        last[:, None] - k + jnp.arange(k + 1, dtype=jnp.int32), 0)
                    chunk = packed[0:t_bucket][idx]                 # fed token rows
                    starts = packed[2 * t_bucket:3 * t_bucket][jnp.maximum(last, 0)] - k
                    accept, nxt = spec_verify_draws(lg, chunk, samp_f[:, 0], samp_f[:, 1],
                                                    seeds, starts)
                    return (accept.astype(jnp.int32), nxt), pools

                self._compiled[key] = jax.jit(fwd, donate_argnums=(4, ))
            elif tree:
                def fwd(params, packed, tree_meta, pools):
                    logits, pools = step_fn(params, packed, pools, t_bucket, s_bucket,
                                            gather_k=k, tree_meta=tree_meta)
                    toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return toks.reshape(s_bucket, k + 1), pools

                self._compiled[key] = jax.jit(fwd, donate_argnums=(3, ))
            else:
                def fwd(params, packed, pools):
                    logits, pools = step_fn(params, packed, pools, t_bucket, s_bucket,
                                            gather_k=k)
                    toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return toks.reshape(s_bucket, k + 1), pools

                self._compiled[key] = jax.jit(fwd, donate_argnums=(2, ))
            rf = get_roofline()
            if rf.enabled:
                # roofline cost capture: the wrapper snapshots this program's
                # abstract signature on its first real call (lazy cost_analysis)
                self._compiled[key] = rf.capture_executable(bucket, self._compiled[key])
            log_dist(f"compiled speculative verify bucket tokens={t_bucket} "
                     f"seqs={s_bucket} k={k} tree={tree} sampled={sampled}", ranks=[0])
        return self._compiled[key]

    def _get_compiled_decode(self, s_bucket: int, n_steps: int, sampled: bool = False):
        key = ("decode", s_bucket, n_steps, bool(sampled))
        if key not in self._compiled:
            bucket = f"decode/s{s_bucket}/n{n_steps}{'/sampled' if sampled else ''}"
            self._note_compile(bucket)
            from .ragged.ragged_wrapper import unpack_descriptors

            max_blocks = self._max_blocks_per_seq
            step_fn = self._ragged_step

            if sampled:
                from .sampling import sample_tokens

                def fwd(params, packed, samp_f, seeds, pools):
                    token_ids = unpack_descriptors(packed, s_bucket, s_bucket, max_blocks)[0]
                    pos_row = packed[2 * s_bucket:3 * s_bucket]

                    def step(carry, t):
                        toks, pl = carry
                        stepped = packed.at[0:s_bucket].set(toks) \
                                        .at[2 * s_bucket:3 * s_bucket].add(t)
                        logits, pl = step_fn(params, stepped, pl, s_bucket, s_bucket)
                        # draw keyed by the NEW token's absolute position —
                        # the same stream the sampled put path would produce
                        nxt = sample_tokens(logits, samp_f[:, 0], samp_f[:, 1], seeds,
                                            pos_row + t + 1)
                        return (nxt, pl), nxt

                    (_, pools), out = jax.lax.scan(
                        step, (token_ids, pools), jnp.arange(n_steps, dtype=jnp.int32))
                    return out.T, pools  # [S, n_steps]

                self._compiled[key] = jax.jit(fwd, donate_argnums=(4, ))
            else:
                def fwd(params, packed, pools):
                    token_ids = unpack_descriptors(packed, s_bucket, s_bucket, max_blocks)[0]

                    def step(carry, t):
                        toks, pl = carry
                        # feed the greedy tokens back into the packed descriptor
                        # and advance positions in-scan from the packed starts
                        # (packed layout: [T ids][T seq_idx][T pos]...)
                        stepped = packed.at[0:s_bucket].set(toks) \
                                        .at[2 * s_bucket:3 * s_bucket].add(t)
                        logits, pl = step_fn(params, stepped, pl, s_bucket, s_bucket)
                        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                        return (nxt, pl), nxt

                    (_, pools), out = jax.lax.scan(
                        step, (token_ids, pools), jnp.arange(n_steps, dtype=jnp.int32))
                    return out.T, pools  # [S, n_steps]

                self._compiled[key] = jax.jit(fwd, donate_argnums=(2, ))
            rf = get_roofline()
            if rf.enabled:
                self._compiled[key] = rf.capture_executable(bucket, self._compiled[key])
            log_dist(f"compiled multi-step decode bucket seqs={s_bucket} steps={n_steps} "
                     f"sampled={sampled}", ranks=[0])
        return self._compiled[key]

    @_serving_compile_scope
    def warmup(self, seq_buckets: Iterable[int], decode_steps,
               token_buckets: Iterable[int] = (), put_samples=("greedy", ),
               declare_warmed: bool = True) -> List[dict]:
        """Pre-compile the lazy shape buckets at startup so the first real
        request does not pay the XLA compile inside its TTFT.

        ``seq_buckets``: sequence counts, each rounded UP to the wrapper's
        static bucket (the same rounding ``decode`` applies); ``decode_steps``:
        one scan horizon or an iterable of them. ``token_buckets`` (optional):
        prefill token counts — each (token-bucket x seq-bucket x sample mode
        in ``put_samples``) ``put`` program is ALSO pre-compiled, closing the
        warmup gap the recompile sentinel otherwise names on the first real
        prefill. Each distinct program is traced, compiled, and executed once
        on an all-zero descriptor against the real (donated-through) KV
        pools, so the jit executable cache holds exactly the signature real
        traffic hits. The zero descriptor scribbles into pool block 0, which
        is harmless before any sequence exists but NOT after — warmup
        therefore refuses to run once sequences are tracked. Each compile is
        recorded as a ``jax_compile`` event on the trace bus (``args.source``
        = "warmup"). Completion declares this engine's recompile-sentinel
        warmup boundary: with the goodput plane armed, every LATER compile of
        a new bucket is flagged as an unexpected steady-state recompile.
        Returns ``[{"seqs", "steps", "seconds", "cached"}, ...]`` (prefill
        entries carry ``"tokens"``/``"sample"`` instead of ``"steps"``).
        """
        if self.state_manager.n_tracked_sequences:
            raise RuntimeError("warmup() must run before serving traffic: its zero descriptor "
                               "writes into KV block 0, which live sequences may own")
        pc = self.state_manager.prefix_cache
        if pc is not None and pc.n_cached_blocks:
            # flushed sequences leave their blocks in the radix tree — block 0
            # may be cache-held, and the zero descriptor would scribble on its
            # KV. Dropping the (re-computable) cache keeps warmup safe.
            pc.clear()
        # materialize: a one-shot iterable would be exhausted by the first
        # seq bucket, silently leaving later buckets un-warmed
        decode_steps = (decode_steps, ) if isinstance(decode_steps, int) else tuple(decode_steps)
        tracer = get_tracer()
        kv = self.state_manager.kv_cache
        max_blocks = self._max_blocks_per_seq
        results = []
        s_buckets = [next_bucket(int(w), self.batch.seq_buckets) for w in seq_buckets]
        for s_bucket in s_buckets:
            for n_steps in decode_steps:
                n_steps = int(n_steps)
                key = ("decode", s_bucket, n_steps, False)
                if key in self._compiled:
                    results.append({"seqs": s_bucket, "steps": n_steps, "seconds": 0.0, "cached": True})
                    continue
                fn = self._get_compiled_decode(s_bucket, n_steps)
                # packed layout [T ids][T idx][T pos][T valid][S*max_blocks][S last]
                # with T == S on the decode path
                packed = jnp.zeros(s_bucket * (5 + max_blocks), jnp.int32)
                t0 = time.perf_counter()
                toks, pools = fn(self.params, packed, kv.pools())
                jax.block_until_ready(toks)
                kv.update(*pools)
                dt = time.perf_counter() - t0
                tracer.complete("jax_compile", t0, dt, tid="compile",
                                args={"source": "warmup", "seqs": s_bucket, "steps": n_steps})
                log_dist(f"warmup compiled decode bucket seqs={s_bucket} steps={n_steps} "
                         f"in {dt:.2f}s", ranks=[0])
                results.append({"seqs": s_bucket, "steps": n_steps, "seconds": dt, "cached": False})
        for sample in put_samples:
            if sample not in (None, "greedy"):
                # the 'sample' variant takes extra per-request sampling
                # operands this zero-descriptor path does not build
                raise ValueError(f"warmup(put_samples=...) supports None/'greedy', got {sample!r}")
        for want_t in token_buckets or ():
            t_bucket = next_bucket(int(want_t), self.batch.token_buckets)
            for s_bucket in s_buckets:
                if s_bucket > t_bucket:
                    continue  # a prefill batch never has more rows than tokens
                for sample in put_samples:
                    key = (t_bucket, s_bucket, sample)
                    if key in self._compiled:
                        results.append({"seqs": s_bucket, "tokens": t_bucket,
                                        "sample": sample, "seconds": 0.0, "cached": True})
                        continue
                    fn = self._get_compiled(t_bucket, s_bucket, sample)
                    # put-path packed layout: [T ids][T idx][T pos][T valid]
                    # [S*max_blocks][S last]
                    packed = jnp.zeros(4 * t_bucket + s_bucket * (max_blocks + 1), jnp.int32)
                    t0 = time.perf_counter()
                    out, pools = fn(self.params, packed, kv.pools())
                    jax.block_until_ready(out)
                    kv.update(*pools)
                    dt = time.perf_counter() - t0
                    tracer.complete("jax_compile", t0, dt, tid="compile",
                                    args={"source": "warmup", "tokens": t_bucket,
                                          "seqs": s_bucket, "sample": sample})
                    log_dist(f"warmup compiled prefill bucket tokens={t_bucket} "
                             f"seqs={s_bucket} sample={sample} in {dt:.2f}s", ranks=[0])
                    results.append({"seqs": s_bucket, "tokens": t_bucket,
                                    "sample": sample, "seconds": dt, "cached": False})
        # warmup boundary declared at COMPLETION: later bucket compiles on
        # this engine are steady-state recompiles the sentinel flags. A
        # caller warming in several calls (the replica's per-entry loop)
        # passes declare_warmed=False and declares once after the last.
        if declare_warmed:
            self.declare_gp_warmed()
        return results

    # ------------------------------------------------------------------
    def query(self, uid: Optional[int] = None):
        """Sequence / engine state introspection (reference ``query:153``)."""
        return self.state_manager.query(uid)

    def flush(self, uid: int) -> None:
        """Finish a sequence and release its KV blocks (reference ``flush:228``)."""
        self.state_manager.flush_sequence(uid)

    def serialize(self, save_path: str) -> None:
        """Persist the engine's (possibly transformed — int8, etc.) params +
        model/engine metadata (reference ``serialize:237`` saves the
        flattened params + metadata per TP rank; tensorstore writes each
        host's shards, so one call covers every rank here)."""
        import dataclasses
        import os
        import pickle

        from ...runtime.checkpoint_engine.orbax_checkpoint_engine import OrbaxCheckpointEngine

        eng = OrbaxCheckpointEngine()
        eng.save({"module": self.params}, save_path)
        from ..quantization import QuantizedWeight, QuantizedWeight4

        _q = (QuantizedWeight, QuantizedWeight4)
        mc = self.model_config
        quantized = any(isinstance(x, _q) for x in jax.tree_util.tree_leaves(
            self.params, is_leaf=lambda x: isinstance(x, _q)))
        meta = {"model_config": dataclasses.asdict(mc) if dataclasses.is_dataclass(mc)
                else dict(getattr(mc, "__dict__", {})),
                "quantized": quantized,  # from the params themselves, not an impl name
                "kv_block_size": self.config.kv_block_size}
        with open(os.path.join(os.path.abspath(save_path), "engine_meta.pkl"), "wb") as f:
            pickle.dump(meta, f)
        log_dist(f"InferenceEngineV2 serialized to {save_path}", ranks=[0])

    @property
    def max_context(self) -> int:
        """Per-sequence context ceiling in tokens (prompt + generation),
        after the model's own ``max_seq_len`` clamp. Public so the request
        plane (``deepspeed_tpu/serving/``) can validate without reaching
        into engine internals — the ``tools/check_gateway_api.py`` gate
        forbids it anything non-public."""
        return self._max_context

    @property
    def max_concurrent_sequences(self) -> int:
        """Sequences one ragged forward may carry (the scheduler/batch
        ceiling) — the request plane's default in-flight bound."""
        return self.config.state_manager.max_ragged_sequence_count

    @property
    def free_blocks(self) -> int:
        return self.state_manager.free_blocks

    @property
    def available_blocks(self) -> int:
        """Free-list blocks plus what prefix-cache eviction could reclaim."""
        return self.state_manager.available_blocks

    @property
    def prefix_cache(self):
        """The :class:`PrefixKVCache` radix tree (None when disabled)."""
        return self.state_manager.prefix_cache

    @property
    def cache_telemetry(self):
        """The :class:`CacheTelemetry` plane (None unless the
        ``ragged.prefix_cache.telemetry`` block is enabled)."""
        return self.state_manager.cache_telemetry

    @property
    def tiered_store(self):
        """The host/disk KV capacity tier (None unless the
        ``ragged.prefix_cache.host_tier`` block is present and enabled)."""
        return self.state_manager.tiered_store

    def shutdown(self) -> None:
        """Stop background workers this engine owns (currently the KV
        tier's migration thread). Idempotent; a no-op without a tier."""
        self.state_manager.shutdown()

    # -- HBM attribution (monitor/memory.py) ----------------------------
    def _memory_sections(self):
        # per-host shard bytes (the pools shard over the model axis under
        # TP — the global logical size would over-count on multi-host)
        kv_bytes = tree_device_bytes(self.state_manager.kv_cache.pools())
        if self._memory_role is not None:
            return {self._memory_role: tree_device_bytes(self.params) + kv_bytes}
        return {"params": tree_device_bytes(self.params),
                "kv_block_pool": kv_bytes}

    def set_memory_role(self, role: Optional[str]) -> None:
        """Re-file this engine's bytes under one named section (a
        speculative draft engine reports as ``spec_draft_engine`` instead
        of inflating the primary ``params``/``kv_block_pool`` rows)."""
        self._memory_role = role

    # -- tenant metering (serving/metering.py) ---------------------------
    def set_tenant_meter(self, meter) -> None:
        """Attach a gateway ``TenantMeter``: builds this engine's
        :class:`~deepspeed_tpu.serving.metering.EngineMeterView` (block ids
        are engine-local) and wires it into the block-lifecycle hooks —
        allocator allocate/free (the CacheTelemetry surface), owner
        stamping in the state manager, and the prefix cache's tenant-level
        publish/hit/evict forwards. The ONE public entry the request plane
        is allowed to use (``tools/check_gateway_api.py`` keeps serving/
        out of engine internals). Idempotent per meter; ``None`` detaches."""
        if meter is None:
            view = self.state_manager.tenant_meter
            if self._tenant_meter is not None and view is not None:
                # settle the view's in-flight residency charges and stop it
                # contributing to reports (a detached view can never see
                # on_free again — kept live it would accrue phantom
                # block-seconds forever)
                self._tenant_meter.drop_view(view)
            self._tenant_meter = None
            self.state_manager.set_tenant_meter(None)
            return
        if self._tenant_meter is meter:
            return  # replica restart: keep the live view (owner stamps survive)
        self._tenant_meter = meter
        view = meter.engine_view(self.state_manager.kv_cache.total_blocks)
        self.state_manager.set_tenant_meter(view)

    def probe_prefix(self, prompt_tokens):
        """PURE prefix lookup (no references taken, no LRU touch, no stats):
        ``(n_cached_tokens, n_shared_full_blocks, n_tree_only, match)`` the
        cache would serve for this prompt. Admission uses it for budget math
        BEFORE committing — a refused request must leave the tree untouched.
        ``n_tree_only`` counts the hit's shared blocks whose sole holder is
        currently the tree: acquisition pins them, so they must come OFF the
        evictable supply in any admission check that subtracts the hit from
        the demand side (counting them on both sides over-admits)."""
        pc = self.state_manager.prefix_cache
        if pc is None:
            return 0, 0, 0, None
        m = pc.match(np.asarray(prompt_tokens, np.int32).reshape(-1))
        tree_only = sum(1 for b in m.shared_blocks
                        if self.state_manager.kv_cache.refcount(b) == 1)
        return m.n_cached_tokens, len(m.shared_blocks), tree_only, m

    def acquire_prefix(self, uid: int, prompt_tokens, match=None,
                       tenant=None) -> Tuple[int, int]:
        """Create the sequence for ``uid`` pre-populated from the prefix
        cache (the scheduler's admission-side entry: it knows the FULL
        prompt, so the match is not limited to the first SplitFuse chunk).
        ``match`` — the object from :meth:`probe_prefix` — skips the
        re-match (valid as long as nothing mutated the tree in between).
        ``tenant`` — the requesting owner identity, stamped on the sequence
        (and its blocks / published tree nodes) when the metering plane is
        attached; None = untenanted.
        Returns ``(n_cached_tokens, n_shared_full_blocks)`` — the scheduler
        feeds ``prompt[n_cached:]`` and charges only the uncached tokens.
        Roll back an abandoned acquisition with ``flush(uid)``."""
        seq, skip = self._create_with_prefix(
            uid, np.asarray(prompt_tokens, np.int32).reshape(-1), match=match,
            tenant=tenant)
        return skip, seq.shared_blocks

    def export_sequence_kv(self, uid: int, tokens):
        """Functional D2H export of one live sequence's FULL KV blocks for a
        cross-replica handoff (``serving/handoff.py``): returns
        ``(token_chunks, payloads)`` — per-block token-id tuples and their
        ``read_block`` value snapshots materialized to numpy. Driver-thread
        only (``read_block`` is a device op); the snapshots are plain host
        arrays afterwards, so the broker can checksum/ship them from any
        thread. ``tokens`` is the prompt + generated-so-far stream; export
        is clamped to the KV the engine has actually materialized
        (``seen_tokens``) — the KV for the newest generated token does not
        exist yet, and partial blocks never travel (the tree only holds
        full blocks, same rule as ``publish``)."""
        sm = self.state_manager
        seq = sm.get_sequence(uid)
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.config.kv_block_size
        n = min(int(seq.seen_tokens), tokens.size)
        n_full = min(n // bs, len(seq.kv_blocks))
        chunks, payloads = [], []
        for i in range(n_full):
            k, v, ks, vs = sm.kv_cache.read_block(seq.kv_blocks[i])
            payloads.append((np.asarray(k), np.asarray(v),
                             None if ks is None else np.asarray(ks),
                             None if vs is None else np.asarray(vs)))
            chunks.append(tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
        return chunks, payloads

    def install_prefix_kv(self, token_chunks, payloads, tenant=None) -> int:
        """Receiving half of the handoff: adopt exported block payloads into
        this engine's prefix cache as HOST-tier residents
        (:meth:`PrefixKVCache.install_host_chain`). Host-memory ops only —
        callable off this replica's driver thread. Returns blocks installed
        (0 when the prefix cache or host tier is absent: the resume then
        simply re-prefills, correct but uncached)."""
        pc = self.state_manager.prefix_cache
        if pc is None:
            return 0
        return pc.install_host_chain(token_chunks, payloads, tenant=tenant)

    def _create_with_prefix(self, uid: int, prompt_tokens, match=None, tenant=None):
        """Sequence creation + the monitor's view of the lookup: hit-rate
        gauge, cached-token counters, and a ``prefix_hit`` trace span. When
        the hit landed on a demoted chain, the synchronous H2D promotion
        wait the request just ate is booked as ``input_wait``-class goodput
        and emitted as a ``serving/promote_wait`` span — a tier that slows
        admission must show up in the ledger, never silently."""
        pc = self.state_manager.prefix_cache
        pw0 = pc.stats["promote_wait_s"] if pc is not None else 0.0
        t0 = time.perf_counter()
        seq, skip = self.state_manager.create_sequence_with_prefix(uid, prompt_tokens,
                                                                   match=match,
                                                                   tenant=tenant)
        if pc is not None:
            m = get_metrics()
            m.counter("serving/prefix_lookups").inc()
            m.gauge("serving/prefix_hit_rate").set(pc.hit_rate)
            if skip:
                m.counter("serving/prefix_hits").inc()
                m.counter("serving/prefix_cached_tokens").inc(skip)
                get_tracer().instant("prefix_hit", tid="serving", uid=int(uid),
                                     tokens=int(skip), blocks=len(seq.kv_blocks))
            promote_wait = pc.stats["promote_wait_s"] - pw0
            if promote_wait > 0.0:
                gl = self.goodput_ledger
                if gl is not None:
                    gl.book("input_wait", promote_wait)
                tr = get_tracer()
                if tr.enabled:
                    tr.complete("serving/promote_wait", t0, promote_wait,
                                tid="serving", args={"uid": int(uid)})
        return seq, skip

    # ------------------------------------------------------------------
    def _get_compiled(self, t_bucket: int, s_bucket: int, sample: Optional[str] = None):
        key = (t_bucket, s_bucket, sample)
        if key not in self._compiled:
            bucket = f"put/t{t_bucket}/s{s_bucket}/{sample or 'logits'}"
            self._note_compile(bucket)
            if sample not in (None, "greedy", "sample"):
                raise ValueError(f"unsupported sample mode {sample!r}: None | 'greedy' | 'sample'")
            step_fn = self._ragged_step
            mb = self._max_blocks_per_seq

            if sample == "sample":
                from .sampling import sample_tokens

                def fwd(params, packed, samp_f, seeds, pools):
                    logits, pools = step_fn(params, packed, pools, t_bucket, s_bucket)
                    last = packed[4 * t_bucket + s_bucket * mb:
                                  4 * t_bucket + s_bucket * mb + s_bucket]
                    # key each draw by the sampled token's OWN position:
                    # replay-deterministic for a fixed (seed, prompt) and
                    # independent of batch composition
                    ctr = packed[2 * t_bucket:3 * t_bucket][jnp.maximum(last, 0)] + 1
                    toks = sample_tokens(logits, samp_f[:, 0], samp_f[:, 1], seeds, ctr)
                    return toks, pools

                self._compiled[key] = jax.jit(fwd, donate_argnums=(4, ))
            else:
                def fwd(params, packed, pools):
                    logits, pools = step_fn(params, packed, pools, t_bucket, s_bucket)
                    out = jnp.argmax(logits, axis=-1).astype(jnp.int32) if sample == "greedy" else logits
                    return out, pools

                self._compiled[key] = jax.jit(fwd, donate_argnums=(2, ))
            rf = get_roofline()
            if rf.enabled:
                self._compiled[key] = rf.capture_executable(bucket, self._compiled[key])
            log_dist(f"compiled ragged forward bucket tokens={t_bucket} seqs={s_bucket} "
                     f"sample={sample}", ranks=[0])
        return self._compiled[key]
