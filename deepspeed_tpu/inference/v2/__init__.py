"""FastGen-equivalent inference v2 (reference ``deepspeed/inference/v2``):
ragged continuous batching over a paged KV cache."""

from .config_v2 import (CacheTelemetryConfig, DSStateManagerConfig, HostTierConfig,
                        ModulesConfig, PrefixCacheConfig, RaggedInferenceEngineConfig,
                        SpeculativeConfig)
from .engine_v2 import InferenceEngineV2
from .engine_factory import build_engine, build_model_engine
from .scheduling_utils import SchedulingError, SchedulingResult
from .scheduler import DynamicSplitFuseScheduler
from .inference_utils import (ActivationType, DtypeEnum, NormTypeEnum, ceil_div,
                              elem_size, is_gated)
from .sampling import SamplingParams
from .speculative import Drafter, DraftModelDrafter, NgramDrafter, build_drafter
