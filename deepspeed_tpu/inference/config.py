"""Inference config.

Mirrors the reference ``deepspeed/inference/config.py`` (304 LoC,
``DeepSpeedInferenceConfig``: dtype, tensor_parallel, moe, quant,
zero-inference knobs) with the same JSON field names.
"""

from typing import Any, Optional

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """``tensor_parallel`` block (reference class of the same name)."""
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field([1], alias="num_experts")
    type: str = "standard"


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    num_bits: int = 8


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """Reference ``DeepSpeedInferenceConfig`` field surface."""
    kernel_inject: bool = Field(False, alias="kernel_injection")
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field({}, alias="tp")
    enable_cuda_graph: bool = False  # accepted for parity; no-op on TPU (XLA compiles whole graphs)
    zero: dict = {}
    triangular_masking: bool = Field(True, alias="tm")
    moe: DeepSpeedMoEConfig = {}
    quant: QuantizationConfig = {}
    checkpoint: Optional[str] = None
    base_dir: str = ""
    max_tokens: int = Field(1024, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_out_tokens")
    transposed_mode: bool = False
    replace_with_kernel_inject: bool = Field(False, alias="replace_method_kernel")
    injection_policy: Optional[dict] = Field(None, alias="injection_dict")
    injection_policy_tuple: Optional[tuple] = None
    replace_method: str = "auto"

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        return {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16, "float16": jnp.float16, "fp16": jnp.float16,
                "half": jnp.float16, "float32": jnp.float32, "fp32": jnp.float32, "int8": jnp.int8}.get(
                    str(self.dtype).replace("torch.", ""), jnp.bfloat16)
