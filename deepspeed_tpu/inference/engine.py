"""Inference engine (v1).

Analog of the reference ``deepspeed/inference/engine.py:39`` (``InferenceEngine``:
TP-sharded, kernel-injected generation; ``_create_model_parallel_group:253``,
CUDA-graph capture :523). TPU-native equivalents: TP sharding is a set of
NamedShardings over the ``model`` mesh axis (no module surgery — the natural
"kernel injection" on TPU is XLA fusing the jitted decode step, and the graph
capture knob is subsumed by jit), and generation is a compiled
prefill + ``lax.scan`` decode loop over a preallocated KV cache.
"""

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .config import DeepSpeedInferenceConfig
from ..parallel import groups
from ..parallel.mesh import MeshConfig, DATA_AXIS, MODEL_AXIS
from ..runtime.zero.partition import PartitionRules
from ..utils.logging import log_dist


class InferenceEngine:

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None, params=None, mesh=None):
        """``model``: framework model object (TransformerLM) — must expose
        ``config``/``init``; ``params``: optional pre-trained params pytree."""
        self.module = model
        self._config = config or DeepSpeedInferenceConfig()
        tp = max(1, self._config.tensor_parallel.tp_size)

        if mesh is not None:
            self.mesh = groups.set_mesh(mesh)
        elif groups.is_initialized():
            self.mesh = groups.get_mesh()
        else:
            self.mesh = groups.initialize_mesh(MeshConfig(data=-1, model=tp))

        self.model_config = getattr(model, "config", None)
        if self.model_config is not None:
            self.model_config.dtype = self._config.compute_dtype

        rules = model.partition_rules() if hasattr(model, "partition_rules") else PartitionRules()
        self._param_rules = rules
        self.params = self._maybe_quantize(self._place_params(params))
        self._compiled: Dict[Any, Any] = {}
        self._cache = None
        self._model_profile_enabled = False
        self._model_times = []
        log_dist(f"InferenceEngine ready: tp={tp} dtype={self._config.dtype} "
                 f"quant={self._config.quant.enabled} mesh={dict(self.mesh.shape)}", ranks=[0])

    def _place_params(self, params):
        if params is None:
            params = jax.jit(lambda r: self.module.init(r, None))(jax.random.PRNGKey(0))
        specs = self._param_rules.tree_specs(params)
        shardings = jax.tree_util.tree_map(lambda s: NamedSharding(self.mesh, s), specs,
                                           is_leaf=lambda x: isinstance(x, P))
        # device_put (not a jit identity with out_shardings): checkpoint
        # loads arrive committed to one device, which jit rejects against a
        # multi-device mesh; device_put reshards from any source placement
        return jax.device_put(params, shardings)

    # ------------------------------------------------------------------
    def forward(self, input_ids):
        """Plain forward → logits (reference engine __call__ path)."""
        from ..models.transformer import forward as model_forward

        if "fwd" not in self._compiled:
            self._compiled["fwd"] = jax.jit(lambda p, ids: model_forward(self.model_config, p, ids))
        t0 = time.time() if self._model_profile_enabled else None
        with self.mesh:
            out = self._compiled["fwd"](self.params, jnp.asarray(input_ids))
        if t0 is not None:
            # a SCALAR host fetch is the barrier (block_until_ready does not
            # actually synchronize on the relayed axon runtime, and fetching
            # the full logits would inflate the very latency being measured)
            try:
                np.asarray(out[(0,) * out.ndim])
            except Exception:  # non-addressable multi-host array: best effort
                jax.block_until_ready(out)
            self._model_times.append(time.time() - t0)
        return out

    __call__ = forward

    # ------------------------------------------------------------------
    def profile_model_time(self, use_cuda_events: bool = True):
        """Enable per-forward wall-clock capture (reference
        ``engine.py:203`` — its CUDA-event hooks become a host-fetch
        barrier here; ``use_cuda_events`` kept for signature parity)."""
        self._model_profile_enabled = True

    def model_times(self):
        """Drain captured per-forward latencies (reference ``engine.py:552``)."""
        assert self._model_profile_enabled, "model profiling is not enabled"
        times, self._model_times = self._model_times, []
        return times

    # ------------------------------------------------------------------
    def generate(self, input_ids, max_new_tokens: int = 32, temperature: float = 0.0, top_k: int = 0,
                 eos_token_id: Optional[int] = None, seed: int = 0):
        """Greedy / sampled generation with a preallocated KV cache.

        input_ids: [B, S_prompt] (right-aligned, no padding support yet).
        Returns [B, S_prompt + max_new_tokens].
        """
        from ..models.transformer import init_kv_cache, forward_with_cache
        from ..monitor.metrics import get_metrics
        from ..monitor.trace import get_tracer

        observing = get_tracer().enabled or get_metrics().enabled
        t0 = time.perf_counter() if observing else 0.0
        cfg = self.model_config
        input_ids = np.asarray(input_ids)
        B, S = input_ids.shape
        max_len = S + max_new_tokens
        key = (B, S, max_new_tokens, float(temperature), int(top_k))

        if key not in self._compiled:

            def gen_fn(params, prompt, rng):
                cache = init_kv_cache(cfg, B, max_len)
                logits, cache = forward_with_cache(cfg, params, prompt, cache)
                next_tok = _select(logits[:, -1], rng, temperature, top_k)

                def step(carry, _):
                    cache, tok, rng = carry
                    rng, sub = jax.random.split(rng)
                    logits, cache = forward_with_cache(cfg, params, tok[:, None], cache)
                    nxt = _select(logits[:, -1], sub, temperature, top_k)
                    return (cache, nxt, rng), nxt

                rng, sub = jax.random.split(rng)
                (_, _, _), toks = jax.lax.scan(step, (cache, next_tok, sub), None, length=max_new_tokens - 1)
                return jnp.concatenate([next_tok[:, None], toks.T], axis=1)

            self._compiled[key] = jax.jit(gen_fn)

        with self.mesh:
            out = self._compiled[key](self.params, jnp.asarray(input_ids), jax.random.PRNGKey(seed))
        out = np.asarray(out)
        if eos_token_id is not None:
            # truncate after first eos per sequence (host-side post-process)
            for b in range(B):
                hits = np.where(out[b] == eos_token_id)[0]
                if hits.size:
                    out[b, hits[0] + 1:] = eos_token_id
        if observing:
            from ..monitor.trace import observe_latency

            observe_latency(t0, "serving/generate", hist_name="serving/generate_ms",
                            gauges={"serving/generate_tokens_per_sec":
                                    lambda dt: B * max_new_tokens / max(dt, 1e-9)},
                            span_args={"batch": int(B), "new_tokens": int(max_new_tokens)})
        return np.concatenate([input_ids, out], axis=1)

    # ------------------------------------------------------------------
    def load_checkpoint(self, path, template=None):
        """Load params from an engine checkpoint (reference
        ``load_model_with_checkpoint:330``)."""
        from ..runtime.checkpoint_engine.orbax_checkpoint_engine import OrbaxCheckpointEngine

        eng = OrbaxCheckpointEngine()
        loaded = eng.load(path, template=template)
        params = loaded.get("module", loaded)
        self.params = self._maybe_quantize(self._place_params(params))
        return self

    def _maybe_quantize(self, params):
        """Apply config.quant to a freshly placed fp tree — used by BOTH
        __init__ and load_checkpoint so a loaded checkpoint cannot silently
        revert a quantized engine to full precision."""
        if not self._config.quant.enabled:
            return params
        from .quantization import quantize_params_for_inference

        return quantize_params_for_inference(params, self._config.quant.num_bits)

    def eval(self):
        return self

    @property
    def config(self):
        return self._config


def _select(logits, rng, temperature, top_k):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
