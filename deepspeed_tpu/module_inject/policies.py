"""Injection policies (reference ``deepspeed/module_inject/containers/`` —
20 per-model policy classes telling the injector which weights are attention
qkv/output and MLP in/out so they can be TP-sharded and kernel-fused).

TPU form: a policy is a table of (param-path regex → TP PartitionSpec over
the ``model`` axis). Column-parallel (output-dim sharded) for QKV and MLP-in,
row-parallel (input-dim sharded) for attention-out and MLP-down — the same
Megatron split the reference encodes per container.
"""

import re
from typing import Dict, List, Tuple

from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MODEL_AXIS
from ..runtime.zero.partition import PartitionRules

COL = P(None, MODEL_AXIS)   # shard output features
ROW = P(MODEL_AXIS, None)   # shard input features
COL3 = P(None, None, MODEL_AXIS)  # stacked-layer [L, in, out]
ROW3 = P(None, MODEL_AXIS, None)


class TransformerPolicy:
    """Base policy (reference ``DSPolicy``/``TransformerPolicy``)."""

    #: patterns matched against 'a/b/c' param paths
    column_patterns: List[str] = [
        r"(^|/)(wq|wk|wv|q_proj|k_proj|v_proj|query|key|value|w_gate|w_up|gate_proj|up_proj"
        r"|fc1|wi|moe_wi|moe_wg)(/|$)",
    ]
    row_patterns: List[str] = [
        r"(^|/)(wo|o_proj|dense|out_proj|w_down|down_proj|fc2|moe_wo)(/|$)",
    ]

    # params whose FIRST dim is the stacked layer dim (scan-stacked models)
    stacked_layer_prefixes: List[str] = [r"^blocks/"]

    @classmethod
    def _is_stacked(cls, path: str) -> bool:
        return any(re.search(p, path) for p in cls.stacked_layer_prefixes)

    @classmethod
    def spec_for(cls, path: str, ndim: int):
        stacked = cls._is_stacked(path)
        for pat in cls.column_patterns:
            if re.search(pat, path):
                return (COL3 if stacked and ndim == 3 else COL) if ndim >= 2 else None
        for pat in cls.row_patterns:
            if re.search(pat, path):
                return (ROW3 if stacked and ndim == 3 else ROW) if ndim >= 2 else None
        return None

    @classmethod
    def partition_rules(cls) -> PartitionRules:
        rules: List[Tuple[str, P]] = []
        for pat in cls.column_patterns:
            rules.append((pat, COL3))
        for pat in cls.row_patterns:
            rules.append((pat, ROW3))
        return PartitionRules(rules)


class LlamaPolicy(TransformerPolicy):
    """llama/llama2 (reference containers/llama.py, llama2.py)."""


class MistralPolicy(LlamaPolicy):
    """mistral shares llama's layout (reference v2 mistral containers)."""


class GPTPolicy(TransformerPolicy):
    """gpt2/gpt-neo/gpt-j (reference containers/gpt2.py et al.): fused
    c_attn is column-sharded, c_proj row-sharded."""
    column_patterns = TransformerPolicy.column_patterns + [r"(^|/)c_attn(/|$)", r"(^|/)c_fc(/|$)"]
    row_patterns = TransformerPolicy.row_patterns + [r"(^|/)c_proj(/|$)"]


class OPTPolicy(TransformerPolicy):
    """opt (reference containers/opt.py)."""


class BloomPolicy(TransformerPolicy):
    """bloom (reference containers/bloom.py): fused query_key_value column,
    dense row, dense_h_to_4h column, dense_4h_to_h row."""
    column_patterns = TransformerPolicy.column_patterns + [
        r"(^|/)query_key_value(/|$)", r"(^|/)dense_h_to_4h(/|$)"
    ]
    row_patterns = TransformerPolicy.row_patterns + [r"(^|/)dense_4h_to_h(/|$)"]


class GPTNeoXPolicy(BloomPolicy):
    """gpt-neox/pythia (reference containers/gptneox.py): same fused
    query_key_value + dense_h_to_4h/4h_to_h naming as bloom."""


class GPTJPolicy(TransformerPolicy):
    """gpt-j (reference containers/gptj.py): separate q/k/v (no bias),
    fc_in column, fc_out row."""
    column_patterns = TransformerPolicy.column_patterns + [r"(^|/)fc_in(/|$)"]
    row_patterns = TransformerPolicy.row_patterns + [r"(^|/)fc_out(/|$)"]


class FalconPolicy(BloomPolicy):
    """falcon (parallel-attention container): fused query_key_value with
    MQA/GQA kv heads — the kv slice stays replicated when n_kv < tp degree
    (handled by sanitize_spec's divisibility check)."""


class Qwen2Policy(LlamaPolicy):
    """qwen2: llama layout with biased qkv — the bias vectors follow their
    projection's column sharding via the shared q/k/v_proj patterns."""


class PhiPolicy(TransformerPolicy):
    """phi-1.5/phi-2 (parallel-residual container): separate q/k/v with
    ``dense`` attention output and fc1/fc2 MLP — covered by the base
    patterns; listed for registry completeness."""


class BertPolicy(TransformerPolicy):
    """bert/roberta (reference containers/bert.py): self-attention q/k/v
    column, attention output + ffn output row."""
    column_patterns = TransformerPolicy.column_patterns + [r"intermediate/kernel"]
    row_patterns = TransformerPolicy.row_patterns + [r"output/kernel"]


POLICY_REGISTRY: Dict[str, type] = {
    "llama": LlamaPolicy,
    "llama2": LlamaPolicy,
    "mistral": MistralPolicy,
    "gpt2": GPTPolicy,
    "gpt": GPTPolicy,
    "gptj": GPTJPolicy,
    "gpt_neox": GPTNeoXPolicy,
    "pythia": GPTNeoXPolicy,
    "opt": OPTPolicy,
    "bert": BertPolicy,
    "roberta": BertPolicy,
    "bloom": BloomPolicy,
    "falcon": FalconPolicy,
    "qwen2": Qwen2Policy,
    "qwen": Qwen2Policy,
    "phi": PhiPolicy,
}
