"""Fused-QKV TP resharding helpers (reference
``module_inject/fusedqkv_utils.py``): a fused ``[..., (q+k+v)·nh·d]``
projection must be split per-projection-then-per-head on the FUSED (last)
axis before column-sharding — a naive column slice hands each rank a block
mixing q/k/v of the wrong heads. Uneven head counts (GQA kv heads not
divisible by the TP degree) follow ``tp_shard``'s earlier-ranks-take-the-
remainder assignment.
"""

from typing import List, Sequence

import numpy as np


def _head_counts(num_heads: int, mp_size: int) -> List[int]:
    return [num_heads // mp_size + (1 if r < num_heads % mp_size else 0)
            for r in range(mp_size)]


def split_by_qkvlist_and_refuse(qkv_list: Sequence[np.ndarray], split_size: int,
                                split_dim: int = 0, cat_dim: int = 0) -> List[np.ndarray]:
    """Reference helper: split each of q/k/v into ``split_size`` chunks along
    ``split_dim`` and re-fuse chunk-wise — shard i gets (q_i|k_i|v_i)."""
    chunks = [np.array_split(np.asarray(t), split_size, axis=split_dim) for t in qkv_list]
    return [np.concatenate([c[i] for c in chunks], axis=cat_dim) for i in range(split_size)]


def require_tp_fused_qkvw(name: str, mp_size: int) -> bool:
    """Whether a param name is a fused qkv weight needing the per-head split
    (reference matches the family-specific fused names)."""
    if mp_size <= 1:
        return False
    fused_names = ("qkv_proj", "query_key_value", "attn.c_attn", "W_pack", "c_attn")
    return any(f in name for f in fused_names)


def _fused_view(src: np.ndarray, num_heads: int):
    fused = src.shape[-1]
    assert fused % (3 * num_heads) == 0, \
        f"fused qkv dim {fused} must be 3 * {num_heads} heads * head_dim"
    d = fused // (3 * num_heads)
    return src.reshape(*src.shape[:-1], 3, num_heads, d), d


def prepare_tp_fused_qkvw(module_str: str, src: np.ndarray, mp_size: int, gpu_index: int,
                          num_heads: int = None) -> np.ndarray:
    """Rank ``gpu_index``'s slice of a fused qkv weight ``[..., 3·nh·d]``:
    the per-projection head block, NOT a naive column slice. Uneven
    ``num_heads % mp_size`` assigns the remainder heads to the earliest
    ranks (``tp_shard`` contract)."""
    src = np.asarray(src)
    if num_heads is None:
        from .tp_shard import get_num_kv_heads

        num_heads = get_num_kv_heads() or mp_size
    view, d = _fused_view(src, num_heads)
    counts = _head_counts(num_heads, mp_size)
    start = sum(counts[:gpu_index])
    mine = view[..., :, start:start + counts[gpu_index], :]
    return mine.reshape(*src.shape[:-1], 3 * counts[gpu_index] * d)


def refuse_tp_fused_qkvw(shards: Sequence[np.ndarray], num_heads: int = None) -> np.ndarray:
    """Inverse of :func:`prepare_tp_fused_qkvw` (merge all ranks' slices).
    Per-shard head counts are recovered from the shard widths."""
    shards = [np.asarray(s) for s in shards]
    total = sum(s.shape[-1] for s in shards)
    if num_heads is None:
        from .tp_shard import get_num_kv_heads

        num_heads = get_num_kv_heads() or len(shards)
    d = total // (3 * num_heads)
    views = []
    for s in shards:
        cnt = s.shape[-1] // (3 * d)
        views.append(s.reshape(*s.shape[:-1], 3, cnt, d))
    merged = np.concatenate(views, axis=-2)
    return merged.reshape(*shards[0].shape[:-1], total)
