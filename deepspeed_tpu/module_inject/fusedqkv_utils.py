"""Fused-QKV TP resharding helpers (reference
``module_inject/fusedqkv_utils.py``): a fused ``[..., (q+k+v)·nh·d]``
projection must be split per-projection-then-per-head on the FUSED (last)
axis before column-sharding — a naive column slice hands each rank a block
mixing q/k/v of the wrong heads. Uneven head counts (GQA kv heads not
divisible by the TP degree) follow ``tp_shard``'s earlier-ranks-take-the-
remainder assignment.
"""

from typing import List, Sequence

import numpy as np


def _head_counts(num_heads: int, mp_size: int) -> List[int]:
    return [num_heads // mp_size + (1 if r < num_heads % mp_size else 0)
            for r in range(mp_size)]


def split_by_qkvlist_and_refuse(qkv_list: Sequence[np.ndarray], split_size: int,
                                split_dim: int = 0, cat_dim: int = 0) -> List[np.ndarray]:
    """Reference helper: split each of q/k/v into ``split_size`` chunks along
    ``split_dim`` and re-fuse chunk-wise — shard i gets (q_i|k_i|v_i)."""
    chunks = [np.array_split(np.asarray(t), split_size, axis=split_dim) for t in qkv_list]
    return [np.concatenate([c[i] for c in chunks], axis=cat_dim) for i in range(split_size)]


def require_tp_fused_qkvw(name: str, mp_size: int) -> bool:
    """Whether a PARAM NAME is a fused qkv weight needing the per-head split
    (reference matches the family-specific fused names). Discovery only: the
    split itself (:func:`prepare_tp_fused_qkvw`) takes the MODULE or its
    block-class name, because a param name alone cannot always determine the
    fused layout ('query_key_value' collides across bloom and ChatGLM)."""
    if mp_size <= 1:
        return False
    fused_names = ("qkv_proj", "query_key_value", "attn.c_attn", "W_pack", "c_attn",
                   "Wqkv")  # Wqkv: MPT (glmtype in the layout table below)
    return any(f in name for f in fused_names)


# Layout dispatch mirrors the reference's fused_type_dict, which keys on the
# BLOCK CLASS (BloomBlock vs GLMBlock), not the param name — the param name
# "query_key_value" collides across layouts (bloom per-head interleaved vs
# ChatGLM projection-major), so a bare param name cannot decide it.
#   bloomtype: PER-HEAD interleaved [q1,k1,v1,q2,k2,v2,...] on the fused axis
#   glmtype:   projection-major [q1..qn, k1..kn, v1..vn]
_BLOOMTYPE_MARKERS = ("BloomBlock", "FalconDecoderLayer", "GPTNeoXLayer", "bloomtype")
_GLMTYPE_MARKERS = ("GLMBlock", "MPTBlock", "MptBlock", "BaichuanLayer", "QWenBlock",
                    "glmtype", "qwentype", "qkv_proj", "c_attn", "W_pack", "Wqkv")


def _fused_layout(module_str) -> str:
    # reference parity: callers may pass the MODULE itself (auto_tp does) —
    # its class name carries the layout
    if module_str is not None and not isinstance(module_str, str):
        module_str = type(module_str).__name__
    s = module_str or ""
    if any(n in s for n in _BLOOMTYPE_MARKERS):
        return "bloomtype"
    if any(n in s for n in _GLMTYPE_MARKERS):
        return "glmtype"
    if "query_key_value" in s:
        # ambiguous: bloom/falcon/gpt-neox use this name with the interleaved
        # layout, ChatGLM with the projection-major one. Refusing beats a
        # silent mis-split (the bug class this dispatch exists to prevent).
        raise ValueError(
            f"fused-qkv layout for {module_str!r} is ambiguous: 'query_key_value' is "
            "per-head interleaved in bloom/falcon/gpt-neox but projection-major in "
            "ChatGLM. Pass the module / block class name (e.g. 'BloomBlock', "
            "'GLMBlock') or an explicit 'bloomtype'/'glmtype' as module_str.")
    # unknown families (e.g. codegentype's rotated interleave) must not fall
    # through to a shape-correct but scrambled projection-major guess
    raise NotImplementedError(
        f"unrecognized fused-qkv module {module_str!r}: known bloomtype markers "
        f"{_BLOOMTYPE_MARKERS}, glmtype markers {_GLMTYPE_MARKERS}. Pass an explicit "
        "'bloomtype'/'glmtype' if this family uses one of those layouts.")


def _fused_view(src: np.ndarray, num_heads: int, layout: str):
    fused = src.shape[-1]
    assert fused % (3 * num_heads) == 0, \
        f"fused qkv dim {fused} must be 3 * {num_heads} heads * head_dim"
    d = fused // (3 * num_heads)
    if layout == "bloomtype":  # [nh, 3, d] per-head interleaved
        return src.reshape(*src.shape[:-1], num_heads, 3, d), d
    return src.reshape(*src.shape[:-1], 3, num_heads, d), d


def prepare_tp_fused_qkvw(module_str: str, src: np.ndarray, mp_size: int, gpu_index: int,
                          num_heads: int = None) -> np.ndarray:
    """Rank ``gpu_index``'s slice of a fused qkv weight ``[..., 3·nh·d]``:
    the per-head block for the family's actual fused layout, NOT a naive
    column slice. ``module_str`` selects the layout (ADVICE r4: bloom-family
    ``query_key_value`` is per-head interleaved — one projection-major view
    for every name silently mixed q/k/v of the wrong heads). Uneven
    ``num_heads % mp_size`` assigns the remainder heads to the earliest
    ranks (``tp_shard`` contract)."""
    src = np.asarray(src)
    if num_heads is None:
        from .tp_shard import get_num_kv_heads

        num_heads = get_num_kv_heads() or mp_size
    layout = _fused_layout(module_str)
    view, d = _fused_view(src, num_heads, layout)
    counts = _head_counts(num_heads, mp_size)
    start = sum(counts[:gpu_index])
    cnt = counts[gpu_index]
    if layout == "bloomtype":
        mine = view[..., start:start + cnt, :, :]
    else:
        mine = view[..., :, start:start + cnt, :]
    return mine.reshape(*src.shape[:-1], 3 * cnt * d)


def refuse_tp_fused_qkvw(shards: Sequence[np.ndarray], module_str: str,
                         num_heads: int = None) -> np.ndarray:
    """Inverse of :func:`prepare_tp_fused_qkvw` (merge all ranks' slices).
    Per-shard head counts are recovered from the shard widths. ``module_str``
    is REQUIRED and must select the same bloomtype/glmtype layout as the
    split — a glmtype default would merge bloomtype shards into a
    shape-correct but silently scrambled weight (code-review r5 finding)."""
    shards = [np.asarray(s) for s in shards]
    total = sum(s.shape[-1] for s in shards)
    if num_heads is None:
        from .tp_shard import get_num_kv_heads

        num_heads = get_num_kv_heads() or len(shards)
    d = total // (3 * num_heads)
    layout = _fused_layout(module_str)
    views = []
    for s in shards:
        cnt = s.shape[-1] // (3 * d)
        if layout == "bloomtype":
            views.append(s.reshape(*s.shape[:-1], cnt, 3, d))
        else:
            views.append(s.reshape(*s.shape[:-1], 3, cnt, d))
    merged = np.concatenate(views, axis=-3 if layout == "bloomtype" else -2)
    return merged.reshape(*shards[0].shape[:-1], total)
