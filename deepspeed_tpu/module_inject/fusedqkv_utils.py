"""Fused-QKV TP resharding helpers (reference
``module_inject/fusedqkv_utils.py``): a fused [H, (q+k+v)·d] projection must
be split per-projection-then-per-head before column-sharding, or each rank
gets a slice mixing q/k/v of the wrong heads.

Numeric cores shared with ``runtime/state_dict_factory`` (the per-head
interleave split/merge used for MP-degree checkpoint resharding).
"""

from typing import List, Sequence

import numpy as np

from ..runtime.state_dict_factory import merge_fused_qkv_per_head, split_fused_qkv_per_head


def split_by_qkvlist_and_refuse(qkv_list: Sequence[np.ndarray], split_size: int,
                                split_dim: int = 0, cat_dim: int = 0) -> List[np.ndarray]:
    """Reference helper: split each of q/k/v into ``split_size`` chunks along
    ``split_dim`` and re-fuse chunk-wise — shard i gets (q_i|k_i|v_i)."""
    chunks = [np.array_split(np.asarray(t), split_size, axis=split_dim) for t in qkv_list]
    return [np.concatenate([c[i] for c in chunks], axis=cat_dim) for i in range(split_size)]


def require_tp_fused_qkvw(name: str, mp_size: int) -> bool:
    """Whether a param name is a fused qkv weight needing the per-head split
    (reference matches the family-specific fused names)."""
    if mp_size <= 1:
        return False
    fused_names = ("qkv_proj", "query_key_value", "attn.c_attn", "W_pack", "c_attn")
    return any(f in name for f in fused_names)


def prepare_tp_fused_qkvw(module_str: str, src: np.ndarray, mp_size: int, gpu_index: int,
                          num_heads: int = None) -> np.ndarray:
    """Rank ``gpu_index``'s slice of a fused qkv weight (reference dispatches
    per model family; the per-head interleave split covers the glu-style and
    megatron layouts this framework's families use)."""
    src = np.asarray(src)
    if num_heads is None:
        from .tp_shard import get_num_kv_heads

        num_heads = get_num_kv_heads() or mp_size
    shards = split_fused_qkv_per_head(src, mp_size, num_heads)
    return shards[gpu_index]


def refuse_tp_fused_qkvw(shards: Sequence[np.ndarray]) -> np.ndarray:
    """Inverse of :func:`prepare_tp_fused_qkvw` (merge all ranks' slices)."""
    return merge_fused_qkv_per_head(list(shards))
