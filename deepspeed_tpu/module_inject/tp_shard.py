"""TP shard-size math (reference ``module_inject/tp_shard.py``):
kv-head-aware uneven sharding — when the kv-head count doesn't divide the
TP degree, earlier ranks take one extra head's worth of columns."""

from typing import List, Optional

num_kv_heads: Optional[int] = None


def set_num_kv_heads(num: Optional[int]):
    global num_kv_heads
    num_kv_heads = num


def get_num_kv_heads() -> Optional[int]:
    return num_kv_heads


def get_shard_size(total_size: int, mp_size: int, rank: int = 0) -> int:
    if num_kv_heads is not None:
        sizes = get_shard_size_list(total_size, mp_size)
        return sizes[rank]
    assert total_size % mp_size == 0, \
        f"size {total_size} must be divisible by mp_size {mp_size} (no kv-head count set)"
    return total_size // mp_size


def get_shard_size_list(total_size: int, mp_size: int) -> List[int]:
    """Per-rank sizes that ALWAYS sum to ``total_size``: a remainder from
    total_size % num_kv_heads goes to the last rank (the reference's
    assignment) so no columns are silently orphaned."""
    if num_kv_heads is None:
        return [get_shard_size(total_size, mp_size, r) for r in range(mp_size)]
    sizes = [total_size * (num_kv_heads // mp_size + (1 if r < num_kv_heads % mp_size else 0))
             // num_kv_heads for r in range(mp_size)]
    sizes[-1] += total_size - sum(sizes)
    return sizes
